"""The shared-memory artifact fabric (ISSUE 19 tentpole core).

One POSIX shared-memory segment per fabric directory, attached by every
frontend process on the box. The segment is a fixed-layout cache:

    [ header | slot table | bump-allocated data heap ]

- **header**: magic + layout version (attach REFUSES on mismatch — a
  peer running different code must not interpret our bytes), slot
  count, heap geometry, the heap write cursor, and an epoch the wipe
  path bumps.
- **slot table**: open-addressed (linear probe) records of
  (generation, key hash, key len, value len, heap offset). The
  generation is a per-slot seqlock: writers bump it to ODD before
  touching the record and to EVEN after — a reader that sees an odd
  generation, or a different generation after copying, discards the
  read. SIGKILL mid-publish therefore leaves at worst an odd slot that
  every reader skips; it can never wedge or poison them.
- **data heap**: bump-allocated key+value bytes. A full heap wipes the
  whole table (it is a cache — losing everything is always safe) and
  bumps the epoch so readers mid-copy discard.

Writers serialize on an `fcntl.flock` over a lockfile in the fabric
directory — the kernel releases flocks when a process dies, so a
SIGKILL'd writer cannot leave the fabric locked. Cross-process readers
take no lock at all (pure seqlock discipline); the in-process
`threading.Lock` only orders this process's threads.

Attachment liveness rides a second flock: every attached process holds
a SHARED lock on `attach.lock` for its lifetime; on close, a process
that can momentarily grab the EXCLUSIVE lock is provably the last one
out and unlinks the segment — no orphaned /dev/shm entries after a
clean shutdown, even when peers were SIGKILL'd (their shared locks died
with them).

Every anomaly raises (or degrades through) the typed `FabricError`;
callers detach to the private in-process lane and keep serving.
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading

#: segment layout version: bump on ANY layout change so old processes
#: refuse to attach instead of misreading
FABRIC_VERSION = 1
MAGIC = b"GTPUSHM1"

#: header: magic, version, slot_count, data_off, data_size,
#: write_cursor (byte offset 32), epoch (byte offset 40)
_HDR = struct.Struct("<8sIIQQQQ")
_CURSOR_OFF = 32
_EPOCH_OFF = 40
#: slot: generation (seqlock), key hash, key len, value len, heap offset
_SLOT = struct.Struct("<QQIIQ")
#: linear-probe window shared by put and get
_PROBES = 64
#: keys are small (template hashes, table names); bound them so a torn
#: or corrupt length can never trigger a huge copy
_MAX_KEY = 4096

#: /dev/shm name prefix — the segment-leak check greps for it
SEGMENT_PREFIX = "gtpu_shm_"


class FabricError(Exception):
    """Typed fabric failure: attach refusal (bad magic/version), a slot
    that failed its bounds check with a stable generation (genuine
    corruption), or an OS-level segment error. Callers degrade to the
    private in-process lane."""


def _hash_key(key: bytes) -> int:
    h = int.from_bytes(hashlib.blake2b(key, digest_size=8).digest(),
                       "little")
    return h or 1  # 0 is the empty-slot sentinel


def segment_name(fabric_dir: str) -> str:
    """Stable /dev/shm name for a fabric directory (every process that
    resolves the same directory attaches the same segment)."""
    real = os.path.realpath(fabric_dir)
    digest = hashlib.blake2b(real.encode(), digest_size=6).hexdigest()
    return f"{SEGMENT_PREFIX}{digest}"


def _unregister_tracker(shm) -> None:
    """Python's resource_tracker unlinks shared memory it thinks the
    process leaked — with N independent attachers that is a use-after-
    unlink for everyone else. Lifetime is managed by the attach-lock
    refcount instead. CPython 3.10 registers on BOTH create and attach,
    so every successful open is followed by exactly one unregister."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # noqa: BLE001 — tracker internals vary by version
        pass


def _unlink_segment(name: str) -> None:
    """Unlink a segment by name without spinning up a fresh
    SharedMemory handle (which would re-map and re-register it)."""
    try:
        from multiprocessing.shared_memory import _posixshmem

        _posixshmem.shm_unlink("/" + name)
    except FileNotFoundError:
        pass
    except (ImportError, AttributeError):
        try:
            os.unlink("/dev/shm/" + name)
        except OSError:
            pass


class Fabric:
    """One attached artifact fabric. Thread-safe; cross-process safe.

    Locking: `_lock` (threading) serializes this process's accesses so
    the flock fd is held by one thread at a time; the flock serializes
    writers across processes. Peer-process readers are lock-free.
    """

    def __init__(self, fabric_dir: str, size: int = 64 << 20,
                 slots: int = 1024):
        from multiprocessing import shared_memory

        size = max(int(size), 1 << 20)
        self.dir = fabric_dir
        os.makedirs(fabric_dir, exist_ok=True)
        self.name = segment_name(fabric_dir)
        self._lock = threading.Lock()
        self._closed = False
        self._attach_fd = os.open(os.path.join(fabric_dir, "attach.lock"),
                                  os.O_CREAT | os.O_RDWR, 0o600)
        self._write_fd = os.open(os.path.join(fabric_dir, "write.lock"),
                                 os.O_CREAT | os.O_RDWR, 0o600)
        import fcntl

        try:
            fcntl.flock(self._attach_fd, fcntl.LOCK_SH)
            # the write flock spans create-or-attach THROUGH header
            # init: without it an attacher could slip between a peer's
            # shm_open(create) and its _init_segment and read zeroed
            # magic with nothing left to wait on
            with _write_flock(self):
                try:
                    self._shm = shared_memory.SharedMemory(name=self.name)
                    created = False
                except FileNotFoundError:
                    try:
                        self._shm = shared_memory.SharedMemory(
                            name=self.name, create=True, size=size)
                        created = True
                    except FileExistsError:  # lost the create race
                        self._shm = shared_memory.SharedMemory(
                            name=self.name)
                        created = False
                _unregister_tracker(self._shm)
                if created:
                    self._init_segment(slots)
            if not created:
                self._validate_header()
        except Exception:
            self._release_fds()
            raise

    # ---- layout ------------------------------------------------------------

    def _init_segment(self, slots: int) -> None:
        """Caller holds the write flock."""
        buf = self._shm.buf
        total = len(buf)
        data_off = _HDR.size + slots * _SLOT.size
        if data_off + (1 << 16) > total:
            raise FabricError(
                f"fabric segment too small: {total} bytes for {slots} "
                "slots")
        buf[:data_off] = bytes(data_off)  # zero header + slot table
        _HDR.pack_into(buf, 0, MAGIC, FABRIC_VERSION, slots, data_off,
                       total - data_off, 0, 1)

    def _validate_header(self) -> None:
        buf = self._shm.buf
        if len(buf) < _HDR.size:
            raise FabricError("fabric segment truncated")
        magic = bytes(buf[:8])
        if magic != MAGIC:
            # the creator may still be mid-init: the write flock orders
            # us after its _init_segment, then re-check once
            with _write_flock(self):
                pass
            magic = bytes(buf[:8])
        magic, version, slots, data_off, data_size, _, _ = \
            _HDR.unpack_from(buf, 0)
        if magic != MAGIC:
            raise FabricError(
                f"bad fabric magic {magic!r} (segment {self.name})")
        if version != FABRIC_VERSION:
            raise FabricError(
                f"fabric layout version {version} != {FABRIC_VERSION} "
                "— refusing to attach (peer runs different code)")
        if slots <= 0 or data_off + data_size > len(buf):
            raise FabricError("fabric header geometry out of bounds")

    def _header(self):
        return _HDR.unpack_from(self._shm.buf, 0)

    # ---- public api --------------------------------------------------------

    def put(self, kind: str, key: bytes, value: bytes) -> bool:
        """Publish one artifact; returns False when it cannot fit
        (over-large values are simply not shared)."""
        full_key = kind.encode() + b"\x00" + key
        if len(full_key) > _MAX_KEY:
            return False
        with self._lock:
            if self._closed:
                return False
            with _write_flock(self):
                return self._put_locked(full_key, value)

    def _put_locked(self, full_key: bytes, value: bytes) -> bool:
        """Caller holds the lock (and the write flock)."""
        buf = self._shm.buf
        (_, _, slots, data_off, data_size, cursor, _) = self._header()
        need = (len(full_key) + len(value) + 7) & ~7
        if need > data_size:
            return False
        if cursor + need > data_size:
            self._wipe_held()
            cursor = 0
        h = _hash_key(full_key)
        base = h % slots
        target = -1
        empty = -1
        for p in range(min(_PROBES, slots)):
            idx = (base + p) % slots
            off = _HDR.size + idx * _SLOT.size
            gen, khash, klen, vlen, koff = _SLOT.unpack_from(buf, off)
            if gen == 0:
                if empty < 0:
                    empty = idx
                continue
            if khash == h and klen == len(full_key) \
                    and bytes(buf[data_off + koff:
                                  data_off + koff + klen]) == full_key:
                target = idx
                break
        if target < 0:
            target = empty if empty >= 0 else base  # clobber on overflow
        soff = _HDR.size + target * _SLOT.size
        gen = _SLOT.unpack_from(buf, soff)[0]
        seq = gen + 1 if gen % 2 == 0 else gen + 2
        # seqlock write: odd generation first, then the record, then
        # even — a reader overlapping any step discards its copy
        struct.pack_into("<Q", buf, soff, seq)
        start = data_off + cursor
        buf[start:start + len(full_key)] = full_key
        buf[start + len(full_key):
            start + len(full_key) + len(value)] = value
        _SLOT.pack_into(buf, soff, seq + 1, h, len(full_key),
                        len(value), cursor)
        struct.pack_into("<Q", buf, _CURSOR_OFF, cursor + need)
        return True

    def get(self, kind: str, key: bytes):
        """Probe one artifact; returns its bytes or None. Takes no
        cross-process lock (seqlock reads). Raises FabricError only on
        genuine corruption (stable generation, out-of-bounds
        geometry)."""
        full_key = kind.encode() + b"\x00" + key
        with self._lock:
            if self._closed:
                return None
            return self._get_locked(full_key)

    def _get_locked(self, full_key: bytes):
        """Caller holds the lock."""
        buf = self._shm.buf
        try:
            (magic, version, slots, data_off, data_size, _,
             epoch0) = self._header()
        except struct.error as e:
            raise FabricError(f"fabric header unreadable: {e}") from e
        if magic != MAGIC or version != FABRIC_VERSION:
            raise FabricError("fabric header overwritten")
        h = _hash_key(full_key)
        base = h % slots
        for p in range(min(_PROBES, slots)):
            idx = (base + p) % slots
            soff = _HDR.size + idx * _SLOT.size
            gen1, khash, klen, vlen, koff = _SLOT.unpack_from(buf, soff)
            if gen1 == 0:
                return None  # probe chain ends at the first empty slot
            if gen1 % 2 == 1 or khash != h:
                continue
            if klen > _MAX_KEY or koff + klen + vlen > data_size:
                # re-check: torn reads are normal (writer mid-publish);
                # a STABLE out-of-bounds record is corruption
                gen2 = struct.unpack_from("<Q", buf, soff)[0]
                if gen2 == gen1:
                    raise FabricError(
                        f"fabric slot {idx} geometry out of bounds")
                continue
            start = data_off + koff
            blob = bytes(buf[start:start + klen + vlen])
            gen2 = struct.unpack_from("<Q", buf, soff)[0]
            epoch2 = struct.unpack_from("<Q", buf, _EPOCH_OFF)[0]
            if gen2 != gen1 or epoch2 != epoch0:
                continue  # torn by a concurrent writer/wipe: a miss
            if blob[:klen] == full_key:
                return blob[klen:]
        return None

    # ---- invalidation versions ---------------------------------------------

    def version(self, db, name) -> int:
        """Monotonic invalidation version for (db, table). Published
        artifacts embed the version they were built under; adopters
        compare against the current one. 0 = never bumped."""
        with self._lock:
            if self._closed:
                return 0
            v = self._get_locked(b"ver\x00" + self._ver_key(db, name))
        return int.from_bytes(v, "little") if v and len(v) == 8 else 0

    def bump_version(self, db, name) -> int:
        """Advance (db, table)'s invalidation version — every published
        artifact built under the old version dies on its next adopt
        check. Rides the same flock as put (read-modify-write)."""
        with self._lock:
            if self._closed:
                return 0
            with _write_flock(self):
                full = b"ver\x00" + self._ver_key(db, name)
                cur = 0
                v = self._get_locked(full)
                if v and len(v) == 8:
                    cur = int.from_bytes(v, "little")
                self._put_locked(full, (cur + 1).to_bytes(8, "little"))
                return cur + 1

    @staticmethod
    def _ver_key(db, name) -> bytes:
        return f"{db}\x00{name}".encode()

    def wipe(self) -> None:
        """Drop every artifact (the fabric analog of invalidate-all:
        the remote-catalog watch can't tell what moved)."""
        with self._lock:
            if self._closed:
                return
            with _write_flock(self):
                self._wipe_held()

    def _wipe_held(self) -> None:
        """Caller holds the lock (and the write flock). Epoch bumps
        FIRST so readers mid-copy discard, then the slot table
        zeroes."""
        buf = self._shm.buf
        (_, _, _, data_off, _, _, epoch) = self._header()
        struct.pack_into("<Q", buf, _EPOCH_OFF, epoch + 1)
        buf[_HDR.size:data_off] = bytes(data_off - _HDR.size)
        struct.pack_into("<Q", buf, _CURSOR_OFF, 0)

    # ---- enumeration (metrics bridge) --------------------------------------

    def scan(self, kind: str) -> list:
        """Every (key, value) currently published under `kind` —
        seqlock-consistent per slot, not across slots (cache reads)."""
        prefix = kind.encode() + b"\x00"
        out = []
        with self._lock:
            if self._closed:
                return out
            buf = self._shm.buf
            (_, _, slots, data_off, data_size, _,
             epoch0) = self._header()
            for idx in range(slots):
                soff = _HDR.size + idx * _SLOT.size
                gen1, khash, klen, vlen, koff = _SLOT.unpack_from(buf,
                                                                  soff)
                if gen1 == 0 or gen1 % 2 == 1:
                    continue
                if klen > _MAX_KEY or koff + klen + vlen > data_size:
                    continue
                start = data_off + koff
                blob = bytes(buf[start:start + klen + vlen])
                gen2 = struct.unpack_from("<Q", buf, soff)[0]
                epoch2 = struct.unpack_from("<Q", buf, _EPOCH_OFF)[0]
                if gen2 != gen1 or epoch2 != epoch0:
                    continue
                if blob[:len(prefix)] == prefix:
                    out.append((blob[len(prefix):klen], blob[klen:]))
        return out

    # ---- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            if self._closed:
                return {}
            buf = self._shm.buf
            (_, _, slots, _, data_size, cursor, epoch) = self._header()
            used_slots = 0
            for idx in range(slots):
                gen = struct.unpack_from(
                    "<Q", buf, _HDR.size + idx * _SLOT.size)[0]
                if gen != 0 and gen % 2 == 0:
                    used_slots += 1
            return {"size": len(buf), "heap_size": data_size,
                    "heap_used": cursor, "slots": slots,
                    "used_slots": used_slots, "epoch": epoch}

    # ---- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Detach; the last process out unlinks the segment (the
        shared attach-lock refcount — kernel-released on SIGKILL, so
        dead peers never pin the segment)."""
        import fcntl

        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            fcntl.flock(self._attach_fd, fcntl.LOCK_UN)
            last = True
            try:
                fcntl.flock(self._attach_fd,
                            fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                last = False  # peers still attached
            self._shm.close()
            if last:
                _unlink_segment(self.name)
        except OSError:
            pass
        finally:
            self._release_fds()

    def _release_fds(self) -> None:
        for attr in ("_attach_fd", "_write_fd"):
            fd = getattr(self, attr, None)
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
                setattr(self, attr, None)


class _write_flock:
    """Cross-process writer lock: flock on write.lock. The kernel
    releases it if the holder dies, so a SIGKILL'd writer cannot wedge
    peers (its half-written slot stays odd and unreadable instead)."""

    __slots__ = ("_fabric",)

    def __init__(self, fabric: Fabric):
        self._fabric = fabric

    def __enter__(self):
        import fcntl

        fcntl.flock(self._fabric._write_fd, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        import fcntl

        try:
            fcntl.flock(self._fabric._write_fd, fcntl.LOCK_UN)
        except OSError:
            pass
