"""Worker→parent metrics bridge (the PR 11 known-gap fix).

A spawn-mode encode worker observes its metrics into ITS OWN process
registry — before the fabric, the parent's /metrics could only show a
parent-side round-trip approximation for `encode_seconds{protocol=
"process"}` and lost the worker-side series entirely. Now every worker
publishes a cumulative pickled snapshot of its touched metrics into the
fabric under ("met", pid) after each encode; the parent registers a
scrape-time collector that folds the latest snapshot per worker into
the matching registry metrics via `set_external` — cumulative
snapshots, so republishing never double-counts, and a worker that dies
keeps its final counts visible (counters are cumulative by contract).

Trust note: snapshots are pickles read from our own uid-scoped fabric
segment — the same-box, same-user trust domain every other fabric
artifact lives in.
"""

from __future__ import annotations

import os
import pickle
import threading

from greptimedb_tpu.shm.fabric import FabricError

#: worker-side metrics worth bridging (the encode path's surface);
#: names resolve against the parent registry at fold time
_BRIDGED_HISTOGRAMS = ("greptimedb_tpu_encode_seconds",)
_BRIDGED_COUNTERS = ("greptimedb_tpu_shm_fabric_events_total",
                     "greptimedb_tpu_encode_pool_events_total")

_installed = {"done": False}
_install_lock = threading.Lock()


def _by_name():
    from greptimedb_tpu.utils.metrics import REGISTRY

    with REGISTRY._lock:
        metrics = list(REGISTRY._metrics)
    return {m.name: m for m in metrics}


def publish_worker_metrics() -> None:
    """Worker side: push this process's cumulative encode-path series
    into the fabric (no-op when the fabric is off/unattached). Never
    raises — metrics must not fail an encode."""
    from greptimedb_tpu import shm

    fabric = shm.get_fabric()
    if fabric is None:
        return
    try:
        metrics = _by_name()
        state: dict = {"hist": {}, "counter": {}}
        for name in _BRIDGED_HISTOGRAMS:
            m = metrics.get(name)
            if m is not None:
                st = m.export_state()
                if st:
                    state["hist"][name] = st
        for name in _BRIDGED_COUNTERS:
            m = metrics.get(name)
            if m is not None:
                # _snapshot folds the worker's own thread shards; the
                # worker has no externals of its own to double-count
                snap = m._snapshot()
                if snap:
                    state["counter"][name] = snap
        if not state["hist"] and not state["counter"]:
            return
        fabric.put("met", str(os.getpid()).encode(),
                   pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL))
    except (FabricError, OSError, ValueError, pickle.PicklingError):
        shm.detach()


def collect_worker_metrics() -> None:
    """Parent side (scrape-time collector): fold every worker's latest
    snapshot into the registry metrics."""
    from greptimedb_tpu import shm

    fabric = shm.get_fabric()
    if fabric is None:
        return
    try:
        published = fabric.scan("met")
    except (FabricError, OSError, ValueError):
        shm.detach()
        return
    if not published:
        return
    metrics = _by_name()
    me = str(os.getpid()).encode()
    for key, val in published:
        if key == me:
            continue  # this process's own publication (it IS the registry)
        try:
            state = pickle.loads(val)
        except Exception:  # noqa: BLE001 — a torn/stale blob must not kill scrape
            continue
        source = f"shm-worker-{key.decode(errors='replace')}"
        for name, st in state.get("hist", {}).items():
            m = metrics.get(name)
            if m is not None and hasattr(m, "set_external"):
                m.set_external(source, st)
        for name, snap in state.get("counter", {}).items():
            m = metrics.get(name)
            if m is not None and hasattr(m, "set_external"):
                m.set_external(source, snap)


def install_collector() -> None:
    """Register the parent-side collector once per process (the
    ConcurrencyPlane calls this when the fabric attaches)."""
    with _install_lock:
        if _installed["done"]:
            return
        _installed["done"] = True
    from greptimedb_tpu.utils.metrics import REGISTRY

    REGISTRY.register_collector(collect_worker_metrics)
