"""Zero-copy result handoff (ISSUE 19 tentpole leg 3).

Process-mode encode workers used to return their encoded payload as a
pickle through the executor's result queue — the parent deserialized
the whole body just to write it to a socket. With the fabric on, the
worker writes the encoded bytes into a shared-memory arena and returns
a tiny (marker, block, offset, length) handle; the parent's socket
writer sends straight from the mapping (`ShmPayload.view` is a
memoryview over the segment — no copy, no pickle).

Arena layout (one segment per fabric directory):

    [ header | block table | bump-allocated payload heap ]

Allocation is a bump cursor under the arena flock; the block table
tracks live payloads: state (free / pending / claimed), the allocating
worker's pid, offset, length. The parent CLAIMS a handle under the
flock before using it — a claim validates the block record against the
handle, so a reaped or recycled block degrades to re-encoding inline
(byte-identical: same encoder function) instead of serving stale
bytes. When every block is free the cursor resets; a worker SIGKILL'd
after allocating but before its handle was claimed is reaped by pid
liveness on the next allocation under pressure, so dead workers cannot
wedge the arena.

Everything degrades typed: arena absent, full, or corrupt means the
worker returns the plain pickled bytes (the pre-fabric behavior) and
the parent counts the event.
"""

from __future__ import annotations

import os
import struct
import threading

from greptimedb_tpu.shm.fabric import FabricError, segment_name

ARENA_VERSION = 1
ARENA_MAGIC = b"GTPUARN1"

#: header: magic, version, nblocks, data_off, data_size, cursor, active
_HDR = struct.Struct("<8sIIQQQQ")
_CURSOR_OFF = 32
_ACTIVE_OFF = 40
#: block: state (0 free / 1 pending / 2 claimed), alloc_pid, off, len
_BLOCK = struct.Struct("<IIQQQ")  # state, pad, pid, off, len
_NBLOCKS = 256

#: result handles are tuples so they pickle through the executor's
#: normal result path; the marker guards against ever confusing one
#: with real payload bytes
HANDLE_MARK = "gtpu_shm_result"

_FREE, _PENDING, _CLAIMED = 0, 1, 2


class ResultArena:
    """One attached result arena (same flock discipline as Fabric)."""

    def __init__(self, fabric_dir: str, size: int = 64 << 20):
        from multiprocessing import shared_memory

        from greptimedb_tpu.shm.fabric import _unregister_tracker

        size = max(int(size), 1 << 20)
        self.dir = fabric_dir
        os.makedirs(fabric_dir, exist_ok=True)
        self.name = segment_name(os.path.join(fabric_dir, "arena"))
        self._lock = threading.Lock()
        self._closed = False
        self._attach_fd = os.open(
            os.path.join(fabric_dir, "arena_attach.lock"),
            os.O_CREAT | os.O_RDWR, 0o600)
        self._write_fd = os.open(
            os.path.join(fabric_dir, "arena_write.lock"),
            os.O_CREAT | os.O_RDWR, 0o600)
        import fcntl

        try:
            fcntl.flock(self._attach_fd, fcntl.LOCK_SH)
            # write flock spans create-or-attach THROUGH header init:
            # an attacher must not slip between a peer's shm_open
            # (create) and its _init_segment and read zeroed magic
            with _flock(self._write_fd):
                try:
                    self._shm = shared_memory.SharedMemory(name=self.name)
                    created = False
                except FileNotFoundError:
                    try:
                        self._shm = shared_memory.SharedMemory(
                            name=self.name, create=True, size=size)
                        created = True
                    except FileExistsError:
                        self._shm = shared_memory.SharedMemory(
                            name=self.name)
                        created = False
                _unregister_tracker(self._shm)
                if created:
                    self._init_segment()
            if not created:
                self._validate_header()
        except Exception:
            self._release_fds()
            raise

    def _init_segment(self) -> None:
        """Caller holds the write flock."""
        buf = self._shm.buf
        total = len(buf)
        data_off = _HDR.size + _NBLOCKS * _BLOCK.size
        if data_off + (1 << 16) > total:
            raise FabricError(f"result arena too small: {total} bytes")
        buf[:data_off] = bytes(data_off)
        _HDR.pack_into(buf, 0, ARENA_MAGIC, ARENA_VERSION, _NBLOCKS,
                       data_off, total - data_off, 0, 0)

    def _validate_header(self) -> None:
        buf = self._shm.buf
        if len(buf) < _HDR.size:
            raise FabricError("result arena truncated")
        if bytes(buf[:8]) != ARENA_MAGIC:
            with _flock(self._write_fd):
                pass  # creator mid-init: order after it, re-check
        magic, version, nblocks, data_off, data_size, _, _ = \
            _HDR.unpack_from(buf, 0)
        if magic != ARENA_MAGIC:
            raise FabricError(f"bad arena magic {magic!r}")
        if version != ARENA_VERSION:
            raise FabricError(
                f"arena layout version {version} != {ARENA_VERSION}")
        if nblocks <= 0 or data_off + data_size > len(buf):
            raise FabricError("arena header geometry out of bounds")

    def _header(self):
        return _HDR.unpack_from(self._shm.buf, 0)

    # ---- worker side -------------------------------------------------------

    def publish(self, data: bytes):
        """Write one encoded payload into the arena; returns a handle
        tuple or None when it cannot fit (caller falls back to the
        pickle path)."""
        with self._lock:
            if self._closed:
                return None
            with _flock(self._write_fd):
                return self._publish_locked(data)

    def _publish_locked(self, data: bytes):
        """Caller holds the lock (and the arena flock)."""
        buf = self._shm.buf
        (_, _, nblocks, data_off, data_size, cursor,
         active) = self._header()
        need = (len(data) + 7) & ~7
        if need > data_size:
            return None
        if cursor + need > data_size or active >= nblocks:
            active = self._reap_locked(nblocks)
            (_, _, _, _, _, cursor, _) = self._header()
            if active == 0:
                cursor = 0
                struct.pack_into("<Q", buf, _CURSOR_OFF, 0)
            if cursor + need > data_size or active >= nblocks:
                return None
        idx = -1
        for i in range(nblocks):
            boff = _HDR.size + i * _BLOCK.size
            if _BLOCK.unpack_from(buf, boff)[0] == _FREE:
                idx = i
                break
        if idx < 0:
            return None
        start = data_off + cursor
        buf[start:start + len(data)] = data
        _BLOCK.pack_into(buf, _HDR.size + idx * _BLOCK.size, _PENDING,
                         0, os.getpid(), cursor, len(data))
        struct.pack_into("<Q", buf, _CURSOR_OFF, cursor + need)
        struct.pack_into("<Q", buf, _ACTIVE_OFF, active + 1)
        return (HANDLE_MARK, idx, cursor, len(data), os.getpid())

    def _reap_locked(self, nblocks: int) -> int:
        """Free PENDING blocks whose allocating worker died before the
        parent claimed the handle (SIGKILL mid-handoff) — claimed
        blocks belong to the live parent and are never reaped. Caller
        holds the lock + flock; returns the new active count."""
        buf = self._shm.buf
        active = 0
        for i in range(nblocks):
            boff = _HDR.size + i * _BLOCK.size
            state, _, pid, off, length = _BLOCK.unpack_from(buf, boff)
            if state == _PENDING and not _pid_alive(pid):
                _BLOCK.pack_into(buf, boff, _FREE, 0, 0, 0, 0)
                continue
            if state != _FREE:
                active += 1
        struct.pack_into("<Q", buf, _ACTIVE_OFF, active)
        return active

    # ---- parent side -------------------------------------------------------

    def claim(self, handle):
        """Validate a worker's handle against the live block record and
        take ownership; returns a ShmPayload or None (block reaped or
        recycled — the caller re-encodes inline, byte-identical)."""
        if not is_handle(handle):
            return None
        _, idx, off, length, pid = handle
        with self._lock:
            if self._closed:
                return None
            buf = self._shm.buf
            (_, _, nblocks, data_off, data_size, _, _) = self._header()
            if not (0 <= idx < nblocks) \
                    or off + length > data_size:
                return None
            boff = _HDR.size + idx * _BLOCK.size
            with _flock(self._write_fd):
                state, _, bpid, boff_v, blen = _BLOCK.unpack_from(buf,
                                                                  boff)
                if state != _PENDING or bpid != pid \
                        or boff_v != off or blen != length:
                    return None
                _BLOCK.pack_into(buf, boff, _CLAIMED, 0, os.getpid(),
                                 off, length)
            view = buf[data_off + off:data_off + off + length]
        return ShmPayload(self, idx, view)

    def free(self, idx: int) -> None:
        """Release a claimed block (idempotent)."""
        with self._lock:
            if self._closed:
                return
            buf = self._shm.buf
            nblocks = self._header()[2]
            if not (0 <= idx < nblocks):
                return
            boff = _HDR.size + idx * _BLOCK.size
            with _flock(self._write_fd):
                state = _BLOCK.unpack_from(buf, boff)[0]
                if state == _FREE:
                    return
                _BLOCK.pack_into(buf, boff, _FREE, 0, 0, 0, 0)
                # re-read active under the flock: peers moved it
                active = max(0, self._header()[6] - 1)
                struct.pack_into("<Q", buf, _ACTIVE_OFF, active)
                if active == 0:
                    struct.pack_into("<Q", buf, _CURSOR_OFF, 0)

    def stats(self) -> dict:
        with self._lock:
            if self._closed:
                return {}
            (_, _, nblocks, _, data_size, cursor,
             active) = self._header()
            return {"size": len(self._shm.buf), "heap_size": data_size,
                    "heap_used": cursor, "blocks": nblocks,
                    "active": active}

    # ---- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Same last-one-out unlink discipline as Fabric.close."""
        import fcntl

        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            fcntl.flock(self._attach_fd, fcntl.LOCK_UN)
            last = True
            try:
                fcntl.flock(self._attach_fd,
                            fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                last = False
            try:
                self._shm.close()
            except BufferError:
                last = False  # a live ShmPayload view pins the mapping
            if last:
                from greptimedb_tpu.shm.fabric import _unlink_segment

                _unlink_segment(self.name)
        except OSError:
            pass
        finally:
            self._release_fds()

    def _release_fds(self) -> None:
        for attr in ("_attach_fd", "_write_fd"):
            fd = getattr(self, attr, None)
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
                setattr(self, attr, None)


class ShmPayload:
    """A claimed result payload: a memoryview straight over the shared
    segment plus its release. The socket writer sends `view` and calls
    `release()`; a dropped payload is released by the GC finalizer so
    an exception path can never leak the block."""

    is_shm_payload = True

    def __init__(self, arena: ResultArena, idx: int, view):
        import weakref

        self.view = view
        self._idx = idx
        self._arena = arena
        self._finalizer = weakref.finalize(self, _release_block, arena,
                                           idx, view)

    def __len__(self) -> int:
        return len(self.view)

    def __bytes__(self) -> bytes:
        return bytes(self.view)

    def release(self) -> None:
        self._finalizer()


def _release_block(arena: ResultArena, idx: int, view) -> None:
    try:
        view.release()
    except (BufferError, AttributeError):
        pass
    arena.free(idx)


def is_handle(obj) -> bool:
    return (isinstance(obj, tuple) and len(obj) == 5
            and obj[0] == HANDLE_MARK)


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


class _flock:
    """flock context over a raw fd (kernel-released on process death —
    a SIGKILL'd holder cannot wedge the arena)."""

    __slots__ = ("_fd",)

    def __init__(self, fd: int):
        self._fd = fd

    def __enter__(self):
        import fcntl

        fcntl.flock(self._fd, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        import fcntl

        try:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
        except OSError:
            pass


# ---- process-wide arena singleton ------------------------------------------

_arena_state = {"arena": None, "inited": False}
_arena_lock = threading.Lock()


def get_arena():
    """The process-wide ResultArena, or None (fabric off / attach
    failed). Workers (spawned with the GTPU_SHM_* env inherited) attach
    lazily on their first encode."""
    from greptimedb_tpu import shm

    with _arena_lock:
        if _arena_state["inited"]:
            return _arena_state["arena"]
        _arena_state["inited"] = True
        cfg = shm.config_from_env()
        if not cfg.fabric:
            return None
        try:
            a = ResultArena(cfg.fabric_dir, size=cfg.fabric_bytes)
        except (FabricError, OSError, ValueError):
            from greptimedb_tpu.utils.metrics import SHM_FABRIC_EVENTS

            SHM_FABRIC_EVENTS.inc(event="detach", kind="result")
            return None
        _arena_state["arena"] = a
        from greptimedb_tpu.utils.metrics import SHM_FABRIC_BYTES

        SHM_FABRIC_BYTES.set(float(cfg.fabric_bytes), segment="arena",
                             dim="size")
        return a


def shutdown_arena():
    with _arena_lock:
        a = _arena_state["arena"]
        _arena_state["arena"] = None
        _arena_state["inited"] = False
    if a is not None:
        try:
            a.close()
        except OSError:
            pass


def shm_encode(fn, *args):
    """The worker-side wrapper the process-mode encode pool submits
    when the fabric is on: run the real encoder, record the EXACT
    worker-side wall time (folded into the parent's /metrics by the
    metrics bridge), and hand the bytes over through the arena."""
    import time

    from greptimedb_tpu.utils.metrics import (
        ENCODE_SECONDS,
        SHM_FABRIC_EVENTS,
    )

    t0 = time.perf_counter()
    data = fn(*args)
    ENCODE_SECONDS.observe(time.perf_counter() - t0, protocol="process")
    out = data
    if isinstance(data, bytes):
        arena = get_arena()
        if arena is not None:
            try:
                handle = arena.publish(data)
            except (FabricError, OSError, ValueError):
                handle = None
            if handle is not None:
                SHM_FABRIC_EVENTS.inc(event="publish", kind="result")
                out = handle
            else:
                SHM_FABRIC_EVENTS.inc(event="miss", kind="result")
    from greptimedb_tpu.shm import metrics_bridge

    metrics_bridge.publish_worker_metrics()
    return out


def resolve(out, fn, args):
    """Parent-side: turn a worker handle back into sendable bytes — a
    ShmPayload on a successful claim, or an inline re-encode when the
    block was reaped/recycled (byte-identical: same encoder)."""
    if not is_handle(out):
        return out
    from greptimedb_tpu.utils.metrics import SHM_FABRIC_EVENTS

    arena = get_arena()
    payload = None
    if arena is not None:
        try:
            payload = arena.claim(out)
        except (FabricError, OSError, ValueError):
            payload = None
    if payload is None:
        SHM_FABRIC_EVENTS.inc(event="corrupt", kind="result")
        return fn(*args)
    SHM_FABRIC_EVENTS.inc(event="hit", kind="result")
    return payload
