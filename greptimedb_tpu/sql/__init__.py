"""SQL frontend (mirrors reference src/sql, ~10.6k LoC: a forked
sqlparser-rs plus GreptimeDB extensions). Hand-written recursive-descent
parser covering the dialect the reference's sqlness suite exercises:
CREATE TABLE with TIME INDEX / PRIMARY KEY / engine options, INSERT,
SELECT with aggregates and time bucketing, SHOW/DESCRIBE/DROP/ALTER,
TQL (PromQL-in-SQL), RANGE queries.
"""

from greptimedb_tpu.sql.parser import parse_sql
from greptimedb_tpu.sql import ast

__all__ = ["parse_sql", "ast"]
