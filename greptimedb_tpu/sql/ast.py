"""SQL AST (mirrors reference src/sql/src/statements/, 17 modules)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


# ---- expressions -----------------------------------------------------------


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class Column(Expr):
    name: str
    table: Optional[str] = None


@dataclass(frozen=True)
class Literal(Expr):
    value: object  # int | float | str | bool | None


@dataclass(frozen=True)
class Interval(Expr):
    nanos: int
    text: str = ""


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str  # + - * / % = != < <= > >= and or like
    left: Expr
    right: Expr


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str  # - not
    operand: Expr


@dataclass(frozen=True)
class WindowSpec:
    """OVER (PARTITION BY ... ORDER BY ...) — window functions
    (reference: DataFusion window exec via sqlparser-rs OVER clause)."""

    partition_by: tuple = ()  # tuple[Expr, ...]
    order_by: tuple = ()  # tuple[(Expr, asc: bool), ...]
    # frame text is accepted and normalized but only the two SQL-default
    # behaviors are executed: whole-partition (no ORDER BY) and
    # running-to-current-row (with ORDER BY)
    frame: Optional[str] = None


@dataclass(frozen=True)
class FuncCall(Expr):
    name: str  # lowercased
    args: tuple[Expr, ...] = ()
    distinct: bool = False
    # `agg(x ORDER BY col [ASC|DESC])` — (col_expr, asc); used by
    # first_value/last_value (DataFusion / TSBS lastpoint syntax)
    order_within: Optional[tuple] = None
    # OVER (...) turns an aggregate/ranking call into a window function
    over: Optional[WindowSpec] = None


@dataclass(frozen=True)
class Subquery(Expr):
    """(SELECT ...) in expression position — scalar subquery, IN
    (SELECT ...), or EXISTS (SELECT ...). Uncorrelated only: the engine
    folds it to literal(s) before planning (reference: DataFusion
    subquery decorrelation; TSDB workloads use the uncorrelated forms)."""

    stmt: object  # Select | Union
    exists: bool = False


@dataclass(frozen=True)
class Star(Expr):
    pass


@dataclass(frozen=True)
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass(frozen=True)
class InList(Expr):
    expr: Expr
    items: tuple[Expr, ...]
    negated: bool = False


@dataclass(frozen=True)
class IsNull(Expr):
    expr: Expr
    negated: bool = False


@dataclass(frozen=True)
class Cast(Expr):
    expr: Expr
    type_name: str


@dataclass(frozen=True)
class Case(Expr):
    operand: Optional[Expr]
    whens: tuple[tuple[Expr, Expr], ...]
    else_: Optional[Expr]


# ---- statements ------------------------------------------------------------


@dataclass
class Statement:
    pass


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None
    # RANGE-select extension (reference range_select): per-item window
    # width and fill policy — `avg(v) RANGE '10s' FILL PREV`
    range_interval: Optional["Interval"] = None
    fill: Optional[object] = None  # 'null' | 'prev' | 'linear' | number


@dataclass
class OrderByItem:
    expr: Expr
    asc: bool = True
    nulls_first: Optional[bool] = None


@dataclass
class Join:
    """One JOIN clause (kind: inner | left | right | full | cross).
    `table` is None when the side is a derived table (`subquery`)."""

    table: Optional[str]
    alias: Optional[str]
    kind: str
    on: Optional["Expr"]  # None for CROSS JOIN
    subquery: Optional["Statement"] = None


@dataclass
class Select(Statement):
    items: list[SelectItem]
    table: Optional[str] = None  # base FROM table
    table_alias: Optional[str] = None
    joins: list = field(default_factory=list)  # list[Join]
    distinct: bool = False
    where: Optional[Expr] = None
    group_by: list[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: list[OrderByItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    # RANGE ... ALIGN extension (reference query/src/range_select)
    align: Optional[Interval] = None
    align_to: Optional[Expr] = None
    align_by: list[Expr] = field(default_factory=list)
    range_fill: Optional[str] = None
    # WITH name AS (...) CTEs in scope for this (outermost) select
    ctes: list = field(default_factory=list)  # list[(name, Statement)]
    # FROM (SELECT ...) [AS] alias — derived table; `table` is None
    from_subquery: Optional["Statement"] = None


@dataclass
class ColumnDef:
    name: str
    type_name: str
    nullable: bool = True
    is_time_index: bool = False
    is_primary_key: bool = False
    default: Optional[Expr] = None


@dataclass
class CreateTable(Statement):
    name: str
    columns: list[ColumnDef]
    time_index: Optional[str] = None
    primary_keys: list[str] = field(default_factory=list)
    if_not_exists: bool = False
    engine: str = "mito"
    options: dict = field(default_factory=dict)
    partitions: Optional[list] = None  # partition bound exprs
    external: bool = False  # CREATE EXTERNAL TABLE (file engine)


@dataclass
class CopyTable(Statement):
    """COPY <table> TO|FROM '<path>' [WITH (format=..., ...)]
    (reference operator/src/statement/copy_table_{to,from}.rs)."""

    table: str
    direction: str  # "to" | "from"
    path: str
    options: dict = field(default_factory=dict)


@dataclass
class CopyDatabase(Statement):
    """COPY DATABASE <db> TO|FROM '<dir>' [WITH (...)]."""

    database: str
    direction: str
    path: str
    options: dict = field(default_factory=dict)


@dataclass
class CreateView(Statement):
    """CREATE [OR REPLACE] VIEW name AS <query> (reference
    common/meta view keys + ddl create_view)."""

    name: str
    query_sql: str  # raw text of the defining query
    or_replace: bool = False
    if_not_exists: bool = False


@dataclass
class DropView(Statement):
    name: str
    if_exists: bool = False


@dataclass
class ShowViews(Statement):
    pass


@dataclass
class CreateDatabase(Statement):
    name: str
    if_not_exists: bool = False


@dataclass
class KillQuery(Statement):
    """KILL [QUERY] <id> — cancel a running statement through the
    frontend running-queries registry (MySQL KILL QUERY compat; the
    same registry backs information_schema.running_queries and
    DELETE /v1/queries/<id>)."""

    query_id: int


@dataclass
class SetVar(Statement):
    """SET <name> = <value> (session variable; reference handles
    time_zone and swallows client-compat vars, statement.rs SetVariables)."""

    name: str
    value: object


@dataclass
class Union(Statement):
    """UNION [ALL] chain of SELECTs (reference: DataFusion set ops).
    Trailing ORDER BY/LIMIT/OFFSET bind to the whole union (SQL
    semantics), lifted off the final branch by the parser."""

    branches: tuple
    all: bool = False
    order_by: list = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    ctes: list = field(default_factory=list)  # list[(name, Statement)]


@dataclass
class Insert(Statement):
    table: str
    columns: list[str]
    rows: list[list[Expr]]
    select: Optional[Select] = None
    #: columnar VALUES payload from the parser's literal fast path:
    #: per-column lists of raw Python values (no per-cell Literal
    #: boxing). When set, `rows` is empty and the engine hands the
    #: columns to the ingest slab seam (ingest.sql_values_batch)
    columnar_values: Optional[list] = None


@dataclass
class Delete(Statement):
    table: str
    where: Optional[Expr] = None


@dataclass
class DropTable(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateFlow(Statement):
    """CREATE FLOW name SINK TO sink AS SELECT ... (reference src/sql
    CREATE FLOW + src/flow continuous aggregation)."""

    name: str
    sink_table: str
    query: "Select"
    if_not_exists: bool = False
    expire_after_s: Optional[int] = None
    comment: str = ""
    raw_query: str = ""  # original SELECT text, persisted with the flow


@dataclass
class DropFlow(Statement):
    name: str
    if_exists: bool = False


@dataclass
class ShowFlows(Statement):
    pass


@dataclass
class TruncateTable(Statement):
    name: str


@dataclass
class ShowTables(Statement):
    database: Optional[str] = None
    like: Optional[str] = None


@dataclass
class ShowDatabases(Statement):
    pass


@dataclass
class ShowCreateTable(Statement):
    name: str
    is_view: bool = False


@dataclass
class DescribeTable(Statement):
    name: str


@dataclass
class Explain(Statement):
    inner: Statement
    analyze: bool = False


@dataclass
class Use(Statement):
    database: str


@dataclass
class Tql(Statement):
    """TQL EVAL (start, end, step) <promql> — PromQL embedded in SQL
    (reference src/sql parser TQL extension + operator/src/statement/tql.rs)."""

    start: float
    end: float
    step: float
    query: str
    analyze: bool = False
    explain: bool = False


@dataclass
class AlterTable(Statement):
    name: str
    action: str  # add_column | drop_column | rename
    column: Optional[ColumnDef] = None
    column_name: Optional[str] = None
    new_name: Optional[str] = None


@dataclass
class AdminFunc(Statement):
    """ADMIN flush_table(...) / compact_table(...) (reference
    common/function administration functions)."""

    func: FuncCall
