"""SQL tokenizer."""

from __future__ import annotations

import re
from dataclasses import dataclass

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "asc", "desc", "and", "or", "not", "in", "between", "like",
    "is", "null", "true", "false", "as", "distinct", "create", "table",
    "database", "schema", "if", "exists", "primary", "key", "time", "index",
    "engine", "with", "insert", "into", "values", "delete", "drop", "show",
    "tables", "databases", "describe", "desc", "explain", "analyze", "use",
    "interval", "cast", "case", "when", "then", "else", "end", "truncate",
    "alter", "add", "column", "rename", "to", "tql", "eval", "evaluate",
    "align", "range", "fill", "partition", "on", "nulls", "first", "last",
    "admin", "verbose", "copy", "default", "flow", "flows", "sink", "set",
    "external",
}

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*|/\*.*?\*/)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*"|`(?:[^`]|``)*`)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><=|>=|!=|<>|=~|!~|\|\||::|[-+*/%(),.=<>;@\[\]{}~:])
    """,
    re.VERBOSE | re.DOTALL,
)


@dataclass(frozen=True)
class Token:
    kind: str  # keyword | ident | number | string | op | eof
    value: str
    pos: int

    def __repr__(self):
        return f"{self.kind}:{self.value}"


class SqlError(Exception):
    pass


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlError(f"unexpected character {sql[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind in ("ws", "comment"):
            continue
        if kind == "ident":
            low = text.lower()
            if low in KEYWORDS:
                tokens.append(Token("keyword", low, m.start()))
            else:
                tokens.append(Token("ident", text, m.start()))
        elif kind == "qident":
            q = text[0]
            inner = text[1:-1].replace(q * 2, q)
            tokens.append(Token("ident", inner, m.start()))
        elif kind == "string":
            tokens.append(Token("string", text[1:-1].replace("''", "'"), m.start()))
        elif kind == "number":
            tokens.append(Token("number", text, m.start()))
        else:
            tokens.append(Token("op", text, m.start()))
    tokens.append(Token("eof", "", len(sql)))
    return tokens
