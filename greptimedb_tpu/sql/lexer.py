"""SQL tokenizer."""

from __future__ import annotations

import re

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "asc", "desc", "and", "or", "not", "in", "between", "like",
    "is", "null", "true", "false", "as", "distinct", "create", "table",
    "database", "schema", "if", "exists", "primary", "key", "time", "index",
    "engine", "with", "insert", "into", "values", "delete", "drop", "show",
    "tables", "databases", "describe", "desc", "explain", "analyze", "use",
    "interval", "cast", "case", "when", "then", "else", "end", "truncate",
    "alter", "add", "column", "rename", "to", "tql", "eval", "evaluate",
    "align", "range", "fill", "partition", "on", "nulls", "first", "last",
    "admin", "verbose", "copy", "default", "flow", "flows", "sink", "set",
    "external",
}

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*|/\*.*?\*/)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*"|`(?:[^`]|``)*`)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><=|>=|!=|<>|=~|!~|\|\||::|[-+*/%(),.=<>;@\[\]{}~:])
    """,
    re.VERBOSE | re.DOTALL,
)


class Token:
    """__slots__ class, not a frozen dataclass: tokenization is on the
    per-statement hot path (a 500-row INSERT is ~13k tokens) and frozen
    dataclass __init__ costs ~3x a plain __init__."""

    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind: str, value: str, pos: int):
        self.kind = kind  # keyword | ident | number | string | op | eof
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.value}"


class SqlError(Exception):
    pass


def tokenize(sql: str) -> list[Token]:
    # one finditer sweep instead of per-token .match calls; gaps between
    # consecutive matches are exactly the "unexpected character" cases
    tokens: list[Token] = []
    append = tokens.append
    keywords = KEYWORDS
    last = 0
    for m in _TOKEN_RE.finditer(sql):
        start = m.start()
        if start != last:
            raise SqlError(
                f"unexpected character {sql[last]!r} at {last}")
        last = m.end()
        kind = m.lastgroup
        if kind == "ws" or kind == "comment":
            continue
        text = m.group()
        if kind == "ident":
            low = text.lower()
            if low in keywords:
                append(Token("keyword", low, start))
            else:
                append(Token("ident", text, start))
        elif kind == "qident":
            q = text[0]
            append(Token("ident", text[1:-1].replace(q * 2, q), start))
        elif kind == "string":
            append(Token("string", text[1:-1].replace("''", "'"), start))
        elif kind == "number":
            append(Token("number", text, start))
        else:
            append(Token("op", text, start))
    if last != len(sql):
        raise SqlError(f"unexpected character {sql[last]!r} at {last}")
    append(Token("eof", "", len(sql)))
    return tokens
