"""Recursive-descent SQL parser (mirrors reference
src/sql/src/parser.rs `ParserContext` + statement parsers).

Supports the subset the sqlness suite exercises most: SELECT (aggregates,
date_bin/time bucketing, WHERE/GROUP/HAVING/ORDER/LIMIT), CREATE TABLE with
TIME INDEX/PRIMARY KEY/engine/options, CREATE DATABASE, INSERT .. VALUES,
DELETE, DROP/TRUNCATE/ALTER TABLE, SHOW TABLES/DATABASES/CREATE TABLE,
DESCRIBE, EXPLAIN [ANALYZE], USE, ADMIN, and TQL EVAL (PromQL embedded in
SQL, reference sql TQL extension).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

from greptimedb_tpu.sql import ast
from greptimedb_tpu.sql.lexer import SqlError, Token, tokenize

# interval text → nanoseconds
_INTERVAL_UNITS = {
    "nanosecond": 1, "nanoseconds": 1, "ns": 1,
    "microsecond": 1_000, "microseconds": 1_000, "us": 1_000,
    "millisecond": 10**6, "milliseconds": 10**6, "ms": 10**6,
    "second": 10**9, "seconds": 10**9, "s": 10**9, "sec": 10**9,
    "minute": 60 * 10**9, "minutes": 60 * 10**9, "m": 60 * 10**9, "min": 60 * 10**9,
    "hour": 3600 * 10**9, "hours": 3600 * 10**9, "h": 3600 * 10**9,
    "day": 86400 * 10**9, "days": 86400 * 10**9, "d": 86400 * 10**9,
    "week": 7 * 86400 * 10**9, "weeks": 7 * 86400 * 10**9, "w": 7 * 86400 * 10**9,
}

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)\s*([A-Za-z]+)")


def parse_interval_text(text: str) -> int:
    """'1 hour', '30s', '1h30m' → nanoseconds."""
    total = 0.0
    matched = False
    for m in _DURATION_RE.finditer(text):
        qty, unit = float(m.group(1)), m.group(2).lower()
        if unit not in _INTERVAL_UNITS:
            raise SqlError(f"unknown interval unit {unit!r} in {text!r}")
        total += qty * _INTERVAL_UNITS[unit]
        matched = True
    if not matched:
        raise SqlError(f"cannot parse interval {text!r}")
    return int(total)


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0

    # ---- token helpers -----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "keyword" and t.value in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            raise SqlError(f"expected {kw.upper()} at {self.peek()!r}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise SqlError(f"expected {op!r} at {self.peek()!r} in {self.sql!r}")

    def ident(self) -> str:
        t = self.peek()
        # many keywords are valid identifiers in column position
        if t.kind in ("ident", "keyword"):
            self.next()
            return t.value
        raise SqlError(f"expected identifier at {t!r}")

    def qualified_name(self) -> str:
        parts = [self.ident()]
        while self.eat_op("."):
            parts.append(self.ident())
        return ".".join(parts)

    def _at_subquery(self) -> bool:
        """True when positioned at '(' SELECT|WITH — an expression-level
        or FROM-level subquery rather than a parenthesized expression."""
        return (self.peek().kind == "op" and self.peek().value == "("
                and self.peek(1).kind == "keyword"
                and self.peek(1).value in ("select", "with"))

    # ---- entry -------------------------------------------------------------

    def parse_statements(self) -> list[ast.Statement]:
        stmts = []
        while True:
            while self.eat_op(";"):
                pass
            if self.peek().kind == "eof":
                break
            stmts.append(self.parse_statement())
        return stmts

    def _raw_statement_text(self) -> str:
        """Consume tokens up to the statement separator (a top-level ';'
        or eof) and return the raw source slice — used where a statement
        embeds another language (TQL's PromQL, CREATE VIEW's query). The
        terminator token's pos is the exact end (eof pos is len(sql))."""
        start = self.peek().pos
        depth = 0
        while self.peek().kind != "eof":
            t = self.peek()
            if t.kind == "op" and t.value == ";" and depth == 0:
                break
            if t.kind == "op" and t.value == "(":
                depth += 1
            if t.kind == "op" and t.value == ")":
                depth -= 1
            self.next()
        return self.sql[start:self.peek().pos].strip()

    def parse_statement(self) -> ast.Statement:
        t = self.peek()
        if t.kind == "ident" and t.value.lower() == "kill":
            # KILL [QUERY] <id> — "kill" isn't a lexer keyword (it must
            # stay usable as a column name), so pre-check the ident
            self.next()
            nxt = self.peek()
            if nxt.kind in ("ident", "keyword") \
                    and nxt.value.lower() == "query":
                self.next()
            idt = self.next()
            if idt.kind != "number":
                raise SqlError(f"KILL QUERY expects a numeric query id, "
                               f"got {idt!r}")
            return ast.KillQuery(int(float(idt.value)))
        if t.kind != "keyword":
            raise SqlError(f"expected statement at {t!r}")
        if t.value == "select":
            sel = self.parse_select()
            return self._maybe_union(sel)
        if t.value == "with":
            return self.parse_with()
        if t.value == "set":
            return self.parse_set()
        if t.value == "create":
            return self.parse_create()
        if t.value == "insert":
            return self.parse_insert()
        if t.value == "delete":
            return self.parse_delete()
        if t.value == "drop":
            return self.parse_drop()
        if t.value == "truncate":
            self.next()
            self.eat_kw("table")
            return ast.TruncateTable(self.qualified_name())
        if t.value == "show":
            return self.parse_show()
        if t.value == "describe" or (t.value == "desc" and self.peek(1).kind != "eof"):
            self.next()
            self.eat_kw("table")
            return ast.DescribeTable(self.qualified_name())
        if t.value == "explain":
            self.next()
            analyze = self.eat_kw("analyze")
            self.eat_kw("verbose")
            return ast.Explain(self.parse_statement(), analyze=analyze)
        if t.value == "use":
            self.next()
            return ast.Use(self.ident())
        if t.value == "tql":
            return self.parse_tql()
        if t.value == "alter":
            return self.parse_alter()
        if t.value == "admin":
            self.next()
            expr = self.parse_expr()
            if not isinstance(expr, ast.FuncCall):
                raise SqlError("ADMIN expects a function call")
            return ast.AdminFunc(expr)
        if t.value == "copy":
            return self.parse_copy()
        raise SqlError(f"unsupported statement start {t.value!r}")

    def parse_with(self) -> ast.Statement:
        """WITH name [(col, ...)] AS (query), ... SELECT ... — common
        table expressions (reference: DataFusion CTEs via sqlparser-rs).
        Each CTE is executed once and visible to later CTEs and the
        outer query; stored as (name, statement, column_names|None)."""
        self.expect_kw("with")
        ctes = []
        while True:
            name = self.ident()
            col_names = None
            if self.at_op("("):
                self.next()
                col_names = []
                while not self.at_op(")"):
                    col_names.append(self.ident())
                    self.eat_op(",")
                self.expect_op(")")
            self.expect_kw("as")
            ctes.append((name, self._parse_subquery_statement(), col_names))
            if not self.eat_op(","):
                break
        if not self.at_kw("select", "with"):
            raise SqlError(f"expected SELECT after WITH at {self.peek()!r}")
        stmt = self.parse_statement()
        if not isinstance(stmt, (ast.Select, ast.Union)):
            raise SqlError("WITH must introduce a SELECT/UNION query")
        stmt.ctes = ctes + list(stmt.ctes)
        return stmt

    def _parse_subquery_statement(self) -> ast.Statement:
        """'(' SELECT ... | WITH ... ')' — the query inside a derived
        table, CTE body, or expression subquery."""
        self.expect_op("(")
        if self.at_kw("with"):
            q = self.parse_with()
        else:
            if not self.at_kw("select"):
                raise SqlError(f"expected SELECT at {self.peek()!r}")
            q = self._maybe_union(self.parse_select())
        self.expect_op(")")
        return q

    def _maybe_union(self, first: ast.Select) -> ast.Statement:
        """SELECT ... [UNION [ALL] SELECT ...]* — reference set operations
        (DataFusion). ORDER BY/LIMIT bind per branch."""
        branches = [first]
        is_all = None
        while self.peek().kind == "ident" \
                and self.peek().value.lower() == "union":
            self.next()
            this_all = False
            if self.peek().kind == "ident" \
                    and self.peek().value.lower() == "all":
                self.next()
                this_all = True
            elif self.eat_kw("distinct"):
                pass
            if is_all is None:
                is_all = this_all
            elif is_all != this_all:
                raise SqlError("mixing UNION and UNION ALL is not supported")
            self.expect_kw("select")
            # parse_select expects to consume the SELECT keyword itself
            self.i -= 1
            branches.append(self.parse_select())
        if len(branches) == 1:
            return first
        # trailing ORDER BY / LIMIT / OFFSET parsed into the last branch
        # actually belong to the whole union (SQL semantics)
        last = branches[-1]
        order_by, limit, offset = last.order_by, last.limit, last.offset
        last.order_by, last.limit, last.offset = [], None, None
        return ast.Union(tuple(branches), all=bool(is_all),
                         order_by=order_by, limit=limit, offset=offset)

    def parse_set(self) -> ast.SetVar:
        """SET [SESSION|LOCAL|GLOBAL] <name> (=|TO) <value>,
        SET TIME ZONE <value>, SET NAMES <charset> (MySQL/PG client
        compat; reference servers swallow these the same way)."""
        self.expect_kw("set")
        t = self.peek()
        if t.kind == "ident" and t.value.lower() in ("session", "local",
                                                     "global"):
            self.next()
        t = self.peek()
        if t.kind == "keyword" and t.value == "time":
            self.next()
            z = self.ident()
            if z.lower() != "zone":
                raise SqlError(f"expected ZONE after SET TIME, got {z!r}")
            name = "time_zone"
        else:
            parts = [self._set_name_part()]
            while self.eat_op("."):
                parts.append(self._set_name_part())
            name = ".".join(parts)
        if not (self.eat_op("=") or self.eat_kw("to")):
            # SET NAMES utf8 style: value follows bare
            pass
        v = self.peek()
        if v.kind == "string":
            self.next()
            value: object = v.value
        elif v.kind == "number":
            self.next()
            value = float(v.value) if "." in v.value else int(v.value)
        elif v.kind == "keyword" and v.value in ("true", "false", "null",
                                                 "default"):
            self.next()
            value = {"true": True, "false": False,
                     "null": None, "default": None}[v.value]
        else:
            value = self.ident()
        return ast.SetVar(name.lower(), value)

    def _set_name_part(self) -> str:
        t = self.peek()
        if t.kind == "op" and t.value == "@":
            # @@session.var / @@var system-variable syntax
            self.next()
            self.eat_op("@")
            return self._set_name_part()
        return self.ident()

    def parse_copy(self) -> ast.Statement:
        """COPY [TABLE] <t> | DATABASE <db>  TO|FROM '<path>' [WITH (...)]"""
        self.expect_kw("copy")
        is_db = self.eat_kw("database")
        if not is_db:
            self.eat_kw("table")
        name = self.qualified_name()
        if self.eat_kw("to"):
            direction = "to"
        elif self.eat_kw("from"):
            direction = "from"
        else:
            raise SqlError("COPY expects TO or FROM")
        t = self.next()
        if t.kind != "string":
            raise SqlError("COPY expects a quoted path")
        path = t.value
        options = {}
        if self.eat_kw("with"):
            self.expect_op("(")
            while not self.at_op(")"):
                k = self.qualified_name()
                self.expect_op("=")
                options[k] = self.next().value
                self.eat_op(",")
            self.expect_op(")")
        if is_db:
            return ast.CopyDatabase(name, direction, path, options)
        return ast.CopyTable(name, direction, path, options)

    # ---- SELECT ------------------------------------------------------------

    def parse_select(self) -> ast.Select:
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        items = [self.parse_select_item()]
        while self.eat_op(","):
            items.append(self.parse_select_item())
        sel = ast.Select(items=items)
        sel.distinct = distinct
        if self.eat_kw("from"):
            if self.at_op("("):
                # FROM (SELECT ...) [AS] alias — derived table
                sel.from_subquery = self._parse_subquery_statement()
                self.eat_kw("as")
                sel.table_alias = self._table_alias()
            else:
                sel.table = self.qualified_name()
                sel.table_alias = self._table_alias()
            # [INNER|LEFT|RIGHT|FULL [OUTER]|CROSS] JOIN <table|(subquery)>
            #   [AS alias] [ON <expr>]
            while True:
                kind = None
                t = self.peek()
                w = t.value.lower() if t.kind == "ident" else ""
                if w == "inner":
                    self.next()
                    kind = "inner"
                elif w in ("left", "right", "full"):
                    self.next()
                    if self.peek().kind == "ident" \
                            and self.peek().value.lower() == "outer":
                        self.next()
                    kind = w
                elif w == "cross":
                    self.next()
                    kind = "cross"
                t = self.peek()
                if t.kind == "ident" and t.value.lower() == "join":
                    self.next()
                elif kind is not None:
                    raise SqlError(f"expected JOIN at {self.peek()!r}")
                else:
                    break
                jsub = None
                jt = None
                if self.at_op("("):
                    jsub = self._parse_subquery_statement()
                else:
                    jt = self.qualified_name()
                self.eat_kw("as")
                jalias = self._table_alias()
                if jsub is not None and jalias is None:
                    raise SqlError("derived table in JOIN requires an alias")
                if kind == "cross":
                    on = None
                else:
                    self.expect_kw("on")
                    on = self.parse_expr()
                sel.joins.append(
                    ast.Join(jt, jalias, kind or "inner", on, subquery=jsub))
        if self.eat_kw("where"):
            sel.where = self.parse_expr()
        # RANGE ... ALIGN extension: ALIGN <interval> [TO <expr>] [BY (cols)] [FILL x]
        if self.eat_kw("align"):
            sel.align = self.parse_interval_literal()
            if self.eat_kw("to"):
                sel.align_to = self.parse_expr()
            if self.eat_kw("by"):
                self.expect_op("(")
                if self.at_op(")"):
                    # BY () — aggregate across all series (range_select
                    # by-empty form); marked with a sentinel literal so
                    # the planner can tell it from "BY clause absent"
                    sel.align_by = [ast.Literal(1)]
                else:
                    sel.align_by = [self.parse_expr()]
                    while self.eat_op(","):
                        sel.align_by.append(self.parse_expr())
                self.expect_op(")")
            if self.eat_kw("fill"):
                # same normalization/validation as the per-item postfix
                sel.range_fill = self.parse_fill_policy()
        if self.eat_kw("group"):
            self.expect_kw("by")
            sel.group_by.append(self.parse_expr())
            while self.eat_op(","):
                sel.group_by.append(self.parse_expr())
        if self.eat_kw("having"):
            sel.having = self.parse_expr()
        if self.eat_kw("order"):
            self.expect_kw("by")
            sel.order_by.append(self.parse_order_item())
            while self.eat_op(","):
                sel.order_by.append(self.parse_order_item())
        if self.eat_kw("limit"):
            sel.limit = int(self.next().value)
        if self.eat_kw("offset"):
            sel.offset = int(self.next().value)
        return sel

    def _table_alias(self) -> Optional[str]:
        if self.eat_kw("as"):
            return self.ident()
        t = self.peek()
        if t.kind == "ident" and t.value.lower() not in (
                "inner", "left", "right", "full", "cross", "outer",
                "join", "union", "on"):
            self.next()
            return t.value
        return None

    def parse_select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.next()
            return ast.SelectItem(ast.Star())
        expr = self.parse_expr()
        rng = None
        fill = None
        # RANGE '10s' [FILL NULL|PREV|LINEAR|<number>] postfix binds the
        # window to the item's aggregates (reference range_select grammar)
        if self.eat_kw("range"):
            rng = self.parse_interval_literal()
        if self.eat_kw("fill"):
            fill = self.parse_fill_policy()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident" \
                and self.peek().value.lower() != "union":
            # a bare ident is an implicit alias — except UNION, which
            # chains set operations at the statement level
            alias = self.ident()
        return ast.SelectItem(expr, alias, range_interval=rng, fill=fill)

    def parse_fill_policy(self):
        t = self.peek()
        if t.kind == "number":
            self.next()
            return float(t.value)
        word = self.ident().lower()
        if word not in ("null", "prev", "linear"):
            raise SqlError(f"bad FILL policy {word!r}")
        return word

    def parse_order_item(self) -> ast.OrderByItem:
        expr = self.parse_expr()
        asc = True
        if self.eat_kw("asc"):
            asc = True
        elif self.eat_kw("desc"):
            asc = False
        nulls_first = None
        if self.eat_kw("nulls"):
            if self.eat_kw("first"):
                nulls_first = True
            elif self.eat_kw("last"):
                nulls_first = False
        return ast.OrderByItem(expr, asc, nulls_first)

    # ---- CREATE ------------------------------------------------------------

    def parse_create(self) -> ast.Statement:
        self.expect_kw("create")
        if self.eat_kw("database") or self.eat_kw("schema"):
            ine = self._if_not_exists()
            return ast.CreateDatabase(self.ident(), if_not_exists=ine)
        if self.eat_kw("flow"):
            return self._parse_create_flow()
        or_replace = False
        if self.eat_kw("or"):
            r = self.ident()
            if r.lower() != "replace":
                raise SqlError(f"expected REPLACE after OR, got {r!r}")
            or_replace = True
        if (self.peek().kind == "ident"
                and self.peek().value.lower() == "view"):
            self.next()
            ine = self._if_not_exists()
            name = self.qualified_name()
            self.expect_kw("as")
            # the defining query is kept as raw text (reference stores
            # view definitions the same way, common/meta view keys)
            query_sql = self._raw_statement_text()
            if not query_sql:
                raise SqlError("CREATE VIEW requires a defining query")
            return ast.CreateView(name, query_sql, or_replace=or_replace,
                                  if_not_exists=ine)
        if or_replace:
            raise SqlError("OR REPLACE is only supported for CREATE VIEW")
        external = self.eat_kw("external")
        self.expect_kw("table")
        ine = self._if_not_exists()
        name = self.qualified_name()
        stmt = ast.CreateTable(name=name, columns=[], if_not_exists=ine,
                               external=external)
        if external:
            stmt.engine = "file"
            if not self.at_op("("):
                # schema inferred from the file
                return self._finish_create_table(stmt)
        self.expect_op("(")
        while not self.at_op(")"):
            if self.at_kw("primary"):
                self.next()
                self.expect_kw("key")
                self.expect_op("(")
                while not self.at_op(")"):
                    stmt.primary_keys.append(self.ident())
                    self.eat_op(",")
                self.expect_op(")")
            elif self.at_kw("time") and self.peek(1).value == "index":
                self.next()
                self.next()
                self.expect_op("(")
                stmt.time_index = self.ident()
                self.expect_op(")")
            else:
                stmt.columns.append(self.parse_column_def())
            self.eat_op(",")
        self.expect_op(")")
        return self._finish_create_table(stmt)

    def _finish_create_table(self, stmt: ast.CreateTable) -> ast.CreateTable:
        if self.eat_kw("partition"):
            # PARTITION ON COLUMNS (...) (...); ON/COLUMNS may lex as
            # keywords or plain idents depending on the keyword table
            for word in ("on", "columns"):
                if not self.eat_kw(word):
                    t = self.peek()
                    if t.value.lower() == word:
                        self.next()
            stmt.partitions = self._parse_partitions()
        if self.eat_kw("engine"):
            self.expect_op("=")
            stmt.engine = self.ident()
        if self.eat_kw("with"):
            self.expect_op("(")
            while not self.at_op(")"):
                k = self.qualified_name()
                self.expect_op("=")
                t = self.next()
                stmt.options[k] = t.value
                self.eat_op(",")
            self.expect_op(")")
        return stmt

    def _parse_partitions(self) -> list:
        # PARTITION ON COLUMNS (col, ...) ( expr, expr, ... )
        cols = []
        self.expect_op("(")
        while not self.at_op(")"):
            cols.append(self.ident())
            self.eat_op(",")
        self.expect_op(")")
        exprs = []
        self.expect_op("(")
        depth = 1
        # partition bound expressions, comma-separated at depth 1
        while depth > 0:
            if self.at_op("("):
                depth += 1
                self.next()
                continue
            if self.at_op(")"):
                depth -= 1
                self.next()
                continue
            if depth == 1:
                if self.eat_op(","):
                    continue
                exprs.append(self.parse_expr())
            else:
                self.next()
        return [cols, exprs]

    def _if_not_exists(self) -> bool:
        if self.at_kw("if"):
            self.next()
            self.expect_kw("not")
            self.expect_kw("exists")
            return True
        return False

    def parse_column_def(self) -> ast.ColumnDef:
        name = self.ident()
        type_name = self.ident()
        # parameterized / two-word types: TIMESTAMP(3), DOUBLE PRECISION, BIGINT UNSIGNED
        if self.at_op("("):
            self.next()
            args = []
            while not self.at_op(")"):
                args.append(self.next().value)
                self.eat_op(",")
            self.expect_op(")")
            type_name = f"{type_name}({','.join(args)})"
        elif self.peek().kind == "ident" and self.peek().value.lower() in ("unsigned", "precision"):
            extra = self.ident().lower()
            type_name = "double" if extra == "precision" else f"{type_name} {extra}"
        col = ast.ColumnDef(name=name, type_name=type_name)
        while True:
            if self.eat_kw("not"):
                self.expect_kw("null")
                col.nullable = False
            elif self.eat_kw("null"):
                col.nullable = True
            elif self.at_kw("time") and self.peek(1).value == "index":
                self.next()
                self.next()
                col.is_time_index = True
            elif self.at_kw("primary"):
                self.next()
                self.expect_kw("key")
                col.is_primary_key = True
            elif self.eat_kw("default"):
                col.default = self.parse_primary()
            else:
                break
        return col

    # ---- INSERT / DELETE ---------------------------------------------------

    def parse_insert(self) -> ast.Insert:
        self.expect_kw("insert")
        self.expect_kw("into")
        table = self.qualified_name()
        columns: list[str] = []
        if self.eat_op("("):
            while not self.at_op(")"):
                columns.append(self.ident())
                self.eat_op(",")
            self.expect_op(")")
        if self.at_kw("select"):
            return ast.Insert(table, columns, rows=[], select=self.parse_select())
        self.expect_kw("values")
        # literal fast path: bulk INSERTs are overwhelmingly plain
        # number/string/NULL tuples, and full precedence descent per
        # value dominates statement cost at TSBS load rates — peek one
        # token ahead and build the Literal directly; anything else
        # (expressions, casts, intervals) falls back to parse_expr
        rows = []
        toks = self.tokens
        while True:
            self.expect_op("(")
            row = []
            while not self.at_op(")"):
                t = toks[self.i]
                nxt = self.peek(1)  # clamps at eof: truncated statements
                # must fall through to parse_expr's clean SqlError
                if nxt.kind == "op" and (nxt.value == ","
                                         or nxt.value == ")"):
                    if t.kind == "number":
                        txt = t.value
                        self.i += 1
                        row.append(ast.Literal(
                            float(txt) if ("." in txt or "e" in txt
                                           or "E" in txt) else int(txt)))
                        self.eat_op(",")
                        continue
                    if t.kind == "string":
                        self.i += 1
                        row.append(ast.Literal(t.value))
                        self.eat_op(",")
                        continue
                    if t.kind == "keyword" and t.value in ("null", "true",
                                                           "false"):
                        self.i += 1
                        row.append(ast.Literal(
                            None if t.value == "null"
                            else t.value == "true"))
                        self.eat_op(",")
                        continue
                row.append(self.parse_expr())
                self.eat_op(",")
            self.expect_op(")")
            rows.append(row)
            if not self.eat_op(","):
                break
        return ast.Insert(table, columns, rows)

    def parse_delete(self) -> ast.Delete:
        self.expect_kw("delete")
        self.expect_kw("from")
        table = self.qualified_name()
        where = self.parse_expr() if self.eat_kw("where") else None
        return ast.Delete(table, where)

    def parse_drop(self) -> ast.Statement:
        self.expect_kw("drop")
        is_flow = self.eat_kw("flow")
        if not is_flow and self.peek().kind == "ident" \
                and self.peek().value.lower() == "view":
            self.next()
            if_exists = False
            if self.at_kw("if"):
                self.next()
                self.expect_kw("exists")
                if_exists = True
            return ast.DropView(self.qualified_name(), if_exists)
        if not is_flow:
            self.expect_kw("table")
        if_exists = False
        if self.at_kw("if"):
            self.next()
            self.expect_kw("exists")
            if_exists = True
        name = self.qualified_name()
        return ast.DropFlow(name, if_exists) if is_flow else ast.DropTable(name, if_exists)

    def _parse_create_flow(self) -> ast.CreateFlow:
        # CREATE FLOW [IF NOT EXISTS] name SINK TO sink
        #   [EXPIRE AFTER <interval>] [COMMENT '...'] AS <select>
        ine = self._if_not_exists()
        name = self.qualified_name()
        self.expect_kw("sink")
        self.expect_kw("to")
        sink = self.qualified_name()
        expire = None
        if self.peek().value == "expire":
            self.next()
            t = self.peek()
            if t.value == "after":
                self.next()
            expr = self.parse_expr()
            if isinstance(expr, ast.Interval):
                expire = expr.nanos // 1_000_000_000
            elif isinstance(expr, ast.Literal):
                expire = int(expr.value)
            else:
                raise SqlError("EXPIRE AFTER expects an interval or seconds")
        comment = ""
        if self.peek().value == "comment":
            self.next()
            t = self.next()
            comment = str(t.value)
        self.expect_kw("as")
        raw_query = self.sql[self.peek().pos:]
        query = self.parse_select()
        return ast.CreateFlow(name=name, sink_table=sink, query=query,
                              if_not_exists=ine, expire_after_s=expire,
                              comment=comment, raw_query=raw_query)

    # ---- SHOW / TQL / ALTER ------------------------------------------------

    def parse_show(self) -> ast.Statement:
        self.expect_kw("show")
        if self.eat_kw("databases"):
            return ast.ShowDatabases()
        if self.eat_kw("flows"):
            return ast.ShowFlows()
        if self.eat_kw("create"):
            if self.peek().kind == "ident" \
                    and self.peek().value.lower() == "view":
                self.next()
                return ast.ShowCreateTable(self.qualified_name(),
                                           is_view=True)
            self.expect_kw("table")
            return ast.ShowCreateTable(self.qualified_name())
        if self.peek().kind == "ident" \
                and self.peek().value.lower() == "views":
            self.next()
            return ast.ShowViews()
        self.expect_kw("tables")
        stmt = ast.ShowTables()
        if self.eat_kw("from") or self.eat_kw("in"):
            stmt.database = self.ident()
        if self.eat_kw("like"):
            stmt.like = self.next().value
        return stmt

    def parse_tql(self) -> ast.Tql:
        """TQL EVAL (start, end, step) <promql until end of statement>."""
        self.expect_kw("tql")
        analyze = explain = False
        if self.eat_kw("analyze"):
            analyze = True
        elif self.eat_kw("explain"):
            explain = True
        else:
            if not (self.eat_kw("eval") or self.eat_kw("evaluate")):
                raise SqlError(f"expected EVAL at {self.peek()!r}")
        if analyze or explain:
            self.eat_kw("eval") or self.eat_kw("evaluate")
        self.expect_op("(")
        start = self._tql_number()
        self.expect_op(",")
        end = self._tql_number()
        self.expect_op(",")
        step = self._tql_duration()
        self.expect_op(")")
        # the rest of the statement (raw text) is PromQL — label matchers
        # ({host=~"web.*"}), durations ([5m]) and strings all pass through
        # verbatim; the slice ends at the statement separator
        query = self._raw_statement_text()
        return ast.Tql(start, end, step, query, analyze=analyze, explain=explain)

    def _tql_number(self) -> float:
        t = self.next()
        if t.kind == "string":
            return _parse_tql_time(t.value)
        if t.kind == "op" and t.value == "-":
            return -float(self.next().value)
        return float(t.value)

    def _tql_duration(self) -> float:
        t = self.next()
        if t.kind == "string":
            try:
                return float(t.value)
            except ValueError:
                return parse_interval_text(t.value) / 1e9
        return float(t.value)

    def parse_alter(self) -> ast.AlterTable:
        self.expect_kw("alter")
        self.expect_kw("table")
        name = self.qualified_name()
        if self.eat_kw("add"):
            self.eat_kw("column")
            col = self.parse_column_def()
            return ast.AlterTable(name, "add_column", column=col)
        if self.eat_kw("drop"):
            self.eat_kw("column")
            return ast.AlterTable(name, "drop_column", column_name=self.ident())
        if self.eat_kw("rename"):
            self.eat_kw("to")
            return ast.AlterTable(name, "rename", new_name=self.ident())
        raise SqlError(f"unsupported ALTER at {self.peek()!r}")

    # ---- expressions (pratt) -----------------------------------------------

    def parse_expr(self) -> ast.Expr:
        return self.parse_or()

    def parse_or(self) -> ast.Expr:
        left = self.parse_and()
        while self.eat_kw("or"):
            left = ast.BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self) -> ast.Expr:
        left = self.parse_not()
        while self.eat_kw("and"):
            left = ast.BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> ast.Expr:
        if self.eat_kw("not"):
            return ast.UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> ast.Expr:
        left = self.parse_additive()
        while True:
            if self.at_op("=", "!=", "<>", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "<>":
                    op = "!="
                left = ast.BinaryOp(op, left, self.parse_additive())
            elif self.at_kw("is"):
                self.next()
                negated = self.eat_kw("not")
                self.expect_kw("null")
                left = ast.IsNull(left, negated)
            elif self.at_kw("between"):
                self.next()
                low = self.parse_additive()
                self.expect_kw("and")
                high = self.parse_additive()
                left = ast.Between(left, low, high)
            elif self.at_kw("in"):
                self.next()
                if self._at_subquery():
                    left = ast.InList(
                        left,
                        (ast.Subquery(self._parse_subquery_statement()),))
                    continue
                self.expect_op("(")
                items = [self.parse_expr()]
                while self.eat_op(","):
                    items.append(self.parse_expr())
                self.expect_op(")")
                left = ast.InList(left, tuple(items))
            elif self.at_kw("like"):
                self.next()
                left = ast.BinaryOp("like", left, self.parse_additive())
            elif self.at_kw("not") and self.peek(1).value in ("in", "between", "like"):
                self.next()
                inner = self.peek().value
                if inner == "in":
                    self.next()
                    if self._at_subquery():
                        left = ast.InList(
                            left,
                            (ast.Subquery(self._parse_subquery_statement()),),
                            negated=True)
                        continue
                    self.expect_op("(")
                    items = [self.parse_expr()]
                    while self.eat_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = ast.InList(left, tuple(items), negated=True)
                elif inner == "between":
                    self.next()
                    low = self.parse_additive()
                    self.expect_kw("and")
                    high = self.parse_additive()
                    left = ast.Between(left, low, high, negated=True)
                else:
                    self.next()
                    left = ast.UnaryOp("not", ast.BinaryOp("like", left, self.parse_additive()))
            else:
                return left

    def parse_additive(self) -> ast.Expr:
        left = self.parse_multiplicative()
        while self.at_op("+", "-"):
            op = self.next().value
            left = ast.BinaryOp(op, left, self.parse_multiplicative())
        return left

    def parse_multiplicative(self) -> ast.Expr:
        left = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = ast.BinaryOp(op, left, self.parse_unary())
        return left

    def parse_unary(self) -> ast.Expr:
        if self.at_op("-"):
            self.next()
            return ast.UnaryOp("-", self.parse_unary())
        if self.at_op("+"):
            self.next()
            return self.parse_unary()
        return self.parse_postfix()

    def parse_postfix(self) -> ast.Expr:
        expr = self.parse_primary()
        while self.eat_op("::"):
            expr = ast.Cast(expr, self.ident())
        return expr

    def parse_primary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            text = t.value
            if "." in text or "e" in text.lower():
                return ast.Literal(float(text))
            return ast.Literal(int(text))
        if t.kind == "string":
            self.next()
            return ast.Literal(t.value)
        if t.kind == "op" and t.value == "(":
            if self._at_subquery():
                return ast.Subquery(self._parse_subquery_statement())
            self.next()
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "keyword":
            if t.value == "exists" and self.peek(1).kind == "op" \
                    and self.peek(1).value == "(":
                self.next()
                return ast.Subquery(self._parse_subquery_statement(),
                                    exists=True)
            if t.value == "null":
                self.next()
                return ast.Literal(None)
            if t.value == "true":
                self.next()
                return ast.Literal(True)
            if t.value == "false":
                self.next()
                return ast.Literal(False)
            if t.value == "interval":
                self.next()
                return self.parse_interval_literal()
            if t.value == "cast":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                self.expect_kw("as")
                type_name = self.ident()
                if self.at_op("("):
                    self.next()
                    args = []
                    while not self.at_op(")"):
                        args.append(self.next().value)
                        self.eat_op(",")
                    self.expect_op(")")
                    type_name = f"{type_name}({','.join(args)})"
                self.expect_op(")")
                return ast.Cast(e, type_name)
            if t.value == "case":
                return self.parse_case()
        # identifier / function call / qualified column (keywords allowed as names)
        if t.kind in ("ident", "keyword"):
            name = self.ident()
            if self.at_op("("):
                self.next()
                if name.lower() == "extract":
                    # EXTRACT(unit FROM expr) — SQL-standard spelling,
                    # normalized to date_part(unit, expr)
                    unit = self.ident().lower()
                    self.expect_kw("from")
                    inner = self.parse_expr()
                    self.expect_op(")")
                    return ast.FuncCall(
                        "date_part", (ast.Literal(unit), inner))
                if self.at_op("*"):
                    self.next()
                    self.expect_op(")")
                    return self._maybe_over(
                        ast.FuncCall(name.lower(), (ast.Star(),)))
                distinct = self.eat_kw("distinct")
                args: list[ast.Expr] = []
                order_within = None
                while not self.at_op(")"):
                    if self.eat_kw("order"):
                        # agg(x ORDER BY col [ASC|DESC]) — DataFusion /
                        # TSBS lastpoint first_value/last_value syntax
                        self.expect_kw("by")
                        oexpr = self.parse_expr()
                        asc = True
                        if self.eat_kw("desc"):
                            asc = False
                        else:
                            self.eat_kw("asc")
                        order_within = (oexpr, asc)
                        break
                    args.append(self.parse_expr())
                    self.eat_op(",")
                self.expect_op(")")
                return self._maybe_over(
                    ast.FuncCall(name.lower(), tuple(args), distinct,
                                 order_within=order_within))
            if self.at_op("."):
                self.next()
                col = self.ident()
                return ast.Column(col, table=name)
            return ast.Column(name)
        raise SqlError(f"unexpected token {t!r} in expression")

    def _maybe_over(self, fc: ast.FuncCall) -> ast.FuncCall:
        """fc OVER (PARTITION BY ... ORDER BY ... [frame]) — window
        function call (reference: DataFusion window functions)."""
        t = self.peek()
        if not (t.kind == "ident" and t.value.lower() == "over"):
            return fc
        self.next()
        self.expect_op("(")
        partition_by: list[ast.Expr] = []
        order_by: list[tuple] = []
        if self.eat_kw("partition"):
            self.expect_kw("by")
            partition_by.append(self.parse_expr())
            while self.eat_op(","):
                partition_by.append(self.parse_expr())
        if self.eat_kw("order"):
            self.expect_kw("by")
            while True:
                it = self.parse_order_item()
                order_by.append((it.expr, it.asc))
                if not self.eat_op(","):
                    break
        frame = None
        t = self.peek()
        if (t.kind in ("ident", "keyword")
                and t.value.lower() in ("rows", "range", "groups")):
            # frame clause: keep the raw text; execution honors the two
            # SQL-default behaviors plus explicit unbounded-following
            start = t.pos
            depth = 0
            while not (self.at_op(")") and depth == 0):
                if self.at_op("("):
                    depth += 1
                elif self.at_op(")"):
                    depth -= 1
                if self.peek().kind == "eof":
                    raise SqlError("unterminated window frame clause")
                self.next()
            frame = self.sql[start:self.peek().pos].strip().lower()
        self.expect_op(")")
        return dataclasses.replace(
            fc, over=ast.WindowSpec(tuple(partition_by), tuple(order_by),
                                    frame))

    def parse_interval_literal(self) -> ast.Interval:
        t = self.next()
        if t.kind == "string":
            text = t.value
        elif t.kind == "number":
            # INTERVAL 1 hour style, or bare '5m' handled as string above
            unit_t = self.next()
            text = f"{t.value} {unit_t.value}"
        else:
            raise SqlError(f"bad interval at {t!r}")
        return ast.Interval(parse_interval_text(text), text)

    def parse_case(self) -> ast.Case:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        whens = []
        while self.eat_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            whens.append((cond, self.parse_expr()))
        else_ = None
        if self.eat_kw("else"):
            else_ = self.parse_expr()
        self.expect_kw("end")
        return ast.Case(operand, tuple(whens), else_)


def _parse_tql_time(text: str) -> float:
    """RFC3339-ish or numeric epoch seconds in TQL bounds."""
    try:
        return float(text)
    except ValueError:
        pass
    import datetime as dt

    for fmt in ("%Y-%m-%dT%H:%M:%S%z", "%Y-%m-%d %H:%M:%S%z",
                "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            d = dt.datetime.strptime(text.replace("Z", "+0000"), fmt)
            if d.tzinfo is None:
                d = d.replace(tzinfo=dt.timezone.utc)
            return d.timestamp()
        except ValueError:
            continue
    raise SqlError(f"cannot parse TQL time {text!r}")


# ---- INSERT fast path -------------------------------------------------------
#
# The statement-ingest hot loop is the generic char-level lexer: a
# 500-row INSERT spends ~70% of its wall time tokenizing + precedence
# descent (round-5 profile: parse 43 ms of 63 ms total). Bulk VALUES
# are overwhelmingly literal tuples, so one compiled regex scans the
# whole tail; anything it doesn't recognize (expressions, casts,
# comments, multiple statements) falls back to the full parser.

_INSERT_HEAD = re.compile(
    r"\s*INSERT\s+INTO\s+([A-Za-z_][\w]*(?:\.[A-Za-z_][\w]*)?)\s*"
    r"(?:\(([^()]*)\))?\s*VALUES\s*", re.IGNORECASE)

_VALUES_TOKEN = re.compile(
    r"\s*(?:"
    r"(?P<str>'(?:[^']|'')*')"
    r"|(?P<num>[-+]?(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?)"
    r"|(?P<kw>[A-Za-z_]+)"
    r"|(?P<punc>[(),;])"
    r")")

_NUM_IS_FLOAT = re.compile(r"[.eE]")


def _fast_parse_insert(sql: str):
    """Parse `INSERT INTO t [(cols)] VALUES (lit, ...), ...` without the
    generic lexer. Returns [ast.Insert] or None to fall back."""
    m = _INSERT_HEAD.match(sql)
    if m is None:
        return None
    table = m.group(1)
    columns = []
    if m.group(2) is not None:
        columns = [c.strip().strip('"') for c in m.group(2).split(",")]
        if not all(c and re.fullmatch(r"[\w]+", c) for c in columns):
            return None
    rows: list = []
    row: list = []
    pos = m.end()
    n = len(sql)
    in_row = False
    expect_value = False
    # one C-driven finditer sweep; contiguity check per token (finditer
    # would silently SKIP an unmatched char — a gap means a construct
    # the fast path doesn't know, so fall back)
    for tm in _VALUES_TOKEN.finditer(sql, pos):
        if tm.start() != pos:
            return None
        pos = tm.end()
        text = tm.lastgroup
        if text == "punc":
            p = tm.group("punc")
            if p == "(":
                if in_row:
                    return None  # nested parens: an expression
                in_row, row = True, []
                expect_value = True
            elif p == ")":
                if not in_row or expect_value:
                    return None
                in_row = False
                rows.append(row)
            elif p == ",":
                if in_row:
                    if expect_value:
                        return None
                    expect_value = True
                # between rows: nothing to do
            else:  # ';' — end of statement
                if in_row:
                    return None
                rest = sql[pos:]
                if rest.strip():
                    return None  # multiple statements: full parser
                pos = n
                break
        elif not in_row or not expect_value:
            return None
        elif text == "str":
            row.append(tm.group("str")[1:-1].replace("''", "'"))
            expect_value = False
        elif text == "num":
            t = tm.group("num")
            row.append(
                float(t) if _NUM_IS_FLOAT.search(t) else int(t))
            expect_value = False
        else:  # keyword literal
            kw = tm.group("kw").lower()
            if kw == "null":
                row.append(None)
            elif kw == "true":
                row.append(True)
            elif kw == "false":
                row.append(False)
            else:
                return None  # function call / identifier: full parser
            expect_value = False
    if in_row or not rows or sql[pos:].strip():
        return None
    ncols = len(rows[0])
    if any(len(r) != ncols for r in rows):
        return None  # let the full parser raise its arity error
    # column-major raw values, no per-cell Literal boxing: one zip
    # transpose hands the engine ready-made columns for the ingest
    # slab seam (a 500x10 INSERT used to allocate 5000 Literal objects
    # only for the engine to immediately unwrap them)
    return [ast.Insert(table, columns, rows=[],
                       columnar_values=[list(c) for c in zip(*rows)])]


def parse_sql(sql: str) -> list[ast.Statement]:
    if len(sql) > 64 and sql.lstrip()[:6].upper() == "INSERT":
        fast = _fast_parse_insert(sql)
        if fast is not None:
            return fast
    return Parser(sql).parse_statements()
