"""Storage engine (mirrors the reference's mito2 LSM engine, SURVEY.md §2.3),
re-designed TPU-first:

- The memtable is an *append log* with dictionary-encoded tags — no BTreeMap
  of encoded keys (reference memtable/time_series.rs:82). Sorting and
  last-write-wins dedup are deferred to the device sort-dedup kernel at scan
  and flush time (ops/dedup.py), which replaces the MergeReader heap.
- SSTs are Parquet with dictionary tag columns + ts + seq + op_type + fields,
  sorted by (tags..., ts, seq), with row-group min/max pruning — the same
  on-disk contract as the reference (sst/parquet/writer.rs:41-87) minus the
  memcomparable key blob: the TPU kernel ABI wants per-tag code columns.
- WAL is a CRC-framed Arrow-IPC log with batch append and replay.
- The manifest is a JSON action log with periodic checkpoints
  (reference manifest/manager.rs:40-42).
"""

from greptimedb_tpu.storage.engine import RegionEngine, RegionRequest
from greptimedb_tpu.storage.region import Region, ScanData

__all__ = ["RegionEngine", "RegionRequest", "Region", "ScanData"]
