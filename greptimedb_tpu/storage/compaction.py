"""Time-window compaction strategy (TWCS).

Mirrors reference src/mito2/src/compaction/twcs.rs:33 + window.rs/buckets.rs:
SSTs are bucketed into time windows; only files within one window merge
together (time-series data arrives roughly in time order, so cross-window
merges are wasted work and churn write amplification). The active (latest)
window tolerates `max_active_files` L0 files before compacting; inactive
windows compact as soon as they hold more than one file.

The merge itself is the device sort-dedup kernel (Region._merge_files) —
compaction is the same computation as query-time dedup, run once and
persisted (SURVEY.md §7 design stance).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

# candidate windows, seconds (reference buckets.rs TIME_BUCKETS)
TIME_BUCKETS_S = (3600, 2 * 3600, 12 * 3600, 24 * 3600, 7 * 24 * 3600,
                  365 * 24 * 3600)


def infer_time_window_ms(files: Sequence) -> int:
    """Pick the smallest bucket covering the typical file span
    (window.rs infer_time_bucket analog)."""
    if not files:
        return TIME_BUCKETS_S[0] * 1000
    spans = sorted(max(f.ts_max - f.ts_min, 0) for f in files)
    typical = spans[len(spans) // 2]
    for b in TIME_BUCKETS_S:
        if typical <= b * 1000:
            return b * 1000
    return TIME_BUCKETS_S[-1] * 1000


@dataclass
class TwcsOptions:
    max_active_window_files: int = 4
    max_inactive_window_files: int = 1
    time_window_ms: Optional[int] = None  # None: infer from data


class TwcsPicker:
    """Pick groups of L0/L1 files to merge, one group per time window."""

    def __init__(self, opts: Optional[TwcsOptions] = None):
        self.opts = opts or TwcsOptions()

    def pick(self, files: Sequence) -> list[list]:
        if len(files) < 2:
            return []
        window = self.opts.time_window_ms or infer_time_window_ms(files)
        by_window: dict[int, list] = {}
        for f in files:
            # a file belongs to the window of its max timestamp
            by_window.setdefault(f.ts_max // window, []).append(f)
        if not by_window:
            return []
        active = max(by_window)
        groups = []
        for w, group in sorted(by_window.items()):
            limit = (
                self.opts.max_active_window_files
                if w == active
                else self.opts.max_inactive_window_files
            )
            if len(group) > limit:
                groups.append(sorted(group, key=lambda f: f.max_seq))
        return groups
