"""RegionEngine: the storage engine's public contract.

Mirrors the reference's `store-api::RegionEngine` trait
(src/store-api/src/region_engine.rs:179-224: handle_request, handle_query)
and `MitoEngine` (mito2/src/engine.rs:83). The reference shards requests to
an actor worker pool (worker.rs:110); here writes are synchronous host work
(dict-encode + append) — cheap enough that the worker pool buys nothing in
a Python host tier — while all heavy lifting (dedup/aggregate) runs on
device at query time.
"""

from __future__ import annotations

import enum
import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.storage.region import OP_DELETE, OP_PUT, Region, ScanData
from greptimedb_tpu.storage.wal import Wal


class RequestType(enum.Enum):
    PUT = "put"
    DELETE = "delete"
    CREATE = "create"
    OPEN = "open"
    CLOSE = "close"
    DROP = "drop"
    FLUSH = "flush"
    COMPACT = "compact"
    TRUNCATE = "truncate"


@dataclass
class RegionRequest:
    """Analog of store-api RegionRequest (region_request.rs)."""

    kind: RequestType
    region_id: int
    batch: Optional[RecordBatch] = None
    schema: Optional[Schema] = None


def _env_int(name: str, default: int) -> int:
    """Env-var int with a safe fallback — a malformed value must not
    abort region open."""
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclass
class EngineConfig:
    data_dir: str
    # fsync at the WAL append boundary (reference raft-engine fsyncs the
    # write batch; appends arrive pre-batched, so this is group commit).
    # Turning it off trades durability of the last writes for latency.
    wal_sync: bool = True
    wal_segment_bytes: int = 64 << 20
    # "local" = segmented files on this node's disk (raft-engine analog);
    # "remote" = objects on shared storage (Kafka-WAL analog,
    # log-store/src/kafka/log_store.rs) so failover candidates can replay
    # without the failed node's disk
    wal_backend: str = "local"
    # explicit shared ObjectStore for the remote WAL; default = the
    # engine's own object store
    wal_store: Optional[object] = None
    # auto-flush when a memtable exceeds this many bytes (reference
    # WriteBufferManager global budget, flush.rs:83-135)
    flush_threshold_bytes: int = 256 << 20
    # write worker group size (reference WorkerGroup, worker.rs:110):
    # 0 = synchronous in-caller writes; -1 = auto (cpu/2); N = N workers.
    # Workers batch concurrent writes per region into one WAL group
    # commit and bound in-flight requests (backpressure)
    write_workers: int = 0
    # host scan-cache snapshots kept per region (decoded-page cache
    # analog); env default so tests/CLI can tune without a config object
    scan_cache_entries: int = field(
        default_factory=lambda: _env_int(
            "GREPTIMEDB_TPU_SCAN_CACHE_ENTRIES", 4))
    # ---- scan pipeline ([scan] options) ----
    # SST decode fan-out per scan; 0 = auto (min(8, cpu)), 1 = the
    # sequential pre-pipeline path (storage/scan_pool.py; the env var
    # GREPTIMEDB_TPU_SCAN_DECODE_THREADS overrides at scan time)
    scan_decode_threads: int = 0
    # byte budget for the per-file decoded-part LRU (incremental scan
    # cache: a flush re-decodes only the files it added)
    scan_part_cache_bytes: int = 1 << 30
    # ---- ingest pipeline ([ingest] options, storage/group_commit.py) ----
    # per-region group commit: concurrent writers coalesce into one WAL
    # append + one fsync + one memtable apply; off = the legacy serial
    # path (WAL+apply under one region-lock hold), kept for bit-for-bit
    # differential tests
    ingest_group_commit: bool = True
    # caps on one drained commit group (ack latency bound)
    ingest_max_batch_rows: int = 65536
    ingest_max_batch_bytes: int = 8 << 20
    # bounded per-region ingest queue; full -> typed Overloaded
    ingest_queue_depth: int = 512
    # pipeline the WAL encode of group N+1 under group N's fsync
    ingest_overlap: bool = True
    # object store backend for SSTs/manifest/index (reference
    # object-store crate; fs|memory|s3, optional LRU read cache)
    object_store: str = "fs"
    object_store_cache_bytes: int = 0
    # backend-specific construction args (s3: bucket/endpoint/keys...)
    object_store_kwargs: dict = field(default_factory=dict)
    # ---- background maintenance plane (maintenance/ package) ----
    # worker pool size; 0 disables the plane (flush/compact run inline
    # on the writer, the pre-plane behavior)
    maintenance_workers: int = 1
    maintenance_queue: int = 64
    # periodic sweep submitting threshold flushes / compactions /
    # rollups / expiry; 0 = event-driven only (writes + ADMIN)
    maintenance_tick_s: float = 0.0
    # hard write-stall thresholds (reference flush.rs stall semantics):
    # writers block once a region's memtable bytes or L0 count cross
    # these; 0 bytes = 2x flush_threshold_bytes
    stall_memtable_bytes: int = 0
    stall_l0_files: int = 32
    # give up stalling after this long and flush inline (memory safety
    # beats latency when the plane is wedged)
    stall_timeout_s: float = 30.0
    # engine-wide TTL for retention expiry jobs; 0 = never expire
    retention_ttl_ms: int = 0
    # [[maintenance.rollup]] rules as dicts: {"resolution_ms": 60000,
    # "fields": [...], "auto": True}
    rollup_rules: list = field(default_factory=list)


class RegionEngine:
    def __init__(self, config: EngineConfig):
        from greptimedb_tpu.objectstore import build_store

        self.config = config
        self.store = build_store(config.object_store,
                                 config.object_store_cache_bytes,
                                 **config.object_store_kwargs)
        os.makedirs(config.data_dir, exist_ok=True)
        from greptimedb_tpu.storage.format import check_and_stamp

        # refuse dirs written by a NEWER build; stamp ours (round-3 dirs
        # carry no stamp and read as version 1 — see storage/format.py)
        self.format_versions = check_and_stamp(config.data_dir)
        if config.wal_backend == "remote":
            from greptimedb_tpu.storage.remote_wal import RemoteWal

            self.wal = RemoteWal(config.wal_store or self.store,
                                 prefix=os.path.join(config.data_dir,
                                                     "remote_wal"))
        else:
            self.wal = Wal(os.path.join(config.data_dir, "wal"),
                           sync=config.wal_sync,
                           segment_bytes=config.wal_segment_bytes)
        self.regions: dict[int, Region] = {}
        # alternate engines (metric engine) hook region-open by id — the
        # RegionServer multi-engine registration analog (datanode.rs:328)
        self.openers: list = []
        self._lock = threading.RLock()
        self.workers = None
        if config.write_workers:
            from greptimedb_tpu.storage.worker import WorkerGroup

            n = None if config.write_workers < 0 else config.write_workers
            self.workers = WorkerGroup(self, num_workers=n)
        # background maintenance plane: owns every flush/compaction/
        # rollup/expiry off the write path (maintenance/scheduler.py)
        self.maintenance = None
        if config.maintenance_workers > 0:
            from greptimedb_tpu.maintenance import MaintenanceScheduler

            self.maintenance = MaintenanceScheduler(
                self,
                workers=config.maintenance_workers,
                queue_size=config.maintenance_queue,
                tick_interval_s=config.maintenance_tick_s,
                retention_ttl_ms=config.retention_ttl_ms,
                rollup_rules=config.rollup_rules,
            )

    def register_opener(self, fn) -> None:
        self.openers.append(fn)

    def _region_dir(self, region_id: int) -> str:
        return os.path.join(self.config.data_dir, f"region_{region_id}")

    def region(self, region_id: int) -> Region:
        r = self.regions.get(region_id)
        if r is None:
            raise KeyError(f"region {region_id} not open")
        return r

    def _apply_scan_config(self, region) -> None:
        """Push the engine's scan + ingest knobs onto a freshly opened
        region (hasattr-guarded: alternate engines register non-Region
        objects via openers)."""
        for attr, value in (
                ("scan_cache_entries", self.config.scan_cache_entries),
                ("decode_threads", self.config.scan_decode_threads),
                ("part_cache_budget", self.config.scan_part_cache_bytes)):
            if hasattr(region, attr):
                setattr(region, attr, value)
        if self.config.ingest_group_commit \
                and hasattr(region, "group_reserve"):
            from greptimedb_tpu.storage.group_commit import GroupCommitter

            region.committer = GroupCommitter(
                region,
                max_batch_rows=self.config.ingest_max_batch_rows,
                max_batch_bytes=self.config.ingest_max_batch_bytes,
                queue_depth=self.config.ingest_queue_depth,
                overlap=self.config.ingest_overlap)

    # ---- handle_request (reference region_server.rs:120) -------------------

    def handle_request(self, req: RegionRequest) -> int:
        # the data path skips the engine-wide lock: region-level locking
        # suffices, and serializing writers here would defeat the worker
        # group's fsync amortization (reference: writes flow through the
        # worker mpsc, never the engine mutex)
        if req.kind is RequestType.PUT:
            return self._write(req.region_id, req.batch, OP_PUT)
        if req.kind is RequestType.DELETE:
            return self._write(req.region_id, req.batch, OP_DELETE)
        with self._lock:
            if req.kind is RequestType.CREATE:
                assert req.schema is not None
                if req.region_id in self.regions:
                    return 0
                region = Region.create(
                    req.region_id, self._region_dir(req.region_id), req.schema,
                    self.wal, self.store
                )
                self._apply_scan_config(region)
                self.regions[req.region_id] = region
                return 0
            if req.kind is RequestType.OPEN:
                if req.region_id not in self.regions:
                    for opener in self.openers:
                        r = opener(req.region_id)
                        if r is not None:
                            self._apply_scan_config(r)
                            self.regions[req.region_id] = r
                            return 0
                    region = Region.open(
                        req.region_id, self._region_dir(req.region_id),
                        self.wal, self.store
                    )
                    self._apply_scan_config(region)
                    self.regions[req.region_id] = region
                return 0
            if req.kind is RequestType.CLOSE:
                r = self.regions.pop(req.region_id, None)
                if r is not None and hasattr(r, "close"):
                    r.close()
                self.wal.close_region(req.region_id)
                return 0
            if req.kind is RequestType.DROP:
                r = self.regions.pop(req.region_id, None)
                if r is not None:
                    r.drop()
                return 0
            if req.kind is RequestType.FLUSH:
                self.region(req.region_id).flush()
                return 0
            if req.kind is RequestType.COMPACT:
                # manual compaction is a full merge (reference manual
                # strict-window strategy); background TWCS runs after flush
                self.region(req.region_id).compact(strategy="full")
                return 0

            raise ValueError(f"unhandled request {req.kind}")

    def _write(self, region_id: int, batch: RecordBatch, op: int) -> int:
        if self.workers is not None:
            n = self.workers.write(region_id, batch, op)
        else:
            n = self.region(region_id).write(batch, op)
        try:
            region = self.region(region_id)
        except KeyError:
            # region closed/dropped right after the write committed — the
            # write itself succeeded; only the flush check is moot
            return n
        if region.memtable_bytes >= self.config.flush_threshold_bytes:
            if self.maintenance is not None:
                # async plane: the writer only SUBMITS; it stalls below
                # only when a hard threshold is crossed
                self.maintenance.submit("flush", region_id)
                self._maybe_stall(region_id, region)
            else:
                region.flush()
                # TWCS picker no-ops unless window thresholds are exceeded
                region.compact()
        return n

    def _stall_threshold_bytes(self) -> int:
        return self.config.stall_memtable_bytes or \
            2 * self.config.flush_threshold_bytes

    def _maybe_stall(self, region_id: int, region: Region) -> None:
        """Write-stall backpressure (reference flush.rs:83-135 write
        buffer stall): block the writer while the region sits past the
        HARD memtable/L0 limits, crediting every stalled second to
        greptimedb_tpu_write_stall_seconds_total. After stall_timeout_s
        the writer flushes inline — memory safety beats latency when the
        plane is wedged or saturated."""
        import time as _time

        from greptimedb_tpu.utils.metrics import (
            WRITE_STALL_SECONDS,
            WRITE_STALL_TIMEOUTS,
        )

        hard_bytes = self._stall_threshold_bytes()
        hard_l0 = self.config.stall_l0_files

        def over() -> Optional[str]:
            if region.memtable_bytes >= hard_bytes:
                return "memtable"
            if hard_l0 and region.l0_count >= hard_l0:
                return "l0"
            return None

        reason = over()
        if reason is None:
            return
        if reason == "l0":
            self.maintenance.submit("compact", region_id)
        deadline = _time.monotonic() + self.config.stall_timeout_s
        cv = self.maintenance._cv
        while True:
            t0 = _time.monotonic()
            if t0 >= deadline:
                WRITE_STALL_TIMEOUTS.inc()
                # inline escape hatch matched to the stall reason: a
                # flush cannot relieve L0 pressure (it ADDS an L0 file)
                if reason == "l0":
                    region.compact()
                else:
                    region.flush()
                return
            with cv:
                cv.wait(min(0.05, deadline - t0))
            WRITE_STALL_SECONDS.inc(_time.monotonic() - t0, reason=reason)
            reason = over()
            if reason is None:
                return

    # ---- convenience wrappers ----------------------------------------------

    def create_region(self, region_id: int, schema: Schema) -> None:
        self.handle_request(RegionRequest(RequestType.CREATE, region_id, schema=schema))

    def open_region(self, region_id: int) -> None:
        self.handle_request(RegionRequest(RequestType.OPEN, region_id))

    def put(self, region_id: int, batch: RecordBatch) -> int:
        return self.handle_request(RegionRequest(RequestType.PUT, region_id, batch=batch))

    def delete(self, region_id: int, batch: RecordBatch) -> int:
        return self.handle_request(RegionRequest(RequestType.DELETE, region_id, batch=batch))

    def flush(self, region_id: int) -> None:
        self.handle_request(RegionRequest(RequestType.FLUSH, region_id))

    def compact(self, region_id: int) -> None:
        self.handle_request(RegionRequest(RequestType.COMPACT, region_id))

    # ---- handle_query (reference region_engine.rs:191) ---------------------

    def scan(
        self,
        region_id: int,
        ts_range: Optional[tuple[int, int]] = None,
        projection: Optional[Sequence[str]] = None,
        tag_predicates: Optional[dict[str, set]] = None,
        seq_min: Optional[int] = None,
    ) -> Optional[ScanData]:
        return self.region(region_id).scan(ts_range, projection,
                                           tag_predicates, seq_min=seq_min)

    def scan_last(self, region_id: int, group_tag: str,
                  projection: Optional[Sequence[str]] = None,
                  ) -> Optional[ScanData]:
        """Lastpoint-pruned newest-first scan (see Region.scan_last);
        None when the region type or data shape cannot serve it — the
        caller falls back to the full scan."""
        region = self.region(region_id)
        fn = getattr(region, "scan_last", None)
        return None if fn is None else fn(group_tag, projection)

    def ts_extent(self, region_id: int):
        """(min, max) data timestamps from metadata only (no data read)."""
        return self.region(region_id).ts_extent()

    def alter_region_schema(self, region_id: int, schema: Schema) -> None:
        """Apply an ALTER'd schema to a region: flush under the old schema,
        then swap and record (reference worker/handle_alter.rs)."""
        region = self.region(region_id)
        region.flush()
        region.schema = schema
        region.memtable.schema = schema
        region.sst_writer.schema = schema
        region.manifest.record_schema(schema)

    def scan_stream(
        self,
        region_id: int,
        ts_range: Optional[tuple[int, int]] = None,
        projection: Optional[Sequence[str]] = None,
        tag_predicates: Optional[dict[str, set]] = None,
    ):
        """Lazy bounded-memory scan (see region.ScanStream)."""
        return self.region(region_id).scan_stream(ts_range, projection,
                                                  tag_predicates)

    def close(self) -> None:
        if self.workers is not None:
            self.workers.stop()  # drain in-flight writes first
        if self.maintenance is not None:
            # after write workers (they submit jobs), before region close
            # (a running compaction still touches region state)
            self.maintenance.stop()
        with self._lock:
            for r in self.regions.values():
                if hasattr(r, "close"):
                    r.close()  # drain grace-deferred SST purges
        self.wal.close()
