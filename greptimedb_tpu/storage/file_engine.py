"""File engine: external files served as read-only tables (mirrors
reference `src/file-engine`: `FileRegionEngine` over common/datasource
formats, src/file-engine/src/engine.rs).

A file region materializes its CSV/JSON/Parquet file into the same
`ScanData` contract the LSM regions produce (tags as dictionary codes,
zero seq/op_type sideband, `needs_dedup=False`), so the device kernels
treat external data exactly like native region scans. Registered as an
opener on the shared RegionEngine — region ids in the 0x7FFD0000 space
route here (the metric engine uses 0x7FFF/0x7FFE the same way).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import numpy as np

from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
from greptimedb_tpu.datatypes.types import DataType, SemanticType
from greptimedb_tpu.storage.region import ScanData

META_PREFIX = "__file_engine/"
FILE_REGION_BASE = 0x7FFD0000 << 32


class FileEngineError(Exception):
    pass


class FileRegion:
    """Read-only region over one external file."""

    def __init__(self, region_id: int, path: str, fmt: str, schema: Schema):
        self.region_id = region_id
        self.path = path
        self.fmt = fmt
        self.schema = schema
        self._cache = None  # (mtime, columns, tag_dicts, nrows)

    # -- region engine contract (read side) ----------------------------------

    @property
    def data_version(self) -> int:
        try:
            return int(os.stat(self.path).st_mtime_ns)
        except OSError:
            return 0

    def scan(self, ts_range=None, projection: Optional[Sequence[str]] = None,
             tag_predicates=None, seq_min=None) -> Optional[ScanData]:
        if seq_min is not None:
            raise NotImplementedError(
                "seq_min scans are not supported on external tables")
        columns, tag_dicts, nrows = self._load()
        if nrows == 0:
            return None
        names = list(projection) if projection else self.schema.names
        ts_name = self.schema.time_index.name
        if ts_name not in names:
            names.append(ts_name)
        cols = {n: columns[n] for n in names}
        mask = None
        if ts_range is not None:
            ts = columns[ts_name]
            lo, hi = ts_range
            mask = (ts >= lo) & (ts <= hi)
        if mask is not None:
            cols = {n: c[mask] for n, c in cols.items()}
            nrows = int(mask.sum())
            if nrows == 0:
                return None
        return ScanData(
            schema=self.schema,
            columns=cols,
            seq=np.zeros(nrows, dtype=np.int64),
            op_type=np.zeros(nrows, dtype=np.int8),
            tag_dicts={k: v for k, v in tag_dicts.items() if k in cols},
            num_rows=nrows,
            needs_dedup=False,
            region_id=self.region_id,
            data_version=self.data_version,
        )

    # -- write side: read-only (reference file-engine rejects writes) --------

    def write(self, batch, op):
        raise FileEngineError("file engine tables are read-only")

    def flush(self):
        pass

    def compact(self, strategy=None):
        pass

    def drop(self):
        self._cache = None

    @property
    def memtable_bytes(self) -> int:
        return 0

    # -- load + coerce ---------------------------------------------------------

    def _load(self):
        from greptimedb_tpu.datasource import read_file
        from greptimedb_tpu.utils.time import coerce_ts_literal

        mtime = self.data_version
        if self._cache is not None and self._cache[0] == mtime:
            return self._cache[1], self._cache[2], self._cache[3]
        t = read_file(self.path, self.fmt)
        nrows = t.num_rows
        have = set(t.schema.names)
        columns: dict[str, np.ndarray] = {}
        tag_dicts: dict[str, np.ndarray] = {}
        for c in self.schema.columns:
            if c.name not in have:
                raise FileEngineError(
                    f"column {c.name!r} missing from {self.path!r}")
            vals = t.column(c.name).to_pylist()
            if c.semantic is SemanticType.TAG or c.dtype.is_string:
                # NULLs encode as code -1, same as native regions
                from greptimedb_tpu.datatypes.vector import DictVector
                dv = DictVector.encode(
                    [None if v is None else str(v) for v in vals])
                columns[c.name] = dv.codes
                tag_dicts[c.name] = dv.values
            elif c.dtype.is_timestamp:
                columns[c.name] = np.asarray(
                    [coerce_ts_literal(v, c.dtype) for v in vals],
                    dtype=np.int64)
            elif c.dtype.is_float:
                columns[c.name] = np.asarray(
                    [np.nan if v is None else float(v) for v in vals],
                    dtype=c.dtype.to_numpy())
            else:
                columns[c.name] = np.asarray(
                    [0 if v is None else int(v) for v in vals],
                    dtype=c.dtype.to_numpy())
        self._cache = (mtime, columns, tag_dicts, nrows)
        return columns, tag_dicts, nrows


class FileEngine:
    """Region-engine facade for external-file tables; persists region
    metadata in the catalog kv so regions reopen across restarts."""

    def __init__(self, region_engine, kv):
        self.engine = region_engine
        self.kv = kv
        region_engine.register_opener(self._open)

    def create_file_table(self, db: str, name: str, schema: Optional[Schema],
                          location: str, fmt: Optional[str]) -> tuple[int, Schema]:
        from greptimedb_tpu.datasource import infer_format, read_file

        fmt = infer_format(location, fmt)
        if schema is None:
            schema = self._infer_schema(read_file(location, fmt))
        rid = FILE_REGION_BASE | (self.kv.incr(META_PREFIX + "seq") & 0xFFFFFFFF)
        meta = {"path": location, "format": fmt,
                "schema": schema.to_dict(), "db": db, "table": name}
        self.kv.put(f"{META_PREFIX}region/{rid}", json.dumps(meta))
        self.engine.regions[rid] = FileRegion(rid, location, fmt, schema)
        return rid, schema

    def drop_file_table(self, region_id: int) -> None:
        self.kv.delete(f"{META_PREFIX}region/{region_id}")
        self.engine.regions.pop(region_id, None)

    def _open(self, region_id: int):
        if (region_id >> 32) != 0x7FFD0000:
            return None
        raw = self.kv.get(f"{META_PREFIX}region/{region_id}")
        if raw is None:
            return None
        meta = json.loads(raw)
        return FileRegion(region_id, meta["path"], meta["format"],
                          Schema.from_dict(meta["schema"]))

    @staticmethod
    def _infer_schema(t) -> Schema:
        """Schema inference (reference file-engine infers from the file):
        timestamp-typed (or ts-named int) column → time index, strings →
        tags, numerics → fields."""
        import pyarrow as pa

        cols: list[ColumnSchema] = []
        ts_col = None
        for field in t.schema:
            if pa.types.is_timestamp(field.type) and ts_col is None:
                ts_col = field.name
        if ts_col is None:
            for field in t.schema:
                if field.name.lower() in ("ts", "timestamp", "time") and (
                        pa.types.is_integer(field.type)):
                    ts_col = field.name
                    break
        if ts_col is None:
            raise FileEngineError(
                "cannot infer a time index column; declare the schema "
                "explicitly in CREATE EXTERNAL TABLE")
        for field in t.schema:
            if field.name == ts_col:
                dt = DataType.from_arrow(field.type) \
                    if pa.types.is_timestamp(field.type) \
                    else DataType.TIMESTAMP_MILLISECOND
                cols.append(ColumnSchema(field.name, dt,
                                         SemanticType.TIMESTAMP, False))
            elif pa.types.is_string(field.type) or \
                    pa.types.is_large_string(field.type):
                cols.append(ColumnSchema(field.name, DataType.STRING,
                                         SemanticType.TAG, True))
            else:
                dt = DataType.from_arrow(field.type)
                cols.append(ColumnSchema(field.name, dt, SemanticType.FIELD,
                                         True))
        return Schema(cols)
