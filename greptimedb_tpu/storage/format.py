"""On-disk format versioning (the reference's cross-version compatibility
contract, tests/compat/test-compat.sh: data written by version N must open
under version N+1, and incompatibility must fail loudly, never corrupt).

A `FORMAT.json` stamp at the data-dir root records the layout versions the
writing build used. Open-time check: a dir stamped with a NEWER version
than this build understands refuses to open (downgrade protection); a dir
with no stamp predates versioning (round-3 builds) and reads as version 1
— every v1 reader path tolerates those files (parquet self-describes its
codec, manifest actions default missing fields, WAL framing is unchanged).

Bump a component's version when its reader can no longer parse what an
older writer produced; keep readers accepting ALL versions <= current.
"""

from __future__ import annotations

import json
import os

#: current writer versions, per component
#: manifest v2: FileMeta grew `null_tags` (lastpoint NULL-group
#: metadata) — v2 readers default it when absent, but a v1 reader's
#: FileMeta(**d) would crash on the unknown key, so v2-written dirs
#: must refuse cleanly under v1 builds
FORMAT_VERSIONS = {"layout": 1, "sst": 1, "wal": 1, "manifest": 2}

_STAMP = "FORMAT.json"


class FormatError(RuntimeError):
    """Data dir written by an incompatible (newer) build."""


def check_and_stamp(data_dir: str) -> dict:
    """Validate `data_dir`'s format stamp against this build and (re)write
    the stamp. Returns the versions the dir was written with."""
    path = os.path.join(data_dir, _STAMP)
    found = dict.fromkeys(FORMAT_VERSIONS, 1)  # unstamped = version 1
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as f:
                found.update(json.load(f).get("versions", {}))
        except (OSError, ValueError) as e:
            raise FormatError(f"unreadable format stamp {path}: {e}") from e
    newer = {k: v for k, v in found.items()
             if v > FORMAT_VERSIONS.get(k, 0)}
    if newer:
        raise FormatError(
            f"data dir {data_dir} was written by a newer build "
            f"({newer}); this build supports {FORMAT_VERSIONS}")
    # pid-unique tmp: N datanode processes stamp a SHARED dir at startup,
    # and a fixed tmp name makes their rename calls race (one renames the
    # other's tmp away → FileNotFoundError aborts startup)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"versions": FORMAT_VERSIONS}, f)
    try:
        os.replace(tmp, path)
    except FileNotFoundError:
        pass  # a concurrent process already stamped the same versions
    return found
