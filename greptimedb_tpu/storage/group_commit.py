"""Per-region group commit: the ingest pipeline's durability stage.

Every front door (SQL INSERT, Influx line protocol, Prometheus
remote-write, OTLP) decodes into RecordBatches and lands here through
``Region.write_many``. Concurrent writers enqueue into a bounded
per-region queue; the first writer in becomes the commit LEADER and
drains the queue up to a row/byte cap into ONE WAL append + ONE fsync +
ONE memtable apply, while followers wait on their commit future
(reference: the mito2 region worker drains ≤64 requests per cycle into
one ``RegionWriteCtx`` WAL write, worker.rs:576-650 — here leadership
is writer-elected instead of a dedicated actor thread, so an idle
region costs no thread).

Pipelining: with ``[ingest] overlap`` on, up to TWO leaders run
concurrently — sequences are reserved under the region lock (fast),
the Arrow-IPC/LZ4 WAL encode runs outside every lock, and a commit
ticket orders the appends so the WAL file stays in sequence order.
While group N's fsync is in flight, group N+1 is already encoding: the
fsync latency amortizes across ALL queued writers instead of gating
each one (``Region.group_commit`` holds no region lock across the
fsync — the blocking-call-in-lock lint checker guards this).

Backpressure: a full queue raises the typed ``Overloaded`` the
admission plane already maps to HTTP 503 / MySQL 1040 — protocol
ingest rides the same degradation contract as queries instead of
piling unbounded memory.

Failure: any error between reserve and apply fails ONLY the drained
group's writers (never acknowledged), burns the reserved sequences (a
WAL gap, which replay tolerates), and advances the commit ticket so
later groups proceed. A crash mid-commit leaves at most a torn WAL
tail that replay truncates — nothing in the group was acknowledged.
Chaos hooks: ``ingest.commit`` fires at op=drain/append/apply.
"""

from __future__ import annotations

import threading
from collections import deque

from greptimedb_tpu.fault import FAULTS
from greptimedb_tpu.utils.metrics import (
    INGEST_BATCH_SIZE,
    INGEST_GROUP_COMMIT_EVENTS,
)


class _Pending:
    """One writer's queued mutation group."""

    __slots__ = ("items", "rows", "nbytes", "error", "event")

    def __init__(self, items: list, rows: int, nbytes: int):
        self.items = items
        self.rows = rows
        self.nbytes = nbytes
        self.error = None
        self.event = threading.Event()

    @property
    def done(self) -> bool:
        return self.event.is_set()


def _batch_nbytes(batch) -> int:
    """Host-byte estimate for the queue's byte cap (cheap, not exact:
    dictionary values undercount like the scan caches do)."""
    n = 0
    for col in batch.columns.values():
        arr = getattr(col, "codes", col)
        nb = getattr(arr, "nbytes", None)
        n += int(nb) if nb is not None else 8 * batch.num_rows
    return n


class GroupCommitter:
    def __init__(self, region, max_batch_rows: int = 65536,
                 max_batch_bytes: int = 8 << 20, queue_depth: int = 512,
                 overlap: bool = True):
        self.region = region
        self.max_batch_rows = max(1, int(max_batch_rows))
        self.max_batch_bytes = max(1, int(max_batch_bytes))
        self.queue_depth = max(1, int(queue_depth))
        # up to 2 concurrent leaders when overlapping: N+1 encodes while
        # N fsyncs; the region's commit ticket keeps the WAL in order
        self._leaders = threading.Semaphore(2 if overlap else 1)
        self._cv = threading.Condition()
        self._queue: deque = deque()

    # ---- the write surface (Region.write_many delegates here) --------------

    def write_many(self, items: list) -> list[int]:
        counts = [b.num_rows for b, _ in items]
        live = [(b, op) for b, op in items if b.num_rows]
        if not live:
            return counts
        rows = sum(b.num_rows for b, _ in live)
        pend = _Pending(live, rows,
                        sum(_batch_nbytes(b) for b, _ in live))
        with self._cv:
            if len(self._queue) >= self.queue_depth:
                INGEST_GROUP_COMMIT_EVENTS.inc(event="overflow")
                # typed rejection riding the admission plane's contract
                # (HTTP 503 / MySQL 1040 / retryable Unavailable) — the
                # lazy import keeps the storage plane's import closure
                # free of the frontend package at module load
                from greptimedb_tpu.concurrency.admission import Overloaded

                raise Overloaded(
                    f"region {self.region.region_id} ingest queue full "
                    f"({len(self._queue)} groups waiting)")
            self._queue.append(pend)
        while True:
            # leadership is opportunistic: whoever finds a free leader
            # slot drains for everyone; the rest sleep on _cv — a
            # finishing leader notifies under it (after resolving its
            # group and after releasing the slot), so a queued writer
            # both learns its result and picks up leadership promptly
            # instead of polling. The timeout is a belt-and-braces
            # re-check, not the wakeup mechanism.
            if self._leaders.acquire(blocking=False):
                try:
                    self._lead(pend)
                finally:
                    self._leaders.release()
                    with self._cv:
                        self._cv.notify_all()
            if pend.done:
                break
            with self._cv:
                if pend.done:
                    break
                # deadline/cancel checkpoint: abandoning is only safe
                # while our pend still sits in the queue — once a
                # leader drained it the write may commit, and then the
                # writer must stay for its true result (exactly-once)
                from greptimedb_tpu.utils import deadline as dl

                tok = dl.current()
                if tok is not None and (tok.cancelled or tok.expired()):
                    try:
                        self._queue.remove(pend)
                    except ValueError:
                        pass  # drained: in flight, wait it out
                    else:
                        INGEST_GROUP_COMMIT_EVENTS.inc(event="deadline")
                        tok.check("group commit wait")
                self._cv.wait(timeout=0.05)
        if pend.error is not None:
            raise pend.error
        return counts

    # ---- leader ------------------------------------------------------------

    def _take_locked(self) -> list:
        """Pop a cap-bounded prefix of the queue (caller holds _cv).
        Always takes at least one group so an oversized single batch
        still commits."""
        take: list = []
        rows = nbytes = 0
        while self._queue:
            p = self._queue[0]
            if take and (rows + p.rows > self.max_batch_rows
                         or nbytes + p.nbytes > self.max_batch_bytes):
                break
            take.append(self._queue.popleft())
            rows += p.rows
            nbytes += p.nbytes
        return take

    def _lead(self, pend: _Pending) -> None:
        region = self.region
        while not pend.done:
            with self._cv:
                take = self._take_locked()
            if not take:
                # queue drained — `pend` is either resolved or inside
                # another leader's in-flight group; wait it out
                return
            rows = sum(p.rows for p in take)
            try:
                FAULTS.fire("ingest.commit", op="drain",
                            region=str(region.region_id))
                self._commit(take)
            except BaseException as e:  # noqa: BLE001 — delivered to writers
                for p in take:
                    p.error = e
                    p.event.set()
                with self._cv:
                    self._cv.notify_all()
                continue
            INGEST_GROUP_COMMIT_EVENTS.inc(event="lead")
            if len(take) > 1:
                INGEST_GROUP_COMMIT_EVENTS.inc(
                    float(len(take) - 1), event="follow")
            INGEST_BATCH_SIZE.observe(float(rows))
            for p in take:
                p.event.set()
            with self._cv:
                self._cv.notify_all()

    def _commit(self, take: list) -> None:
        """One drained group → reserve, encode, ticket-ordered
        append+fsync, memtable apply (see Region.group_commit)."""
        region = self.region
        live = [item for p in take for item in p.items]
        ticket, entries = region.group_reserve(live)
        entered = False
        try:
            # WAL encode outside every lock: this is the stage that
            # overlaps the previous group's fsync
            encode = getattr(region.wal, "encode_entries", None)
            blob = None if encode is None else \
                encode(region.region_id, entries)
            entered = True
            region.group_commit(ticket, entries, blob=blob)
        finally:
            if not entered:
                # encode failed before the commit owned the ticket —
                # release it so later groups don't wait forever
                region.group_abort(ticket)
