"""Inverted index: sorted term dictionary + segment bitmaps, per SST.

Mirrors reference `src/index/src/inverted_index` (format.rs:28: an FST of
tag values mapping to bitmaps of row-segment positions) stored in a puffin
container next to each SST (reference `src/puffin`), and mito2's applier
integration (sst/parquet/reader.rs:335-425 prune path; predicate kinds
Eq/In/Range/Regex per search/index_apply.rs:26-58).

Per SST file, one puffin blob per tag column holds:
  - the sorted distinct UTF-8 *values* present (the FST analog — binary
    search replaces FST lookup, an ordered slice replaces FST range scan),
  - one packed bitmap per value over fixed-size row segments
    (``segment_rows`` rows each, finer than parquet row groups).

Scan-time predicates (Eq/In from ``=``/``IN``, Range from comparisons and
BETWEEN, Regex from LIKE and PromQL ``=~``) intersect those bitmaps to
skip whole row groups — and whole files — before any Parquet page is
touched. Pruning is purely an IO reduction: the scan may still return rows
a predicate rejects; the device filter always runs afterwards.

Values (not per-file codes) key the index so it stays valid as the region
tag registry grows.

Blob binary layout (little-endian, blob type "gtpu-inverted-index-v1"):

    u32 n_terms | u32 n_segments | u32 segment_rows | u8 has_null | pad[3]
    u32 term_offsets[n_terms + 1]        # into the term byte stream
    term bytes (utf-8, concatenated)
    bitmaps: (n_terms + has_null) rows x ceil(n_segments/8) bytes,
             packbits(bitorder="little"); the NULL bitmap is last
"""

from __future__ import annotations

import os
import re
import struct
from dataclasses import dataclass
from typing import Optional, Sequence, Union

import numpy as np

from greptimedb_tpu.objectstore import default_store
from greptimedb_tpu.storage.puffin import PuffinReader, PuffinWriter

BLOB_TYPE = "gtpu-inverted-index-v1"
DEFAULT_SEGMENT_ROWS = 8192


# ---- predicates ------------------------------------------------------------


@dataclass(frozen=True)
class InSet:
    """value ∈ {…} — from ``tag = 'v'`` and ``tag IN (…)``."""

    values: tuple[str, ...]  # sorted

    @staticmethod
    def of(values) -> "InSet":
        return InSet(tuple(sorted(str(v) for v in values)))


@dataclass(frozen=True)
class Range:
    """lo (<|<=) value (<|<=) hi over the tag's string ordering — from
    comparisons and BETWEEN on tag columns. Either bound may be None."""

    lo: Optional[str]
    hi: Optional[str]
    lo_inc: bool = True
    hi_inc: bool = True


@dataclass(frozen=True)
class Regex:
    """value matches an anchored regular expression — from LIKE and
    PromQL ``=~`` matchers."""

    pattern: str


Predicate = Union[InSet, Range, Regex]

# A predicate map is tag name -> tuple of Predicates (ANDed), but a plain
# set of values (the historical form, still produced by callers like
# metric_engine and the Flight wire) is accepted anywhere and treated as
# one InSet.
PredicateMap = dict[str, object]


def _norm_preds(v) -> tuple[Predicate, ...]:
    if isinstance(v, (set, frozenset, list)) and not isinstance(v, tuple):
        return (InSet.of(v),)
    if isinstance(v, (InSet, Range, Regex)):
        return (v,)
    out = []
    for p in v:
        out.extend(_norm_preds(p))
    return tuple(out)


def normalize_predicates(preds: Optional[PredicateMap]) \
        -> dict[str, tuple[Predicate, ...]]:
    if not preds:
        return {}
    return {k: _norm_preds(v) for k, v in preds.items()}


def predicates_cache_key(preds: Optional[PredicateMap]):
    """Hashable, order-independent key for scan caches."""
    if not preds:
        return None
    return tuple(sorted(
        (k, tuple(sorted(map(repr, v))))
        for k, v in normalize_predicates(preds).items()
    ))


def serialize_predicates(preds: Optional[PredicateMap]) -> Optional[dict]:
    """JSON-able form for the Flight region-scan wire (reference ships
    these inside the QueryRequest alongside the substrait plan)."""
    if not preds:
        return None
    out: dict[str, list] = {}
    for k, pv in normalize_predicates(preds).items():
        ser = []
        for p in pv:
            if isinstance(p, InSet):
                ser.append({"in": list(p.values)})
            elif isinstance(p, Range):
                ser.append({"range": [p.lo, p.hi, p.lo_inc, p.hi_inc]})
            else:
                ser.append({"regex": p.pattern})
        out[k] = ser
    return out


def serialize_predicates_legacy(preds: Optional[PredicateMap]) \
        -> Optional[dict]:
    """Bare value-list wire form — the only shape pre-Range/Regex peers
    parse. Tags whose predicates aren't a single InSet are DROPPED (losing
    pruning, never correctness: pruning is advisory, the scan-side filter
    still runs). Ship alongside serialize_predicates under a separate key
    so either end of a mixed-version pair finds a form it understands."""
    if not preds:
        return None
    out = {}
    for k, pv in normalize_predicates(preds).items():
        if len(pv) == 1 and isinstance(pv[0], InSet):
            out[k] = list(pv[0].values)
    return out or None


def deserialize_predicates(obj) -> Optional[dict]:
    if not obj:
        return None
    out: dict[str, tuple[Predicate, ...]] = {}
    for k, v in obj.items():
        preds: list[Predicate] = []
        if isinstance(v, list) and v and not isinstance(v[0], dict):
            # legacy wire form: bare list of values = one IN set
            preds.append(InSet.of(v))
        else:
            for p in v:
                if "in" in p:
                    preds.append(InSet.of(p["in"]))
                elif "range" in p:
                    lo, hi, li, hi_inc = p["range"]
                    preds.append(Range(lo, hi, li, hi_inc))
                else:
                    preds.append(Regex(p["regex"]))
        out[k] = tuple(preds)
    return out


# ---- build side ------------------------------------------------------------


def _index_path(sst_dir: str, file_id: str) -> str:
    return os.path.join(sst_dir, f"{file_id}.puffin")


class InvertedIndexWriter:
    """Build + persist the per-file index at SST write time (reference
    create/sort_create.rs role; here the values arrive already
    dictionary-encoded, so 'external sort' reduces to bincount over
    codes)."""

    def __init__(self, sst_dir: str, store=None,
                 segment_rows: int = DEFAULT_SEGMENT_ROWS):
        self.sst_dir = sst_dir
        self.store = default_store(store)
        self.segment_rows = int(segment_rows)

    def path(self, file_id: str) -> str:
        return _index_path(self.sst_dir, file_id)

    def write(
        self,
        file_id: str,
        tag_codes: dict[str, np.ndarray],  # tag -> int codes per row
        tag_dicts: dict[str, np.ndarray],  # tag -> value table
        row_group_size: int,
        num_rows: int,
    ) -> None:
        if not tag_codes or num_rows == 0:
            return
        seg = self.segment_rows
        n_segments = (num_rows + seg - 1) // seg
        w = PuffinWriter({"num_rows": num_rows,
                          "row_group_size": int(row_group_size)})
        for tag, codes in tag_codes.items():
            blob = self._build_blob(
                np.asarray(codes), np.asarray(tag_dicts[tag]), n_segments)
            w.add_blob(BLOB_TYPE, blob, {"column": tag})
        self.store.write(self.path(file_id), w.finish())

    def _build_blob(self, codes: np.ndarray, values: np.ndarray,
                    n_segments: int) -> bytes:
        seg = self.segment_rows
        n = len(codes)
        seg_ids = np.arange(n, dtype=np.int64) // seg
        null_rows = codes < 0
        has_null = bool(null_rows.any())

        # distinct codes present, mapped to their sorted-term order
        present = np.unique(codes[~null_rows]) if (~null_rows).any() \
            else np.empty(0, dtype=codes.dtype)
        terms = np.asarray([str(values[c]) for c in present], dtype=object)
        order = np.argsort(terms, kind="stable")
        terms = terms[order]
        present = present[order]
        n_terms = len(terms)

        # bitmap matrix [n_terms (+null), n_segments]
        rank = np.full(int(values.shape[0]) + 1, -1, dtype=np.int64)
        rank[present] = np.arange(n_terms)
        bm = np.zeros((n_terms + (1 if has_null else 0), n_segments),
                      dtype=bool)
        if n_terms:
            rows = rank[np.where(null_rows, len(values), codes)]
            ok = rows >= 0
            bm[rows[ok], seg_ids[ok]] = True
        if has_null:
            bm[n_terms, seg_ids[null_rows]] = True
        packed = np.packbits(bm, axis=1, bitorder="little").tobytes() \
            if bm.size else b""

        term_bytes = [t.encode() for t in terms]
        offsets = np.zeros(n_terms + 1, dtype=np.uint32)
        offsets[1:] = np.cumsum([len(b) for b in term_bytes])
        return b"".join([
            struct.pack("<IIIB3x", n_terms, n_segments, seg,
                        1 if has_null else 0),
            offsets.tobytes(),
            b"".join(term_bytes),
            packed,
        ])

    def delete(self, file_id: str) -> None:
        path = self.path(file_id)
        if self.store.exists(path):
            self.store.delete(path)
        # remove a pre-puffin JSON sidecar if one exists (format upgrade)
        legacy = os.path.join(self.sst_dir, f"{file_id}.idx.json")
        if self.store.exists(legacy):
            self.store.delete(legacy)


# ---- search side -----------------------------------------------------------


class _TagIndex:
    """Parsed in-memory form of one tag's blob. Bitmaps stay *packed*
    (one byte row per 8 segments); only the term rows a predicate actually
    hits are unpacked — O(hits), not O(n_terms * n_segments)."""

    __slots__ = ("terms", "_packed", "_n_terms", "_has_null", "n_segments",
                 "segment_rows")

    def __init__(self, data: bytes):
        n_terms, n_segments, seg_rows, has_null = \
            struct.unpack_from("<IIIB", data, 0)
        off = 16
        offsets = np.frombuffer(data, dtype=np.uint32, count=n_terms + 1,
                                offset=off)
        off += 4 * (n_terms + 1)
        blob = data[off:off + int(offsets[-1])]
        self.terms = [
            blob[offsets[i]:offsets[i + 1]].decode()
            for i in range(n_terms)
        ]
        off += int(offsets[-1])
        width = (n_segments + 7) // 8
        rows = n_terms + (1 if has_null else 0)
        self._packed = np.frombuffer(
            data, dtype=np.uint8, count=rows * width, offset=off
        ).reshape(rows, width)
        self._n_terms = n_terms
        self._has_null = bool(has_null)
        self.n_segments = n_segments
        self.segment_rows = seg_rows

    # each evaluator returns a bool[n_segments] of segments that MAY match

    def eval(self, pred: Predicate) -> np.ndarray:
        if isinstance(pred, InSet):
            return self._eval_in(pred.values)
        if isinstance(pred, Range):
            return self._eval_range(pred)
        return self._eval_regex(pred.pattern)

    def _or_rows(self, rows: np.ndarray, with_null: bool) -> np.ndarray:
        idx = list(np.asarray(rows, dtype=np.int64))
        if with_null and self._has_null:
            idx.append(self._n_terms)
        if not idx:
            return np.zeros(self.n_segments, dtype=bool)
        merged = np.bitwise_or.reduce(self._packed[idx], axis=0)
        return np.unpackbits(merged, bitorder="little")[:self.n_segments] \
            .astype(bool)

    def _eval_in(self, values: Sequence[str]) -> np.ndarray:
        terms = self.terms
        lo = np.searchsorted(terms, list(values))
        hits = [
            i for v, i in zip(values, lo)
            if i < len(terms) and terms[i] == v
        ]
        # an absent tag is NULL here but the empty string in PromQL's
        # data model — `host=""` must keep NULL segments
        return self._or_rows(np.asarray(hits, dtype=np.int64),
                             with_null="" in values)

    def _eval_range(self, p: Range) -> np.ndarray:
        terms = self.terms
        lo = 0 if p.lo is None else \
            np.searchsorted(terms, p.lo, side="left" if p.lo_inc else "right")
        hi = len(terms) if p.hi is None else \
            np.searchsorted(terms, p.hi, side="right" if p.hi_inc else "left")
        return self._or_rows(np.arange(lo, max(lo, hi), dtype=np.int64),
                             with_null=False)

    def _eval_regex(self, pattern: str) -> np.ndarray:
        try:
            rx = re.compile(pattern)
        except re.error:
            return np.ones(self.n_segments, dtype=bool)  # can't prune
        hits = np.asarray(
            [i for i, t in enumerate(self.terms) if rx.fullmatch(t)],
            dtype=np.int64)
        return self._or_rows(hits, with_null=rx.fullmatch("") is not None)


@dataclass
class SegmentSelection:
    """Which fixed-size row segments of a file may contain matches."""

    mask: np.ndarray  # bool[n_segments]
    segment_rows: int

    @property
    def is_empty(self) -> bool:
        return not bool(self.mask.any())

    @property
    def all_set(self) -> bool:
        return bool(self.mask.all())

    def row_groups(self, group_row_counts: Sequence[int]) -> list[int]:
        """Map surviving segments onto parquet row groups given each
        group's row count (reference row-selection analog)."""
        keep = []
        start = 0
        seg = self.segment_rows
        for g, rows in enumerate(group_row_counts):
            s0 = start // seg
            s1 = (start + rows - 1) // seg + 1 if rows else s0
            if self.mask[s0:min(s1, len(self.mask))].any():
                keep.append(g)
            start += rows
        return keep


class IndexApplier:
    """Evaluate tag predicates against a file's index.

    Returns the allowed row-group indices, or None when the file has no
    index / nothing is pruned (scan everything), or [] when provably
    empty."""

    CACHE_FILES = 64  # parsed per-file indexes kept (LRU)

    def __init__(self, sst_dir: str, store=None):
        from collections import OrderedDict

        self.sst_dir = sst_dir
        self.store = default_store(store)
        self._cache: "OrderedDict[str, Optional[dict]]" = OrderedDict()

    def _load(self, file_id: str) -> Optional[dict]:
        if file_id in self._cache:
            self._cache.move_to_end(file_id)
            return self._cache[file_id]
        entry = None
        path = _index_path(self.sst_dir, file_id)
        if self.store.exists(path):
            reader = PuffinReader(self.store.open_input(path))
            entry = {"tags": {}, "props": reader.properties}
            for blob in reader.blobs_of_type(BLOB_TYPE):
                entry["tags"][blob.properties.get("column")] = \
                    _TagIndex(reader.read_blob(blob))
        self._cache[file_id] = entry
        while len(self._cache) > self.CACHE_FILES:
            self._cache.popitem(last=False)
        return entry

    def select(self, file_id: str,
               predicates: Optional[PredicateMap]) -> Optional[SegmentSelection]:
        preds = normalize_predicates(predicates)
        if not preds:
            return None
        data = self._load(file_id)
        if data is None:
            return None
        mask = None
        for tag, plist in preds.items():
            tix: Optional[_TagIndex] = data["tags"].get(tag)
            if tix is None:
                continue  # tag not indexed in this file
            for p in plist:
                m = tix.eval(p)
                mask = m if mask is None else (mask & m)
                if not mask.any():
                    return SegmentSelection(mask, tix.segment_rows)
        if mask is None:
            return None
        seg_rows = next(iter(data["tags"].values())).segment_rows
        return SegmentSelection(mask, seg_rows)

    def apply(
        self, file_id: str, predicates: Optional[PredicateMap],
        group_row_counts: Optional[Sequence[int]] = None,
    ) -> Optional[list[int]]:
        """Row-group form of `select`. Without `group_row_counts` (parquet
        meta not opened yet) only the fully-empty answer is decidable."""
        sel = self.select(file_id, predicates)
        if sel is None:
            return None
        if sel.is_empty:
            return []
        if sel.all_set:
            return None
        if group_row_counts is None:
            props = self._load(file_id)["props"]
            rg = int(props.get("row_group_size", 0))
            num = int(props.get("num_rows", 0))
            if not rg or not num:
                return None
            group_row_counts = [min(rg, num - s) for s in range(0, num, rg)]
        return sel.row_groups(group_row_counts)

    def invalidate(self, file_id: str) -> None:
        self._cache.pop(file_id, None)


# ---- predicate extraction from SQL -----------------------------------------


def _sql_like_to_regex(pat: str) -> str:
    # inline (?is): the query-side LIKE filter compiles with
    # re.IGNORECASE | re.DOTALL (query/expr.py _like_to_regex) — index
    # pruning must never be stricter than the filter it serves
    out = ["(?is)"]
    for ch in pat:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)


def extract_tag_predicates(where, schema) -> dict[str, tuple]:
    """Conservatively extract tag constraints from the top-level
    conjunction of a raw (pre-bind) WHERE AST: `tag = 'v'`, `tag IN (…)`,
    `tag  (<|<=|>|>=)  'v'`, `tag BETWEEN a AND b`, `tag LIKE 'p%'`.
    Anything not provably restrictive is ignored — pruning must never
    drop rows."""
    from greptimedb_tpu.sql import ast

    tags = {c.name for c in schema.tag_columns}
    out: dict[str, list] = {}

    def add(name: str, pred: Predicate):
        out.setdefault(name, []).append(pred)

    def tag_lit(e):
        """(column, literal) if e is `tag OP literal` in either order,
        plus whether the operands were swapped."""
        l, r = e.left, e.right
        swapped = False
        if isinstance(r, ast.Column) and isinstance(l, ast.Literal):
            l, r, swapped = r, l, True
        if isinstance(l, ast.Column) and l.name in tags \
                and isinstance(r, ast.Literal) and r.value is not None:
            return l.name, str(r.value), swapped
        return None

    def walk(e):
        if isinstance(e, ast.BinaryOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if isinstance(e, ast.BinaryOp) and e.op == "=":
            hit = tag_lit(e)
            if hit:
                add(hit[0], InSet.of([hit[1]]))
            return
        if isinstance(e, ast.BinaryOp) and e.op in ("<", "<=", ">", ">="):
            hit = tag_lit(e)
            if hit:
                name, v, swapped = hit
                op = e.op
                if swapped:  # 'v' < tag  ==  tag > 'v'
                    op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}[op]
                if op in ("<", "<="):
                    add(name, Range(None, v, hi_inc=(op == "<=")))
                else:
                    add(name, Range(v, None, lo_inc=(op == ">=")))
            return
        if isinstance(e, ast.BinaryOp) and e.op == "like":
            if isinstance(e.left, ast.Column) and e.left.name in tags \
                    and isinstance(e.right, ast.Literal) \
                    and e.right.value is not None:
                add(e.left.name, Regex(_sql_like_to_regex(str(e.right.value))))
            return
        if (
            isinstance(e, ast.Between)
            and not getattr(e, "negated", False)
            and isinstance(e.expr, ast.Column)
            and e.expr.name in tags
            and isinstance(e.low, ast.Literal)
            and isinstance(e.high, ast.Literal)
            and e.low.value is not None
            and e.high.value is not None
        ):
            add(e.expr.name, Range(str(e.low.value), str(e.high.value)))
            return
        if (
            isinstance(e, ast.InList)
            and not e.negated
            and isinstance(e.expr, ast.Column)
            and e.expr.name in tags
            and all(isinstance(i, ast.Literal) for i in e.items)
        ):
            add(e.expr.name,
                InSet.of([str(i.value) for i in e.items
                          if i.value is not None]))

    if where is not None:
        walk(where)
    return {k: tuple(v) for k, v in out.items()}
