"""Inverted index: tag value → row-group bitmap, per SST.

Mirrors reference src/index/src/inverted_index (format.rs:28: FST of tag
values → bitmaps of row segments) + mito2's index applier integration
(sst/parquet/reader.rs:335-425 prune path). Per SST file we store, for each
tag column, the sorted distinct *values* present and a row-group bitmask
per value; scan-time predicates (eq / IN on tags) intersect those bitmasks
to skip whole row groups — and whole files — before any Parquet page is
touched.

Values (not per-file codes) key the index so it stays valid as the region
tag registry grows. Serialization is a JSON sidecar next to the SST — the
puffin-container analog, one blob per file.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import numpy as np

from greptimedb_tpu.objectstore import default_store


class InvertedIndexWriter:
    """Build + persist the per-file index at SST write time."""

    def __init__(self, sst_dir: str, store=None):
        self.sst_dir = sst_dir
        self.store = default_store(store)

    def path(self, file_id: str) -> str:
        return os.path.join(self.sst_dir, f"{file_id}.idx.json")

    def write(
        self,
        file_id: str,
        tag_codes: dict[str, np.ndarray],  # tag -> int32 codes per row
        tag_dicts: dict[str, np.ndarray],  # tag -> value table
        row_group_size: int,
        num_rows: int,
    ) -> None:
        if not tag_codes or num_rows == 0:
            return
        n_groups = (num_rows + row_group_size - 1) // row_group_size
        index: dict[str, dict] = {}
        for tag, codes in tag_codes.items():
            values = tag_dicts[tag]
            masks: dict[str, int] = {}
            codes = np.asarray(codes)
            for rg in range(n_groups):
                chunk = codes[rg * row_group_size:(rg + 1) * row_group_size]
                for code in np.unique(chunk):
                    if code < 0:
                        key = None  # NULL
                    else:
                        key = str(values[code])
                    k = "\x00null" if key is None else key
                    masks[k] = masks.get(k, 0) | (1 << rg)
            index[tag] = {"masks": masks}
        self.store.write(self.path(file_id),
                         json.dumps({"n_groups": n_groups, "tags": index}).encode())

    def delete(self, file_id: str) -> None:
        self.store.delete(self.path(file_id))


class IndexApplier:
    """Evaluate tag predicates against a file's index.

    `predicates`: tag name -> set of allowed values (from conjunctive
    eq/IN clauses). Returns the allowed row-group indices, or None when the
    file has no index (scan everything), or [] when provably empty.
    """

    def __init__(self, sst_dir: str, store=None):
        self.sst_dir = sst_dir
        self.store = default_store(store)
        self._cache: dict[str, Optional[dict]] = {}

    def _load(self, file_id: str) -> Optional[dict]:
        if file_id in self._cache:
            return self._cache[file_id]
        path = os.path.join(self.sst_dir, f"{file_id}.idx.json")
        data = None
        if self.store.exists(path):
            data = json.loads(self.store.read(path).decode())
        self._cache[file_id] = data
        return data

    def apply(
        self, file_id: str, predicates: dict[str, set]
    ) -> Optional[list[int]]:
        data = self._load(file_id)
        if data is None or not predicates:
            return None
        n_groups = data["n_groups"]
        allowed = (1 << n_groups) - 1
        for tag, values in predicates.items():
            tag_index = data["tags"].get(tag)
            if tag_index is None:
                continue  # tag not indexed in this file
            mask = 0
            for v in values:
                mask |= tag_index["masks"].get(str(v), 0)
            allowed &= mask
            if allowed == 0:
                return []
        if allowed == (1 << n_groups) - 1:
            return None  # nothing pruned
        return [rg for rg in range(n_groups) if allowed & (1 << rg)]

    def invalidate(self, file_id: str) -> None:
        self._cache.pop(file_id, None)


def extract_tag_predicates(where, schema) -> dict[str, set]:
    """Conservatively extract `tag = 'v'` / `tag IN (...)` constraints from
    the top-level conjunction of a raw (pre-bind) WHERE AST. Anything not
    provably restrictive is ignored — pruning must never drop rows.
    """
    from greptimedb_tpu.sql import ast

    tags = {c.name for c in schema.tag_columns}
    out: dict[str, set] = {}

    def walk(e):
        if isinstance(e, ast.BinaryOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if isinstance(e, ast.BinaryOp) and e.op == "=":
            l, r = e.left, e.right
            if isinstance(r, ast.Column) and isinstance(l, ast.Literal):
                l, r = r, l
            if (
                isinstance(l, ast.Column)
                and l.name in tags
                and isinstance(r, ast.Literal)
            ):
                out.setdefault(l.name, set()).add(str(r.value))
            return
        if (
            isinstance(e, ast.InList)
            and not e.negated
            and isinstance(e.expr, ast.Column)
            and e.expr.name in tags
            and all(isinstance(i, ast.Literal) for i in e.items)
        ):
            out.setdefault(e.expr.name, set()).update(str(i.value) for i in e.items)

    if where is not None:
        walk(where)
    return out
