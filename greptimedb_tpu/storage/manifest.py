"""Region manifest: a JSON action log with periodic checkpoints.

Mirrors the reference's manifest manager (mito2/src/manifest/manager.rs:40-42,
action.rs): every mutation of the region's file set / metadata is an action
appended as `<version>.json`; every `checkpoint_distance` actions a full
`RegionCheckpoint` is written and older deltas are pruned. Region open
replays checkpoint + deltas (region/opener.rs:62-117), then the WAL.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Optional

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.objectstore import default_store
from greptimedb_tpu.storage.sst import FileMeta

CHECKPOINT_DISTANCE = 10
_DELTA_RE = re.compile(r"^(\d{10})\.json$")


@dataclass
class RegionManifestState:
    """Replayed manifest state."""

    schema: Optional[Schema] = None
    files: dict[str, FileMeta] = field(default_factory=dict)
    flushed_seq: int = 0  # WAL entries below this are obsolete
    manifest_version: int = 0
    tag_dicts: dict[str, list] = field(default_factory=dict)

    def apply(self, action: dict) -> None:
        from greptimedb_tpu.storage.format import FORMAT_VERSIONS, FormatError

        # absent stamp = v1 (pre-versioning rounds); newer than this
        # build understands must refuse, not misinterpret
        fmt = action.get("format", 1)
        if fmt > FORMAT_VERSIONS["manifest"]:
            raise FormatError(
                f"manifest action format v{fmt}; this build reads "
                f"<= v{FORMAT_VERSIONS['manifest']}")
        kind = action["kind"]
        if kind == "change":
            self.schema = Schema.from_dict(action["schema"])
        elif kind == "edit":
            for f in action.get("files_to_add", []):
                fm = FileMeta.from_dict(f)
                self.files[fm.file_id] = fm
            for fid in action.get("files_to_remove", []):
                self.files.pop(fid, None)
            if action.get("flushed_seq") is not None:
                self.flushed_seq = max(self.flushed_seq, action["flushed_seq"])
            if action.get("tag_dicts") is not None:
                self.tag_dicts = action["tag_dicts"]
        elif kind == "truncate":
            self.files.clear()
            self.flushed_seq = max(self.flushed_seq, action.get("truncated_seq", self.flushed_seq))
        elif kind == "checkpoint":
            self.schema = Schema.from_dict(action["schema"]) if action.get("schema") else None
            self.files = {f["file_id"]: FileMeta.from_dict(f) for f in action["files"]}
            self.flushed_seq = action["flushed_seq"]
            self.tag_dicts = action.get("tag_dicts", {})
        else:
            raise ValueError(f"unknown manifest action {kind!r}")


class ManifestManager:
    def __init__(self, manifest_dir: str, store=None):
        self.dir = manifest_dir
        self.store = default_store(store)
        self.state = RegionManifestState()
        self._replay()

    # ---- replay ------------------------------------------------------------

    def _versions(self) -> list[int]:
        out = []
        for key in self.store.list(self.dir + os.sep):
            m = _DELTA_RE.match(os.path.basename(key))
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _replay(self) -> None:
        for v in self._versions():
            action = json.loads(self.store.read(self._path(v)).decode())
            self.state.apply(action)
            self.state.manifest_version = v

    def _path(self, version: int) -> str:
        return os.path.join(self.dir, f"{version:010d}.json")

    # ---- append ------------------------------------------------------------

    def append(self, action: dict) -> None:
        from greptimedb_tpu.storage.format import FORMAT_VERSIONS

        action.setdefault("format", FORMAT_VERSIONS["manifest"])
        v = self.state.manifest_version + 1
        # FsStore.write is atomic (tmp + rename)
        self.store.write(self._path(v), json.dumps(action).encode())
        self.state.apply(action)
        self.state.manifest_version = v
        if v % CHECKPOINT_DISTANCE == 0:
            self._checkpoint()

    def _checkpoint(self) -> None:
        from greptimedb_tpu.storage.format import FORMAT_VERSIONS

        st = self.state
        action = {
            "format": FORMAT_VERSIONS["manifest"],
            "kind": "checkpoint",
            "schema": st.schema.to_dict() if st.schema else None,
            "files": [f.to_dict() for f in st.files.values()],
            "flushed_seq": st.flushed_seq,
            "tag_dicts": st.tag_dicts,
        }
        v = st.manifest_version + 1
        self.store.write(self._path(v), json.dumps(action).encode())
        st.manifest_version = v
        # prune deltas older than the checkpoint
        for old in self._versions():
            if old < v:
                self.store.delete(self._path(old))

    # ---- convenience -------------------------------------------------------

    def record_schema(self, schema: Schema) -> None:
        self.append({"kind": "change", "schema": schema.to_dict()})

    def record_flush(
        self,
        added: list[FileMeta],
        flushed_seq: Optional[int],
        tag_dicts: dict[str, list],
        removed: Optional[list[str]] = None,
    ) -> None:
        """Record a file-set edit. `flushed_seq` must be None unless the
        memtable was actually persisted up to that sequence — replay
        skips WAL entries below it, so a compaction/expiry edit passing
        next_seq here would silently drop unflushed acknowledged writes
        on the next open."""
        self.append(
            {
                "kind": "edit",
                "files_to_add": [f.to_dict() for f in added],
                "files_to_remove": removed or [],
                "flushed_seq": flushed_seq,
                "tag_dicts": tag_dicts,
            }
        )

    def record_truncate(self, truncated_seq: int) -> None:
        self.append({"kind": "truncate", "truncated_seq": truncated_seq})
