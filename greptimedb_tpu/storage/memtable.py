"""Append-log columnar memtable.

TPU-first re-design of the reference's `TimeSeriesMemtable`
(mito2/src/memtable/time_series.rs:82, BTreeMap of memcomparable keys →
per-series buffers): here the memtable is an *unsorted append log* of
column chunks with tags dictionary-encoded against the region's tag
registry. There is no per-write tree maintenance — ordering and
last-write-wins dedup happen in the device sort-dedup kernel at scan/flush
time (ops/dedup.py), which is both cheaper on ingest and exactly the shape
the TPU wants.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.datatypes.types import SemanticType
from greptimedb_tpu.datatypes.vector import DictVector


class TagRegistry:
    """Region-global dictionary per tag column: value -> dense int32 code.

    The analog of mito's primary-key dictionary (sst/parquet/format.rs),
    kept per-tag so kernels get dense per-tag codes. Codes are stable for
    the lifetime of the region (append-only)."""

    def __init__(self, tag_names: list[str]):
        import threading

        self.tables: dict[str, dict] = {n: {} for n in tag_names}
        self.values: dict[str, list] = {n: [] for n in tag_names}
        # encode() is reached from BOTH the write path (region lock held)
        # and scan-time SST dictionary remapping (no region lock, by
        # design): the registry guards itself
        self._lock = threading.Lock()

    def encode(self, name: str, strings: np.ndarray) -> np.ndarray:
        """Vectorized: unique the batch (O(n log n) in C), then walk only
        the (small) set of distinct values through the dictionary."""
        arr = np.asarray(strings, dtype=object)
        null_mask = np.frompyfunc(lambda x: x is None, 1, 1)(arr).astype(bool)
        codes = np.full(len(arr), -1, dtype=np.int32)
        present = ~null_mask
        if present.any():
            uniq, inv = np.unique(arr[present].astype(str), return_inverse=True)
            mapping = np.empty(len(uniq), dtype=np.int32)
            with self._lock:
                table = self.tables[name]
                vals = self.values[name]
                for i, s in enumerate(uniq):
                    c = table.get(s)
                    if c is None:
                        c = len(vals)
                        table[s] = c
                        vals.append(s)
                    mapping[i] = c
            codes[present] = mapping[inv]
        return codes

    def remap_dict(self, name: str, file_values: np.ndarray) -> np.ndarray:
        """Mapping array old_code->region_code for a file-local dictionary."""
        return self.encode(name, file_values)

    def dict_array(self, name: str) -> np.ndarray:
        with self._lock:
            return np.asarray(self.values[name], dtype=object)

    def cardinality(self, name: str) -> int:
        with self._lock:
            return len(self.values[name])

    def snapshot(self) -> dict[str, list]:
        with self._lock:
            return {k: list(v) for k, v in self.values.items()}


@dataclass
class MemtableChunk:
    columns: dict[str, np.ndarray]  # tags as int32 codes; ts int64; fields raw
    seq: np.ndarray  # int64 per-row write sequence
    op_type: np.ndarray  # int8


class Memtable:
    def __init__(self, schema: Schema, registry: TagRegistry):
        self.schema = schema
        self.registry = registry
        self.chunks: list[MemtableChunk] = []
        self.num_rows = 0
        self.bytes_estimate = 0
        self.ts_min: Optional[int] = None
        self.ts_max: Optional[int] = None
        # newest write sequence held (rollup staleness checks compare
        # this against a job's as_of_seq; -1 = empty)
        self.max_seq: int = -1

    def write(self, batch: RecordBatch, seq_start: int, op_type: int) -> int:
        """Append a batch; returns the number of rows written. Tags are
        re-encoded against the region registry here (the only host-side
        per-row work on the ingest path)."""
        n = batch.num_rows
        if n == 0:
            return 0
        cols: dict[str, np.ndarray] = {}
        for c in self.schema.columns:
            col = batch.columns[c.name]
            if c.semantic is SemanticType.TAG:
                if isinstance(col, DictVector):
                    from greptimedb_tpu.datatypes.vector import remap_codes

                    mapping = self.registry.remap_dict(c.name, col.values)
                    cols[c.name] = remap_codes(col.codes, mapping)
                else:
                    cols[c.name] = self.registry.encode(c.name, np.asarray(col, dtype=object))
            elif isinstance(col, DictVector):
                # non-tag string field: store decoded (no region dictionary)
                cols[c.name] = col.decode()
            else:
                cols[c.name] = np.asarray(col)
        chunk = MemtableChunk(
            columns=cols,
            seq=np.arange(seq_start, seq_start + n, dtype=np.int64),
            op_type=np.full(n, op_type, dtype=np.int8),
        )
        self.chunks.append(chunk)
        self.num_rows += n
        self.bytes_estimate += sum(a.nbytes if a.dtype != object else a.nbytes * 8 for a in cols.values())
        ts = cols[self.schema.time_index.name]
        lo, hi = int(ts.min()), int(ts.max())
        self.ts_min = lo if self.ts_min is None else min(self.ts_min, lo)
        self.ts_max = hi if self.ts_max is None else max(self.ts_max, hi)
        self.max_seq = max(self.max_seq, seq_start + n - 1)
        return n

    def is_empty(self) -> bool:
        return self.num_rows == 0

    def concat(self, ts_range: Optional[tuple[int, int]] = None):
        """Concatenate chunks (optionally pre-filtered by a coarse time
        range) → (columns, seq, op_type) numpy arrays."""
        if not self.chunks:
            return None
        if ts_range is not None and self.ts_min is not None:
            if self.ts_max < ts_range[0] or self.ts_min >= ts_range[1]:
                return None
        names = self.schema.names
        cols = {n: np.concatenate([c.columns[n] for c in self.chunks]) for n in names}
        seq = np.concatenate([c.seq for c in self.chunks])
        op = np.concatenate([c.op_type for c in self.chunks])
        return cols, seq, op
