"""Merge per-region scans into one columnar scan — the MergeScan gather.

Mirrors reference src/query/src/dist_plan/merge_scan.rs:122-259: the
frontend gathers each region's stream and concatenates. TPU-native twist:
instead of streaming ragged batches, we concatenate whole columnar scans on
the host and remap each region's tag dictionary codes into a union
dictionary with one vectorized searchsorted pass — the result feeds the same
fused device kernels as a single-region scan. (Partial-aggregate pushdown —
the Commutativity analysis — happens above this layer: when the plan is a
pure segment aggregation, per-region partials combine on the mesh instead,
greptimedb_tpu/parallel/mesh.py.)
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.storage.region import ScanData


def merge_scans(parts: list[ScanData]) -> ScanData | None:
    parts = [p for p in parts if p is not None and p.num_rows > 0]
    if not parts:
        return None
    if len(parts) == 1:
        return parts[0]
    schema = parts[0].schema

    # union tag dictionaries + code remap per region
    tag_names = list(parts[0].tag_dicts.keys())
    union_dicts: dict[str, np.ndarray] = {}
    remaps: list[dict[str, np.ndarray]] = [dict() for _ in parts]
    for name in tag_names:
        all_vals = np.concatenate([p.tag_dicts[name] for p in parts])
        union = np.unique(all_vals.astype(str))
        union_dicts[name] = union
        for i, p in enumerate(parts):
            local = p.tag_dicts[name].astype(str)
            remaps[i][name] = np.searchsorted(union, local).astype(np.int32)

    columns: dict[str, np.ndarray] = {}
    for cname in parts[0].columns:
        if cname in union_dicts:
            mapped = []
            for i, p in enumerate(parts):
                codes = p.columns[cname]
                remap = remaps[i][cname]
                out = np.where(codes >= 0, remap[np.clip(codes, 0, None)], -1)
                mapped.append(out.astype(np.int32))
            columns[cname] = np.concatenate(mapped)
        else:
            columns[cname] = np.concatenate([p.columns[cname] for p in parts])

    # sequences are per-region counters; partitioned tables have disjoint
    # keys across regions so cross-region LWW never arises — keep seqs as-is
    seq = np.concatenate([p.seq for p in parts])
    op_type = np.concatenate([p.op_type for p in parts])
    return ScanData(
        schema=schema,
        columns=columns,
        seq=seq,
        op_type=op_type,
        tag_dicts=union_dicts,
        num_rows=int(sum(p.num_rows for p in parts)),
        needs_dedup=any(p.needs_dedup for p in parts),
        region_id=-1,
        data_version=0,
        scan_fingerprint=tuple(
            (p.region_id, p.data_version, p.scan_fingerprint) for p in parts
        ),
    )
