"""Metric engine: many logical tables over one physical region.

Mirrors reference src/metric-engine (engine.rs:57-98): Prometheus workloads
create one table per metric — thousands to millions of tiny tables — which
would drown a region-per-table design. The reference multiplexes logical
tables onto one physical mito region pair (data + metadata).

TPU-native re-design: the physical data region stores exactly two tag
columns — `__table` (logical table name) and `__labels` (the canonical
serialized label set, i.e. THE SERIES ID as one dictionary code) — plus
`greptime_timestamp` / `greptime_value`. Logical tag columns are virtual:
at scan time each distinct label-set value is parsed once (dictionary-sized
work, not row-sized) and per-tag code columns are derived by mapping label-
set codes through a small lookup table — a single numpy gather. This keeps
the device kernel ABI identical to normal tables while the storage side
collapses arbitrary table counts into one LSM region.

Logical table metadata (the reference's metadata region) lives in the kv
backend under `__metric_engine/`.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from greptimedb_tpu.catalog.kv import KvBackend
from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
from greptimedb_tpu.datatypes.types import DataType, SemanticType
from greptimedb_tpu.datatypes.vector import DictVector
from greptimedb_tpu.storage.engine import RegionEngine
from greptimedb_tpu.storage.region import ScanData

TABLE_COL = "__table"
LABELS_COL = "__labels"
TS_COL = "greptime_timestamp"
VALUE_COL = "greptime_value"

META_PREFIX = "__metric_engine/"


def physical_schema() -> Schema:
    return Schema([
        ColumnSchema(TABLE_COL, DataType.STRING, SemanticType.TAG),
        ColumnSchema(LABELS_COL, DataType.STRING, SemanticType.TAG),
        ColumnSchema(TS_COL, DataType.TIMESTAMP_MILLISECOND,
                     SemanticType.TIMESTAMP, nullable=False),
        ColumnSchema(VALUE_COL, DataType.FLOAT64, SemanticType.FIELD),
    ])


def encode_labels(tags: dict[str, Optional[str]]) -> str:
    """Canonical series encoding: sorted k=v pairs, \\x1f-separated (tag
    values may contain commas; \\x1f cannot appear in Prometheus labels)."""
    items = sorted((k, v) for k, v in tags.items() if v is not None)
    return "\x1f".join(f"{k}={v}" for k, v in items)


def decode_labels(s: str) -> dict[str, str]:
    if not s:
        return {}
    out = {}
    for part in s.split("\x1f"):
        k, _, v = part.partition("=")
        out[k] = v
    return out


@dataclass
class LogicalTableMeta:
    name: str
    tag_names: list[str]
    physical_region: int
    logical_region: int
    ts_name: str = TS_COL
    value_name: str = VALUE_COL

    def to_json(self) -> str:
        return json.dumps(self.__dict__)

    @staticmethod
    def from_json(s: str) -> "LogicalTableMeta":
        return LogicalTableMeta(**json.loads(s))


class LogicalRegion:
    """Region-shaped view of one logical table over the physical region.

    Registered in the RegionEngine's region map under the logical region id
    so the entire query path (scan/put/flush) works unchanged."""

    def __init__(self, meta: LogicalTableMeta, engine: RegionEngine):
        self.meta = meta
        self.engine = engine
        self.region_id = meta.logical_region
        self.schema = logical_schema(meta.tag_names, meta.ts_name, meta.value_name)

    # -- write: logical batch -> physical rows --
    def write(self, batch: RecordBatch, op: int) -> int:
        phys = self.engine.region(self.meta.physical_region)
        n = batch.num_rows
        tag_cols = {}
        for t in self.meta.tag_names:
            col = batch.columns.get(t)
            tag_cols[t] = (
                col.decode() if isinstance(col, DictVector) else
                (np.asarray(col) if col is not None else np.full(n, None, dtype=object))
            )
        labels = []
        for i in range(n):
            labels.append(encode_labels(
                {t: (None if tag_cols[t][i] is None else str(tag_cols[t][i]))
                 for t in self.meta.tag_names}
            ))
        cols = {
            TABLE_COL: DictVector.encode([self.meta.name] * n),
            LABELS_COL: DictVector.encode(labels),
            TS_COL: np.asarray(batch.columns[self.meta.ts_name], dtype=np.int64),
            VALUE_COL: np.asarray(batch.columns[self.meta.value_name],
                                  dtype=np.float64),
        }
        written = phys.write(RecordBatch(physical_schema(), cols), op)
        if phys.memtable_bytes >= self.engine.config.flush_threshold_bytes:
            phys.flush()
            phys.compact()
        return written

    @property
    def memtable_bytes(self) -> int:
        return 0  # flush policy is owned by the physical region

    @property
    def registry(self):
        return _VirtualRegistry(self)

    @property
    def data_version(self) -> int:
        return self.engine.region(self.meta.physical_region).data_version

    def flush(self):
        self.engine.region(self.meta.physical_region).flush()

    def compact(self, strategy: str = "twcs"):
        return self.engine.region(self.meta.physical_region).compact(strategy)

    def drop(self):
        pass  # logical drop = metadata removal; physical data is shared

    # -- scan: physical rows -> virtual logical columns --
    def scan(
        self,
        ts_range: Optional[tuple[int, int]] = None,
        projection: Optional[Sequence[str]] = None,
        tag_predicates: Optional[dict[str, set]] = None,
        seq_min: Optional[int] = None,
    ) -> Optional[ScanData]:
        if seq_min is not None:
            # logical regions share a physical region: a sequence
            # boundary over the shared store is not table-scoped, so
            # incremental consumers must fall back to full scans
            raise NotImplementedError(
                "seq_min scans are not supported on metric-engine "
                "logical regions")
        phys = self.engine.region(self.meta.physical_region)
        # push the table selector down; label predicates are mapped to
        # label-set values that contain the wanted pair (dictionary-sized)
        phys_preds: dict[str, set] = {TABLE_COL: {self.meta.name}}
        scan = phys.scan(ts_range, None, phys_preds)
        if scan is None:
            return None
        table_dict = scan.tag_dicts[TABLE_COL]
        tcodes = np.where(np.asarray(table_dict).astype(str) == self.meta.name)[0]
        if len(tcodes) == 0:
            return None
        mask = scan.columns[TABLE_COL] == tcodes[0]
        if not mask.any():
            return None
        idx = np.nonzero(mask)[0]
        labels_dict = np.asarray(scan.tag_dicts[LABELS_COL]).astype(str)
        label_codes = scan.columns[LABELS_COL][idx]
        # dictionary-sized parse: label-set value -> per-tag value
        parsed = [decode_labels(v) for v in labels_dict]
        columns: dict[str, np.ndarray] = {}
        tag_dicts: dict[str, np.ndarray] = {}
        names = projection or self.schema.names
        # all tags always materialize (dedup needs the full primary key,
        # Region._scan_columns invariant); each is one dictionary-sized
        # parse + one numpy gather
        for t in self.meta.tag_names:
            per_set = np.asarray([p.get(t) for p in parsed], dtype=object)
            present = np.asarray([v for v in per_set if v is not None], dtype=object)
            uniq = np.unique(present.astype(str)) if len(present) else np.asarray([], dtype=object)
            lookup = {v: i for i, v in enumerate(uniq)}
            remap = np.asarray(
                [(-1 if v is None else lookup[str(v)]) for v in per_set],
                dtype=np.int32,
            )
            columns[t] = remap[label_codes]
            tag_dicts[t] = uniq.astype(object)
        columns[self.meta.ts_name] = scan.columns[TS_COL][idx]
        if self.meta.value_name in names:
            columns[self.meta.value_name] = scan.columns[VALUE_COL][idx]
        # series key for dedup: the label-set code itself (denser and
        # cheaper than re-combining the virtual tags)
        return ScanData(
            schema=self.schema,
            columns=columns,
            seq=scan.seq[idx],
            op_type=scan.op_type[idx],
            tag_dicts=tag_dicts,
            num_rows=int(len(idx)),
            needs_dedup=scan.needs_dedup,
            region_id=self.region_id,
            data_version=scan.data_version,
            scan_fingerprint=("metric", self.meta.name, ts_range,
                              tuple(names or ()), scan.scan_fingerprint),
        )


class _VirtualRegistry:
    """Registry-shaped accessor for label values (HTTP label-values API)."""

    def __init__(self, region: LogicalRegion):
        self._region = region

    @property
    def values(self) -> dict[str, list[str]]:
        scan = self._region.scan()
        if scan is None:
            return {t: [] for t in self._region.meta.tag_names}
        return {t: list(v) for t, v in scan.tag_dicts.items()}


def logical_schema(tag_names: list[str], ts_name: str = TS_COL,
                   value_name: str = VALUE_COL) -> Schema:
    cols = [ColumnSchema(t, DataType.STRING, SemanticType.TAG) for t in tag_names]
    cols.append(ColumnSchema(ts_name, DataType.TIMESTAMP_MILLISECOND,
                             SemanticType.TIMESTAMP, nullable=False))
    cols.append(ColumnSchema(value_name, DataType.FLOAT64, SemanticType.FIELD))
    return Schema(cols)


class MetricEngine:
    """Logical-table multiplexer over a RegionEngine (engine.rs:57-98)."""

    def __init__(self, engine: RegionEngine, kv: KvBackend):
        self.engine = engine
        self.kv = kv
        self.engine.register_opener(self._open_logical)

    # physical region management: one data region per (db) group
    def _physical_region_id(self, db: str) -> int:
        key = f"{META_PREFIX}physical/{db}"
        existing = self.kv.get(key)
        if existing is not None:
            return int(existing)
        rid = (0x7FFF0000 << 32) | (self.kv.incr(META_PREFIX + "physical_seq") & 0xFFFFFFFF)
        if not self.kv.compare_and_put(key, None, str(rid)):
            return int(self.kv.get(key))
        return rid

    def create_logical_table(
        self, db: str, name: str, tag_names: list[str],
        ts_name: str = TS_COL, value_name: str = VALUE_COL,
    ) -> LogicalTableMeta:
        phys_rid = self._physical_region_id(db)
        try:
            self.engine.region(phys_rid)
        except KeyError:
            try:
                self.engine.open_region(phys_rid)
            except FileNotFoundError:
                self.engine.create_region(phys_rid, physical_schema())
        logical_rid = (0x7FFE0000 << 32) | (self.kv.incr(META_PREFIX + "logical_seq") & 0xFFFFFFFF)
        meta = LogicalTableMeta(
            name=name, tag_names=sorted(tag_names),
            physical_region=phys_rid, logical_region=logical_rid,
            ts_name=ts_name, value_name=value_name,
        )
        self.kv.put(f"{META_PREFIX}table/{db}/{name}", meta.to_json())
        self.kv.put(f"{META_PREFIX}region/{logical_rid}", meta.to_json())
        self.engine.regions[logical_rid] = LogicalRegion(meta, self.engine)
        return meta

    def drop_logical_table(self, db: str, name: str) -> None:
        raw = self.kv.get(f"{META_PREFIX}table/{db}/{name}")
        if raw is None:
            return
        meta = LogicalTableMeta.from_json(raw)
        self.kv.delete(f"{META_PREFIX}table/{db}/{name}")
        self.kv.delete(f"{META_PREFIX}region/{meta.logical_region}")
        self.engine.regions.pop(meta.logical_region, None)

    def list_logical_tables(self, db: str) -> list[str]:
        prefix = f"{META_PREFIX}table/{db}/"
        return [k[len(prefix):] for k, _ in self.kv.range(prefix)]

    def _open_logical(self, region_id: int):
        """Opener hook: rebuild a LogicalRegion from kv metadata when the
        engine is asked to open a logical region id (e.g. after restart)."""
        raw = self.kv.get(f"{META_PREFIX}region/{region_id}")
        if raw is None:
            return None
        meta = LogicalTableMeta.from_json(raw)
        try:
            self.engine.region(meta.physical_region)
        except KeyError:
            self.engine.open_region(meta.physical_region)
        return LogicalRegion(meta, self.engine)
