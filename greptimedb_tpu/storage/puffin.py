"""Puffin-style blob container for index files.

Mirrors the reference's `src/puffin` crate (file_format/: magic + blobs +
footer holding per-blob metadata; partial_reader/ for range reads): a single
container file stores any number of typed binary blobs next to an SST, and a
reader can fetch one blob without parsing the rest.

Layout (little-endian):

    magic  b"GTPF1\\n"                       (6 bytes)
    blob_0 .. blob_{n-1}                     (raw bytes, concatenated)
    footer JSON utf-8                        (variable)
    footer_len u32                           (4 bytes)
    magic  b"GTPF"                           (4 bytes)

Footer JSON: {"blobs": [{"type": str, "offset": int, "length": int,
"properties": {...}}, ...], "properties": {...}}. Offsets are absolute so
a reader seeks straight to a blob (reference partial_reader analog).
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field

HEAD_MAGIC = b"GTPF1\n"
TAIL_MAGIC = b"GTPF"


class PuffinError(Exception):
    pass


@dataclass
class BlobEntry:
    type: str
    offset: int
    length: int
    properties: dict = field(default_factory=dict)


class PuffinWriter:
    """Accumulates blobs in memory, then writes one container object.

    Index payloads are bounded (dictionary-sized, not data-sized), so a
    buffered build matches how the storage layer writes every other object
    (SSTs are staged the same way before `store.write`).
    """

    def __init__(self, properties: dict | None = None):
        self._parts: list[bytes] = []
        self._entries: list[BlobEntry] = []
        self._pos = len(HEAD_MAGIC)
        self.properties = dict(properties or {})

    def add_blob(self, blob_type: str, data: bytes,
                 properties: dict | None = None) -> None:
        self._entries.append(
            BlobEntry(blob_type, self._pos, len(data), dict(properties or {})))
        self._parts.append(data)
        self._pos += len(data)

    def finish(self) -> bytes:
        footer = json.dumps({
            "blobs": [
                {"type": e.type, "offset": e.offset, "length": e.length,
                 "properties": e.properties}
                for e in self._entries
            ],
            "properties": self.properties,
        }).encode()
        return b"".join([HEAD_MAGIC, *self._parts, footer,
                         struct.pack("<I", len(footer)), TAIL_MAGIC])


class PuffinReader:
    """Reads the footer once, then serves per-blob range reads from a
    seekable input (ObjectStore.open_input)."""

    def __init__(self, fobj):
        self._f = fobj
        fobj.seek(0, 2)
        size = fobj.tell()
        if size < len(HEAD_MAGIC) + 8:
            raise PuffinError("file too small for a puffin container")
        fobj.seek(size - 8)
        tail = fobj.read(8)
        footer_len = struct.unpack("<I", tail[:4])[0]
        if tail[4:] != TAIL_MAGIC:
            raise PuffinError("bad tail magic")
        footer_start = size - 8 - footer_len
        if footer_start < len(HEAD_MAGIC):
            raise PuffinError("footer overlaps header")
        fobj.seek(footer_start)
        meta = json.loads(fobj.read(footer_len).decode())
        fobj.seek(0)
        if fobj.read(len(HEAD_MAGIC)) != HEAD_MAGIC:
            raise PuffinError("bad head magic")
        self.blobs = [
            BlobEntry(b["type"], b["offset"], b["length"],
                      b.get("properties", {}))
            for b in meta.get("blobs", [])
        ]
        self.properties = meta.get("properties", {})

    def blobs_of_type(self, blob_type: str) -> list[BlobEntry]:
        return [b for b in self.blobs if b.type == blob_type]

    def read_blob(self, entry: BlobEntry) -> bytes:
        self._f.seek(entry.offset)
        data = self._f.read(entry.length)
        if len(data) != entry.length:
            raise PuffinError(
                f"short read: wanted {entry.length}, got {len(data)}")
        return data
