"""Region: one LSM instance (mirrors reference `MitoRegion` +
`VersionControl`, mito2/src/region/version.rs:83-138).

Write path (reference worker/handle_write.rs:34): WAL append is the
durability boundary, then the memtable ingests and the committed sequence
advances. Scan path (reference read/scan_region.rs:148-279): collect
memtable chunks + SSTs overlapping the time predicate, remap file-local tag
dictionaries into the region registry, and hand the concatenated columns to
the device tier — sort-dedup and aggregation happen in kernels, not here.
Flush (worker/handle_flush.rs:34-170): memtable → sorted SST, manifest
edit, WAL truncation.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np
import pyarrow as pa

from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.datatypes.types import SemanticType
from greptimedb_tpu.datatypes.vector import DictVector
from greptimedb_tpu.storage.manifest import ManifestManager
from greptimedb_tpu.storage.memtable import Memtable, TagRegistry
from greptimedb_tpu.storage.sst import OP_COL, SEQ_COL, FileMeta, SstReader, SstWriter
from greptimedb_tpu.storage.wal import Wal
from greptimedb_tpu.utils import deadline as dl

OP_PUT = 0
OP_DELETE = 1


class RegionDroppedError(RuntimeError):
    """Write raced a DROP: the region is gone; the write did not happen."""


@dataclass
class _PartEntry:
    """One per-file decoded scan part: `part` is (cols, seq, op) for the
    rows an SST contributes under a (ts_range, names, predicates) shape,
    or None when the file prunes to nothing under that shape (cached too
    — re-proving emptiness costs a parquet footer read)."""

    part: Optional[tuple]
    nbytes: int


def _scan_nbytes(sd: "ScanData") -> int:
    """Host bytes a whole-scan snapshot holds (column arrays + seq/op).
    Object columns undercount their string payload — the budget errs
    permissive there, like the part cache does."""
    n = 0
    for v in sd.columns.values():
        if isinstance(v, np.ndarray):
            n += v.nbytes
    if isinstance(sd.seq, np.ndarray):
        n += sd.seq.nbytes
    if isinstance(sd.op_type, np.ndarray):
        n += sd.op_type.nbytes
    return n


def _part_nbytes(part: Optional[tuple]) -> int:
    if part is None:
        return 64  # bookkeeping floor for cached pruned-empty entries
    cols, seq, op = part
    return sum(int(a.nbytes) for a in cols.values()) \
        + int(seq.nbytes) + int(op.nbytes)


@dataclass
class ScanData:
    """Host-side scan output: concatenated columns ready for device blocks.

    Tags are int32 codes against `tag_dicts`; rows are NOT yet deduplicated
    or exactly time-filtered — `seq`/`op_type` ride along so the device
    sort-dedup kernel can apply last-write-wins + tombstones (the analog of
    the reference's MergeReader output contract, read.rs:59-73)."""

    schema: Schema
    columns: dict[str, np.ndarray]
    seq: np.ndarray
    op_type: np.ndarray
    tag_dicts: dict[str, np.ndarray]
    num_rows: int
    needs_dedup: bool = True
    # identity for the device block cache: (region_id, incarnation,
    # data_version, scan_fingerprint) names an immutable column snapshot.
    # incarnation is the owning Region INSTANCE's id: TRUNCATE recreates
    # the region and resets data_version, so version alone could collide
    # with a pre-truncate snapshot (0 = unknown/remote/synthetic)
    region_id: int = -1
    data_version: int = 0
    incarnation: int = 0
    scan_fingerprint: tuple = ()
    # row offsets of the per-SST sorted segments inside `columns`:
    # rows [offsets[i], offsets[i+1]) are one flushed file's rows, sorted
    # by (tags..., ts, seq) (see Region._sort_order); rows past offsets[-1]
    # come from the memtable in arbitrary order. Lets first/last-class
    # aggregates gather per-series boundary rows instead of reducing the
    # whole scan (reference exploits the same order via per-file
    # last-row semantics in its merge reader, mito2/src/read/merge.rs).
    # () means "no sortedness information" (merged/remote scans).
    sorted_part_offsets: tuple = ()
    # per-SST-part identity aligned with sorted_part_offsets' segments:
    # (file_id, ts_range, pred_key) per contributing file, in row order.
    # The device hot set keys HBM column blocks by this, so a part's
    # uploads survive data-version bumps for the life of its file
    # (rows past offsets[-1] are memtable and carry no part identity).
    # () = no per-part identity (merged/synthetic/seq-sliced scans).
    part_keys: tuple = ()
    # observability: how this snapshot was built (ssts considered /
    # pruned, scan-cache reuse count) — piggybacked on the region wire
    # protocol so distributed EXPLAIN ANALYZE shows datanode-side IO.
    # None for synthetic/merged scans. Mutated only under the region
    # lock (cache_hits bumps on each cached reuse).
    stats: Optional[dict] = None

    @property
    def tag_cardinalities(self) -> dict[str, int]:
        return {k: len(v) for k, v in self.tag_dicts.items()}


@dataclass
class ScanStream:
    """Lazy scan: metadata upfront, columns delivered as bounded chunks
    (reference streams lazy row groups with a page cache,
    sst/parquet/row_group.rs + reader.rs:335-447; here each chunk becomes
    one padded device block, so host memory stays flat regardless of scan
    size). Tag dictionaries come from the region's registry — complete
    without touching the data. Only append-mode (no-dedup) scans stream;
    last-write-wins needs the whole scan in one sort."""

    schema: Schema
    tag_dicts: dict[str, np.ndarray]
    region_id: int
    data_version: int
    est_rows: int
    ts_min: int  # over the pruned file set + memtable (chunk key planning)
    ts_max: int
    _chunks: object  # () -> Iterator[(cols dict, nrows)]
    _close: object = None  # idempotent; releases file pins
    incarnation: int = 0  # owning Region instance id (see ScanData)

    def chunks(self):
        return self._chunks()

    def close(self):
        """Release the snapshot's SST file pins. Idempotent, and required
        whenever the stream is abandoned before (or instead of) being
        iterated — a never-started generator's finally never runs."""
        if self._close is not None:
            self._close()


#: process-wide Region instance ids — TRUNCATE recreates a region with
#: the same region_id and a reset data_version, so snapshot identity
#: (device/snap cache keys) must also carry WHICH instance produced it
_REGION_INCARNATIONS = itertools.count(1)


class Region:
    def __init__(self, region_id: int, region_dir: str, schema: Schema, wal: Wal,
                 store=None, manifest: "ManifestManager" = None):
        self.region_id = region_id
        self.incarnation = next(_REGION_INCARNATIONS)
        self.region_dir = region_dir
        self.schema = schema
        self.wal = wal
        self.store = store
        self.manifest = manifest if manifest is not None else \
            ManifestManager(os.path.join(region_dir, "manifest"), store)
        self.sst_writer = SstWriter(os.path.join(region_dir, "sst"), schema,
                                    store=store)
        self.sst_reader = SstReader(os.path.join(region_dir, "sst"), store)
        tag_names = [c.name for c in schema.tag_columns]
        self.registry = TagRegistry(tag_names)
        self.memtable = Memtable(schema, self.registry)
        self.next_seq = 0
        self.files: dict[str, FileMeta] = {}
        # worker-model discipline (reference mito2 region worker,
        # worker.rs:110-650): one lock serializes this region's mutations;
        # scans take a consistent snapshot under it and decode outside
        self._lock = threading.RLock()
        # one compaction at a time per region (reference FlushScheduler /
        # CompactionScheduler serialize per region); the slow merge runs
        # outside the main lock so writes keep flowing
        self._compact_lock = threading.Lock()
        # set by drop(): late writers must fail, not resurrect WAL/SSTs
        self.dropped = False
        # compacted-away SSTs are purged only once no reader holds them —
        # scans pin their snapshot's files (the reference's FilePurger
        # refcount, mito2/src/sst/file_purger.rs)
        self._purge_queue: list[tuple[str, float]] = []
        self._file_refs: dict[str, int] = {}
        # bumped on every mutation; device cache keys include it
        self.data_version = 0
        # host scan cache: decoded-column snapshots keyed by
        # (data_version, ts_range, columns) — the analog of the reference's
        # decoded-page cache (mito2/src/cache.rs); repeated dashboard/TSBS
        # queries skip parquet decode entirely
        self._scan_cache: "OrderedDict[tuple, ScanData]" = OrderedDict()
        self.scan_cache_entries = 4  # overridden from EngineConfig
        # whole-scan snapshots and per-file parts draw on ONE shared
        # byte budget (part_cache_budget): the snapshot is a concat
        # COPY of the parts, so accounting them separately
        # double-counted host RAM (ROADMAP carry-over). The NEWEST
        # snapshot is exempt from the budget — refusing to cache the
        # working set of the current dashboard would trade a bounded
        # overshoot for re-decoding the table every query.
        self._scan_cache_sizes: dict[tuple, int] = {}
        self._scan_cache_bytes = 0
        # per-file decoded-part cache: (file_id, ts_range, names, preds)
        # -> _PartEntry, byte-budgeted LRU. SSTs are immutable, so an
        # entry stays valid for the file's whole life — a flush only
        # adds files, meaning a post-flush scan decodes ONLY the new
        # file and concats the rest from here (the monolithic
        # data_version-keyed cache above threw everything away on every
        # mutation). Entries die with their file: compaction swap,
        # retention expiry, and DROP/TRUNCATE call
        # _invalidate_file_parts.
        self._part_cache: "OrderedDict[tuple, _PartEntry]" = OrderedDict()
        self._part_cache_bytes = 0
        self.part_cache_budget = 1 << 30  # overridden from EngineConfig
        # SST decode fan-out cap; 0 = auto (storage/scan_pool.py)
        self.decode_threads = 0
        # ---- group-commit ingest pipeline (storage/group_commit.py) ----
        # attached by the engine when [ingest] group_commit is on; None
        # = the legacy serial write path (bit-for-bit differential tests
        # compare the two)
        self.committer = None
        # commit tickets order the WAL appends of concurrent group
        # commits: sequences are reserved under the region lock (fast),
        # but the append+fsync runs OUTSIDE it — the ticket turn keeps
        # the WAL file in sequence order anyway
        self._commit_tickets = itertools.count()
        self._wal_turn = 0
        self._wal_turn_cv = threading.Condition()
        # tickets reserved but not yet applied: flush/drop must wait for
        # these — a flush between reserve and apply would record a
        # flushed_seq past rows that are not yet in the memtable and
        # lose them on replay (acked-write loss)
        self._inflight_commits: set = set()
        self._commit_idle = threading.Condition(self._lock)
        # tickets abandoned before their turn (interrupt mid-wait): the
        # turn counter skips them instead of wedging every later commit
        self._dead_tickets: set = set()
        # flush/drop waiting for the commit pipeline to drain: while
        # nonzero, group_reserve holds new reservations back — without
        # the gate, overlapped commits under sustained ingest keep the
        # in-flight set nonempty and the quiesce would starve
        self._quiesce_waiters = 0

    # ---- lifecycle ---------------------------------------------------------

    @classmethod
    def create(cls, region_id: int, region_dir: str, schema: Schema, wal: Wal,
               store=None) -> "Region":
        region = cls(region_id, region_dir, schema, wal, store)
        region.manifest.record_schema(schema)
        return region

    @classmethod
    def open(cls, region_id: int, region_dir: str, wal: Wal, store=None) -> "Region":
        """Replay manifest (checkpoint + deltas), then WAL from flushed_seq
        (reference region/opener.rs:62-117)."""
        manifest = ManifestManager(os.path.join(region_dir, "manifest"), store)
        st = manifest.state
        if st.schema is None:
            raise FileNotFoundError(f"region {region_id} has no manifest at {region_dir}")
        region = cls(region_id, region_dir, st.schema, wal, store,
                     manifest=manifest)
        region.files = dict(st.files)
        # restore the tag registry snapshot taken at last flush; WAL replay
        # below re-adds any values seen since
        for name, values in st.tag_dicts.items():
            for v in values:
                region.registry.encode(name, np.asarray([v], dtype=object))
        region.next_seq = st.flushed_seq
        for entry in wal.replay(region_id, from_seq=st.flushed_seq):
            n = region.memtable.write(entry.batch, entry.seq, entry.op_type)
            region.next_seq = max(region.next_seq, entry.seq + n)
        return region

    def drop(self) -> None:
        with self._lock:
            self.dropped = True
            # in-flight group commits may still be appending to the WAL
            # this is about to delete; `dropped` blocks new reservations
            # and fails the in-flight ones at apply time
            self._quiesce_commits_locked()
            self._drain_purge(force=True)
            self.wal.delete_region(self.region_id)
            for fid in list(self.files):
                self.sst_reader.delete(fid)
            self._invalidate_file_parts(list(self.files))
            # snapshot-anchored hot-set entries must die too: TRUNCATE
            # recreates the region with the SAME region_id and resets
            # data_version, so a re-ingest could otherwise hit a
            # pre-truncate HBM block under a colliding version + shape
            self._notify_device_cache("invalidate_region")
            self.files.clear()
            self._scan_cache.clear()
            self._scan_cache_sizes.clear()
            self._scan_cache_bytes = 0

    def close(self) -> None:
        """Release deferred resources (deleted-but-grace-held SSTs)."""
        with self._lock:
            self._drain_purge(force=True)

    def _drain_purge(self, force: bool = False) -> None:
        """Delete deferred SSTs no reader pins (caller holds
        self._lock — drop/close/_unpin_files all enter under it)."""
        keep: list[tuple[str, float]] = []
        for fid, t in self._purge_queue:
            if self._file_refs.get(fid, 0) > 0 and not force:
                keep.append((fid, t))  # a reader still holds it
            else:
                self.sst_reader.delete(fid)
        self._purge_queue = keep

    def _pin_files(self, metas) -> None:
        for m in metas:
            self._file_refs[m.file_id] = self._file_refs.get(m.file_id, 0) + 1

    def _unpin_files(self, metas) -> None:
        with self._lock:
            for m in metas:
                n = self._file_refs.get(m.file_id, 0) - 1
                if n <= 0:
                    self._file_refs.pop(m.file_id, None)
                else:
                    self._file_refs[m.file_id] = n
            if self._purge_queue:
                self._drain_purge()

    # ---- per-file decoded-part cache + parallel decode ---------------------

    @property
    def _host_cache_bytes(self) -> int:
        """Bytes the part cache AND the whole-scan snapshots hold —
        the one number the shared budget bounds (caller holds
        self._lock; both put paths read it under the region lock)."""
        return self._part_cache_bytes + self._scan_cache_bytes

    def _part_cache_put(self, key: tuple, ent: _PartEntry) -> None:
        """Insert under the SHARED byte budget (caller holds self._lock):
        parts and whole-scan snapshots compete for the same bytes; a
        part insert evicts older parts, never snapshots (the snapshot is
        the hotter end product)."""
        from greptimedb_tpu.utils.metrics import SCAN_PART_CACHE_EVENTS

        # parts get whatever the resident snapshots leave over; when a
        # budget-exempt newest snapshot alone exceeds the budget there
        # is nothing left — refuse the insert instead of thrash-evicting
        # every part (including this one) on every decode
        avail = self.part_cache_budget - self._scan_cache_bytes
        if ent.nbytes > avail:
            return  # an entry that can never fit must not wipe the cache
        old = self._part_cache.pop(key, None)
        if old is not None:
            self._part_cache_bytes -= old.nbytes
        self._part_cache[key] = ent
        self._part_cache_bytes += ent.nbytes
        evicted = 0
        while self._part_cache_bytes > avail \
                and self._part_cache:
            _, e = self._part_cache.popitem(last=False)
            self._part_cache_bytes -= e.nbytes
            evicted += 1
        if evicted:
            SCAN_PART_CACHE_EVENTS.inc(float(evicted), event="evict")

    def _scan_cache_put(self, key: tuple, result: "ScanData") -> None:
        """Cache a whole-scan snapshot against the shared budget
        (caller holds self._lock): evict older snapshots beyond the
        entry-count limit, then cold parts, then older snapshots until
        the total fits — the newest snapshot itself always caches (it
        is live in the caller regardless; bounded overshoot beats
        re-decoding the active dashboard's table every query)."""
        nb = _scan_nbytes(result)
        old = self._scan_cache.pop(key, None)
        if old is not None:
            self._scan_cache_bytes -= self._scan_cache_sizes.pop(key, 0)
        from greptimedb_tpu.utils.metrics import SCAN_PART_CACHE_EVENTS

        self._scan_cache[key] = result
        self._scan_cache_sizes[key] = nb
        self._scan_cache_bytes += nb
        evicted = 0
        while len(self._scan_cache) > self.scan_cache_entries:
            k, _ = self._scan_cache.popitem(last=False)
            self._scan_cache_bytes -= self._scan_cache_sizes.pop(k, 0)
            evicted += 1
        while self._host_cache_bytes > self.part_cache_budget \
                and self._part_cache:
            _, e = self._part_cache.popitem(last=False)
            self._part_cache_bytes -= e.nbytes
            evicted += 1
        while self._host_cache_bytes > self.part_cache_budget \
                and len(self._scan_cache) > 1:
            k, _ = self._scan_cache.popitem(last=False)
            self._scan_cache_bytes -= self._scan_cache_sizes.pop(k, 0)
            evicted += 1
        if evicted:
            # snapshot evictions count here too: both caches spend the
            # ONE shared budget, so the operator's evict series must
            # show all of its churn, not just the part half
            SCAN_PART_CACHE_EVENTS.inc(float(evicted), event="evict")

    def _invalidate_file_parts(self, file_ids) -> None:
        """Drop part-cache entries for removed SSTs (compaction swap,
        retention expiry, DROP/TRUNCATE). Caller holds self._lock."""
        gone = set(file_ids)
        for k in [k for k in self._part_cache if k[0] in gone]:
            ent = self._part_cache.pop(k)
            self._part_cache_bytes -= ent.nbytes
        # the HBM columnar hot set keys device blocks by the same file
        # identity — the seams that kill host parts kill device blocks
        self._notify_device_cache("invalidate_files", gone)

    def _notify_device_cache(self, fn_name: str, *args) -> None:
        """Best-effort invalidation fan-out to the query-layer caches
        keyed by file identity: the HBM columnar hot set AND the
        partial-aggregate cache (per-part [G, F] planes) die through
        the exact same seams that kill host parts. sys.modules lookup,
        not an import: a storage-only process that never ran a query
        has no caches to notify (and this runs under the region lock —
        the caches take only their own locks)."""
        import sys

        for modname in ("greptimedb_tpu.query.device_cache",
                        "greptimedb_tpu.query.partial_cache"):
            mod = sys.modules.get(modname)
            if mod is not None:
                try:
                    getattr(mod, fn_name)(self.region_id, *args)
                except Exception:  # noqa: BLE001 — upkeep must not fail the seam
                    pass

    def _decode_file_part(self, meta: FileMeta, ts_range, names,
                          tag_predicates) -> Optional[tuple]:
        """Read+decode one SST into host columns (the per-file body the
        old scan loop ran serially). Returns (cols, seq, op) or None
        when pruning/filtering leaves nothing."""
        from greptimedb_tpu.utils.metrics import (
            SCAN_DECODE_BYTES,
            SCAN_DECODE_SECONDS,
        )

        with SCAN_DECODE_SECONDS.time():
            table = self.sst_reader.read(meta, self.schema, ts_range, names,
                                         tag_predicates=tag_predicates)
            if table is None or table.num_rows == 0:
                return None
            part = self._decode_table_part(table, ts_range, names)
        if part is None:
            return None
        SCAN_DECODE_BYTES.inc(float(_part_nbytes(part)))
        return part

    def _decode_table_part(self, table, ts_range, names) -> Optional[tuple]:
        """Arrow table -> (cols, seq, op) with the exact ts row filter —
        the decode body shared by the whole-file and split-row-group
        paths (identical bytes either way; the split path just runs it
        per group chunk and concatenates in group order)."""
        ts_name = self.schema.time_index.name
        cols = self._decode_sst(table, names)
        seq_col = table.column(SEQ_COL).to_numpy(
            zero_copy_only=False).astype(np.int64)
        op_col = table.column(OP_COL).to_numpy(
            zero_copy_only=False).astype(np.int8)
        if ts_range is not None:
            # exact row filter: SSTs sort by (pk, ts), so a row
            # group from one large flush can span the whole time
            # range and row-group stats cannot prune it — drop
            # out-of-range rows here so downstream (device
            # transfer + kernels) only sees the queried window.
            # All versions/tombstones of an instant share its ts,
            # so LWW dedup still sees every candidate.
            tsv = cols[ts_name]
            # [lo, hi) — extract_ts_bounds emits half-open upper
            # bounds (ts <= v becomes hi = v+1), matching every
            # other pruner here (sst/memtable/scan_stream)
            m = (tsv >= ts_range[0]) & (tsv < ts_range[1])
            if not m.all():
                if not m.any():
                    return None
                cols = {n: v[m] for n, v in cols.items()}
                seq_col = seq_col[m]
                op_col = op_col[m]
        return (cols, seq_col, op_col)

    def _decode_file_part_split(self, meta: FileMeta, ts_range, names,
                                tag_predicates,
                                threads: int) -> tuple[Optional[tuple], int]:
        """One SST decoded by SEVERAL workers: the surviving row groups
        split into contiguous runs, each run read through its own
        parquet handle + decoded on the shared pool, reassembled in
        group order — byte-for-byte the single-worker result (ISSUE 5
        carry-over: one huge file used to serialize the decode stage).
        Returns (part or None, workers observed)."""
        from greptimedb_tpu.storage import scan_pool
        from greptimedb_tpu.utils.metrics import (
            SCAN_DECODE_BYTES,
            SCAN_DECODE_SECONDS,
        )

        plan = self.sst_reader.plan_groups(meta, self.schema, ts_range,
                                           names,
                                           tag_predicates=tag_predicates)
        k = 0 if plan is None else min(threads, len(plan[1]))
        if k <= 1:
            # nothing to split (pruned empty / one row group): the
            # classic whole-file path, so read()-level test spies and
            # fault seams see exactly the pre-split behavior
            return (self._decode_file_part(meta, ts_range, names,
                                           tag_predicates), 1)
        pf0, groups, cols_proj = plan
        with SCAN_DECODE_SECONDS.time():
            # contiguous runs preserve row order under reassembly
            bounds = [len(groups) * i // k for i in range(k + 1)]
            runs = [groups[bounds[i]:bounds[i + 1]] for i in range(k)]
            pool = scan_pool.get(k)
            seen: set = set()

            def work(run, pf=None):
                seen.add(threading.get_ident())
                if pf is not None:
                    # the planning handle already parsed the footer —
                    # exactly ONE worker may reuse it (pyarrow readers
                    # are not safe for concurrent reads on one handle)
                    table = pf.read_row_groups(list(run),
                                               columns=cols_proj)
                else:
                    table = self.sst_reader.read_groups(meta, run,
                                                        cols_proj)
                if table.num_rows == 0:
                    return None
                return self._decode_table_part(table, ts_range, names)

            from greptimedb_tpu.utils import tracing

            live_runs = [run for run in runs if run]
            run_one = tracing.propagate(work)
            # scan_pool.submit re-adopts the query's CancelToken in the
            # worker: queued units for a dead query unwind typed
            futs = [scan_pool.submit(pool, run_one, run,
                                     pf0 if i == 0 else None)
                    for i, run in enumerate(live_runs)]
            chunks: list = []
            first_err = None
            for f in futs:
                try:
                    chunks.append(dl.wait_future(f, "scan gather"))
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    chunks.append(None)
                    if first_err is None:
                        first_err = e
            if first_err is not None:
                raise first_err
            live = [c for c in chunks if c is not None]
            if not live:
                return None, max(1, len(seen))
            if len(live) == 1:
                part = live[0]
            else:
                part = (
                    {n: np.concatenate([c[0][n] for c in live])
                     for n in live[0][0]},
                    np.concatenate([c[1] for c in live]),
                    np.concatenate([c[2] for c in live]),
                )
        SCAN_DECODE_BYTES.inc(float(_part_nbytes(part)))
        return part, max(1, len(seen))

    def _decode_parts(self, metas, ts_range, names,
                      tag_predicates) -> tuple[list, int]:
        """Decode several SSTs, fanning across the shared per-datanode
        pool (storage/scan_pool.py). Returns (parts in `metas` order,
        distinct workers observed). decode_threads=1 decodes inline,
        byte-for-byte the sequential path; a SINGLE multi-row-group
        file splits its row groups across the pool instead of
        serializing on one worker (order-preserving reassembly).

        Fault discipline: every submitted future is WAITED ON before
        this returns or raises, so no worker touches SST bytes after
        the caller's unpin; the first error in file order propagates
        (typed FaultError/Unavailable from objectstore.read included),
        exactly as the serial loop raised it."""
        from greptimedb_tpu.storage import scan_pool

        # resolve against the CONFIGURED cap, not the file count: a
        # single huge SST gets its row groups split across the spare
        # workers instead of serializing on one (order-preserving —
        # see _decode_file_part_split)
        threads = scan_pool.resolve(self.decode_threads,
                                    max(len(metas), 1_000_000))
        if len(metas) == 1 and threads > 1:
            part, workers = self._decode_file_part_split(
                metas[0], ts_range, names, tag_predicates, threads)
            return [part], workers
        threads = min(threads, len(metas))
        if threads <= 1 or len(metas) <= 1:
            return ([self._decode_file_part(m, ts_range, names,
                                            tag_predicates)
                     for m in metas], 1)
        pool = scan_pool.get(threads)
        seen: set = set()

        def work(meta):
            seen.add(threading.get_ident())
            return self._decode_file_part(meta, ts_range, names,
                                          tag_predicates)

        # carry the request's trace/span/ledger context onto the pool
        # workers: per-file decode (and the objectstore_read spans
        # inside it) lands in the query's span tree
        from greptimedb_tpu.utils import tracing

        futs = [scan_pool.submit(pool, tracing.propagate(work), m)
                for m in metas]
        results: list = []
        first_err = None
        for f in futs:
            try:
                results.append(dl.wait_future(f, "decode gather"))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                results.append(None)
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return results, max(1, len(seen))

    def _concat_columns(self, names, parts_cols) -> dict:
        """Assemble the whole-scan columns from per-file parts. Columns
        are independent, so the concat copies fan across the decode
        pool too (numpy releases the GIL for the memcpy) — on the
        incremental path this copy IS the remaining scan cost."""
        from greptimedb_tpu.storage import scan_pool

        threads = scan_pool.resolve(self.decode_threads, len(names))
        if threads <= 1 or len(names) <= 1:
            return {n: np.concatenate([p[n] for p in parts_cols])
                    for n in names}
        pool = scan_pool.get(threads)
        futs = {n: scan_pool.submit(
            pool, np.concatenate, [p[n] for p in parts_cols])
            for n in names}
        return {n: dl.wait_future(f, "concat gather")
                for n, f in futs.items()}

    def _cached_parts(self, file_list, ts_range, names, pred_key,
                      tag_predicates, insert: bool = True
                      ) -> tuple[list, dict]:
        """Per-file decoded parts for `file_list` (which the caller has
        pinned), through the part cache; misses decode in parallel.
        `insert=False` reuses hits but keeps misses out of the cache
        (compaction reads its soon-to-be-removed inputs once — caching
        them would evict warm query parts for zero retained value).
        Returns (list of _PartEntry aligned with file_list, stats)."""
        from greptimedb_tpu.utils.metrics import SCAN_PART_CACHE_EVENTS

        keys = [(m.file_id, ts_range, tuple(names), pred_key)
                for m in file_list]
        parts: list = [None] * len(file_list)
        hits = 0
        with self._lock:
            for i, k in enumerate(keys):
                ent = self._part_cache.get(k)
                if ent is not None:
                    self._part_cache.move_to_end(k)
                    parts[i] = ent
                    hits += 1
        missing = [i for i in range(len(file_list)) if parts[i] is None]
        workers = 0
        t0 = time.perf_counter()
        if missing:
            decoded, workers = self._decode_parts(
                [file_list[i] for i in missing], ts_range, names,
                tag_predicates)
            with self._lock:
                for i, part in zip(missing, decoded):
                    ent = _PartEntry(part, _part_nbytes(part))
                    parts[i] = ent
                    # a scan races compaction/expiry: its pinned files
                    # may have been removed (and invalidated) while it
                    # decoded — inserting then would strand dead
                    # entries in the budget forever
                    if insert and file_list[i].file_id in self.files:
                        self._part_cache_put(keys[i], ent)
        from greptimedb_tpu.utils import ledger

        if hits:
            SCAN_PART_CACHE_EVENTS.inc(float(hits), event="hit")
            ledger.cache_event("scan_part", "hit", float(hits))
        if missing:
            SCAN_PART_CACHE_EVENTS.inc(float(len(missing)), event="miss")
            ledger.cache_event("scan_part", "miss", float(len(missing)))
            # decode-byte attribution on the request thread (the global
            # SCAN_DECODE_BYTES inc fires on pool workers, which don't
            # carry this request's contextvars)
            ledger.add("bytes_decoded",
                       float(sum(parts[i].nbytes for i in missing
                                 if parts[i] is not None)))
        return parts, {
            "part_hits": hits,
            "files_decoded": len(missing),
            "decode_workers": workers,
            "decode_s": round(time.perf_counter() - t0, 4),
        }

    # ---- write -------------------------------------------------------------

    def write(self, batch: RecordBatch, op_type: int = OP_PUT) -> int:
        """Durable write: WAL first, then memtable (reference
        region_write_ctx.rs:92-144 + wal.rs:133). Returns affected rows."""
        return self.write_many([(batch, op_type)])[0]

    def write_many(self, items: list[tuple[RecordBatch, int]]) -> list[int]:
        """Apply several mutations with ONE WAL group commit (reference
        RegionWriteCtx batches all of a worker cycle's mutations into one
        WalWriter write, region_write_ctx.rs:92-144). Returns per-item
        affected rows.

        With the [ingest] group-commit pipeline attached, concurrent
        callers coalesce through the per-region bounded queue (one WAL
        append + one fsync + one memtable apply per drained group, the
        fsync OUTSIDE the region lock); otherwise the legacy serial path
        below runs — preserved bit-for-bit for differential tests."""
        if self.committer is not None:
            return self.committer.write_many(items)
        return self.write_many_serial(items)

    def write_many_serial(self, items: list[tuple[RecordBatch, int]]
                          ) -> list[int]:
        """The pre-pipeline write path: WAL append (and its fsync) and
        memtable apply under one region-lock hold."""
        counts = [b.num_rows for b, _ in items]
        live = [(b, op) for b, op in items if b.num_rows]
        if not live:
            return counts
        with self._lock:
            if self.dropped:
                # a write racing DROP must error, not silently append to
                # (and resurrect) the deleted region's WAL
                raise RegionDroppedError(
                    f"region {self.region_id} is dropped")
            seq = self.next_seq
            entries = []
            for batch, op_type in live:
                entries.append((seq, op_type, batch))
                seq += batch.num_rows
            self.wal.append_many(self.region_id, entries)
            for s, op_type, batch in entries:
                self.memtable.write(batch, s, op_type)
            self.next_seq = seq
            self.data_version += 1
        return counts

    # ---- group-commit hooks (storage/group_commit.py drives these) ---------

    def group_reserve(self, live: list[tuple[RecordBatch, int]]
                      ) -> tuple[int, list]:
        """Reserve the group's WAL sequences and a commit ticket under
        the region lock — metadata only, the slow encode/fsync work runs
        outside. Returns (ticket, [(seq, op_type, batch), ...])."""
        with self._lock:
            # a pending flush/DROP quiesce has priority: new
            # reservations wait so the in-flight set can actually drain
            while self._quiesce_waiters:
                self._commit_idle.wait(timeout=1.0)
            if self.dropped:
                raise RegionDroppedError(
                    f"region {self.region_id} is dropped")
            seq = self.next_seq
            entries = []
            for batch, op_type in live:
                entries.append((seq, op_type, batch))
                seq += batch.num_rows
            self.next_seq = seq
            ticket = next(self._commit_tickets)
            self._inflight_commits.add(ticket)
            return ticket, entries

    def group_commit(self, ticket: int, entries: list,
                     blob: Optional[bytes] = None) -> None:
        """Ticket-ordered durable commit: WAL append + fsync OUTSIDE the
        region lock (readers and other regions' writers never wait on
        the disk), then the memtable apply under it. `blob` is the
        pre-encoded WAL frame blob (encoded outside every lock, so the
        next group's encode overlaps this one's fsync); None falls back
        to the backend's own encode (remote WAL)."""
        from greptimedb_tpu.fault import FAULTS
        from greptimedb_tpu.utils.metrics import INGEST_WAL_FSYNC_SECONDS

        try:
            with self._wal_turn_cv:
                while self._wal_turn != ticket:
                    # bounded laps, never abandoned: the ticket MUST
                    # retire in sequence or every later commit wedges
                    self._wal_turn_cv.wait(timeout=1.0)
            # sole owner of this region's WAL tail until the turn
            # advances; a crash in here leaves at most a torn tail that
            # replay truncates (nothing in the group was acknowledged)
            FAULTS.fire("ingest.commit", op="append",
                        region=str(self.region_id))
            with INGEST_WAL_FSYNC_SECONDS.time():
                if blob is not None:
                    self.wal.append_blob(self.region_id, blob)
                else:
                    self.wal.append_many(self.region_id, entries)
            FAULTS.fire("ingest.commit", op="apply",
                        region=str(self.region_id))
            with self._lock:
                dropped = self.dropped
                if not dropped:
                    for s, op_type, batch in entries:
                        self.memtable.write(batch, s, op_type)
                    self.data_version += 1
            if dropped:
                # the rows are durable in a WAL that drop() is about to
                # delete — the write must not be acknowledged
                raise RegionDroppedError(
                    f"region {self.region_id} is dropped")
        finally:
            self._finish_commit(ticket)

    def group_abort(self, ticket: int) -> None:
        """Release a reserved ticket whose commit never started (encode
        failed, fault fired pre-append). Waits its WAL turn so the turn
        counter stays strictly sequential; the reserved sequences become
        a gap, which replay tolerates. The finally mirrors
        group_commit's: an interrupt landing mid-wait must still retire
        the ticket (as a dead one) or every later commit wedges."""
        try:
            with self._wal_turn_cv:
                while self._wal_turn != ticket:
                    self._wal_turn_cv.wait(timeout=1.0)
        finally:
            self._finish_commit(ticket)

    def _finish_commit(self, ticket: int) -> None:
        with self._wal_turn_cv:
            if self._wal_turn == ticket:
                self._wal_turn = ticket + 1
                while self._wal_turn in self._dead_tickets:
                    self._dead_tickets.discard(self._wal_turn)
                    self._wal_turn += 1
                self._wal_turn_cv.notify_all()
            elif self._wal_turn < ticket:
                # abandoned before its turn came up (interrupt during
                # the wait): let the predecessor's advance skip it
                self._dead_tickets.add(ticket)
        with self._lock:
            self._inflight_commits.discard(ticket)
            if not self._inflight_commits:
                self._commit_idle.notify_all()

    def _quiesce_commits_locked(self) -> None:
        """Wait (holding self._lock, released during the wait) until no
        group commit sits between reserve and apply: flush would record
        a flushed_seq past the reserved-but-unapplied rows and lose them
        on replay; drop would delete the WAL a commit is appending to.
        While waiting, group_reserve holds NEW reservations back (the
        _quiesce_waiters gate), so the drain is bounded by the already-
        reserved groups' fsyncs even under sustained overlapped ingest —
        commits always terminate via their finally."""
        self._quiesce_waiters += 1
        try:
            while self._inflight_commits:
                self._commit_idle.wait(timeout=5.0)
        finally:
            self._quiesce_waiters -= 1
            if not self._quiesce_waiters:
                self._commit_idle.notify_all()

    # ---- flush -------------------------------------------------------------

    def flush(self) -> Optional[FileMeta]:
        """Memtable → sorted SST; manifest edit; WAL truncate."""
        with self._lock:
            self._quiesce_commits_locked()
            return self._flush_locked()

    def _flush_locked(self) -> Optional[FileMeta]:
        self._drain_purge()
        data = self.memtable.concat()
        if data is None:
            return None
        cols, seq, op = data
        order = self._sort_order(cols, seq)
        sorted_cols = {k: v[order] for k, v in cols.items()}
        tag_dicts = {
            c.name: self.registry.dict_array(c.name) for c in self.schema.tag_columns
        }
        meta = self.sst_writer.write(sorted_cols, tag_dicts, seq[order], op[order])
        self.files[meta.file_id] = meta
        self.manifest.record_flush([meta], flushed_seq=self.next_seq,
                                   tag_dicts=self.registry.snapshot())
        self.memtable = Memtable(self.schema, self.registry)
        self.wal.obsolete(self.region_id, self.next_seq)
        self.data_version += 1
        return meta

    def _sort_order(self, cols: dict[str, np.ndarray], seq: np.ndarray) -> np.ndarray:
        keys = [seq, cols[self.schema.time_index.name]]
        for c in reversed(self.schema.tag_columns):
            keys.append(cols[c.name])
        return np.lexsort(keys)

    # ---- compaction (TWCS: merge within time windows) ----------------------

    def compact(self, strategy: str = "twcs") -> list[FileMeta]:
        """Compact SSTs. "twcs": time-window groups picked by TwcsPicker
        (reference compaction/twcs.rs); "full": everything into one file
        (manual strict-window analog, ADMIN compact_table). The merge runs
        the device sort-dedup kernel — compaction is the same computation
        as query-time dedup, persisted (SURVEY.md §7)."""
        from greptimedb_tpu.storage.compaction import TwcsPicker

        with self._compact_lock:
            with self._lock:
                files = list(self.files.values())
            if strategy == "full":
                groups = [files] if len(files) > 1 else []
            else:
                groups = TwcsPicker().pick(files)
            out: list[FileMeta] = []
            for group in groups:
                meta = self._merge_files(group)
                if meta is not None:
                    out.append(meta)
            return out

    def _merge_files(self, group: list[FileMeta]) -> Optional[FileMeta]:
        """Read `group`'s SSTs, sort-dedup on device, rewrite as one L1
        file, swap in the manifest (compaction/task.rs analog)."""
        names = self.schema.names
        from greptimedb_tpu.storage.index import predicates_cache_key

        # the merge reads full files with no range/predicates — exactly
        # the shape a full scan caches, so compaction REUSES warm scan
        # parts and decodes cold inputs in parallel; insert=False keeps
        # its one-shot inputs from evicting warm query entries
        with self._lock:
            self._pin_files(group)
        try:
            entries, _ = self._cached_parts(
                group, None, names, predicates_cache_key(None), None,
                insert=False)
        finally:
            self._unpin_files(group)
        parts_cols, parts_seq, parts_op = [], [], []
        for ent in entries:
            if ent.part is None:
                continue
            cols_p, seq_p, op_p = ent.part
            parts_cols.append(cols_p)
            parts_seq.append(seq_p)
            parts_op.append(op_p)
        if not parts_cols:
            return None
        columns = self._concat_columns(names, parts_cols)
        seq = np.concatenate(parts_seq)
        op = np.concatenate(parts_op)
        n_rows = len(seq)

        import jax.numpy as jnp
        from greptimedb_tpu.ops.dedup import sort_dedup
        from greptimedb_tpu.ops.segment import combine_group_ids

        tag_names = [c.name for c in self.schema.tag_columns]
        sizes = [max(len(self.registry.dict_array(n)), 1) + 1 for n in tag_names]
        if tag_names:
            # int64: the cardinality product of several tags can exceed 2^31
            sid = combine_group_ids(
                [jnp.asarray(columns[n] + 1) for n in tag_names], sizes,
                dtype=jnp.int64,
            )
        else:
            sid = jnp.zeros(n_rows, dtype=jnp.int64)
        ts = jnp.asarray(columns[self.schema.time_index.name])
        covers_all = len(group) == len(self.files)
        order, keep = sort_dedup(
            sid, ts, jnp.asarray(seq), jnp.asarray(op),
            jnp.ones(n_rows, dtype=bool),
            keep_tombstones=not covers_all,
        )
        order = np.asarray(order)[np.asarray(keep)]
        cols = {k: v[order] for k, v in columns.items()}
        tag_dicts = {n: self.registry.dict_array(n) for n in tag_names}
        meta = self.sst_writer.write(
            cols, tag_dicts, seq[order], op[order], level=1
        )
        removed = [f.file_id for f in group]
        import time as _time

        from greptimedb_tpu.fault import FAULTS

        # chaos seam: a crash HERE (new SST durable, manifest not yet
        # edited) must leave the pre-compaction file list authoritative —
        # the new file is an unreferenced orphan, never a half-swap
        FAULTS.fire("maintenance.job", op="compact", phase="swap")
        with self._lock:
            for fid in removed:
                self.files.pop(fid, None)
            self.files[meta.file_id] = meta
            # the inputs' decoded parts die with them — a later scan
            # must decode the merged output, never concat stale inputs
            self._invalidate_file_parts(removed)
            # flushed_seq=None: this edit persists NO memtable rows —
            # advancing it here would mark concurrent unflushed writes
            # replay-obsolete (acked-write loss on crash)
            self.manifest.record_flush(
                [meta], flushed_seq=None,
                tag_dicts=self.registry.snapshot(), removed=removed)
            # defer physical deletion: concurrent scans may still hold
            # the pre-compaction file list
            now = _time.monotonic()
            self._purge_queue.extend((fid, now) for fid in removed)
            self.data_version += 1
        return meta

    def _tag_inset_mask(self, tag_predicates, columns):
        """Row mask for the InSet (=/IN) parts of the tag predicates over
        global-code columns, or None when no InSet applies. Regex/Range
        predicates stay with the device filter."""
        from greptimedb_tpu.storage.index import InSet, normalize_predicates

        keep = None
        for tag, preds in normalize_predicates(tag_predicates).items():
            if tag not in columns:
                continue
            allowed = None
            for p in preds:
                if isinstance(p, InSet):
                    s = set(p.values)
                    allowed = s if allowed is None else (allowed & s)
            if allowed is None:
                continue
            d = self.registry.dict_array(tag)
            codes = [c for v in allowed
                     for c in np.flatnonzero(d == v).tolist()]
            m = np.isin(columns[tag], np.asarray(codes, dtype=np.int64))
            keep = m if keep is None else (keep & m)
        return keep

    def _widen_covering_range(self, ts_range):
        """None when `ts_range` covers at least half of the region's
        data span (see scan: canonical-cache sharing), else unchanged."""
        if ts_range is None:
            return None
        lo, hi = ts_range
        with self._lock:
            # metadata-only snapshot under the lock: flush mutates
            # self.files and swaps self.memtable concurrently
            mins = [m.ts_min for m in self.files.values()]
            maxs = [m.ts_max for m in self.files.values()]
            mem = self.memtable
            mem_min, mem_max = mem.ts_min, mem.ts_max
        if mem_min is not None and mem_max is not None:
            mins.append(mem_min)
            maxs.append(mem_max)
        if not mins:
            return ts_range
        glo, ghi = min(mins), max(maxs)
        if lo <= glo and hi > ghi:
            return None  # covers everything: exactly the full scan
        covered = min(hi, ghi + 1) - max(lo, glo)
        return None if 2 * covered >= (ghi + 1 - glo) else ts_range

    # ---- scan --------------------------------------------------------------

    def scan(
        self,
        ts_range: Optional[tuple[int, int]] = None,
        projection: Optional[Sequence[str]] = None,
        tag_predicates: Optional[dict[str, set]] = None,
        seq_min: Optional[int] = None,
    ) -> Optional[ScanData]:
        """Collect memtable + pruned SSTs into concatenated host columns.
        `tag_predicates` (tag -> allowed values) drives inverted-index
        row-group pruning; the scan result may then contain rows the
        predicate rejects — the device filter still runs, pruning is purely
        an IO reduction (never affects correctness).

        `seq_min`: return only rows written AFTER that sequence — the
        incremental-consumer scan (flow ticks fold each row exactly
        once). Prunes whole SSTs by FileMeta.max_seq, so the IO cost is
        O(new data + files that straddle the boundary), not O(table)."""
        names = self._scan_columns(projection)
        from greptimedb_tpu.storage.index import predicates_cache_key
        pred_key = predicates_cache_key(tag_predicates)
        if seq_min is not None:
            return self._scan_since(seq_min, ts_range, names,
                                    tag_predicates)
        # wide windows (>= half the region's time span) serve the
        # CANONICAL full scan instead of a range-keyed copy: every
        # distinct ts_range otherwise caches its own host columns AND
        # its own HBM blocks (the fingerprint keys them), so a handful
        # of overlapping dashboards would hold several copies of the
        # table. Kernels mask exactly either way; narrow windows still
        # get a filtered copy (that is where filtering pays), and
        # tag-predicated scans keep their exact range — the inverted
        # index already shrank them, so the copy is cheap and computing
        # over the shared full rows would cost more than it saves.
        if not tag_predicates:
            ts_range = self._widen_covering_range(ts_range)
        # snapshot phase under the region lock: version + file list +
        # memtable rows form one consistent view; SST decode (the slow
        # part) runs outside, on immutable grace-protected files
        with self._lock:
            version = self.data_version
            cache_key = (version, ts_range, tuple(names), pred_key)
            cached = self._scan_cache.get(cache_key)
            if cached is not None:
                self._scan_cache.move_to_end(cache_key)
                if cached.stats is not None:
                    cached.stats["cache_hits"] += 1
                return cached
            file_list = list(self.files.values())
            self._pin_files(file_list)
            mem = self.memtable.concat(ts_range)
        # parallel decode through the per-file part cache: misses fan
        # across the shared pool, hits are free, and the assembly below
        # preserves the exact serial part order (so LWW dedup, the
        # sorted part_offsets contract, and fault propagation order all
        # behave as the old one-file-at-a-time loop did)
        try:
            part_entries, decode_stats = self._cached_parts(
                file_list, ts_range, names, pred_key, tag_predicates)
        finally:
            self._unpin_files(file_list)
        parts_cols: list[dict[str, np.ndarray]] = []
        parts_seq: list[np.ndarray] = []
        parts_op: list[np.ndarray] = []
        sst_part_lens: list[int] = []
        part_keys: list[tuple] = []
        for meta, ent in zip(file_list, part_entries):
            if ent.part is None:
                continue
            cols, seq_col, op_col = ent.part
            parts_cols.append(cols)
            parts_seq.append(seq_col)
            parts_op.append(op_col)
            sst_part_lens.append(len(seq_col))
            # device hot-set identity: a part's rows depend only on the
            # immutable file + the window/predicate key (the inset
            # filter below keeps whole series deterministically)
            part_keys.append((meta.file_id, ts_range, pred_key))

        if mem is not None:
            mcols, mseq, mop = mem
            parts_cols.append({n: mcols[n] for n in names})
            parts_seq.append(mseq)
            parts_op.append(mop)

        if not parts_cols:
            return None
        if len(parts_cols) == 1:
            # single part (one big SST, or memtable only): concatenate
            # would copy ~the whole table for nothing — cold scans at the
            # TSBS 17M-row scale spend seconds here otherwise
            columns = dict(parts_cols[0])
            seq = parts_seq[0]
            op = parts_op[0]
        else:
            columns = self._concat_columns(names, parts_cols)
            seq = np.concatenate(parts_seq)
            op = np.concatenate(parts_op)
        part_offsets = np.cumsum([0] + sst_part_lens)
        if tag_predicates:
            # exact row filter for equality/IN tag predicates: the
            # inverted index prunes row groups, but one row group holds
            # hundreds of series — dropping non-matching rows here keeps
            # the cached scan (and device compute) proportional to the
            # SELECTED series. Whole series keep/drop together, so LWW
            # dedup and tombstones stay intact; the device WHERE still
            # evaluates the predicate exactly (incl. NULL semantics).
            keep = self._tag_inset_mask(tag_predicates, columns)
            if keep is not None and not keep.all():
                idx = np.flatnonzero(keep)
                if idx.size == 0:
                    # preserve the "no rows" contract: consumers
                    # None-check, they never expect a 0-row ScanData
                    return None
                columns = {n: v[idx] for n, v in columns.items()}
                seq = seq[idx]
                op = op[idx]
                # ascending-index gather preserves within-part order; the
                # part boundaries just shift to the count of kept rows
                # before each original offset
                part_offsets = np.searchsorted(idx, part_offsets)
        tag_dicts = {
            c.name: self.registry.dict_array(c.name)
            for c in self.schema.tag_columns
            if c.name in names
        }
        result = ScanData(
            schema=self.schema,
            columns=columns,
            seq=seq,
            op_type=op,
            tag_dicts=tag_dicts,
            num_rows=len(seq),
            region_id=self.region_id,
            data_version=version,
            incarnation=self.incarnation,
            scan_fingerprint=(ts_range, tuple(names), pred_key),
            sorted_part_offsets=tuple(int(o) for o in part_offsets),
            part_keys=tuple(part_keys),
            stats={"ssts": len(file_list),
                   "ssts_pruned": len(file_list) - len(sst_part_lens),
                   "cache_hits": 0,
                   **decode_stats},
        )
        with self._lock:
            self._scan_cache_put(cache_key, result)
        return result

    def scan_last(self, group_tag: str,
                  projection: Optional[Sequence[str]] = None,
                  ) -> Optional[ScanData]:
        """Lastpoint-pruned scan: visit SSTs NEWEST-FIRST (FileMeta
        ts_max order) and stop once every series grouped by `group_tag`
        provably holds its last row in the visited set — instead of
        decoding the whole table for a handful of winner rows (TSBS
        `lastpoint` is the user; the reference's merge reader gets the
        same effect from per-file last-row semantics).

        Termination argument: files are visited in descending ts_max,
        so every unvisited file only holds rows with ts <= the next
        file's ts_max. Once a series has a candidate with ts STRICTLY
        above that bound (strict: an equal ts in an older file could
        carry a higher seq and win LWW), no unvisited file can hold its
        winner — or any version of the winning instant, so the subset
        dedup picks the true row. The known-series set is the tag
        registry's value list (a superset of live values; codes with no
        surviving rows block early stop, which costs pruning, never
        correctness). NULL-tag rows form a group the registry cannot
        name: FileMeta.null_tags says which files may hold them
        (None = pre-upgrade file, assumed to), and termination also
        waits for the NULL group whenever an unvisited file might
        contribute to it.

        Returns None when the path cannot serve the query exactly —
        any DELETE tombstone in the visited rows or memtable (the
        newest row may be a tombstone, making an interior row the
        answer) — and the caller falls back to the full scan."""
        names = self._scan_columns(projection)
        tag_names = [c.name for c in self.schema.tag_columns]
        if group_tag not in tag_names or group_tag not in names:
            return None
        from greptimedb_tpu.storage.index import predicates_cache_key
        pred_key = predicates_cache_key(None)
        ts_name = self.schema.time_index.name
        with self._lock:
            version = self.data_version
            cache_key = ("lastpoint", version, group_tag, tuple(names))
            cached = self._scan_cache.get(cache_key)
            if cached is not None:
                self._scan_cache.move_to_end(cache_key)
                if cached.stats is not None:
                    cached.stats["cache_hits"] += 1
                return cached
            # deterministic newest-first order (ties broken by id so
            # parallel and serial runs visit identical prefixes)
            file_list = sorted(
                self.files.values(),
                key=lambda m: (m.ts_max, m.max_seq, m.file_id),
                reverse=True)
            self._pin_files(file_list)
            mem = self.memtable.concat(None)
            card = self.registry.cardinality(group_tag)
        # suffix_null[i]: may any of file_list[i:] hold NULL group_tag?
        suffix_null = [False] * (len(file_list) + 1)
        for i in range(len(file_list) - 1, -1, -1):
            m = file_list[i]
            has = m.null_tags is None or group_tag in m.null_tags
            suffix_null[i] = suffix_null[i + 1] or has
        # best[0] = newest ts seen for the NULL group, best[1 + code]
        # for each registry code; int64 min = "never seen"
        floor = np.iinfo(np.int64).min
        best = np.full(card + 1, floor, dtype=np.int64)

        def fold(codes: np.ndarray, ts: np.ndarray) -> None:
            nonlocal best
            if codes.size == 0:
                return
            slot = codes.astype(np.int64) + 1
            mx = int(slot.max())
            if mx >= best.size:
                # a file dictionary introduced values the registry
                # snapshot predates — grow; they were seen here, so
                # their termination entries are live
                best = np.concatenate(
                    [best, np.full(mx + 1 - best.size, floor,
                                   dtype=np.int64)])
            np.maximum.at(best, slot, ts.astype(np.int64))

        aborted = False
        if mem is not None:
            mcols, _mseq, mop = mem
            if bool((mop != OP_PUT).any()):
                aborted = True
            else:
                fold(np.asarray(mcols[group_tag]),
                     np.asarray(mcols[ts_name]))
        visited_entries: list = []
        visited = 0
        part_hits = files_decoded = 0
        workers = 1
        try:
            from greptimedb_tpu.storage import scan_pool

            while not aborted and visited < len(file_list):
                # decode in waves of the pool width: parallelism inside
                # a wave, the early-stop check between waves (a wave may
                # over-read at most threads-1 files past the stop point)
                threads = scan_pool.resolve(self.decode_threads,
                                            len(file_list) - visited)
                wave = file_list[visited:visited + max(1, threads)]
                parts, st = self._cached_parts(wave, None, names,
                                               pred_key, None)
                part_hits += st["part_hits"]
                files_decoded += st["files_decoded"]
                workers = max(workers, st["decode_workers"])
                for ent in parts:
                    visited_entries.append(ent)
                    if ent.part is None:
                        continue
                    cols, _seq_col, op_col = ent.part
                    if bool((op_col != OP_PUT).any()):
                        aborted = True
                        break
                    fold(np.asarray(cols[group_tag]),
                         np.asarray(cols[ts_name]))
                visited += len(wave)
                if aborted or visited >= len(file_list):
                    break
                nxt = file_list[visited].ts_max
                if bool((best[1:] > nxt).all()) and \
                        (not suffix_null[visited] or best[0] > nxt):
                    break
        finally:
            self._unpin_files(file_list)
        if aborted:
            return None  # tombstones: caller runs the full scan
        parts_cols: list = []
        parts_seq: list = []
        parts_op: list = []
        sst_part_lens: list = []
        part_keys: list = []
        for meta, ent in zip(file_list, visited_entries):
            if ent.part is None:
                continue
            cols, seq_col, op_col = ent.part
            parts_cols.append(cols)
            parts_seq.append(seq_col)
            parts_op.append(op_col)
            sst_part_lens.append(len(seq_col))
            # full-file parts (no window, no predicates): these HBM
            # blocks are shared with full-scan keys of the same file
            part_keys.append((meta.file_id, None, pred_key))
        if mem is not None:
            mcols, mseq, mop = mem
            parts_cols.append({n: mcols[n] for n in names})
            parts_seq.append(mseq)
            parts_op.append(mop)
        if not parts_cols:
            return None
        if len(parts_cols) == 1:
            columns = dict(parts_cols[0])
            seq = parts_seq[0]
            op = parts_op[0]
        else:
            columns = self._concat_columns(names, parts_cols)
            seq = np.concatenate(parts_seq)
            op = np.concatenate(parts_op)
        part_offsets = np.cumsum([0] + sst_part_lens)
        tag_dicts = {
            c.name: self.registry.dict_array(c.name)
            for c in self.schema.tag_columns
            if c.name in names
        }
        result = ScanData(
            schema=self.schema,
            columns=columns,
            seq=seq,
            op_type=op,
            tag_dicts=tag_dicts,
            num_rows=len(seq),
            region_id=self.region_id,
            data_version=version,
            incarnation=self.incarnation,
            # distinct from any full scan: the row set is pruned, so
            # device blocks must never be shared with full-scan keys
            scan_fingerprint=("lastpoint", group_tag, tuple(names)),
            sorted_part_offsets=tuple(int(o) for o in part_offsets),
            part_keys=tuple(part_keys),
            stats={"ssts": len(file_list),
                   "ssts_pruned": len(file_list) - visited,
                   "cache_hits": 0,
                   "lastpoint_visited": visited,
                   "part_hits": part_hits,
                   "files_decoded": files_decoded,
                   "decode_workers": workers},
        )
        with self._lock:
            self._scan_cache_put(cache_key, result)
        return result

    def _scan_since(self, seq_min: int, ts_range, names,
                    tag_predicates) -> Optional[ScanData]:
        """The seq_min slice of scan(): rows with seq > seq_min only.
        The whole-scan result is uncached (each consumer's boundary
        differs and moves every tick), but the per-file decode rides
        the shared part cache + decode pool — a boundary-straddling
        file decodes once, not once per tick, and misses fan out in
        parallel exactly like scan(); SSTs whose max_seq <= seq_min
        never leave disk."""
        from greptimedb_tpu.storage.index import predicates_cache_key

        pred_key = predicates_cache_key(tag_predicates)
        with self._lock:
            version = self.data_version
            file_list = [m for m in self.files.values()
                         if m.max_seq > seq_min]
            self._pin_files(file_list)
            mem = self.memtable.concat(ts_range)
        parts_cols: list[dict] = []
        parts_seq: list[np.ndarray] = []
        parts_op: list[np.ndarray] = []
        sst_part_lens: list[int] = []
        try:
            part_entries, _stats = self._cached_parts(
                file_list, ts_range, names, pred_key, tag_predicates)
        finally:
            self._unpin_files(file_list)
        for ent in part_entries:
            if ent.part is None:
                continue
            # parts are ts-filtered already; the seq boundary applies on
            # COPIES — cached entries must stay whole for full scans
            cols, seq_col, op_col = ent.part
            m = seq_col > seq_min
            if not m.any():
                continue
            if not m.all():
                cols = {n: v[m] for n, v in cols.items()}
                seq_col = seq_col[m]
                op_col = op_col[m]
            parts_cols.append(cols)
            parts_seq.append(seq_col)
            parts_op.append(op_col)
            sst_part_lens.append(len(seq_col))
        if mem is not None:
            mcols, mseq, mop = mem
            m = mseq > seq_min
            if m.any():
                parts_cols.append({n: mcols[n][m] for n in names})
                parts_seq.append(mseq[m])
                parts_op.append(mop[m])
        if not parts_cols:
            return None
        if len(parts_cols) == 1:
            columns = dict(parts_cols[0])
            seq = parts_seq[0]
            op = parts_op[0]
        else:
            columns = self._concat_columns(names, parts_cols)
            seq = np.concatenate(parts_seq)
            op = np.concatenate(parts_op)
        part_offsets = np.cumsum([0] + sst_part_lens)
        tag_dicts = {
            c.name: self.registry.dict_array(c.name)
            for c in self.schema.tag_columns
            if c.name in names
        }
        return ScanData(
            schema=self.schema, columns=columns, seq=seq, op_type=op,
            tag_dicts=tag_dicts, num_rows=len(seq),
            region_id=self.region_id, data_version=version,
            incarnation=self.incarnation,
            scan_fingerprint=(ts_range, tuple(names), "seq", int(seq_min)),
            sorted_part_offsets=tuple(int(o) for o in part_offsets),
        )

    def scan_stream(
        self,
        ts_range: Optional[tuple[int, int]] = None,
        projection: Optional[Sequence[str]] = None,
        tag_predicates: Optional[dict[str, set]] = None,
        groups_per_chunk: int = 8,
    ) -> Optional["ScanStream"]:
        """Lazy bounded-memory scan (see ScanStream). Returns None when the
        time range prunes everything."""
        names = self._scan_columns(projection)
        with self._lock:
            snapshot_files = list(self.files.values())
            self._pin_files(snapshot_files)
            mem = self.memtable.concat(ts_range)
            stream_version = self.data_version
        files = [
            meta for meta in snapshot_files
            if ts_range is None
            or (meta.ts_max >= ts_range[0] and meta.ts_min < ts_range[1])
        ]
        if not files and mem is None:
            self._unpin_files(snapshot_files)
            return None
        bounds = [(m.ts_min, m.ts_max) for m in files]
        if mem is not None and len(mem[1]):
            ts_name = self.schema.time_index.name
            bounds.append((int(mem[0][ts_name].min()),
                           int(mem[0][ts_name].max())))
        ts_min = min(b[0] for b in bounds)
        ts_max = max(b[1] for b in bounds)
        est = sum(m.num_rows for m in files) + (len(mem[1]) if mem else 0)

        unpinned = [False]

        def unpin_once():
            if not unpinned[0]:
                unpinned[0] = True
                self._unpin_files(snapshot_files)

        def gen():
            from greptimedb_tpu.storage import scan_pool

            workers = scan_pool.resolve(self.decode_threads, len(files))
            try:
                if workers <= 1 or len(files) <= 1:
                    # decode_threads=1: byte-for-byte the sequential
                    # pre-pipeline path (parity tests compare to it)
                    for meta in files:
                        for table in self.sst_reader.iter_chunks(
                                meta, self.schema, ts_range, names,
                                tag_predicates=tag_predicates,
                                groups_per_chunk=groups_per_chunk):
                            if table.num_rows:
                                yield (self._decode_sst(table, names),
                                       table.num_rows)
                else:
                    yield from self._stream_files_parallel(
                        files, ts_range, names, tag_predicates,
                        groups_per_chunk, workers)
                if mem is not None and len(mem[1]):
                    yield {n: mem[0][n] for n in names}, len(mem[1])
            finally:
                unpin_once()

        return ScanStream(
            schema=self.schema,
            tag_dicts={
                c.name: self.registry.dict_array(c.name)
                for c in self.schema.tag_columns if c.name in names
            },
            region_id=self.region_id,
            data_version=stream_version,
            incarnation=self.incarnation,
            est_rows=est,
            ts_min=ts_min,
            ts_max=ts_max,
            _chunks=gen,
            _close=unpin_once,
        )

    def _stream_files_parallel(self, files, ts_range, names,
                               tag_predicates, groups_per_chunk,
                               workers: int):
        """Streaming-scan decode pipeline: up to `workers` files decode
        concurrently, each producing into its own small bounded queue;
        the consumer drains queues in file order, so chunks come out in
        EXACTLY the serial order (file order, chunk order within a file
        — the bit-for-bit parity contract) while later files decode in
        the background. Host memory stays bounded: workers x (queue of
        2 + 1 in-flight) chunks. Errors surface at the failing file's
        position in the consumption order, like the serial loop raised
        them.

        Producers run on a PER-STREAM executor, not the shared scan
        pool: a stream is consumer-paced — a client that pauses between
        chunks parks its producers against their full queues for
        arbitrarily long, and on the shared pool those parked workers
        would starve every other scan's decode on the datanode. The
        worker COUNT still honors the [scan] decode_threads sizing."""
        import queue as _queue
        from concurrent.futures import ThreadPoolExecutor
        from concurrent.futures import TimeoutError as _FutTimeout

        from greptimedb_tpu.storage import scan_pool

        pool = ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix="gtpu-stream-decode")
        stop = threading.Event()

        def produce(meta, out):
            try:
                for table in self.sst_reader.iter_chunks(
                        meta, self.schema, ts_range, names,
                        tag_predicates=tag_predicates,
                        groups_per_chunk=groups_per_chunk):
                    if stop.is_set():
                        return
                    if not table.num_rows:
                        continue
                    item = ("chunk",
                            (self._decode_sst(table, names),
                             table.num_rows))
                    while not stop.is_set():
                        try:
                            out.put(item, timeout=0.05)
                            break
                        except _queue.Full:
                            continue
            except BaseException as e:  # noqa: BLE001 — shipped in order
                while not stop.is_set():
                    try:
                        out.put(("error", e), timeout=0.05)
                        return
                    except _queue.Full:
                        continue
            finally:
                while not stop.is_set():
                    try:
                        out.put(("end", None), timeout=0.05)
                        return
                    except _queue.Full:
                        continue

        queues: dict[int, _queue.Queue] = {}
        futs = []
        nxt = 0
        try:
            for i in range(len(files)):
                while nxt < len(files) and nxt < i + workers:
                    q = _queue.Queue(maxsize=2)
                    queues[nxt] = q
                    futs.append(scan_pool.submit(pool, produce,
                                                 files[nxt], q))
                    nxt += 1
                q = queues.pop(i)
                while True:
                    try:
                        kind, payload = q.get(timeout=0.1)
                    except _queue.Empty:
                        # deadline checkpoint: a dead consumer unwinds
                        # typed; the finally stops the producers
                        dl.check("streaming scan wait")
                        continue
                    if kind == "end":
                        break
                    if kind == "error":
                        raise payload
                    yield payload
        finally:
            # producers poll `stop` on every put/iteration; wait for
            # every submitted future so no worker touches SST bytes
            # after the caller's unpin
            stop.set()
            for q in queues.values():
                try:
                    while True:
                        q.get_nowait()
                except _queue.Empty:
                    pass
            for f in futs:
                while True:
                    try:
                        f.result(timeout=30)
                        break
                    except _FutTimeout:
                        # a producer wedged in a slow read still holds
                        # SST handles — the caller's unpin MUST wait it
                        # out, or compaction could delete bytes mid-read
                        continue
                    except Exception:  # noqa: BLE001 — already surfaced
                        break
            pool.shutdown(wait=False)

    def _scan_columns(self, projection: Optional[Sequence[str]]) -> list[str]:
        ts_name = self.schema.time_index.name
        if projection is None:
            return self.schema.names
        names = list(dict.fromkeys(projection))
        if ts_name not in names:
            names.append(ts_name)
        # dedup correctness needs the full primary key
        for c in self.schema.tag_columns:
            if c.name not in names:
                names.append(c.name)
        return [n for n in self.schema.names if n in names]

    def _decode_sst(self, table: pa.Table, names: list[str]) -> dict[str, np.ndarray]:
        cols: dict[str, np.ndarray] = {}
        n = table.num_rows
        for c in self.schema.columns:
            if c.name not in names:
                continue
            if c.name not in table.column_names:
                # column added by ALTER after this SST was written: backfill
                # with the declared default, else NULL (NaN / None / -1 code)
                if c.semantic is SemanticType.TAG:
                    cols[c.name] = np.full(n, -1, dtype=np.int32)
                elif c.dtype.is_string:
                    cols[c.name] = np.full(n, c.default, dtype=object)
                elif c.dtype.is_float:
                    fill = np.nan if c.default is None else float(c.default)
                    cols[c.name] = np.full(n, fill, dtype=c.dtype.to_numpy())
                else:
                    fill = c.default if c.default is not None else 0
                    cols[c.name] = np.full(n, fill, dtype=c.dtype.to_numpy())
                continue
            arr = table.column(c.name)
            if c.semantic is SemanticType.TAG:
                dv = DictVector.from_arrow(
                    arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
                )
                from greptimedb_tpu.datatypes.vector import remap_codes

                mapping = self.registry.remap_dict(c.name, dv.values)
                cols[c.name] = remap_codes(dv.codes, mapping)
            elif c.dtype.is_timestamp:
                cols[c.name] = arr.to_numpy(zero_copy_only=False).astype(np.int64)
            else:
                cols[c.name] = arr.to_numpy(zero_copy_only=False)
        return cols

    # ---- stats -------------------------------------------------------------

    @property
    def num_sst_rows(self) -> int:
        return sum(f.num_rows for f in self.files.values())

    def ts_extent(self) -> Optional[tuple[int, int]]:
        """(min, max) timestamp over SST metas + memtable, or None when
        the region is empty — metadata only, no data read (drives the
        bucket-top-k scan narrowing, physical.py)."""
        with self._lock:
            bounds = [(m.ts_min, m.ts_max) for m in self.files.values()]
            if self.memtable.ts_min is not None:
                bounds.append((self.memtable.ts_min, self.memtable.ts_max))
        if not bounds:
            return None
        return (min(b[0] for b in bounds), max(b[1] for b in bounds))

    @property
    def memtable_bytes(self) -> int:
        return self.memtable.bytes_estimate

    @property
    def l0_count(self) -> int:
        """Unmerged flush outputs — the write-stall backpressure signal
        (the reference stalls writers on L0 pressure the same way)."""
        with self._lock:
            return sum(1 for f in self.files.values() if f.level == 0)
