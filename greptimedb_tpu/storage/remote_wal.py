"""Shared-storage WAL: the remote/Kafka-WAL analog.

Mirrors the reference's `KafkaLogStore` (src/log-store/src/kafka/
log_store.rs — a shared-topic remote WAL so a failover candidate can
replay a dead datanode's unflushed writes from durable shared storage).
The TPU build's shared medium is the object store (fs/memory/S3): each
acknowledged append is one immutable object visible to any node, so a
failover candidate can replay the region — no access to the failed
node's local disk required.

Batching: `append_many` writes ONE segment object per group-commit
cycle, with every entry CRC-framed back-to-back inside it — the analog
of the reference batching records per Kafka producer
(src/log-store/src/kafka/client_manager.rs). On real object stores this
turns a round-trip per entry into a round-trip per commit cycle, which
is what makes group commit effective on exactly the backend that needs
it.

Key layout: `wal/<region_id>/<first_seq:020d>` → one or more CRC-framed
Arrow IPC payloads (same frame as the local WAL, so torn/corrupt tails
are detected). Listing order of the zero-padded keys IS sequence order.
A per-region in-memory segment index (seeded with one listing, then
maintained by append/obsolete) keeps steady-state `obsolete` free of
listings; replay on a fresh node lists once, which is unavoidable.
"""

from __future__ import annotations

import logging
import struct
import threading
import zlib
from typing import Iterator

logger = logging.getLogger(__name__)

from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.fault import FAULTS, FaultError, retry_call
from greptimedb_tpu.objectstore import ObjectStore, ObjectStoreError
from greptimedb_tpu.utils import tracing
from greptimedb_tpu.storage.wal import WalEntry, _decode_batch, _encode_batch

_HEADER = struct.Struct("<IIQQB")  # payload_len, crc32, region_id, seq, op_type


def _encode_entries(region_id: int, entries) -> bytes:
    parts = []
    for seq, op_type, batch in entries:
        payload = _encode_batch(batch)
        parts.append(_HEADER.pack(len(payload), zlib.crc32(payload),
                                  region_id, seq, op_type))
        parts.append(payload)
    return b"".join(parts)


def _decode_entries(data: bytes) -> Iterator[WalEntry]:
    """Parse back-to-back frames; stop at the first torn/corrupt frame
    (nothing after it is trustworthy)."""
    off = 0
    while off + _HEADER.size <= len(data):
        plen, crc, rid, seq, op = _HEADER.unpack_from(data, off)
        payload = data[off + _HEADER.size:off + _HEADER.size + plen]
        if len(payload) != plen or zlib.crc32(payload) != crc:
            return
        yield WalEntry(rid, seq, op, _decode_batch(payload))
        off += _HEADER.size + plen


class RemoteWal:
    """Object-store-backed WAL with the local `Wal` surface (append /
    replay / obsolete / delete_region / close_region / close)."""

    def __init__(self, store: ObjectStore, prefix: str = "wal"):
        self.store = store
        self.prefix = prefix.rstrip("/")
        # region -> sorted list of (first_seq, last_seq, key); None until
        # seeded by one listing
        self._segments: dict[int, list] = {}
        self._lock = threading.Lock()

    def _key(self, region_id: int, seq: int) -> str:
        return f"{self.prefix}/{region_id}/{seq:020d}"

    def _region_prefix(self, region_id: int) -> str:
        return f"{self.prefix}/{region_id}/"

    def _list_segments(self, region_id: int) -> list:
        """(first_seq, key) pairs in sequence order, from one listing.
        last_seq is unknown without reading the object; recorded as None
        and resolved lazily (only `obsolete` cares, and only to decide
        deletability — an unknown last_seq is simply kept)."""
        out = []
        for key in sorted(self.store.list(self._region_prefix(region_id))):
            try:
                first = int(key.rsplit("/", 1)[-1])
            except ValueError:
                continue
            out.append((first, None, key))
        return out

    def _seeded(self, region_id: int) -> list:
        segs = self._segments.get(region_id)
        if segs is None:
            segs = self._list_segments(region_id)
            self._segments[region_id] = segs
        return segs

    # ---- write -------------------------------------------------------------

    def append(self, region_id: int, seq: int, op_type: int,
               batch: RecordBatch) -> None:
        self.append_many(region_id, [(seq, op_type, batch)])

    def append_many(self, region_id: int, entries) -> None:
        """Group-commit: ONE segment object per call, all entries framed
        inside (durable once the object write returns — the object store
        is the fsync)."""
        entries = list(entries)
        if not entries:
            return
        first = entries[0][0]
        last = entries[-1][0]
        key = self._key(region_id, first)
        blob = _encode_entries(region_id, entries)

        # a torn write here is SAFE to leave in place: segments are
        # separate immutable objects, so a corrupt tail in this one
        # never shadows later acknowledged segments at replay
        with tracing.span("wal_append", region=region_id,
                          bytes=len(blob), backend="remote"):
            def attempt():
                try:
                    FAULTS.mangled_write(
                        "wal.append", blob,
                        lambda mangled: self.store.write(key, mangled),
                        # ENOSPC spill: the partial segment lands as a
                        # real object (the multipart-upload-interrupted
                        # shape)...
                        spill=lambda mangled: self.store.write(key,
                                                               mangled))
                except FaultError as e:
                    if e.kind == "enospc":
                        # ...and must NOT survive: the unacknowledged
                        # partial's intact leading frames would replay
                        # as phantom writes on a failover candidate
                        self._erase_partial(key)
                    raise
            retry_call(attempt, point="wal.append")
        with self._lock:
            self._seeded(region_id).append((first, last, key))

    def _erase_partial(self, key: str) -> None:
        """A spilled partial segment must not remain readable: its
        intact leading frames would replay as acknowledged rows. Delete
        it; if the delete ALSO fails, neutralize by overwriting with an
        empty object (zero frames replay as nothing); if even that
        fails, log loudly — silence here is acknowledged-write
        corruption waiting for a failover."""
        try:
            self.store.delete(key)
            return
        except ObjectStoreError:
            pass
        try:
            self.store.write(key, b"")
        except Exception:  # noqa: BLE001 — last resort is the log line
            logger.error(
                "remote WAL: failed to erase partial segment %s after "
                "ENOSPC — unacknowledged frames may replay as phantom "
                "writes", key)

    # ---- replay ------------------------------------------------------------

    def replay(self, region_id: int, from_seq: int = 0) -> Iterator[WalEntry]:
        # transient replay faults retry like the local WAL's; the object
        # reads below carry their own retry at the objectstore seam
        # (no yield inside the with: the span closes before the
        # generator can suspend)
        with tracing.span("wal_replay", region=region_id,
                          backend="remote"):
            retry_call(lambda: FAULTS.fire("wal.replay"),
                       point="wal.replay")
        segs = []
        for key in sorted(self.store.list(self._region_prefix(region_id))):
            try:
                segs.append((int(key.rsplit("/", 1)[-1]), key))
            except ValueError:
                continue
        for i, (first, key) in enumerate(segs):
            # a segment can be skipped WITHOUT reading it when the next
            # segment starts at-or-below from_seq (its entries all
            # precede the next first_seq)
            if i + 1 < len(segs) and segs[i + 1][0] <= from_seq:
                continue
            data = self.store.read(key)
            for entry in _decode_entries(data):
                if entry.seq >= from_seq:
                    yield entry

    # ---- truncation --------------------------------------------------------

    def obsolete(self, region_id: int, up_to_seq: int) -> None:
        """Delete segments whose every entry is below the flushed
        sequence. Uses the in-memory segment index (no listing in steady
        state); a segment with unknown extent (pre-existing object seen
        only via listing) resolves its last entry by reading the object
        once."""
        with self._lock:
            segs = list(self._seeded(region_id))
        resolved = []  # (first, last, key) with last resolved
        deleted: set[str] = set()
        for first, last, key in segs:
            if first < up_to_seq:
                if last is None:
                    last = self._segment_last_seq(key, first)
                if last < up_to_seq:
                    try:
                        self.store.delete(key)
                        deleted.add(key)
                    except ObjectStoreError:
                        pass
            resolved.append((first, last, key))
        resolved_by_key = {key: (first, last)
                           for first, last, key in resolved}
        with self._lock:
            # merge against the CURRENT list: segments appended
            # concurrently must survive, and a region removed by
            # delete_region/close_region must not be resurrected
            current = self._segments.get(region_id)
            if current is not None:
                self._segments[region_id] = [
                    (resolved_by_key.get(key, (first, last))[0],
                     resolved_by_key.get(key, (first, last))[1], key)
                    for first, last, key in current if key not in deleted]

    def _segment_last_seq(self, key: str, first: int) -> int:
        try:
            data = self.store.read(key)
        except ObjectStoreError:
            # unreadable (transient store error): report "infinite" so
            # the caller KEEPS the segment — deleting on a read failure
            # could drop unflushed entries a failover still needs
            return (1 << 62)
        last = first
        for entry in _decode_entries(data):
            last = entry.seq
        return last

    def delete_region(self, region_id: int) -> None:
        for key in self.store.list(self._region_prefix(region_id)):
            try:
                self.store.delete(key)
            except ObjectStoreError:
                pass
        with self._lock:
            self._segments.pop(region_id, None)

    # ---- lifecycle (no per-region handles to manage) ------------------------

    def close_region(self, region_id: int) -> None:
        with self._lock:
            self._segments.pop(region_id, None)

    def close(self) -> None:
        pass
