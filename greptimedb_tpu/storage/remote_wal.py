"""Shared-storage WAL: the remote/Kafka-WAL analog.

Mirrors the reference's `KafkaLogStore` (src/log-store/src/kafka/
log_store.rs — a shared-topic remote WAL so a failover candidate can
replay a dead datanode's unflushed writes from durable shared storage).
The TPU build's shared medium is the object store (fs/memory/S3): each
acknowledged append is one immutable object keyed by sequence, so any
node that can see the store can replay the region — no access to the
failed node's local disk required.

Key layout: `wal/<region_id>/<seq:020d>` → CRC-framed Arrow IPC payload
(same frame as the local WAL, so torn/corrupt objects are detected).
`append` is durable once the object write returns (the object store is
the fsync). `obsolete` deletes keys below the flushed sequence —
per-object, no rewrite. Listing is ordered by the zero-padded key, which
IS sequence order.
"""

from __future__ import annotations

import struct
import zlib
from typing import Iterator

from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.objectstore import ObjectStore, ObjectStoreError
from greptimedb_tpu.storage.wal import WalEntry, _decode_batch, _encode_batch

_HEADER = struct.Struct("<IIQQB")  # payload_len, crc32, region_id, seq, op_type


class RemoteWal:
    """Object-store-backed WAL with the local `Wal` surface (append /
    replay / obsolete / delete_region / close_region / close)."""

    def __init__(self, store: ObjectStore, prefix: str = "wal"):
        self.store = store
        self.prefix = prefix.rstrip("/")

    def _key(self, region_id: int, seq: int) -> str:
        return f"{self.prefix}/{region_id}/{seq:020d}"

    def _region_prefix(self, region_id: int) -> str:
        return f"{self.prefix}/{region_id}/"

    # ---- write -------------------------------------------------------------

    def append(self, region_id: int, seq: int, op_type: int,
               batch: RecordBatch) -> None:
        payload = _encode_batch(batch)
        frame = _HEADER.pack(len(payload), zlib.crc32(payload), region_id,
                             seq, op_type)
        self.store.write(self._key(region_id, seq), frame + payload)

    def append_many(self, region_id: int, entries) -> None:
        """Group-commit analog: one object per entry (object puts are
        atomic; there is no fsync to amortize), same call shape as the
        local WAL so the write workers treat both backends alike."""
        for seq, op_type, batch in entries:
            self.append(region_id, seq, op_type, batch)

    # ---- replay ------------------------------------------------------------

    def replay(self, region_id: int, from_seq: int = 0) -> Iterator[WalEntry]:
        for key in sorted(self.store.list(self._region_prefix(region_id))):
            seq_str = key.rsplit("/", 1)[-1]
            try:
                seq = int(seq_str)
            except ValueError:
                continue
            if seq < from_seq:
                continue
            data = self.store.read(key)
            if len(data) < _HEADER.size:
                break  # torn object: nothing after it is trustworthy
            plen, crc, rid, hseq, op = _HEADER.unpack_from(data, 0)
            payload = data[_HEADER.size:_HEADER.size + plen]
            if len(payload) != plen or zlib.crc32(payload) != crc:
                break
            yield WalEntry(rid, hseq, op, _decode_batch(payload))

    # ---- truncation --------------------------------------------------------

    def obsolete(self, region_id: int, up_to_seq: int) -> None:
        for key in self.store.list(self._region_prefix(region_id)):
            try:
                seq = int(key.rsplit("/", 1)[-1])
            except ValueError:
                continue
            if seq < up_to_seq:
                try:
                    self.store.delete(key)
                except ObjectStoreError:
                    pass

    def delete_region(self, region_id: int) -> None:
        for key in self.store.list(self._region_prefix(region_id)):
            try:
                self.store.delete(key)
            except ObjectStoreError:
                pass

    # ---- lifecycle (no per-region handles to manage) ------------------------

    def close_region(self, region_id: int) -> None:
        pass

    def close(self) -> None:
        pass
