"""Shared bounded thread pool for parallel SST scan decode.

One pool per datanode process (the reference sizes its `SeqScan`
parallelism per ScanRegion against a shared runtime, mito2
read/scan_region.rs): every region's scan fans its parquet
read+decode across the same workers, so the global decode concurrency
is bounded no matter how many regions a query touches. Parquet decode
is C++ (pyarrow releases the GIL), so threads buy real parallelism.

Sizing: `decode_threads` from `[scan]` (EngineConfig.scan_decode_threads)
caps the pool; 0 means auto (min(8, cpu_count)). A scan with one file —
or `decode_threads = 1` — bypasses the pool entirely and decodes inline,
which is byte-for-byte the pre-pipeline sequential path (the chaos
parity tests compare against it). The pool only ever grows: a later
region asking for more workers than the pool has re-creates it larger;
the old executor drains its in-flight work before being collected.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

_AUTO_CAP = 8

_lock = threading.Lock()
_pool: ThreadPoolExecutor | None = None
_pool_size = 0


def resolve(decode_threads: int, num_files: int) -> int:
    """Effective worker count for one scan: the configured cap (0 =
    auto) bounded by the files actually needing decode. The env var
    (set by bench A/B runs and tests) wins over the config object."""
    env = os.environ.get("GREPTIMEDB_TPU_SCAN_DECODE_THREADS")
    if env:
        try:
            decode_threads = int(env)
        except ValueError:
            pass
    if decode_threads <= 0:
        decode_threads = min(_AUTO_CAP, os.cpu_count() or 1)
    return max(1, min(decode_threads, num_files))


def get(workers: int) -> ThreadPoolExecutor:
    """The shared pool, grown to at least `workers`."""
    global _pool, _pool_size
    with _lock:
        if _pool is None or workers > _pool_size:
            _pool_size = max(workers, _pool_size)
            _pool = ThreadPoolExecutor(
                max_workers=_pool_size,
                thread_name_prefix="gtpu-scan-decode")
        return _pool


def submit(pool: ThreadPoolExecutor, fn, *args, **kwargs):
    """Submit a decode unit with the caller's CancelToken re-adopted
    inside the worker (contextvars don't cross threads on their own):
    each unit checkpoints before decoding, so a cancelled or expired
    query's still-queued units unwind typed instead of burning pool
    workers on dead work. Tokenless callers get a plain submit."""
    from greptimedb_tpu.utils import deadline as dl

    token = dl.current()
    if token is None:
        return pool.submit(fn, *args, **kwargs)

    def run():
        with dl.activate(token):
            dl.check("scan decode")
            return fn(*args, **kwargs)

    return pool.submit(run)
