"""SST files: sorted Parquet with min/max pruning.

Mirrors the reference's parquet SST contract (mito2/src/sst/parquet/writer.rs:41-87,
reader row-group pruning at reader.rs:335-447): rows sorted by
(tags..., ts, seq); internal columns `__seq` (write sequence) and `__op_type`
(PUT/DELETE) ride alongside; region schema JSON is stored in the parquet
key-value metadata (analog of PARQUET_METADATA_KEY, sst/parquet.rs:37).

TPU-first deltas from the reference: tags are stored as per-column parquet
dictionary columns (not one memcomparable key blob) because the kernel ABI
wants dense per-tag codes; row groups default to 1M rows so a single row
group fills a device block.
"""

from __future__ import annotations

import json
import os
import uuid
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.datatypes.types import SemanticType
from greptimedb_tpu.datatypes.vector import DictVector
from greptimedb_tpu.objectstore import default_store

SEQ_COL = "__seq"
OP_COL = "__op_type"
METADATA_KEY = b"greptimedb_tpu:region_schema"
# sst format version stamp; files without it predate versioning (= v1)
FORMAT_KEY = b"greptimedb_tpu:sst_format"
DEFAULT_ROW_GROUP = 1 << 20


@dataclass
class FileMeta:
    """Catalog entry for one SST (reference sst/file.rs FileMeta)."""

    file_id: str
    num_rows: int
    ts_min: int
    ts_max: int
    max_seq: int
    level: int = 0
    size_bytes: int = 0
    # tag columns holding any NULL (-1) code in this file, or None when
    # unknown (files written before this field existed). The lastpoint
    # newest-first pruner needs it: NULL-tag rows form a group the
    # registry's cardinality cannot account for, so a file that might
    # hold them blocks early termination unless the NULL group already
    # has a newer candidate.
    null_tags: Optional[list] = None

    def to_dict(self) -> dict:
        return self.__dict__.copy()

    @staticmethod
    def from_dict(d: dict) -> "FileMeta":
        return FileMeta(**d)


class SstWriter:
    def __init__(self, sst_dir: str, schema: Schema,
                 row_group_size: int = DEFAULT_ROW_GROUP, store=None):
        self.sst_dir = sst_dir
        self.schema = schema
        self.row_group_size = row_group_size
        self.store = default_store(store)

    def write(
        self,
        columns: dict[str, np.ndarray],
        tag_dicts: dict[str, np.ndarray],
        seq: np.ndarray,
        op_type: np.ndarray,
        level: int = 0,
    ) -> FileMeta:
        """Write pre-sorted columns (tag columns as int32 codes against
        `tag_dicts`) to a new SST file. Caller guarantees sort order
        (tags..., ts, seq) — flush runs the device sort-dedup first."""
        ts_name = self.schema.time_index.name
        n = len(columns[ts_name])
        arrays, fields = [], []
        for c in self.schema.columns:
            if c.semantic is SemanticType.TAG:
                codes = np.asarray(columns[c.name], dtype=np.int32)
                dv = DictVector(codes, tag_dicts[c.name])
                arrays.append(dv.to_arrow())
            else:
                arrays.append(pa.array(columns[c.name], type=c.dtype.to_arrow()))
            fields.append(pa.field(c.name, arrays[-1].type, nullable=c.nullable))
        arrays.append(pa.array(np.asarray(seq, dtype=np.int64), type=pa.int64()))
        fields.append(pa.field(SEQ_COL, pa.int64(), nullable=False))
        arrays.append(pa.array(np.asarray(op_type, dtype=np.int8), type=pa.int8()))
        fields.append(pa.field(OP_COL, pa.int8(), nullable=False))

        from greptimedb_tpu.storage.format import FORMAT_VERSIONS

        meta = {METADATA_KEY: json.dumps(self.schema.to_dict()).encode(),
                FORMAT_KEY: str(FORMAT_VERSIONS["sst"]).encode()}
        table = pa.Table.from_arrays(arrays, schema=pa.schema(fields, metadata=meta))

        file_id = uuid.uuid4().hex
        path = os.path.join(self.sst_dir, f"{file_id}.parquet")
        sink = pa.BufferOutputStream()
        # physical encodings tuned for the TSBS shape (readers are
        # format-agnostic — parquet self-describes, so old zstd/dict
        # files keep opening, test_compat.py):
        # - lz4 over zstd: scan decode is single-thread bound on the
        #   serving box; lz4 decompresses ~2.6x faster for ~14% more
        #   bytes
        # - BYTE_STREAM_SPLIT on float fields: sensor-range doubles have
        #   near-constant exponent bytes, so splitting byte planes lets
        #   lz4 find them (write 0.90->0.44s, 175->144MB per 2M rows)
        # - DELTA_BINARY_PACKED on ts/seq: repeated or incrementing
        #   int64s collapse to near-nothing
        # tag columns must be listed in use_dictionary explicitly:
        # use_dictionary=False would materialize their DictionaryArrays
        # as dense PLAIN strings (full hostname per row) — the listed
        # form keeps RLE_DICTIONARY on tags while column_encoding
        # applies to the rest.
        encodings = {c.name: "BYTE_STREAM_SPLIT"
                     for c in self.schema.field_columns
                     if c.dtype.is_float}
        encodings[ts_name] = "DELTA_BINARY_PACKED"
        encodings[SEQ_COL] = "DELTA_BINARY_PACKED"
        tag_cols = [c.name for c in self.schema.tag_columns]
        pq.write_table(
            table,
            sink,
            row_group_size=self.row_group_size,
            compression="lz4",
            use_dictionary=tag_cols,
            column_encoding=encodings,
            write_statistics=True,
        )
        self.store.write(path, sink.getvalue())  # pa.Buffer, zero extra copy
        # build the per-file inverted index (tag value -> row-group bitmap)
        from greptimedb_tpu.storage.index import (
            DEFAULT_SEGMENT_ROWS,
            InvertedIndexWriter,
        )

        InvertedIndexWriter(
            self.sst_dir, self.store,
            segment_rows=min(DEFAULT_SEGMENT_ROWS, self.row_group_size),
        ).write(
            file_id,
            {c.name: np.asarray(columns[c.name], dtype=np.int32)
             for c in self.schema.tag_columns},
            tag_dicts,
            self.row_group_size,
            n,
        )
        ts = np.asarray(columns[ts_name])
        null_tags = [
            c.name for c in self.schema.tag_columns
            if n and bool((np.asarray(columns[c.name],
                                      dtype=np.int32) < 0).any())
        ]
        return FileMeta(
            file_id=file_id,
            num_rows=n,
            ts_min=int(ts.min()) if n else 0,
            ts_max=int(ts.max()) if n else 0,
            max_seq=int(np.max(seq)) if n else 0,
            level=level,
            size_bytes=self.store.size(path),
            null_tags=null_tags,
        )


class SstReader:
    def __init__(self, sst_dir: str, store=None):
        from greptimedb_tpu.storage.index import IndexApplier

        self.sst_dir = sst_dir
        self.store = default_store(store)
        self.index_applier = IndexApplier(sst_dir, self.store)

    def path(self, file_id: str) -> str:
        return os.path.join(self.sst_dir, f"{file_id}.parquet")

    def plan_groups(
        self,
        meta: FileMeta,
        schema: Schema,
        ts_range: Optional[tuple[int, int]] = None,
        projection: Optional[Sequence[str]] = None,
        tag_predicates: Optional[dict[str, set]] = None,
    ) -> Optional[tuple]:
        """Pruning phase of `read`, factored out so the scan layer can
        split the surviving row groups across decode workers (one huge
        SST no longer serializes the parallel decode stage). Returns
        (ParquetFile, row-group indices, projected column names) or
        None when pruning rules the whole file out."""
        if ts_range is not None and (meta.ts_max < ts_range[0] or meta.ts_min >= ts_range[1]):
            return None
        # inverted-index pruning first: may rule the file out with no
        # parquet metadata read at all (reference reader.rs:335-425)
        idx_groups = None
        if tag_predicates:
            idx_groups = self.index_applier.apply(meta.file_id, tag_predicates)
            if idx_groups == []:
                return None
        pf = pq.ParquetFile(self.store.open_input(self.path(meta.file_id)))
        _check_sst_format(pf, meta.file_id)
        ts_name = schema.time_index.name
        groups = self._prune_row_groups(pf, ts_name, ts_range)
        if idx_groups is not None:
            allowed = set(idx_groups)
            groups = [g for g in groups if g in allowed]
        if not groups:
            return None
        cols = None
        if projection is not None:
            cols = list(dict.fromkeys(list(projection) + [ts_name, SEQ_COL, OP_COL]))
            # tolerate schema evolution: drop columns the file predates
            avail = set(pf.schema_arrow.names)
            cols = [c for c in cols if c in avail]
        return pf, groups, cols

    def read(
        self,
        meta: FileMeta,
        schema: Schema,
        ts_range: Optional[tuple[int, int]] = None,
        projection: Optional[Sequence[str]] = None,
        tag_predicates: Optional[dict[str, set]] = None,
    ) -> Optional[pa.Table]:
        """Read an SST with row-group pruning on the time index (reference
        reader.rs:427-447 min/max stats pruning). Returns None if fully
        pruned. Internal columns are always materialized."""
        plan = self.plan_groups(meta, schema, ts_range, projection,
                                tag_predicates)
        if plan is None:
            return None
        pf, groups, cols = plan
        return pf.read_row_groups(groups, columns=cols)

    def read_groups(self, meta: FileMeta, groups: Sequence[int],
                    columns: Optional[Sequence[str]]) -> pa.Table:
        """Read specific row groups through a FRESH ParquetFile handle —
        concurrent workers each open their own (pyarrow readers are not
        safe for concurrent reads on one handle). `groups`/`columns`
        come from a prior `plan_groups` call."""
        pf = pq.ParquetFile(self.store.open_input(self.path(meta.file_id)))
        return pf.read_row_groups(list(groups), columns=columns)

    def iter_chunks(
        self,
        meta: FileMeta,
        schema: Schema,
        ts_range: Optional[tuple[int, int]] = None,
        projection: Optional[Sequence[str]] = None,
        tag_predicates: Optional[dict[str, set]] = None,
        groups_per_chunk: int = 8,
    ):
        """Lazily yield row-group batches of an SST (reference
        sst/parquet/row_group.rs lazy InMemoryRowGroup + reader.rs
        FileRange streaming) — bounded memory for beyond-RAM scans. Same
        pruning as `read`; each yield decodes only `groups_per_chunk`
        row groups."""
        if ts_range is not None and (meta.ts_max < ts_range[0]
                                     or meta.ts_min >= ts_range[1]):
            return
        idx_groups = None
        if tag_predicates:
            idx_groups = self.index_applier.apply(meta.file_id, tag_predicates)
            if idx_groups == []:
                return
        pf = pq.ParquetFile(self.store.open_input(self.path(meta.file_id)))
        _check_sst_format(pf, meta.file_id)
        ts_name = schema.time_index.name
        groups = self._prune_row_groups(pf, ts_name, ts_range)
        if idx_groups is not None:
            allowed = set(idx_groups)
            groups = [g for g in groups if g in allowed]
        if not groups:
            return
        cols = None
        if projection is not None:
            cols = list(dict.fromkeys(list(projection) + [ts_name, SEQ_COL, OP_COL]))
            avail = set(pf.schema_arrow.names)
            cols = [c for c in cols if c in avail]
        for i in range(0, len(groups), groups_per_chunk):
            yield pf.read_row_groups(groups[i:i + groups_per_chunk],
                                     columns=cols)

    def _prune_row_groups(
        self, pf: pq.ParquetFile, ts_name: str, ts_range: Optional[tuple[int, int]]
    ) -> list[int]:
        n = pf.metadata.num_row_groups
        if ts_range is None:
            return list(range(n))
        ts_idx = pf.schema_arrow.get_field_index(ts_name)
        ts_type = pf.schema_arrow.field(ts_idx).type
        keep = []
        for g in range(n):
            col = pf.metadata.row_group(g).column(ts_idx)
            stats = col.statistics
            if stats is None or not stats.has_min_max:
                keep.append(g)
                continue
            lo, hi = _ts_stat(stats.min, ts_type), _ts_stat(stats.max, ts_type)
            if hi < ts_range[0] or lo >= ts_range[1]:
                continue
            keep.append(g)
        return keep

    def delete(self, file_id: str) -> None:
        self.store.delete(self.path(file_id))
        from greptimedb_tpu.storage.index import InvertedIndexWriter

        InvertedIndexWriter(self.sst_dir, self.store).delete(file_id)
        self.index_applier.invalidate(file_id)


def _check_sst_format(pf: pq.ParquetFile, file_id: str) -> None:
    """Refuse files stamped with a NEWER sst format (a v1 reader must
    not half-parse a v2 file); absent stamp = v1 (pre-versioning)."""
    from greptimedb_tpu.storage.format import FORMAT_VERSIONS, FormatError

    md = pf.schema_arrow.metadata or {}
    raw = md.get(FORMAT_KEY)
    if raw is not None and int(raw) > FORMAT_VERSIONS["sst"]:
        raise FormatError(
            f"sst {file_id} has format v{int(raw)}; this build reads "
            f"<= v{FORMAT_VERSIONS['sst']}")


def _ts_stat(v, ts_type) -> int:
    """Parquet timestamp stats come back as datetime — normalize to an int
    in the column's own storage unit."""
    if isinstance(v, (int, np.integer)):
        return int(v)
    return pa.scalar(v).cast(ts_type).cast(pa.int64()).as_py()


def schema_from_parquet(path: str) -> Schema:
    pf = pq.ParquetFile(path)
    md = pf.schema_arrow.metadata or {}
    if METADATA_KEY in md:
        return Schema.from_dict(json.loads(md[METADATA_KEY].decode()))
    raise ValueError(f"{path} has no region schema metadata")
