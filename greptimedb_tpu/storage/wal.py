"""Write-ahead log: CRC-framed Arrow IPC entries on local disk.

Mirrors the reference's `LogStore` trait + raft-engine implementation
(src/log-store/src/raft_engine/log_store.rs:44,199) and mito2's `Wal`
append-batch/scan/obsolete surface (mito2/src/wal.rs:53-150). One file per
region namespace; entries are appended with a length+CRC32 frame so torn
tails are detected and truncated on replay. Payload is an Arrow IPC stream
(zero parsing cost on replay — columns come back ready for the memtable).
"""

from __future__ import annotations

import io
import os
import struct
import zlib
from dataclasses import dataclass
from typing import Iterator, Optional

import pyarrow as pa

from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.datatypes.schema import Schema

_HEADER = struct.Struct("<IIQQB")  # payload_len, crc32, region_id, seq, op_type


@dataclass
class WalEntry:
    region_id: int
    seq: int  # sequence of the FIRST row in the batch
    op_type: int
    batch: RecordBatch


class Wal:
    """Per-region write-ahead log over a directory of region files."""

    def __init__(self, wal_dir: str, sync: bool = False):
        self.wal_dir = wal_dir
        self.sync = sync
        os.makedirs(wal_dir, exist_ok=True)
        self._files: dict[int, io.BufferedWriter] = {}

    def _path(self, region_id: int) -> str:
        return os.path.join(self.wal_dir, f"region_{region_id}.wal")

    def _file(self, region_id: int):
        f = self._files.get(region_id)
        if f is None:
            f = open(self._path(region_id), "ab")
            self._files[region_id] = f
        return f

    # ---- write -------------------------------------------------------------

    def append(self, region_id: int, seq: int, op_type: int, batch: RecordBatch) -> None:
        payload = _encode_batch(batch)
        frame = _HEADER.pack(len(payload), zlib.crc32(payload), region_id, seq, op_type)
        f = self._file(region_id)
        f.write(frame)
        f.write(payload)
        f.flush()
        if self.sync:
            os.fsync(f.fileno())

    # ---- replay ------------------------------------------------------------

    def replay(self, region_id: int, from_seq: int = 0) -> Iterator[WalEntry]:
        """Scan entries for a region (reference wal.rs:77 `scan`). Truncates
        a torn tail in place if the last frame is incomplete/corrupt."""
        path = self._path(region_id)
        if not os.path.exists(path):
            return
        self.close_region(region_id)
        valid_end = 0
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        entries = []
        while pos + _HEADER.size <= len(data):
            plen, crc, rid, seq, op = _HEADER.unpack_from(data, pos)
            payload = data[pos + _HEADER.size : pos + _HEADER.size + plen]
            if len(payload) != plen or zlib.crc32(payload) != crc:
                break  # torn tail
            pos += _HEADER.size + plen
            valid_end = pos
            if seq >= from_seq:
                entries.append(WalEntry(rid, seq, op, _decode_batch(payload)))
        if valid_end < len(data):
            with open(path, "r+b") as f:
                f.truncate(valid_end)
        yield from entries

    # ---- truncation (post-flush, reference handle_flush.rs WAL truncate) ----

    def obsolete(self, region_id: int, up_to_seq: int) -> None:
        """Drop entries with seq < up_to_seq by rewriting the file."""
        kept = [e for e in self.replay(region_id) if e.seq >= up_to_seq]
        self.close_region(region_id)
        tmp = self._path(region_id) + ".tmp"
        with open(tmp, "wb") as f:
            for e in kept:
                payload = _encode_batch(e.batch)
                f.write(_HEADER.pack(len(payload), zlib.crc32(payload), e.region_id, e.seq, e.op_type))
                f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(region_id))

    def delete_region(self, region_id: int) -> None:
        self.close_region(region_id)
        try:
            os.remove(self._path(region_id))
        except FileNotFoundError:
            pass

    def close_region(self, region_id: int) -> None:
        f = self._files.pop(region_id, None)
        if f is not None:
            f.close()

    def close(self) -> None:
        for rid in list(self._files):
            self.close_region(rid)


def _encode_batch(batch: RecordBatch) -> bytes:
    sink = pa.BufferOutputStream()
    arrow = batch.to_arrow()
    # carry full schema metadata (semantic roles) through the IPC stream
    schema = batch.schema.to_arrow()
    arrow = pa.RecordBatch.from_arrays(
        [arrow.column(i) for i in range(arrow.num_columns)],
        schema=pa.schema(
            [pa.field(f.name, arrow.schema.field(i).type, metadata=schema.field(i).metadata)
             for i, f in enumerate(schema)],
            metadata=schema.metadata,
        ),
    )
    with pa.ipc.new_stream(sink, arrow.schema) as w:
        w.write_batch(arrow)
    return sink.getvalue().to_pybytes()


def _decode_batch(payload: bytes) -> RecordBatch:
    with pa.ipc.open_stream(payload) as r:
        table = r.read_all()
    if table.num_rows:
        arrow = table.combine_chunks().to_batches()[0]
    else:
        arrow = pa.RecordBatch.from_pydict({f.name: [] for f in table.schema}, schema=table.schema)
    schema = Schema.from_arrow(table.schema)
    return RecordBatch.from_arrow(arrow, schema)
