"""Write-ahead log: CRC-framed Arrow IPC entries in segmented local files.

Mirrors the reference's `LogStore` trait + raft-engine implementation
(src/log-store/src/raft_engine/log_store.rs:44,199) and mito2's `Wal`
append-batch/scan/obsolete surface (mito2/src/wal.rs:53-150). Entries are
appended with a length+CRC32 frame so torn tails are detected and truncated
on replay. Payload is an Arrow IPC stream (zero parsing cost on replay —
columns come back ready for the memtable).

Durability: fsync at the append boundary by DEFAULT (the reference's
raft-engine fsyncs its write batch; a database that loses acknowledged
writes on power cut isn't one). Writes arrive pre-batched (one frame per
put), so the fsync amortizes over the batch exactly like the reference's
group commit (mito2 worker batches ≤64 requests into one WAL write,
worker.rs:576-650).

Truncation: the log is a sequence of SEGMENT files per region
(`region_<id>.<segno>.wal`), rolled at a size threshold. `obsolete`
deletes whole segments whose entries are all below the flushed sequence —
O(#segments) header scans, no payload rewrite (the round-1 implementation
replayed and rewrote the entire file per flush).
"""

from __future__ import annotations

import io
import os
import re
import struct
import zlib
from dataclasses import dataclass
from typing import Iterator


def _try_native():
    try:
        from greptimedb_tpu.native import try_load
        return try_load()
    except Exception:  # noqa: BLE001 — WAL must work without a toolchain
        return None


_native = _try_native()

import pyarrow as pa

from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.utils import tracing
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.fault import FAULTS, FaultError, retry_call

_HEADER = struct.Struct("<IIQQB")  # payload_len, crc32, region_id, seq, op_type

DEFAULT_SEGMENT_BYTES = 64 << 20

_SEG_RE = re.compile(r"^region_(\d+)\.(\d+)\.wal$")


@dataclass
class WalEntry:
    region_id: int
    seq: int  # sequence of the FIRST row in the batch
    op_type: int
    batch: RecordBatch


class Wal:
    """Per-region segmented write-ahead log over a directory."""

    def __init__(self, wal_dir: str, sync: bool = True,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        self.wal_dir = wal_dir
        self.sync = sync
        self.segment_bytes = segment_bytes
        self.sync_count = 0  # fsyncs issued (observability + group-commit tests)
        os.makedirs(wal_dir, exist_ok=True)
        # region -> (segno, open append handle)
        self._files: dict[int, tuple[int, io.BufferedWriter]] = {}

    def _seg_path(self, region_id: int, segno: int) -> str:
        return os.path.join(self.wal_dir, f"region_{region_id}.{segno:08d}.wal")

    def _segments(self, region_id: int) -> list[tuple[int, str]]:
        """Sorted (segno, path) for a region, including a legacy unsegmented
        `region_<id>.wal` file as segment -1 if present."""
        out = []
        legacy = os.path.join(self.wal_dir, f"region_{region_id}.wal")
        if os.path.exists(legacy):
            out.append((-1, legacy))
        try:
            names = os.listdir(self.wal_dir)
        except FileNotFoundError:
            return out
        for name in names:
            m = _SEG_RE.match(name)
            if m and int(m.group(1)) == region_id:
                out.append((int(m.group(2)), os.path.join(self.wal_dir, name)))
        out.sort()
        return out

    def _writer(self, region_id: int):
        ent = self._files.get(region_id)
        if ent is None:
            segs = self._segments(region_id)
            segno = segs[-1][0] if segs else 0
            if segno < 0:
                segno = 0
            f = open(self._seg_path(region_id, segno), "ab")
            ent = (segno, f)
            self._files[region_id] = ent
        return ent

    def _roll(self, region_id: int) -> None:
        segno, f = self._files.pop(region_id)
        f.close()
        nf = open(self._seg_path(region_id, segno + 1), "ab")
        self._files[region_id] = (segno + 1, nf)

    # ---- write -------------------------------------------------------------

    def append(self, region_id: int, seq: int, op_type: int, batch: RecordBatch) -> None:
        self.append_many(region_id, [(seq, op_type, batch)])

    def append_many(self, region_id: int,
                    entries: list[tuple[int, int, "RecordBatch"]]) -> None:
        """Append several (seq, op_type, batch) entries with ONE fsync —
        the group-commit boundary the write workers amortize over
        (reference WalWriter::write_to_wal batches per flush,
        mito2/src/wal.rs:133-150)."""
        if not entries:
            return
        self.append_blob(region_id, self.encode_entries(region_id, entries))

    @staticmethod
    def encode_entries(region_id: int,
                       entries: list[tuple[int, int, "RecordBatch"]]
                       ) -> bytes:
        """Frame (seq, op_type, batch) entries into the CRC'd append
        blob WITHOUT touching file state. Pure CPU (Arrow IPC + LZ4), so
        the group-commit pipeline runs it outside every lock: batch N+1
        encodes while batch N's fsync is still in flight."""
        parts = []
        for seq, op_type, batch in entries:
            payload = _encode_batch(batch)
            parts.append(_HEADER.pack(len(payload), zlib.crc32(payload),
                                      region_id, seq, op_type))
            parts.append(payload)
        return b"".join(parts)

    def append_blob(self, region_id: int, blob: bytes) -> None:
        """Durably append a pre-encoded frame blob: one write, one
        fsync, crash-consistent (a partial tail is truncated before the
        error surfaces). Callers serialize per region — group commit by
        ticket order, the legacy path under the region lock."""
        _segno, f = self._writer(region_id)

        # span-covered durability boundary: on the serial write path the
        # append lands in the request's trace; group-commit leaders
        # record it unattributed (one span per drained group, not per
        # writer — a leader serves many writers' traces at once)
        with tracing.span("wal_append", region=region_id,
                          bytes=len(blob)):
            def sink(mangled: bytes) -> None:
                f.write(mangled)
                f.flush()
                if self.sync:
                    os.fsync(f.fileno())  # ← the durability boundary
                    self.sync_count += 1

            def attempt():
                start = f.tell()
                try:
                    # spill=sink: an injected ENOSPC lands its partial
                    # bytes in the file tail first (what a real full
                    # disk does to an append) — the repair below must
                    # erase them
                    FAULTS.mangled_write("wal.append", blob, sink,
                                         spill=sink)
                except BaseException:
                    # crash-consistency repair: an append lands whole or
                    # not at all. A partial tail left in place would
                    # orphan every LATER acknowledged frame at replay
                    # (replay stops at the first corrupt frame); a
                    # partial ENOSPC tail is the same shape and takes
                    # the same truncate.
                    try:
                        f.flush()
                        f.truncate(start)
                        f.seek(start)
                    except OSError:
                        pass
                    raise
            retry_call(attempt, point="wal.append")
        if f.tell() >= self.segment_bytes:
            self._roll(region_id)

    # ---- replay ------------------------------------------------------------

    def replay(self, region_id: int, from_seq: int = 0) -> Iterator[WalEntry]:
        """Scan entries across segments in order (reference wal.rs:77
        `scan`). A torn tail in the LAST segment is truncated in place; a
        corrupt frame in an earlier segment stops replay there (entries
        beyond it were never acknowledged as durable in order)."""
        self.close_region(region_id)
        segs = self._segments(region_id)
        for i, (segno, path) in enumerate(segs):
            # the with-block holds no yield: the span closes before the
            # generator can suspend, so the caller's span-parent context
            # is never left dangling across a consumption gap
            with tracing.span("wal_replay_read", region=region_id,
                              segment=segno):
                def read_segment(path=path):
                    with open(path, "rb") as f:
                        raw = f.read()
                    mangled, _ = FAULTS.mangle("wal.replay", raw)
                    if len(mangled) < len(raw):
                        # injected short read: surfacing the truncated
                        # bytes would truncate DURABLE frames below —
                        # treat as a transient I/O error and re-read
                        raise FaultError("wal.replay", kind="short_read")
                    return raw
                data = retry_call(read_segment, point="wal.replay")
            entries = []
            if _native is not None:
                # one native pass: bounds + checksum + record table
                recs, valid_end = _native.wal_scan(data)
                for off, plen, rid, seq, op in recs:
                    if seq >= from_seq:
                        entries.append(WalEntry(
                            rid, seq, op,
                            _decode_batch(data[off:off + plen])))
            else:
                pos = 0
                valid_end = 0
                while pos + _HEADER.size <= len(data):
                    plen, crc, rid, seq, op = _HEADER.unpack_from(data, pos)
                    payload = data[pos + _HEADER.size : pos + _HEADER.size + plen]
                    if len(payload) != plen or zlib.crc32(payload) != crc:
                        break  # torn tail
                    pos += _HEADER.size + plen
                    valid_end = pos
                    if seq >= from_seq:
                        entries.append(WalEntry(rid, seq, op, _decode_batch(payload)))
            if valid_end < len(data):
                with open(path, "r+b") as f:
                    f.truncate(valid_end)
                yield from entries
                return  # nothing after a torn frame is trustworthy
            yield from entries

    # ---- truncation (post-flush, reference handle_flush.rs WAL truncate) ----

    def obsolete(self, region_id: int, up_to_seq: int) -> None:
        """Drop whole segments whose entries all have seq < up_to_seq.
        Header-only scan per segment — no payload decode, no rewrite. The
        active (last) segment is never deleted; its obsolete prefix is
        bounded by segment_bytes and ignored on replay via from_seq."""
        self.close_region(region_id)
        segs = self._segments(region_id)
        for segno, path in segs[:-1] if segs else []:
            if self._max_seq(path) < up_to_seq:
                os.remove(path)
            else:
                break  # segments are in seq order; later ones are newer

    @staticmethod
    def _max_seq(path: str) -> int:
        """Highest frame seq in a sealed segment (header-skip scan)."""
        best = -1
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            pos = 0
            while pos + _HEADER.size <= size:
                hdr = f.read(_HEADER.size)
                if len(hdr) < _HEADER.size:
                    break
                plen, _, _, seq, _ = _HEADER.unpack(hdr)
                if pos + _HEADER.size + plen > size:
                    break  # torn
                best = max(best, seq)
                pos += _HEADER.size + plen
                f.seek(pos)
        return best

    def delete_region(self, region_id: int) -> None:
        self.close_region(region_id)
        for _, path in self._segments(region_id):
            try:
                os.remove(path)
            except FileNotFoundError:
                pass

    def close_region(self, region_id: int) -> None:
        ent = self._files.pop(region_id, None)
        if ent is not None:
            ent[1].close()

    def close(self) -> None:
        for rid in list(self._files):
            self.close_region(rid)


def _encode_batch(batch: RecordBatch) -> bytes:
    sink = pa.BufferOutputStream()
    arrow = batch.to_arrow()
    # carry full schema metadata (semantic roles) through the IPC stream
    schema = batch.schema.to_arrow()
    arrow = pa.RecordBatch.from_arrays(
        [arrow.column(i) for i in range(arrow.num_columns)],
        schema=pa.schema(
            [pa.field(f.name, arrow.schema.field(i).type, metadata=schema.field(i).metadata)
             for i, f in enumerate(schema)],
            metadata=schema.metadata,
        ),
    )
    # LZ4-frame body compression: the IPC stream records it, so replay
    # transparently reads both compressed and legacy uncompressed frames.
    # Halves WAL bytes at the fsync boundary (the ingest bottleneck) for
    # ~GB/s compression cost.
    opts = pa.ipc.IpcWriteOptions(compression="lz4")
    with pa.ipc.new_stream(sink, arrow.schema, options=opts) as w:
        w.write_batch(arrow)
    return sink.getvalue().to_pybytes()


def _decode_batch(payload: bytes) -> RecordBatch:
    with pa.ipc.open_stream(payload) as r:
        table = r.read_all()
    if table.num_rows:
        arrow = table.combine_chunks().to_batches()[0]
    else:
        arrow = pa.RecordBatch.from_pydict({f.name: [] for f in table.schema}, schema=table.schema)
    schema = Schema.from_arrow(table.schema)
    return RecordBatch.from_arrow(arrow, schema)
