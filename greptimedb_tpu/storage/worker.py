"""Write worker group: sharded queues, request batching, backpressure.

Mirrors the reference's actor-style write path (mito2/src/worker.rs:110:
a WorkerGroup of N=cpu/2 workers; region→worker by
``(table_id % N + region_number % N) % N`` :310-312; each worker drains
its request buffer in batches of ≤64 :576-650 and issues one WAL write
for the whole cycle via RegionWriteCtx).

Shape here: one thread per worker draining a BOUNDED queue (the
backpressure boundary — submit blocks when a worker falls behind, exactly
like the reference's bounded mpsc), grouping the drained cycle's
mutations per region, and committing each region's group through
``Region.write_many`` (one fsync). Callers get a Future; ``put``-style
callers block on it, so the synchronous RegionEngine API is unchanged
while concurrent callers' fsyncs amortize."""

from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional

BATCH_MAX = 64  # requests drained per worker cycle (worker.rs:650)


@dataclass
class _WriteReq:
    region_id: int
    batch: object
    op_type: int
    future: Future = field(default_factory=Future)


class WorkerGroup:
    def __init__(self, engine, num_workers: Optional[int] = None,
                 queue_capacity: int = 256):
        if num_workers is None:
            num_workers = max(1, (os.cpu_count() or 2) // 2)
        self.engine = engine
        self.n = num_workers
        self._queues = [queue.Queue(maxsize=queue_capacity)
                        for _ in range(num_workers)]
        self._threads = []
        self._stopping = False
        # serializes submit vs stop: guarantees no request is enqueued
        # AFTER a worker's shutdown sentinel (such a request's Future
        # would never resolve and its caller would hang forever)
        self._submit_lock = threading.Lock()
        for i in range(num_workers):
            t = threading.Thread(target=self._run, args=(i,), daemon=True,
                                 name=f"write-worker-{i}")
            t.start()
            self._threads.append(t)

    def _shard(self, region_id: int) -> int:
        table_id = region_id >> 32
        region_number = region_id & 0xFFFFFFFF
        return (table_id % self.n + region_number % self.n) % self.n

    def submit(self, region_id: int, batch, op_type: int) -> Future:
        req = _WriteReq(region_id, batch, op_type)
        with self._submit_lock:
            if self._stopping:
                raise RuntimeError("worker group is stopped")
            # blocks when the worker's queue is full = backpressure
            self._queues[self._shard(region_id)].put(req)
        return req.future

    def write(self, region_id: int, batch, op_type: int) -> int:
        """Submit + wait — the synchronous RegionEngine surface."""
        return self.submit(region_id, batch, op_type).result()

    # ---- worker loop --------------------------------------------------------

    def _run(self, idx: int) -> None:
        q = self._queues[idx]
        while True:
            req = q.get()
            if req is None:
                self._drain_and_exit(q)
                return
            cycle = [req]
            while len(cycle) < BATCH_MAX:
                try:
                    nxt = q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._flush_cycle(cycle)
                    self._drain_and_exit(q)
                    return
                cycle.append(nxt)
            self._flush_cycle(cycle)

    def _drain_and_exit(self, q) -> None:
        """Complete anything still queued at shutdown (submit/stop are
        mutually excluded, so nothing can arrive after this drain)."""
        leftover = []
        while True:
            try:
                nxt = q.get_nowait()
            except queue.Empty:
                break
            if nxt is not None:
                leftover.append(nxt)
        if leftover:
            self._flush_cycle(leftover)

    def _flush_cycle(self, cycle: list[_WriteReq]) -> None:
        # group per region, order preserved within a region (LWW depends
        # on submission order mapping to sequence order)
        by_region: dict[int, list[_WriteReq]] = {}
        for r in cycle:
            by_region.setdefault(r.region_id, []).append(r)
        for region_id, reqs in by_region.items():
            try:
                region = self.engine.region(region_id)
                counts = region.write_many(
                    [(r.batch, r.op_type) for r in reqs])
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for r in reqs:
                    if not r.future.done():
                        r.future.set_exception(e)
                continue
            for r, n in zip(reqs, counts):
                r.future.set_result(n)

    def stop(self) -> None:
        with self._submit_lock:
            self._stopping = True
            for q in self._queues:
                q.put(None)
        for t in self._threads:
            t.join(timeout=10)
