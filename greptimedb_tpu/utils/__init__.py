"""Shared utilities (the analog of reference src/common/{time,base,...})."""
