"""End-to-end query deadlines + cooperative cancellation.

Every statement executes under a `CancelToken` carrying an absolute
deadline (from the X-Greptime-Timeout header, the MySQL
`max_execution_time` / PG `statement_timeout` session vars, or
`[query] default_timeout_ms`) and a cancel event (KILL QUERY,
DELETE /v1/queries/<id>, or client disconnect). The token rides a
contextvar so every layer under the statement — admission wait, device
dispatch loop, scan-pool decode units, group-commit waits, retry
backoff — can call `check()` / `sleep()` / `wait_event()` without
plumbing arguments through ten signatures, and worker threads re-adopt
it via `activate()`.

Expiry raises the typed `DeadlineExceeded`, cancellation the typed
`Cancelled` (both `Unavailable` siblings, fault/retry.py) — wire
servers map them to HTTP 408/499, MySQL 3024/1317, PG 57014 instead of
a 503 or a stack trace. The remaining budget also rides Flight
scan/fragment tickets as milliseconds (`budget_ms()` on the client,
`token_for_budget()` at datanode ingress) so datanodes abandon work for
requests whose frontend already gave up.

The frontend `RUNNING` registry (one entry per in-flight statement)
backs `information_schema.running_queries`, `/v1/queries`, and
`KILL QUERY <id>`.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from typing import Optional

from greptimedb_tpu.fault.retry import (  # noqa: F401 — re-exported taxonomy
    Cancelled,
    DeadlineExceeded,
)
from greptimedb_tpu.utils.metrics import DEADLINE_EVENTS

#: how often a blocked wait re-checks its token when nothing else wakes
#: it — the cancellation-latency floor for waits on foreign events
POLL_S = 0.05


class CancelToken:
    """One query's deadline + cancel state. Thread-safe; shared by every
    thread working for the query (scan-pool workers, batch leaders,
    hedge attempts). `check()` raises typed exactly once per cause —
    the first raise counts the deadline event, later raises unwind the
    remaining layers without inflating the counter."""

    __slots__ = ("query_id", "deadline", "reason", "kind", "_event",
                 "_counted", "_lock")

    def __init__(self, timeout_ms: Optional[float] = None,
                 query_id: Optional[int] = None):
        self.query_id = query_id
        self.deadline = (time.monotonic() + timeout_ms / 1000.0) \
            if timeout_ms and timeout_ms > 0 else None
        self.reason: str = ""
        self.kind: str = ""      # "" | expired | cancelled | killed
        self._event = threading.Event()
        self._counted = False
        self._lock = threading.Lock()

    # -- state ----------------------------------------------------------------

    def cancel(self, reason: str = "cancelled",
               kind: str = "cancelled", count: bool = True) -> None:
        """Cooperatively cancel (kind: cancelled = disconnect/hedge
        loser, killed = KILL QUERY / DELETE-to-kill). Idempotent; the
        first cause wins. `count=False` pre-marks the token as counted:
        hedge losers are infrastructure churn, not query deadline
        events, and must not inflate the counter."""
        with self._lock:
            if not self.kind:
                self.kind = kind
                self.reason = reason
            if not count:
                self._counted = True
        self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def set_timeout(self, timeout_ms: Optional[float]) -> None:
        """Arm the deadline if none is set yet (servers pre-create the
        token for disconnect detection; the engine resolves the budget
        once session vars and defaults are known)."""
        if timeout_ms and timeout_ms > 0 and self.deadline is None:
            self.deadline = time.monotonic() + timeout_ms / 1000.0

    def expired(self) -> bool:
        return self.deadline is not None \
            and time.monotonic() >= self.deadline

    def remaining_s(self) -> Optional[float]:
        """Seconds of budget left; None = no deadline; never negative."""
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.monotonic())

    def remaining_ms(self) -> Optional[float]:
        r = self.remaining_s()
        return None if r is None else r * 1000.0

    # -- the cooperative checkpoint -------------------------------------------

    def _count_once(self, kind: str) -> None:
        with self._lock:
            if self._counted:
                return
            self._counted = True
            if not self.kind:
                self.kind = kind
        DEADLINE_EVENTS.inc(event=self.kind or kind)

    def check(self, where: str = "") -> None:
        """Raise typed if this query is cancelled or past its deadline.
        The cheap per-iteration checkpoint: one Event.is_set + one
        monotonic read."""
        at = f" at {where}" if where else ""
        if self._event.is_set():
            self._count_once(self.kind or "cancelled")
            why = f" ({self.reason})" if self.reason else ""
            raise Cancelled(f"query cancelled{at}{why}")
        if self.expired():
            self._count_once("expired")
            raise DeadlineExceeded(f"query deadline exceeded{at}")

    def clip(self, timeout_s: float) -> float:
        """`timeout_s` clipped to the remaining budget (for bounded
        waits that already have their own timeout)."""
        r = self.remaining_s()
        return timeout_s if r is None else min(timeout_s, r)


# ---- contextvar plumbing ----------------------------------------------------

_current: contextvars.ContextVar = contextvars.ContextVar(
    "gtpu_cancel_token", default=None)


def current() -> Optional[CancelToken]:
    return _current.get()


@contextlib.contextmanager
def activate(token: Optional[CancelToken]):
    """Install `token` as the calling thread's active token (None = run
    unbounded — e.g. maintenance work that must not inherit a query's
    budget). Worker threads executing on a query's behalf re-adopt the
    submitting thread's token through this."""
    cv_token = _current.set(token)
    try:
        yield token
    finally:
        _current.reset(cv_token)


def check(where: str = "") -> None:
    """Module-level checkpoint: no-op without an active token."""
    token = _current.get()
    if token is not None:
        token.check(where)


def remaining_ms() -> Optional[float]:
    token = _current.get()
    return None if token is None else token.remaining_ms()


def budget_ms() -> Optional[int]:
    """The remaining budget to stamp on an outgoing scan/fragment
    ticket (whole milliseconds; None = unbounded)."""
    r = remaining_ms()
    return None if r is None else max(0, int(r))


def default_timeout_ms() -> float:
    """[query] default_timeout_ms, env-mediated (options.py writes
    GTPU_QUERY_DEFAULT_TIMEOUT_MS so children inherit); 0 = unbounded."""
    try:
        return float(os.environ.get("GTPU_QUERY_DEFAULT_TIMEOUT_MS",
                                    "0") or 0.0)
    except ValueError:
        return 0.0


def parse_timeout_ms(value) -> Optional[float]:
    """Tolerant session-var parse: 500 / '500' are milliseconds (the
    MySQL max_execution_time unit), '500ms' / '2s' / '1min' carry a PG
    interval unit, quotes are shed. None/unparseable -> None."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip().strip("'\"").lower()
    if not s:
        return None
    mult = 1.0
    if s.endswith("ms"):
        s = s[:-2]
    elif s.endswith("min"):
        s, mult = s[:-3], 60000.0
    elif s.endswith("s"):
        s, mult = s[:-1], 1000.0
    try:
        return float(s) * mult
    except ValueError:
        return None


def token_for_budget(budget: Optional[float]) -> Optional[CancelToken]:
    """Datanode ingress: a local token enforcing the budget a ticket
    carried (server-side deadline enforcement — the frontend's token
    cannot cross the process boundary)."""
    if budget is None:
        return None
    return CancelToken(timeout_ms=float(budget))


def sleep(delay_s: float, point: str = "") -> None:
    """Interruptible sleep: wakes (and raises typed) the moment the
    active token is cancelled, and never sleeps past its deadline.
    Without a token this is a plain time.sleep."""
    token = _current.get()
    if token is None:
        if delay_s > 0:
            time.sleep(delay_s)
        return
    token.check(point)
    remaining = token.remaining_s()
    bounded = delay_s if remaining is None else min(delay_s, remaining)
    if bounded > 0 and token._event.wait(bounded):
        pass  # cancelled mid-sleep: fall through to the typed raise
    token.check(point)


def propagate(fn):
    """Wrap `fn` so the CALLER's active token rides into whichever
    worker thread runs it (contextvars don't cross threads on their
    own) — the deadline analog of tracing.propagate, for fan-out sites
    that hand per-region/per-file work to an executor."""
    token = _current.get()
    if token is None:
        return fn

    def run(*args, **kwargs):
        with activate(token):
            return fn(*args, **kwargs)

    return run


def wait_future(fut, where: str = ""):
    """Deadline-aware Future.result(): re-checks the active token every
    POLL_S so a cancelled/expired query unwinds typed instead of
    parking on a wedged worker. Tokenless callers block plainly (with a
    long bound so a wedged pool is diagnosable, not a silent hang)."""
    from concurrent.futures import TimeoutError as _FutTimeout

    token = _current.get()
    if token is None:
        return fut.result(timeout=3600.0)
    while True:
        token.check(where)
        try:
            return fut.result(timeout=POLL_S)
        except _FutTimeout:
            continue


def wait_event(event: threading.Event, timeout_s: float,
               where: str = "") -> bool:
    """Wait on a foreign event (admission grant, batch-leader done,
    single-flight result) while honoring the active token: returns
    event.is_set() within `timeout_s`, raises typed on cancel/expiry.
    The foreign event's owner doesn't know about the token, so the wait
    re-checks every POLL_S."""
    token = _current.get()
    if token is None:
        return event.wait(timeout_s)
    end = time.monotonic() + timeout_s
    while True:
        token.check(where)
        left = end - time.monotonic()
        if left <= 0:
            return event.is_set()
        if event.wait(min(POLL_S, token.clip(left))):
            return True


def watch_disconnect(sock, token: CancelToken):
    """Cancel `token` when the client socket hits EOF while its
    statement executes (the HTTP/MySQL/PG request is fully read, so
    readable-with-zero-bytes means the peer closed — abandoning work for
    a dead client is the whole point of the cancellation plane).
    Returns a stop() callable the server invokes once the statement
    finishes. Non-fatal best effort: a TLS-wrapped socket can't be
    MSG_PEEKed with flags, so the watcher just stands down."""
    import socket as _socket

    done = threading.Event()

    def run():
        while not done.wait(POLL_S):
            try:
                data = sock.recv(1, _socket.MSG_PEEK | _socket.MSG_DONTWAIT)
            except (BlockingIOError, InterruptedError, TimeoutError):
                continue  # nothing readable: the client is still there
            except ValueError:
                return  # TLS socket: flags unsupported, cannot watch
            except OSError:
                token.cancel("client disconnected", kind="cancelled")
                return
            if data == b"":
                token.cancel("client disconnected", kind="cancelled")
                return
            return  # pipelined next request, not a close: stand down

    threading.Thread(target=run, name="gtpu-disconnect-watch",
                     daemon=True).start()
    return done.set


# ---- frontend running-queries registry --------------------------------------


class RunningQueries:
    """Every in-flight statement on this frontend, keyed by a
    process-unique query id — the surface behind
    information_schema.running_queries, /v1/queries, and KILL QUERY."""

    def __init__(self):
        self._ids = itertools.count(1)
        self._entries: dict[int, dict] = {}
        self._lock = threading.Lock()

    def register(self, token: CancelToken, sql: str, db: str = "",
                 channel: str = "", tenant: str = "",
                 trace_id: str = "") -> int:
        qid = next(self._ids)
        token.query_id = qid
        with self._lock:
            self._entries[qid] = {
                "id": qid, "token": token, "query": sql, "db": db,
                "channel": channel, "tenant": tenant or "default",
                "trace_id": trace_id or "",
                "start_monotonic": time.monotonic(),
                "start_time_ms": int(time.time() * 1000),
            }
        return qid

    def unregister(self, qid: Optional[int]) -> None:
        if qid is None:
            return
        with self._lock:
            self._entries.pop(qid, None)

    def get(self, qid: int) -> Optional[dict]:
        with self._lock:
            return self._entries.get(qid)

    def kill(self, qid: int, reason: str = "killed") -> bool:
        """Cancel query `qid` (KILL QUERY / DELETE /v1/queries/<id>).
        False when the id is unknown or already finished."""
        with self._lock:
            entry = self._entries.get(qid)
        if entry is None:
            return False
        entry["token"].cancel(reason=reason, kind="killed")
        return True

    def list(self) -> list[dict]:
        """Snapshot for the observability surfaces (token objects
        replaced by their state)."""
        now = time.monotonic()
        with self._lock:
            entries = [dict(e) for e in self._entries.values()]
        out = []
        for e in entries:
            token: CancelToken = e.pop("token")
            rem = token.remaining_ms()
            e["elapsed_ms"] = (now - e.pop("start_monotonic")) * 1000.0
            e["remaining_ms"] = rem
            e["cancelled"] = token.cancelled
            out.append(e)
        out.sort(key=lambda e: e["id"])
        return out


#: process-wide registry (frontends register; datanode budget tokens
#: are anonymous and never land here)
RUNNING = RunningQueries()
