"""TPU runtime telemetry: XLA compile, device memory, and link traffic.

The reference exposes per-subsystem prometheus registries (SURVEY §5);
the TPU-native equivalent must also surface what the ACCELERATOR is
doing — a 25 s XLA recompile or an HBM cache that stopped fitting is
invisible in query latency histograms alone. Three feeds:

- **Compiles**: `jax.monitoring` emits a duration event per backend
  compile (`/jax/core/compile/backend_compile_duration`) for every
  `jax.jit` entry point in ops/ and query/physical.py — one listener
  covers them all without wrapping call sites.
- **Device memory**: a render-time collector reads the PJRT allocator's
  `memory_stats()` (bytes_in_use / bytes_limit on TPU; the CPU backend
  reports none) plus the device block cache's own pinned-bytes
  accounting, which works on every backend.
- **Transfers**: `count_h2d`/`count_d2h` are called at the scan-block
  upload and result-readback seams in query/physical.py and
  query/device_cache.py.

`install()` is idempotent and cheap; importing query/physical.py wires
everything.
"""

from __future__ import annotations

import threading
import weakref

from greptimedb_tpu.utils import ledger
from greptimedb_tpu.utils.metrics import (
    DEVICE_MEMORY,
    DEVICE_TRANSFER_BYTES,
    REGISTRY,
    XLA_COMPILE_SECONDS,
    XLA_COMPILES,
)

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
#: fired INSIDE backend_compile when the persistent compilation cache
#: serves the executable — that enclosing compile event is a retrieval,
#: not a compilation, and must not count as one (the serving fabric's
#: shared-executable contract is "process 2 compiles nothing", asserted
#: as an xla_compile_total delta of zero)
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_retrieval_time_sec"

#: both events fire on the thread running the compile, so a plain
#: thread-local flag pairs a retrieval with its enclosing compile event
_compile_tls = threading.local()

_install_lock = threading.Lock()
_installed = False

#: live DeviceCache instances (registered by DeviceCache.__init__) —
#: the memory collector sums their pinned bytes at scrape time
_caches: "weakref.WeakSet" = weakref.WeakSet()


def register_cache(cache) -> None:
    _caches.add(cache)


def count_h2d(nbytes: int) -> None:
    if nbytes:
        DEVICE_TRANSFER_BYTES.inc(float(nbytes), direction="h2d")
        ledger.add("h2d_bytes", float(nbytes))


def count_d2h(nbytes: int) -> None:
    if nbytes:
        DEVICE_TRANSFER_BYTES.inc(float(nbytes), direction="d2h")
        ledger.add("d2h_bytes", float(nbytes))


def _on_event_duration(event: str, duration_secs: float, **kwargs) -> None:
    if event == _CACHE_HIT_EVENT:
        pending = getattr(_compile_tls, "cache_hits", 0)
        _compile_tls.cache_hits = pending + 1
        return
    if event != _COMPILE_EVENT:
        return
    pending = getattr(_compile_tls, "cache_hits", 0)
    if pending:
        # persistent-cache retrieval wrapped in a compile event: the
        # backend compiled nothing, so the compile counter stays put
        _compile_tls.cache_hits = pending - 1
        return
    import jax

    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — never let telemetry break a compile
        backend = "unknown"
    XLA_COMPILES.inc(backend=backend)
    XLA_COMPILE_SECONDS.observe(float(duration_secs), backend=backend)


def _collect_device_memory() -> None:
    """Scrape-time gauge refresh (registered on REGISTRY)."""
    cache_bytes = 0
    for cache in list(_caches):
        cache_bytes += getattr(cache, "_bytes", 0)
    DEVICE_MEMORY.set(float(cache_bytes), kind="cache")
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — backend may not be initialized yet
        stats = None
    if stats:
        if "bytes_in_use" in stats:
            DEVICE_MEMORY.set(float(stats["bytes_in_use"]), kind="in_use")
        if "bytes_limit" in stats:
            DEVICE_MEMORY.set(float(stats["bytes_limit"]), kind="limit")
    else:
        # CPU backend (no PJRT allocator stats): the block cache's pinned
        # bytes ARE the device working set — report them so the series
        # exists with meaning on every backend
        DEVICE_MEMORY.set(float(cache_bytes), kind="in_use")


def install() -> None:
    """Wire the jax.monitoring listener + the memory collector. Safe to
    call from several modules; only the first call does work."""
    global _installed
    with _install_lock:
        if _installed:
            return
        _installed = True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_event_duration)
    except Exception:  # noqa: BLE001 — older jax without monitoring
        pass
    REGISTRY.register_collector(_collect_device_memory)
