"""Self-scrape: periodically write the process's own metrics into tables.

Mirrors the reference's `export_metrics` (servers/src/export_metrics.rs,
wired at frontend/src/instance.rs:267-277): the DB monitors itself by
turning every /metrics sample into rows of a `greptime_metrics` database,
one table per metric, labels as tag columns, so operational history is
queryable with plain SQL/PromQL."""

from __future__ import annotations

import logging
import re
import threading
import time
from collections import defaultdict


logger = logging.getLogger(__name__)

GREPTIME_TIMESTAMP = "greptime_timestamp"
GREPTIME_VALUE = "greptime_value"


def _sanitize(name: str) -> str:
    return re.sub(r"[^0-9a-zA-Z_]", "_", name)


def write_metrics_once(query_engine, db: str = "greptime_metrics") -> int:
    """One scrape: REGISTRY samples -> rows. Returns rows written."""
    from greptimedb_tpu.ingest import TableSlab, ensure_table
    from greptimedb_tpu.query.engine import QueryContext
    from greptimedb_tpu.utils.metrics import REGISTRY

    query_engine.execute_one(f"CREATE DATABASE IF NOT EXISTS {db}")
    ctx = QueryContext(db=db)
    now = int(time.time() * 1000)
    by_table: dict[str, list[tuple[dict, float]]] = defaultdict(list)
    for name, value, labels in REGISTRY.samples_dict():
        by_table[_sanitize(name)].append((labels, float(value)))
    total = 0
    for table, entries in by_table.items():
        # one broken metric table (e.g. a label key that appeared after
        # creation) must not stop the rest of the scrape — skip it loudly
        try:
            slab = TableSlab()
            for labels, v in entries:
                slab.add_row(
                    [(k, None if val is None else str(val))
                     for k, val in labels.items()],
                    [(GREPTIME_VALUE, v)], now)
            slab.tags = {k: slab.tags[k] for k in sorted(slab.tags)}
            info = ensure_table(query_engine, ctx, table, slab,
                                time_index=GREPTIME_TIMESTAMP,
                                value_field=GREPTIME_VALUE)
            batch = slab.to_batch(info.schema)
            total += query_engine._sharded_write(info, batch, delete=False)
        except Exception:  # noqa: BLE001
            logger.warning("self-scrape: skipping metric table %r",
                           table, exc_info=True)
    return total


class ExportMetricsTask:
    """Background self-scrape loop (RepeatedTask analog,
    common/runtime/src/repeated_task.rs)."""

    def __init__(self, query_engine, db: str = "greptime_metrics",
                 interval_s: float = 30.0):
        self.qe = query_engine
        self.db = db
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.errors = 0

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="export-metrics")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                write_metrics_once(self.qe, self.db)
            except Exception:  # noqa: BLE001 — scrape must never kill serving
                self.errors += 1
                logger.warning("self-scrape cycle failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
