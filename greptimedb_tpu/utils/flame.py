"""Continuous profiling: a bounded always-on sampler with stage tags.

`utils/profiling.py` is pull-on-demand — hit /debug/pprof/cpu, block
for five seconds, get one flat flame.  This module is the push twin: a
single daemon thread samples every Python stack at a low default rate
(~19 Hz, deliberately co-prime with common periodic work so it doesn't
alias against 10/20/100 Hz loops), attributes each sample to the query
stage and execution path that thread was serving, and aggregates into
rolling per-stage flame windows.  The instrument is always warm: "where
did the last half hour of CPU go, per stage, across the cluster?" is a
single GET away, with no profiling session to arrange.

Attribution works without touching contextvars from the sampler thread
(contextvars are invisible cross-thread): `tracing.span()` pushes and
pops the active span name into a thread-id-keyed registry here, and the
physical executor notes its `last_path` tag the same way.  Both hooks
are guarded by the module-level `_ENABLED` flag so the cost when
profiling is off is one attribute read.

Bounds, because always-on must never become the outage: stack depth is
capped, distinct stacks per window overflow into an ``(other)`` bucket,
windows are a fixed-length deque, and dead-thread registry entries are
purged from the sampler tick itself.  Sampler threads register with
profiling._PROFILER_TIDS so neither sampler ever appears in any flame.

Cluster rollup: datanodes fold `summary()` digests onto the Flight span
piggyback and the metasrv heartbeat; the frontend merges them here
(`note_node_summary` / `cluster_view`) into one deterministic view
served at /v1/profile/cluster and information_schema.cluster_profile.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from typing import Optional

from greptimedb_tpu.utils import profiling as _prof

#: fast-path flag read by tracing.span() and the executor path setter;
#: flipped only by configure()/shutdown()
_ENABLED = False

_DEPTH_CAP = 64          # frames kept per sampled stack
_STACK_CAP = 4000        # distinct stacks per window before "(other)"
_THREAD_CAP = 512        # stage-registry entries before a purge pass
_CLUSTER_CAP = 128       # remote node summaries retained

#: thread-id -> stack of active span names (innermost last)
_STAGES: dict = {}
#: thread-id -> last execution-path tag (dense_fused / mesh / ...)
_PATHS: dict = {}

_lock = threading.Lock()          # guards windows + cluster store
_WINDOWS: collections.deque = collections.deque(maxlen=10)
_CLUSTER: "collections.OrderedDict[str, dict]" = collections.OrderedDict()

_SAMPLER: Optional["_Sampler"] = None
_NODE = "local"
_HZ = 19.0
_WINDOW_S = 30.0

_IDLE_MARKS = ("wait", "select", "poll", "accept", "read (")


# ---- hot-path hooks (called from tracing.span / executor) ------------------

def push_stage(name: str) -> None:
    tid = threading.get_ident()
    st = _STAGES.get(tid)
    if st is None:
        _STAGES[tid] = [name]
    else:
        st.append(name)


def pop_stage() -> None:
    st = _STAGES.get(threading.get_ident())
    if st:
        st.pop()


def note_path(tag) -> None:
    if tag:
        _PATHS[threading.get_ident()] = str(tag)


# ---- sampler ---------------------------------------------------------------

def _new_window() -> dict:
    return {"start_ms": int(time.time() * 1000),
            "counts": collections.Counter()}


def _coarse(stage: str) -> str:
    # metric label + rollup key: "http:POST /v1/sql" -> "http",
    # "stmt:Select" -> "stmt"; span names without a kind pass through
    return stage.split(":", 1)[0] if stage else "host"


_SAMPLES_METRIC = None


def _samples_metric():
    # late-bound: flame is imported by tracing which is imported by
    # metrics, so a top-level metrics import here would be circular
    global _SAMPLES_METRIC
    if _SAMPLES_METRIC is None:
        from greptimedb_tpu.utils.metrics import PROFILE_SAMPLES
        _SAMPLES_METRIC = PROFILE_SAMPLES
    return _SAMPLES_METRIC


class _Sampler(threading.Thread):
    def __init__(self, hz: float, window_s: float):
        super().__init__(name="gtpu-flame-sampler", daemon=True)
        self.period = 1.0 / max(float(hz), 0.1)
        self.window_s = max(float(window_s), 1.0)
        self._halt = threading.Event()

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:
        _prof.register_profiler_thread(threading.get_ident())
        try:
            next_roll = time.monotonic() + self.window_s
            while not self._halt.wait(self.period):
                try:
                    self._tick()
                except Exception:
                    pass  # the instrument must never take the node down
                if time.monotonic() >= next_roll:
                    with _lock:
                        _WINDOWS.append(_new_window())
                    next_roll = time.monotonic() + self.window_s
        finally:
            _prof.unregister_profiler_thread(threading.get_ident())

    def _tick(self) -> None:
        me = threading.get_ident()
        frames = sys._current_frames()
        if len(_STAGES) > _THREAD_CAP or len(_PATHS) > _THREAD_CAP:
            live = set(frames)
            for reg in (_STAGES, _PATHS):
                for tid in [t for t in list(reg) if t not in live]:
                    reg.pop(tid, None)
        metric = None
        try:
            metric = _samples_metric()
        except Exception:
            pass
        batch = []
        for tid, frame in frames.items():
            if tid == me or tid in _prof._PROFILER_TIDS:
                continue
            parts = []
            f = frame
            while f is not None and len(parts) < _DEPTH_CAP:
                code = f.f_code
                parts.append(
                    f"{code.co_name} "
                    f"({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            if not parts:
                continue
            leaf = parts[0]
            st = _STAGES.get(tid)
            stage = None
            if st:
                try:
                    stage = st[-1]
                except IndexError:
                    stage = None
            path = _PATHS.get(tid) if stage is not None else None
            if stage is None and any(m in leaf for m in _IDLE_MARKS):
                continue  # parked pool/acceptor threads are not CPU time
            parts.reverse()
            key = (stage or "host", path or "-", tuple(parts))
            batch.append(key)
            if metric is not None:
                metric.inc(stage=_coarse(stage) if stage else "host")
        if not batch:
            return
        with _lock:
            if not _WINDOWS:
                _WINDOWS.append(_new_window())
            counts = _WINDOWS[-1]["counts"]
            for key in batch:
                if key not in counts and len(counts) >= _STACK_CAP:
                    key = (key[0], key[1], ("(other)",))
                counts[key] += 1


# ---- configuration ---------------------------------------------------------

def enabled() -> bool:
    return _ENABLED


def running() -> bool:
    return _SAMPLER is not None and _SAMPLER.is_alive()


def configure(enabled: bool = True, hz: float = 19.0,
              window_s: float = 30.0, windows: int = 10,
              node: Optional[str] = None) -> None:
    """Start, retune, or stop the continuous sampler (idempotent)."""
    global _ENABLED, _SAMPLER, _NODE, _HZ, _WINDOW_S
    if node is not None:
        _NODE = str(node)
    _HZ, _WINDOW_S = float(hz), float(window_s)
    with _lock:
        if _WINDOWS.maxlen != int(windows):
            kept = list(_WINDOWS)[-int(windows):]
            new = collections.deque(kept, maxlen=max(int(windows), 1))
            _WINDOWS.clear()
            globals()["_WINDOWS"] = new
    if not enabled:
        shutdown()
        return
    if (_SAMPLER is not None and _SAMPLER.is_alive()
            and abs(_SAMPLER.period - 1.0 / max(hz, 0.1)) < 1e-9
            and abs(_SAMPLER.window_s - max(window_s, 1.0)) < 1e-9):
        _ENABLED = True
        return
    shutdown()
    with _lock:
        if not _WINDOWS:
            _WINDOWS.append(_new_window())
    _SAMPLER = _Sampler(hz=hz, window_s=window_s)
    _ENABLED = True
    _SAMPLER.start()


def shutdown() -> None:
    global _ENABLED, _SAMPLER
    _ENABLED = False
    s, _SAMPLER = _SAMPLER, None
    if s is not None and s.is_alive():
        s.stop()
        s.join(timeout=2.0)


def maybe_install() -> None:
    """Apply `GTPU_PROFILE*` env (the [profiling] twins).

    Called from options.apply_observability at boot and from child
    datanode processes, which inherit the env — same layering as
    tracing/OTLP: env is truth.
    """
    raw = os.environ.get("GTPU_PROFILE", "1").strip().lower()
    on = raw not in ("off", "0", "false", "no")

    def _f(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, "") or default)
        except ValueError:
            return default

    configure(enabled=on,
              hz=_f("GTPU_PROFILE_HZ", 19.0),
              window_s=_f("GTPU_PROFILE_WINDOW_S", 30.0),
              windows=int(_f("GTPU_PROFILE_WINDOWS", 10)),
              node=os.environ.get("GTPU_NODE_ID") or None)


# ---- views -----------------------------------------------------------------

def _merged() -> collections.Counter:
    with _lock:
        total: collections.Counter = collections.Counter()
        for w in _WINDOWS:
            total.update(w["counts"])
        return total


def reset() -> None:
    """Drop all windows and remote summaries (tests / bench A/B)."""
    with _lock:
        _WINDOWS.clear()
        _WINDOWS.append(_new_window())
        _CLUSTER.clear()


def folded(stage: Optional[str] = None) -> str:
    """Rolling windows as folded stacks, stage/path as root frames.

    `stage:<name>;path:<tag>;frame;...;leaf count` per line — feed to
    any flamegraph renderer; grep a `stage:` prefix for one stage.
    """
    merged = _merged()
    lines = [f"# flame: {sum(merged.values())} samples @ {_HZ:g}Hz, "
             f"{len(_WINDOWS)} x {_WINDOW_S:g}s windows, node={_NODE}"]
    rows = []
    for (stg, path, frames), count in merged.items():
        if stage is not None and stg != stage and _coarse(stg) != stage:
            continue
        rows.append((f"stage:{stg};path:{path};" + ";".join(frames), count))
    rows.sort(key=lambda r: (-r[1], r[0]))
    lines.extend(f"{stack} {count}" for stack, count in rows)
    return "\n".join(lines) + "\n"


def speedscope() -> dict:
    """The same windows as a speedscope 'sampled' profile document."""
    merged = _merged()
    frame_ix: dict = {}
    frames_out = []
    samples = []
    weights = []
    for (stg, path, frames), count in sorted(
            merged.items(), key=lambda kv: (-kv[1], kv[0])):
        stack = [f"stage:{stg}", f"path:{path}", *frames]
        ixs = []
        for name in stack:
            ix = frame_ix.get(name)
            if ix is None:
                ix = frame_ix[name] = len(frames_out)
                frames_out.append({"name": name})
            ixs.append(ix)
        samples.append(ixs)
        weights.append(count)
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames_out},
        "profiles": [{
            "type": "sampled",
            "name": f"greptimedb_tpu continuous ({_NODE})",
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "exporter": "greptimedb_tpu.utils.flame",
        "activeProfileIndex": 0,
    }


def summary(top: int = 10, node: Optional[str] = None) -> dict:
    """Compact digest for piggyback/heartbeat/bench: bounded, mergeable."""
    merged = _merged()
    total = sum(merged.values())
    attributed = 0
    stages: collections.Counter = collections.Counter()
    paths: collections.Counter = collections.Counter()
    self_time: collections.Counter = collections.Counter()
    for (stg, path, frames), count in merged.items():
        if stg != "host" or path != "-":
            attributed += count
        stages[_coarse(stg)] += count
        if path != "-":
            paths[path] += count
        self_time[frames[-1] if frames else "(other)"] += count
    out = {
        "node": str(node) if node is not None else _NODE,
        "ts_ms": int(time.time() * 1000),
        "hz": _HZ,
        "window_s": _WINDOW_S,
        "samples": total,
        "attributed": attributed,
        "stages": {k: int(v) for k, v in sorted(stages.items())},
        "paths": {k: int(v) for k, v in sorted(paths.items())},
        "top": [{"frame": f, "self": int(c)}
                for f, c in sorted(self_time.items(),
                                   key=lambda kv: (-kv[1], kv[0]))[:top]],
    }
    led = _ledger_rollup()
    if led:
        out["ledger"] = led
    return out


def _ledger_rollup() -> dict:
    """Cumulative node-level byte/query totals riding along the digest."""
    try:
        from greptimedb_tpu.utils.metrics import (DEVICE_TRANSFER_BYTES,
                                                  QUERY_ACHIEVED_GBPS)
        out = {}
        for labels, val in DEVICE_TRANSFER_BYTES.series():
            d = labels.get("direction", "?")
            out[f"{d}_bytes"] = int(out.get(f"{d}_bytes", 0) + val)
        out["queries_accounted"] = int(QUERY_ACHIEVED_GBPS.total_count())
        out["gbps_sum"] = float(QUERY_ACHIEVED_GBPS.total_sum())
        return out
    except Exception:
        return {}


# ---- cluster rollup --------------------------------------------------------

def note_node_summary(node: str, summ: dict) -> None:
    """Record a remote node's digest (Flight piggyback / heartbeat)."""
    if not isinstance(summ, dict):
        return
    node = str(node)
    with _lock:
        _CLUSTER.pop(node, None)
        _CLUSTER[node] = summ
        while len(_CLUSTER) > _CLUSTER_CAP:
            _CLUSTER.popitem(last=False)


def cluster_view(top: int = 10) -> dict:
    """Local + remote digests merged into one deterministic view.

    Merging is a commutative sum keyed by stage/path/frame, emitted in
    sorted order — the view is identical whatever order node summaries
    arrived in (the determinism the tests pin).
    """
    local = summary(top=top)
    with _lock:
        nodes = dict(_CLUSTER)
    nodes[local["node"]] = local
    stages: collections.Counter = collections.Counter()
    paths: collections.Counter = collections.Counter()
    self_time: collections.Counter = collections.Counter()
    samples = 0
    attributed = 0
    for summ in nodes.values():
        samples += int(summ.get("samples", 0))
        attributed += int(summ.get("attributed", 0))
        for k, v in (summ.get("stages") or {}).items():
            stages[k] += int(v)
        for k, v in (summ.get("paths") or {}).items():
            paths[k] += int(v)
        for row in (summ.get("top") or []):
            self_time[row.get("frame", "?")] += int(row.get("self", 0))
    return {
        "nodes": {k: nodes[k] for k in sorted(nodes)},
        "merged": {
            "samples": samples,
            "attributed": attributed,
            "stages": {k: int(v) for k, v in sorted(stages.items())},
            "paths": {k: int(v) for k, v in sorted(paths.items())},
            "top": [{"frame": f, "self": int(c)}
                    for f, c in sorted(self_time.items(),
                                       key=lambda kv: (-kv[1], kv[0]))[:top]],
        },
    }
