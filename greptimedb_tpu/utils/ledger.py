"""Per-query resource ledger: one request-scoped accumulator every
subsystem feeds, answering "where did THIS query's time and bytes go".

The metrics registry aggregates across requests; the span ring shows
wall time per stage — neither attributes *resources* (cache hits, H2D
bytes, rows folded, admission wait) to one statement. The ledger closes
that gap: servers (or the engine, for direct callers) attach one per
request, the seams that already count global metrics also feed the
active ledger, and the result is stamped onto the root span, the
slow-query record, and EXPLAIN ANALYZE.

Feeds (same call sites as the global counters, so the two surfaces can
never drift):

- caches: plan cache, fast lane, scan part cache, partial-aggregate
  cache, device hot set — per-cache hit/miss/... under ``cache.<name>.<event>``
- admission: wait seconds (``admission_wait_ms``)
- scan: rows scanned and host bytes decoded (fed from scan spans /
  the decode seam, including scan-pool worker threads via
  `tracing.propagate`)
- device: H2D/D2H bytes (the device_telemetry seams), host-vs-device
  aggregation milliseconds (fed from span completion)

`GTPU_TRACING=off` disables the ledger together with span recording —
the observability plane A/Bs as one unit (the bench's overhead gate).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
from typing import Optional

_current: contextvars.ContextVar[Optional["Ledger"]] = \
    contextvars.ContextVar("gtpu_ledger", default=None)

#: span name -> ledger key for duration feeds. `agg_ms` is the whole
#: aggregation wall (host + device); `device_ms` the device-kernel
#: portion nested inside it — `host_ms` is DERIVED as their difference
#: at export time (a nested span must not double-count)
_SPAN_MS_KEYS = {
    "device_agg": "device_ms",
    "vmapped_fragments": "device_ms",
    "aggregate": "agg_ms",
    "range_agg": "agg_ms",
}


def enabled() -> bool:
    """The GTPU_TRACING master switch — the CANONICAL parse for the
    whole observability plane (tracing.enabled delegates here; tracing
    imports ledger, never the reverse), so spans and the ledger always
    agree on what "off" means."""
    return os.environ.get("GTPU_TRACING", "").lower() not in (
        "off", "0", "false", "no")


class Ledger:
    """Thread-safe numeric accumulator. Adds happen on request threads
    AND pool workers (scan decode, region RPC fan-out) that inherited
    the contextvar via `tracing.propagate` — hence the lock (adds are
    per-part/per-event, not per-row; contention is negligible)."""

    __slots__ = ("_data", "_lock")

    def __init__(self):
        self._data: dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, key: str, value: float = 1.0) -> None:
        with self._lock:
            self._data[key] = self._data.get(key, 0.0) + value

    def note_span(self, span) -> None:
        """Span-completion feed (called by tracing._record): scan rows
        and the host-vs-device time split fall out of spans that already
        exist — no extra instrumentation at those sites. Piggybacked
        remote copies (node set) are skipped: the frontend's own scan
        span already covers the distributed gather, and counting the
        merged datanode span too would double every row."""
        if span.node is not None:
            return
        key = _SPAN_MS_KEYS.get(span.name)
        if key is not None:
            self.add(key, span.duration_ms)
        if span.name in ("scan", "region_scan"):
            rows = span.attrs.get("rows")
            if isinstance(rows, (int, float)):
                self.add("rows_scanned", float(rows))

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._data)

    def to_dict(self) -> dict[str, float]:
        d = derive(self.snapshot())
        return {k: round(v, 3) for k, v in sorted(d.items())}

    def summary(self) -> str:
        """Compact ``k=v`` rendering for span attrs and log lines."""
        return format_dict(derive(self.snapshot()))


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else f"{v:.3f}"


def format_dict(d: dict) -> str:
    """Compact ``k=v`` line for a ledger slice (span attrs, ANALYZE)."""
    return " ".join(f"{k}={_fmt(v)}" for k, v in sorted(d.items()))


def derive(d: dict) -> dict:
    """Derived fields over raw counters: the host share of aggregation
    time is agg_ms minus the device-kernel spans nested inside it."""
    agg = d.get("agg_ms")
    if agg is not None:
        host = agg - d.get("device_ms", 0.0)
        if host > 0:
            d = dict(d)
            d["host_ms"] = round(host, 3)
    return d


def diff(before: dict, after: dict) -> dict[str, float]:
    """after - before, dropping zero deltas — the per-statement slice of
    a request-scoped ledger (multi-statement requests share one).
    Derived fields are computed over the slice."""
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0.0)
        if d:
            out[k] = round(d, 3)
    return derive(out)


def active() -> Optional[Ledger]:
    return _current.get()


def add(key: str, value: float = 1.0) -> None:
    """Feed the active ledger (no-op outside a request)."""
    led = _current.get()
    if led is not None:
        led.add(key, value)


def cache_event(cache: str, event: str, n: float = 1.0) -> None:
    """Per-cache attribution (``cache.<name>.<event>``) — called next to
    the global *_EVENTS counter incs so the surfaces cannot drift."""
    led = _current.get()
    if led is not None:
        led.add(f"cache.{cache}.{event}", n)


@contextlib.contextmanager
def attach():
    """Install a fresh ledger unless the context already carries one
    (nested statements — views, TQL-inside-SQL, EXPLAIN's inner run —
    accumulate into their request's ledger). Yields the active ledger,
    or None when the observability plane is off."""
    led = _current.get()
    if led is not None or not enabled():
        yield led
        return
    led = Ledger()
    token = _current.set(led)
    try:
        yield led
    finally:
        _current.reset(token)


@contextlib.contextmanager
def attach_fresh():
    """Force a new ledger (EXPLAIN ANALYZE: the report must cover the
    inner statement alone, not the whole connection's request)."""
    if not enabled():
        yield None
        return
    led = Ledger()
    token = _current.set(led)
    try:
        yield led
    finally:
        _current.reset(token)
