"""Internal metrics registry (mirrors the reference's lazy_static
prometheus registries in every crate's metrics.rs, exposed at /metrics and
self-scraped — SURVEY.md §5)."""

from __future__ import annotations

import threading
import time
import weakref
from collections import defaultdict


class Counter:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = defaultdict(float)
        # cumulative snapshots published by OTHER processes (encode-pool
        # workers via the shm fabric), folded into every read — the
        # cross-process twin of the per-thread shards
        self._external: dict[str, dict] = {}
        self._lock = threading.Lock()

    def set_external(self, source: str, snapshot: dict) -> None:
        """Install a cumulative series snapshot from another process
        (keyed by a stable source id, e.g. the worker pid); replaces
        that source's previous snapshot — snapshots are cumulative, so
        folding the latest one per source never double-counts."""
        with self._lock:
            self._external[source] = dict(snapshot)

    def _fold_external_locked(self, out: dict) -> dict:
        """Caller holds self._lock."""
        for snap in self._external.values():
            for k, v in snap.items():
                out[k] = out.get(k, 0.0) + v
        return out

    def inc(self, value: float = 1.0, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += value

    def get(self, **labels) -> float:
        # via _snapshot (copied under the lock): a bare dict read races
        # concurrent inc/set and could observe a half-applied update;
        # subclasses that shard their writes only override _snapshot
        return self._snapshot().get(tuple(sorted(labels.items())), 0.0)

    def total(self, **labels) -> float:
        """Sum over every series whose labels are a superset of the
        given ones (PromQL `sum by` analog) — assertions stay valid
        when a call site starts attaching extra labels."""
        want = set(labels.items())
        return sum(v for key, v in self._snapshot().items()
                   if want <= set(key))

    def series(self, **labels) -> list:
        """Every (labels dict, value) series whose labels are a superset
        of the given ones — feeds per-node/per-edge breakdowns in debug
        surfaces (information_schema.cluster_faults, /v1/faults)."""
        want = set(labels.items())
        return [(dict(key), v)
                for key, v in sorted(self._snapshot().items())
                if want <= set(key)]

    def _snapshot(self) -> dict:
        """Point-in-time copy of every series (Registry sampling uses
        this so sharded subclasses can fold their shards in)."""
        with self._lock:
            return self._fold_external_locked(dict(self._values))

    def render(self, exemplars: bool = False) -> list[str]:
        # OpenMetrics family naming: the metric FAMILY drops the _total
        # suffix while counter samples keep it — a strict OM parser
        # (modern Prometheus negotiates OM by default) rejects a family
        # named ..._total. The classic text format keeps the suffixed
        # name, byte-stable for legacy scrapers.
        family = self.name
        if exemplars and family.endswith("_total"):
            family = family[:-len("_total")]
        out = [f"# HELP {family} {self.help}", f"# TYPE {family} counter"]
        items = sorted(self._snapshot().items())
        for key, v in items:
            out.append(f"{self.name}{_labels(key)} {v}")
        return out


class ShardedCounter(Counter):
    """Counter whose `inc` writes a per-thread shard instead of taking
    the global metric lock.

    The per-request counters (http_requests, admission events, plan/
    fast-lane cache events) are incremented by every serving thread on
    every request; under 50 concurrent clients the single `Counter`
    lock is a measurable contention point. Each thread owns a private
    dict (only that thread ever writes it — plain dict updates are
    GIL-atomic), and the read side folds base + shards at scrape/assert
    time. A dying thread's shard is folded into the base dict by a
    weakref finalizer on its Thread object, so counts survive thread
    churn and the shard list stays bounded by live threads."""

    def __init__(self, name: str, help_: str):
        super().__init__(name, help_)
        self._shards: list[dict] = []
        self._tls = threading.local()

    def _cell(self) -> dict:
        cell = getattr(self._tls, "cell", None)
        if cell is None:
            cell = {}
            with self._lock:
                self._shards.append(cell)
            # fold the shard into the durable base when the thread dies
            # (cumulative counters must never lose counts)
            weakref.finalize(threading.current_thread(),
                             self._fold, cell)
            self._tls.cell = cell
        return cell

    def _fold(self, cell: dict) -> None:
        with self._lock:
            try:
                self._shards.remove(cell)
            except ValueError:
                return
            for k, v in cell.items():
                self._values[k] += v

    def inc(self, value: float = 1.0, **labels):
        cell = self._cell()
        key = tuple(sorted(labels.items()))
        # single-writer dict update: no lock, no condition, no CAS loop
        cell[key] = cell.get(key, 0.0) + value

    def shard_count(self) -> int:
        with self._lock:
            return len(self._shards)

    def _snapshot(self) -> dict:
        # the read methods (get/total/series/render) all fold through
        # here — the only read-side difference from a plain Counter
        with self._lock:
            out = dict(self._values)
            shards = list(self._shards)
        for cell in shards:
            # list(dict.items()) is one C call — an atomic snapshot of
            # a shard another thread may be appending to
            for k, v in list(cell.items()):
                out[k] = out.get(k, 0.0) + v
        with self._lock:
            return self._fold_external_locked(out)


class Gauge(Counter):
    def set(self, value: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def render(self, exemplars: bool = False) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_labels(key)} {v}")
        return out


class Histogram:
    BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0)

    def __init__(self, name: str, help_: str, buckets=None,
                 exemplars: bool = False):
        self.name = name
        self.help = help_
        if buckets is not None:
            # per-instance bounds for non-latency shapes (batch sizes,
            # byte counts) — the default decade grid is seconds-tuned
            self.BUCKETS = tuple(sorted(buckets))
        self._buckets: dict[tuple, list[int]] = {}
        self._sum: dict[tuple, float] = defaultdict(float)
        self._count: dict[tuple, int] = defaultdict(int)
        # OpenMetrics exemplars: per (labels, bucket) the most recent
        # (trace_id, value, ts) — the metrics→trace join (a slow
        # gtpu_query_stage_seconds bucket links to a trace to pull)
        self._exemplars_on = exemplars
        self._exemplar: dict[tuple, tuple] = {}
        # cumulative (buckets, sum, count) snapshots published by other
        # processes (encode-pool workers via the shm fabric); folded
        # into every read so worker-side observations are exact in the
        # parent's /metrics instead of parent-side approximations
        self._external: dict[str, dict] = {}
        self._lock = threading.Lock()

    def set_external(self, source: str, state: dict) -> None:
        """Install another process's cumulative series state (the shape
        `export_state` returns). Replaces that source's previous
        snapshot, so cumulative republishing never double-counts."""
        with self._lock:
            self._external[source] = state

    def export_state(self) -> dict:
        """This process's cumulative series, keyed for set_external:
        {label-key: ([bucket counts], sum, count)}."""
        with self._lock:
            return {key: (list(b), self._sum[key], self._count[key])
                    for key, b in self._buckets.items()}

    def _merged_locked(self):
        """Local series with every external snapshot folded in —
        caller holds self._lock."""
        buckets = {key: list(b) for key, b in self._buckets.items()}
        sums = dict(self._sum)
        counts = dict(self._count)
        for state in self._external.values():
            for key, (b, s, c) in state.items():
                if len(b) != len(self.BUCKETS) + 1:
                    continue  # bucket-grid drift across versions: skip
                if key in buckets:
                    buckets[key] = [x + y for x, y in zip(buckets[key], b)]
                else:
                    buckets[key] = list(b)
                sums[key] = sums.get(key, 0.0) + s
                counts[key] = counts.get(key, 0) + c
        return buckets, sums, counts

    def observe(self, value: float, **labels):
        tid = None
        if self._exemplars_on:
            from greptimedb_tpu.utils import tracing

            # gate on the tracing master switch: with GTPU_TRACING=off
            # no spans exist, so an exemplar would point at a trace
            # whose /v1/traces lookup can only 404
            if tracing.enabled():
                tid = tracing.current_trace_id()
        key = tuple(sorted(labels.items()))
        with self._lock:
            b = self._buckets.setdefault(key, [0] * (len(self.BUCKETS) + 1))
            for i, ub in enumerate(self.BUCKETS):
                if value <= ub:
                    b[i] += 1
                    break
            else:
                i = len(self.BUCKETS)
                b[-1] += 1
            self._sum[key] += value
            self._count[key] += 1
            if tid:
                self._exemplar[(key, i)] = (tid, value, time.time())

    def time(self, **labels):
        return _Timer(self, labels)

    def sum(self, **labels) -> float:
        """Total of observed values for one label set (benches read the
        execute/encode wall-time split from here)."""
        with self._lock:
            _, sums, _ = self._merged_locked()
        return sums.get(tuple(sorted(labels.items())), 0.0)

    def count(self, **labels) -> int:
        with self._lock:
            _, _, counts = self._merged_locked()
        return counts.get(tuple(sorted(labels.items())), 0)

    def total_count(self, **labels) -> int:
        """Observation count summed over every series whose labels are
        a superset of the given ones (Counter.total's analog)."""
        want = set(labels.items())
        with self._lock:
            _, _, counts = self._merged_locked()
        return sum(c for key, c in counts.items() if want <= set(key))

    def total_sum(self, **labels) -> float:
        """Observed-value total over matching series (see total_count)."""
        want = set(labels.items())
        with self._lock:
            _, sums, _ = self._merged_locked()
        return sum(s for key, s in sums.items() if want <= set(key))

    def render(self, exemplars: bool = False) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            buckets, sums, counts = self._merged_locked()
            snapshot = sorted(
                (key, b, sums[key], counts[key])
                for key, b in buckets.items()
            )
            ex = dict(self._exemplar) if exemplars else {}
        for key, b, _sum, _count in snapshot:
            cum = 0
            for i, ub in enumerate(self.BUCKETS):
                cum += b[i]
                out.append(f"{self.name}_bucket{_labels(key, le=str(ub))} "
                           f"{cum}{_exemplar_suffix(ex.get((key, i)))}")
            cum += b[-1]
            out.append(f"{self.name}_bucket{_labels(key, le='+Inf')} {cum}"
                       f"{_exemplar_suffix(ex.get((key, len(self.BUCKETS))))}")
            out.append(f"{self.name}_sum{_labels(key)} {_sum}")
            out.append(f"{self.name}_count{_labels(key)} {_count}")
        return out


class _Timer:
    def __init__(self, hist, labels):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, **self.labels)


def _exemplar_suffix(ex) -> str:
    """OpenMetrics exemplar rendering for one bucket line:
    ` # {trace_id="<id>"} <value> <timestamp>` — omitted (empty string)
    when no exemplar was captured for that bucket."""
    if ex is None:
        return ""
    tid, value, ts = ex
    return (f' # {{trace_id="{_escape_label_value(tid)}"}} '
            f"{value} {round(ts, 3)}")


def _escape_label_value(v) -> str:
    """Prometheus exposition-format escaping: backslash, double-quote and
    newline must be escaped inside label values (a raw newline would
    split the sample line and corrupt the whole scrape)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(key: tuple, **extra) -> str:
    items = list(key) + sorted(extra.items())
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + inner + "}"


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._collectors: list = []
        self._lock = threading.Lock()

    def register_collector(self, fn) -> None:
        """Register a callback run before every render/sample pass —
        for gauges whose truth lives elsewhere (device memory stats,
        cache residency) and is only worth reading at scrape time."""
        with self._lock:
            self._collectors.append(fn)

    def _collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a scrape must never fail
                pass

    def counter(self, name, help_="") -> Counter:
        m = Counter(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def sharded_counter(self, name, help_="") -> ShardedCounter:
        """Lock-light counter for the per-request hot path: inc() writes
        a per-thread shard, reads fold at scrape time."""
        m = ShardedCounter(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def gauge(self, name, help_="") -> Gauge:
        m = Gauge(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def histogram(self, name, help_="", buckets=None,
                  exemplars: bool = False) -> Histogram:
        m = Histogram(name, help_, buckets=buckets, exemplars=exemplars)
        with self._lock:
            self._metrics.append(m)
        return m

    def render(self, openmetrics: bool = False) -> str:
        """Exposition text. `openmetrics=True` (the scraper sent
        Accept: application/openmetrics-text) adds exemplar suffixes to
        histogram bucket lines and the spec's `# EOF` terminator; the
        classic text format stays byte-stable for legacy parsers."""
        self._collect()
        with self._lock:
            metrics = list(self._metrics)
        lines = []
        for m in metrics:
            lines.extend(m.render(exemplars=openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def _iter_samples(self):
        """(metric_name, value, label-pairs tuple) over every metric."""
        self._collect()
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            if isinstance(m, Histogram):
                with m._lock:
                    _, sums, counts = m._merged_locked()
                items = [(key, sums[key], counts[key]) for key in counts]
                for key, s, c in items:
                    yield m.name + "_sum", s, key
                    yield m.name + "_count", c, key
            else:
                items = sorted(m._snapshot().items())
                for key, v in items:
                    yield m.name, v, key

    def samples(self):
        """Flat (metric_name, value, rendered labels) samples — feeds
        information_schema.runtime_metrics."""
        return [(n, v, _labels(k)) for n, v, k in self._iter_samples()]

    def samples_dict(self):
        """(metric_name, value, labels dict) — feeds the self-scrape
        exporter (reference export_metrics writes label columns)."""
        return [(n, v, dict(k)) for n, v, k in self._iter_samples()]


REGISTRY = Registry()

# framework-wide metrics (analogs of servers/src/metrics.rs etc.)
# per-request counters are SHARDED: every serving thread touches them on
# every request, and a single counter lock is measurable contention at
# benchmark concurrency (ISSUE 14)
HTTP_REQUESTS = REGISTRY.sharded_counter(
    "greptimedb_tpu_http_requests_total",
    "HTTP requests by path and status")
QUERY_DURATION = REGISTRY.histogram("greptimedb_tpu_query_duration_seconds",
                                    "Query execution latency",
                                    exemplars=True)
INGEST_ROWS = REGISTRY.sharded_counter(
    "greptimedb_tpu_ingest_rows_total",
    "Rows ingested by protocol")

# ingest pipeline (storage/group_commit.py + the protocol front doors):
# every front door lands on the bulk path through a per-region group
# commit — these series prove the fsync amortization is real (batch
# size > 1 under concurrency) and show where admission pressure lands
INGEST_BATCH_SIZE = REGISTRY.histogram(
    "greptimedb_tpu_ingest_batch_size",
    "Rows per group-committed WAL batch (one fsync each; sizes > the "
    "per-writer batch mean concurrent writers were coalesced)",
    buckets=(1, 8, 64, 256, 1024, 4096, 16384, 65536, 262144))
INGEST_GROUP_COMMIT_EVENTS = REGISTRY.counter(
    "greptimedb_tpu_ingest_group_commit_events_total",
    "Group-commit events by kind (lead = a writer drained the queue and "
    "paid the fsync, follow = a writer rode another's commit, overflow "
    "= the bounded ingest queue rejected a writer with typed "
    "Overloaded)")
INGEST_WAL_FSYNC_SECONDS = REGISTRY.histogram(
    "greptimedb_tpu_ingest_wal_fsync_seconds",
    "WAL append+fsync wall time per group commit (the durability "
    "boundary every queued writer amortizes over)")
STMT_DURATION = REGISTRY.histogram(
    "greptimedb_tpu_statement_duration_seconds",
    "Statement execution latency by statement kind", exemplars=True)

# resilience plane (fault/ package): every injected fault, every retry,
# every exhaustion, and every degradation is observable at /metrics so
# chaos runs assert behavior instead of eyeballing logs
FAULT_INJECTIONS = REGISTRY.counter(
    "greptimedb_tpu_fault_injections_total",
    "Injected faults by injection point and kind")
RETRY_ATTEMPTS = REGISTRY.counter(
    "greptimedb_tpu_retry_attempts_total",
    "Retries after a transient failure, by injection point")
RETRY_EXHAUSTED = REGISTRY.counter(
    "greptimedb_tpu_retry_exhausted_total",
    "Operations that exhausted their retry budget, by injection point")
DEGRADED = REGISTRY.counter(
    "greptimedb_tpu_degraded_total",
    "Graceful degradations (route re-resolution after retry exhaustion)")
CHAOS_RUNS = REGISTRY.counter(
    "greptimedb_tpu_chaos_runs_total",
    "Chaos-explorer runs by outcome (pass|fail|error)")
CHAOS_SHRINK_STEPS = REGISTRY.counter(
    "greptimedb_tpu_chaos_shrink_steps_total",
    "Delta-debugging probe runs spent shrinking failing chaos schedules")
FLOW_TICK_ERRORS = REGISTRY.counter(
    "greptimedb_tpu_flow_tick_errors_total",
    "Flow engine tick failures deferred to the next tick, by flow")

# deadline/cancellation/hedging plane (utils/deadline.py,
# cluster/cluster.py): tail tolerance is only credible when every
# expiry, kill, and hedge decision is a counted event
DEADLINE_EVENTS = REGISTRY.counter(
    "greptimedb_tpu_query_deadline_events_total",
    "Query deadline-plane terminal events by event (expired = the "
    "absolute deadline passed at a cooperative checkpoint, cancelled = "
    "client disconnect or hedge-loser cancellation, killed = KILL "
    "QUERY / DELETE /v1/queries/<id>); counted once per query at the "
    "first typed raise")
HEDGE_EVENTS = REGISTRY.counter(
    "greptimedb_tpu_hedge_events_total",
    "Hedged region-request events by event (fired = a backup fragment "
    "was issued after the adaptive straggler delay, won = the hedge "
    "finished first, lost = the primary finished first and the hedge "
    "was cancelled, budget_denied = the <=5% token-bucket hedge budget "
    "suppressed a hedge)")
REQUEST_BUDGET_REMAINING = REGISTRY.histogram(
    "greptimedb_tpu_region_request_budget_remaining_ms",
    "Remaining deadline budget (ms) observed at datanode ingress on "
    "scan/fragment tickets that carried one — low buckets mean "
    "frontends are shipping nearly-dead work to datanodes",
    buckets=(5, 25, 100, 250, 500, 1000, 2500, 5000, 10000, 30000))

# TPU runtime telemetry (SURVEY §5: the north star is unfalsifiable
# without per-device numbers): XLA compiles, device memory, link
# traffic, and HBM block-cache behavior — wired by
# utils/device_telemetry.py, rendered at /metrics, self-scraped by
# utils/export_metrics.py like every other series
XLA_COMPILES = REGISTRY.counter(
    "greptimedb_tpu_xla_compile_total",
    "XLA compilations observed via jax.monitoring, by backend")
XLA_COMPILE_SECONDS = REGISTRY.histogram(
    "greptimedb_tpu_xla_compile_duration_seconds",
    "XLA backend-compile wall time per compilation, by backend")
DEVICE_MEMORY = REGISTRY.gauge(
    "greptimedb_tpu_device_memory_bytes",
    "Accelerator memory by kind (in_use/limit from the PJRT allocator "
    "when available, cache = bytes pinned by the device block cache)")
DEVICE_TRANSFER_BYTES = REGISTRY.counter(
    "greptimedb_tpu_device_transfer_bytes_total",
    "Host<->device bytes moved by the query engine, by direction "
    "(h2d uploads of scan blocks, d2h result readbacks)")
DEVICE_CACHE_EVENTS = REGISTRY.counter(
    "greptimedb_tpu_device_cache_events_total",
    "HBM block cache events by kind (hit/miss/evict/prefetch_join — a "
    "join is an upload the background prefetch worker already did)")
DEVICE_HOT_SET_EVENTS = REGISTRY.counter(
    "greptimedb_tpu_device_hot_set_events_total",
    "HBM-resident columnar hot set events by kind (hit/miss/evict/pin — "
    "pin = a file-anchored column block entered HBM residency and stays "
    "across queries and data versions until its file dies)")
DEVICE_HOT_SET_BYTES = REGISTRY.gauge(
    "greptimedb_tpu_device_hot_set_bytes",
    "Bytes currently pinned in HBM by the device columnar hot set")
PALLAS_DISPATCHES = REGISTRY.counter(
    "greptimedb_tpu_pallas_dispatch_total",
    "Pallas TPU kernel dispatches by kernel (fused_agg = the fused "
    "scan/filter/bucket/aggregate kernel, segment_sum = the one-hot "
    "matmul segment-sum; fused_agg_failed = mid-query degradations to "
    "the XLA scatter path)")
SPARSE_DISPATCHES = REGISTRY.counter(
    "greptimedb_tpu_sparse_dispatch_total",
    "Sparse sort-compact aggregation dispatches by path (classic = "
    "whole-scan XLA segment reduce, fused = tiled Pallas windows, "
    "sharded = per-shard compaction + gid-space combine, incremental = "
    "per-part value-space partials, vmapped = shared compaction across "
    "stacked batch members)")
SPARSE_COMPACTION_RATIO = REGISTRY.gauge(
    "greptimedb_tpu_sparse_compaction_ratio",
    "Observed groups per scanned row in the last sparse aggregation "
    "(1.0 = every row its own group, no compaction win)")
TIER_ADMISSION = REGISTRY.counter(
    "greptimedb_tpu_tier_admission_total",
    "Hot-set-aware tier admission decisions by reason (device_hot/"
    "host_hot = routed to the tier already holding the scan's "
    "file-anchored blocks, cold = no tier holds them, off = the "
    "GREPTIMEDB_TPU_TIER_ADMISSION knob disabled the probe)")
SLOW_QUERIES = REGISTRY.counter(
    "greptimedb_tpu_slow_queries_total",
    "Statements slower than the slow-query threshold, by kind")

# scan pipeline (storage/region.py + query/device_cache.py): the cold
# scan is the wall on first-touch queries (BENCH r03: 20.2s of a 27.5s
# statement inside scan) — these series prove the three pipeline stages
# (parallel SST decode, per-file part cache, upload prefetch) are doing
# their jobs
SCAN_DECODE_SECONDS = REGISTRY.histogram(
    "greptimedb_tpu_scan_decode_seconds",
    "Per-SST parquet read+decode wall time inside the region scan "
    "(cache misses only; parallel decodes observe concurrently)")
SCAN_PART_CACHE_EVENTS = REGISTRY.counter(
    "greptimedb_tpu_scan_part_cache_events_total",
    "Per-file decoded-part scan cache events by kind (hit/miss/evict; "
    "evict includes whole-scan snapshots aged out of the shared host "
    "byte budget)")
SCAN_DECODE_BYTES = REGISTRY.counter(
    "greptimedb_tpu_scan_decode_bytes_total",
    "Host bytes materialized by SST scan decode (part-cache misses)")
SCAN_PIPELINE_OVERLAP = REGISTRY.gauge(
    "greptimedb_tpu_scan_pipeline_overlap",
    "Fraction of prefetched device block uploads already built when the "
    "query asked for them (1.0 = host build fully hidden behind "
    "upload/compute; cumulative ratio since process start)")

# background maintenance plane (maintenance/ package): job throughput,
# queue pressure, writer stalls, and the rollup/retention outcomes —
# the observability contract of "the write path never does maintenance"
MAINTENANCE_JOBS = REGISTRY.counter(
    "greptimedb_tpu_maintenance_jobs_total",
    "Maintenance jobs by kind (flush/compact/rollup/expire) and "
    "terminal status (done/failed)")
MAINTENANCE_QUEUE_DEPTH = REGISTRY.gauge(
    "greptimedb_tpu_maintenance_queue_depth",
    "Maintenance jobs currently queued (bounded; excess submissions "
    "run inline on the caller)")
MAINTENANCE_JOB_SECONDS = REGISTRY.histogram(
    "greptimedb_tpu_maintenance_job_duration_seconds",
    "Maintenance job execution wall time by kind")
WRITE_STALL_SECONDS = REGISTRY.counter(
    "greptimedb_tpu_write_stall_seconds_total",
    "Seconds writers spent stalled at the hard memtable/L0 backpressure "
    "threshold, by reason (memtable/l0)")
WRITE_STALL_TIMEOUTS = REGISTRY.counter(
    "greptimedb_tpu_write_stall_timeouts_total",
    "Stalls that hit stall_timeout_s and fell back to an inline flush "
    "(the maintenance plane is wedged or saturated)")
# frontend concurrency plane (concurrency/ package): the shape-keyed
# plan cache, admission control, and cross-query batching that carry
# fleet-scale dashboard traffic (ISSUE 6) — hit rates and rejection
# behavior are asserted from these series, not eyeballed
PLAN_CACHE_EVENTS = REGISTRY.sharded_counter(
    "greptimedb_tpu_plan_cache_events_total",
    "Shape-keyed logical-plan cache events by kind (hit/miss/evict/"
    "invalidate — invalidations come from DDL, schema drift, and "
    "rollup-substitution state changes; skip events carry a reason "
    "label naming why a statement never reached the cache: join/cte/"
    "subquery/range_select/window)")
ADMISSION_EVENTS = REGISTRY.sharded_counter(
    "greptimedb_tpu_admission_events_total",
    "Admission control decisions by kind (admit/queue/reject_full/"
    "reject_timeout; rejections carry the tenant label)")
ADMISSION_QUEUE_DEPTH = REGISTRY.gauge(
    "greptimedb_tpu_admission_queue_depth",
    "Statements currently waiting in the bounded admission queue")
ADMISSION_WAIT_SECONDS = REGISTRY.histogram(
    "greptimedb_tpu_admission_wait_seconds",
    "Time queued statements waited for an execution slot",
    exemplars=True)
QUERY_BATCH_EVENTS = REGISTRY.sharded_counter(
    "greptimedb_tpu_query_batch_events_total",
    "Cross-query batching events by kind (join/coalesced/vmapped/"
    "stacked/serial_fallback — coalesced, vmapped, and stacked members "
    "skipped their own device dispatch; vmapped_failed marks the "
    "runtime latch that degrades to the fallbacks)")
QUERY_BATCH_SIZE = REGISTRY.histogram(
    "greptimedb_tpu_query_batch_size",
    "Queries served per batch group (leader + members)", exemplars=True)
VMAP_BATCH_WIDTH = REGISTRY.histogram(
    "greptimedb_tpu_query_vmap_batch_width",
    "Distinct parameter-sibling queries executed per vmapped multi-"
    "query dispatch (the stacked member axis M)",
    buckets=(2, 4, 8, 16, 32, 64, 128), exemplars=True)
ENCODE_POOL_EVENTS = REGISTRY.sharded_counter(
    "greptimedb_tpu_encode_pool_events_total",
    "Result-encode pool decisions by kind (offload = serialized on a "
    "pool worker, inline = pool saturated, small_inline = result "
    "under encode_min_rows; inline encodes run on the request thread)")
ENCODE_POOL_QUEUE_DEPTH = REGISTRY.gauge(
    "greptimedb_tpu_encode_pool_queue_depth",
    "Result serializations queued or running in the bounded encode "
    "pool")
ENCODE_SECONDS = REGISTRY.histogram(
    "greptimedb_tpu_encode_seconds",
    "Wall time serializing one query result to its wire format "
    "(HTTP JSON / MySQL packets), by protocol — compare against "
    "query_duration_seconds for the execute-vs-encode split; "
    "protocol=process series are measured inside the spawn-mode encode "
    "workers and folded in through the shm fabric metrics bridge, so "
    "they are exact worker wall time, not a parent-side round trip",
    exemplars=True)

# cross-process serving fabric (greptimedb_tpu/shm/): the shared-memory
# artifact plane N frontend processes on one box attach to — fast-lane
# templates, plan-cache entries, warm XLA shape keys, zero-copy result
# handoff, and the worker->parent metrics bridge all ride it
SHM_FABRIC_EVENTS = REGISTRY.sharded_counter(
    "greptimedb_tpu_shm_fabric_events_total",
    "Serving-fabric events by kind (hit = an artifact adopted from a "
    "peer process instead of rebuilt, miss = probed but absent, "
    "publish = a locally built artifact shared, invalidate = a version "
    "bump or wipe fanned out to peers, corrupt = a slot failed its "
    "generation/bounds check, detach = this process fell back to the "
    "private in-process lane; the kind label names the artifact plane: "
    "template/plan/result/metrics/fabric)")
SHM_FABRIC_BYTES = REGISTRY.gauge(
    "greptimedb_tpu_shm_fabric_bytes",
    "Bytes of the attached shared-memory fabric by segment "
    "(fabric = the artifact plane, arena = the zero-copy result "
    "arena) and dimension (size = mapped capacity, used = heap bytes "
    "behind the current write cursor)")

# parse-free serving fast lane (concurrency/fast_lane.py, ISSUE 14): a
# text-keyed template cache in front of the plan cache — a repeat-shape
# statement goes socket bytes -> admission -> bind -> execute -> encode
# with zero parse_sql, zero AST, zero logical planning
FAST_LANE_EVENTS = REGISTRY.sharded_counter(
    "greptimedb_tpu_fast_lane_events_total",
    "Text-template serving fast-lane events by kind (hit = a statement "
    "executed from its cached bound-plan template without parsing, "
    "miss = first sighting of a template (built via the slow lane), "
    "fallback = scanned but ineligible — the reason label names why: "
    "ambiguous literals, comments, non-SELECT verbs, plugins, pending "
    "rollup-substitution probes — invalidate = entries dropped by DDL "
    "or a TableInfo drift check, coalesced = concurrent identical "
    "requests that rode another request's in-flight execution)")
STAGE_SECONDS = REGISTRY.histogram(
    "greptimedb_tpu_query_stage_seconds",
    "Per-request serving-stage wall time by stage (parse / plan = "
    "plan-cache lookup + substitution probe + plan_select / execute on "
    "the slow lane; fast_bind / fast_execute on the fast lane) — with "
    "admission_wait_seconds and encode_seconds this makes the QPS "
    "breakdown attributable per stage instead of inferred; buckets "
    "carry OpenMetrics trace_id exemplars — a slow bucket links "
    "straight to a trace to pull via /v1/traces/<id>", exemplars=True)
COUNTER_SHARDS = REGISTRY.gauge(
    "greptimedb_tpu_metrics_counter_shards",
    "Live per-thread shard cells across all sharded hot counters "
    "(folded into the base series when their thread dies); scrape-time "
    "visibility into the lock-light counter plane")


def _collect_counter_shards() -> None:
    n = 0
    with REGISTRY._lock:
        metrics = list(REGISTRY._metrics)
    for m in metrics:
        if isinstance(m, ShardedCounter):
            n += m.shard_count()
    COUNTER_SHARDS.set(float(n))


REGISTRY.register_collector(_collect_counter_shards)

ROLLUP_SUBSTITUTIONS = REGISTRY.counter(
    "greptimedb_tpu_maintenance_rollup_substitutions_total",
    "Aggregate queries served from rollup plane SSTs instead of raw "
    "data, by table and resolution")

# mesh-sharded hot path (parallel/sharded_dispatch.py) + distributed
# plan-fragment pushdown (query/dist_agg.py): the scale-out surface —
# how often queries ride the device mesh / ship partial planes instead
# of raw rows, and how balanced the shard assignment is
MESH_DISPATCHES = REGISTRY.counter(
    "greptimedb_tpu_mesh_dispatch_total",
    "Aggregate scans dispatched over the device mesh, by kernel path "
    "(sharded/sharded_prepared) and shard count")
MESH_SHARD_SKEW = REGISTRY.gauge(
    "greptimedb_tpu_mesh_shard_skew_ratio",
    "Row-balance of the latest mesh shard plan: max per-shard rows over "
    "the mean (1.0 = perfectly balanced; padding wastes cycles above it)")
FRAGMENT_PUSHDOWNS = REGISTRY.counter(
    "greptimedb_tpu_fragment_pushdown_total",
    "Distributed plan fragments shipped to region owners, by mode "
    "(agg/topk/rows/rows_agg/window/lastpoint/rollup/vmapped — partial "
    "planes or pruned candidates return, never raw region scans)")
EXPIRED_SSTS = REGISTRY.counter(
    "greptimedb_tpu_maintenance_expired_ssts_total",
    "SSTs dropped whole by retention (TTL) expiry")

# incremental aggregation (query/partial_cache.py): per-part partial-
# aggregate planes cached by immutable file identity — repeated
# aggregate queries fold only the delta (memtable rows + files flushed
# since) instead of re-reducing every SST part from scratch
PARTIAL_AGG_CACHE_EVENTS = REGISTRY.counter(
    "greptimedb_tpu_partial_agg_cache_events_total",
    "Partial-aggregate cache events by kind (hit = an immutable part's "
    "[G, F] partial served without touching its rows, miss = computed "
    "and cached, evict = aged out of the byte budget, invalidate = "
    "dropped by a region seam — compaction swap, retention expiry, "
    "DROP/TRUNCATE, fallback = an aggregate shape the incremental fold "
    "could not serve exactly: tombstones, cross-part dedup, sparse "
    "cardinality, or multi-block parts)")
PARTIAL_AGG_CACHE_BYTES = REGISTRY.gauge(
    "greptimedb_tpu_partial_agg_cache_bytes",
    "Host bytes held by the partial-aggregate cache (per-part [G, F] "
    "planes + their decoded group-key columns, plus cached per-region "
    "fragment planes in cluster mode)")
PARTIAL_AGG_DELTA_ROWS = REGISTRY.counter(
    "greptimedb_tpu_partial_agg_delta_rows_total",
    "Rows actually folded by incremental aggregate executions, by kind "
    "(delta = uncached part + memtable rows that ran through kernels, "
    "cached = rows whose partial plane was served from the cache)")

# continuous profiling & roofline (utils/flame.py + utils/roofline.py):
# the always-on sampler's attribution counts and the per-query achieved
# memory bandwidth the roofline accountant folds out of the resource
# ledger — ROADMAP item 1's headline capture metric, now a live series
PROFILE_SAMPLES = REGISTRY.counter(
    "greptimedb_tpu_profile_samples_total",
    "Continuous-profiler stack samples by coarse stage (http/stmt/scan/"
    "device_agg/... from the innermost active span; host = a busy "
    "thread outside any span) — attributed/total ratio is the sampler's "
    "own health metric")
QUERY_ACHIEVED_GBPS = REGISTRY.histogram(
    "greptimedb_tpu_query_achieved_gbps",
    "Per-statement achieved memory bandwidth in GB/s from the roofline "
    "accountant ((h2d + d2h + decoded bytes) / device span time); "
    "compare against the chip peak (819 GB/s on v5e) for the roofline "
    "fraction; buckets carry trace_id exemplars so an anomalous "
    "bandwidth bin links straight to its trace",
    buckets=(0.1, 0.5, 1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 200.0, 400.0,
             819.0), exemplars=True)

# ---- static analysis (tools/gtpu_lint.py, tier-1) --------------------------

LINT_FINDINGS = REGISTRY.gauge(
    "greptimedb_tpu_lint_findings_total",
    "gtpu-lint findings per checker from the latest lint run "
    "(allowlisted included) — the machine-checked invariant surface; "
    "anything unallowed fails tier-1")
