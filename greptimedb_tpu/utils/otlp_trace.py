"""OTLP/HTTP trace export: completed spans leave the process.

The span ring (utils/tracing.py) answers "what happened recently on
THIS node"; fleet operators want the same trees in their tracing
backend (Jaeger/Tempo/anything OTLP). A bounded-queue background
exporter drains completed spans into OTLP/HTTP **JSON**
(`/v1/traces` ExportTraceServiceRequest) — no protobuf dependency, and
the payload builder is a pure function the golden-payload test pins.

Sampling is two-sided:

- **head**: a deterministic per-trace hash against `sample_ratio`
  decides at record time whether a trace's spans enter the queue;
- **tail keep**: spans from unsampled traces park in a bounded
  lookback ring, and `mark_keep(trace_id)` — called by the slow-query
  log for every slow or failed statement — promotes them after the
  fact, so the traces worth keeping survive even at 1% head sampling.

Failure contract: the exporter must NEVER impact a query. Enqueue past
the bound drops (counted), a dead endpoint counts `failed` and moves
on (log-throttled), and the chaos point `otlp.export` injects exactly
that failure in tests. Health is observable at /metrics:
`otlp_trace_queue_depth` + `otlp_trace_spans_total{event=...}`.

Configuration: `[tracing]` options (options.apply_observability) write
the `GTPU_OTLP_*` env knobs this module reads via `maybe_install()` —
env-is-truth layering, so child datanode processes inherit the
operator's endpoint and export their own spans too.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
import zlib
from collections import OrderedDict, deque
from typing import Optional

from greptimedb_tpu.utils.metrics import REGISTRY

OTLP_TRACE_SPANS = REGISTRY.counter(
    "greptimedb_tpu_otlp_trace_spans_total",
    "OTLP trace exporter span outcomes by event (exported = delivered, "
    "dropped = bounded queue was full, failed = endpoint error after "
    "the span was queued, kept = promoted from the unsampled lookback "
    "ring by a tail-based keep — slow/failed statements)")
OTLP_TRACE_QUEUE_DEPTH = REGISTRY.gauge(
    "greptimedb_tpu_otlp_trace_queue_depth",
    "Spans waiting in the bounded OTLP exporter queue")
OTLP_TRACE_EXPORTS = REGISTRY.counter(
    "greptimedb_tpu_otlp_trace_exports_total",
    "OTLP export batches by outcome (ok/error)")
OTLP_LOG_RECORDS = REGISTRY.counter(
    "greptimedb_tpu_otlp_log_records_total",
    "OTLP log exporter record outcomes by event (exported = delivered "
    "to /v1/logs, dropped = bounded queue was full, failed = endpoint "
    "error, throttled = over the per-second rate cap) — fault, "
    "slow-query, and degradation warnings ride the trace exporter's "
    "queue with trace_id correlation")

_log = logging.getLogger("greptimedb_tpu.otlp_trace")

#: traces remembered as keep-worthy / recently-decided (bounded)
_KEEP_CAP = 512
_LOOKBACK_CAP = 2048


def _span_otlp(s) -> dict:
    """One tracing.Span -> OTLP JSON span."""
    start_ns = int(s.started_at * 1e9)
    out = {
        "traceId": (s.trace_id or "").rjust(32, "0"),
        "spanId": (s.span_id or "").rjust(16, "0"),
        "name": s.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(start_ns + int(s.duration_ms * 1e6)),
        "attributes": [
            {"key": str(k), "value": _attr_value(v)}
            for k, v in s.attrs.items()
        ],
    }
    if s.parent_id:
        out["parentSpanId"] = s.parent_id.rjust(16, "0")
    if s.node:
        out["attributes"].append(
            {"key": "gtpu.node", "value": {"stringValue": str(s.node)}})
    return out


def _attr_value(v) -> dict:
    if isinstance(v, bool):  # before int: bool subclasses int
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # proto3 JSON maps int64 to string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def payload(spans, service_name: str = "greptimedb_tpu",
            node: Optional[str] = None) -> dict:
    """ExportTraceServiceRequest JSON for one batch — pure, so the
    golden-payload test pins the wire shape without a live endpoint."""
    resource_attrs = [
        {"key": "service.name", "value": {"stringValue": service_name}},
    ]
    if node:
        resource_attrs.append(
            {"key": "service.instance.id", "value": {"stringValue": node}})
    return {
        "resourceSpans": [{
            "resource": {"attributes": resource_attrs},
            "scopeSpans": [{
                "scope": {"name": "greptimedb_tpu.tracing"},
                "spans": [_span_otlp(s) for s in spans],
            }],
        }],
    }


#: python logging levelno -> OTLP severityNumber (spec table)
_SEVERITY = ((logging.CRITICAL, 21, "FATAL"), (logging.ERROR, 17, "ERROR"),
             (logging.WARNING, 13, "WARN"), (logging.INFO, 9, "INFO"),
             (logging.DEBUG, 5, "DEBUG"))


def _severity(levelno: int):
    for floor, num, text in _SEVERITY:
        if levelno >= floor:
            return num, text
    return 1, "TRACE"


def log_payload(records, service_name: str = "greptimedb_tpu",
                node: Optional[str] = None) -> dict:
    """ExportLogsServiceRequest JSON for one batch of log-record dicts
    (see OtlpLogHandler.emit for the dict shape) — pure, golden-testable
    like payload()."""
    resource_attrs = [
        {"key": "service.name", "value": {"stringValue": service_name}},
    ]
    if node:
        resource_attrs.append(
            {"key": "service.instance.id", "value": {"stringValue": node}})
    out = []
    for r in records:
        num, text = _severity(int(r.get("levelno", logging.INFO)))
        rec = {
            "timeUnixNano": str(int(r.get("ts", 0.0) * 1e9)),
            "severityNumber": num,
            "severityText": text,
            "body": {"stringValue": str(r.get("body", ""))},
            "attributes": [
                {"key": "logger",
                 "value": {"stringValue": str(r.get("logger", ""))}},
            ],
        }
        # trace correlation: same 32-hex zero-pad as span export, so the
        # backend joins this record to the statement's exported tree
        tid = r.get("trace_id") or ""
        if tid:
            rec["traceId"] = tid.rjust(32, "0")
        out.append(rec)
    return {
        "resourceLogs": [{
            "resource": {"attributes": resource_attrs},
            "scopeLogs": [{
                "scope": {"name": "greptimedb_tpu.logging"},
                "logRecords": out,
            }],
        }],
    }


def _sampled(trace_id: str, ratio: float) -> bool:
    """Deterministic head sampling: the same trace decides the same way
    on every node (crc32 over the id, uniform in [0, 1))."""
    if ratio >= 1.0:
        return True
    if ratio <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) / 2**32 < ratio


class OtlpTraceExporter:
    """Bounded-queue background exporter. Thread starts lazily on the
    first enqueued span; `flush()` is for tests and shutdown."""

    def __init__(self, endpoint: str, sample_ratio: float = 1.0,
                 queue_size: int = 2048, batch: int = 256,
                 flush_interval_s: float = 2.0, timeout_s: float = 5.0,
                 node: Optional[str] = None):
        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.endswith("/v1/traces"):
            self.endpoint += "/v1/traces"
        self.sample_ratio = float(sample_ratio)
        self.queue_size = int(queue_size)
        self.batch = int(batch)
        self.flush_interval_s = float(flush_interval_s)
        self.timeout_s = float(timeout_s)
        self.node = node or os.environ.get("GTPU_NODE_ID") or ""
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._busy = 0          # spans taken off the queue, not yet posted
        self._keep: "OrderedDict[str, bool]" = OrderedDict()
        self._lookback: deque = deque(maxlen=_LOOKBACK_CAP)
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._fail_streak = 0
        # log lane: fault/slow-query/degradation records share this
        # exporter's worker + endpoint host, posted to /v1/logs
        self.log_endpoint = self.endpoint[:-len("/v1/traces")] + "/v1/logs"
        self._logq: deque = deque()
        self._log_rate = 20.0          # records/s token bucket
        self._log_tokens = self._log_rate
        self._log_refill = time.monotonic()
        self._log_fail_streak = 0

    # -- producer side (called from tracing._record; must never raise) -------

    def on_span(self, span) -> None:
        try:
            tid = span.trace_id
            if not tid:
                return  # background spans outside any request trace
            with self._cv:
                keep = tid in self._keep
            if keep or _sampled(tid, self.sample_ratio):
                self._enqueue([span])
            else:
                self._lookback.append(span)
        except Exception:  # noqa: BLE001 — telemetry must never hurt a query
            pass

    def mark_keep(self, trace_id: str) -> None:
        """Tail-based keep: promote an unsampled trace (the slow-query
        ring calls this for every slow or failed statement) — its parked
        spans enter the queue, and spans still being recorded follow."""
        if not trace_id:
            return
        try:
            with self._cv:
                already = trace_id in self._keep
                self._keep[trace_id] = True
                while len(self._keep) > _KEEP_CAP:
                    self._keep.popitem(last=False)
            if already or self.sample_ratio >= 1.0:
                return
            promoted = [s for s in list(self._lookback)
                        if s.trace_id == trace_id]
            if promoted:
                OTLP_TRACE_SPANS.inc(float(len(promoted)), event="kept")
                self._enqueue(promoted)
        except Exception:  # noqa: BLE001 — telemetry must never hurt a query
            pass

    def on_log(self, record: dict) -> None:
        """Enqueue one log-record dict (throttled, bounded, never
        raises) — the OtlpLogHandler's sink."""
        try:
            now = time.monotonic()
            with self._cv:
                # token bucket: a fault storm logging thousands of
                # warnings must not monopolize the export lane
                self._log_tokens = min(
                    self._log_rate,
                    self._log_tokens + (now - self._log_refill)
                    * self._log_rate)
                self._log_refill = now
                if self._log_tokens < 1.0:
                    OTLP_LOG_RECORDS.inc(event="throttled")
                    return
                self._log_tokens -= 1.0
                if len(self._logq) >= self.queue_size:
                    OTLP_LOG_RECORDS.inc(event="dropped")
                    return
                self._logq.append(record)
                if self._thread is None and not self._stop:
                    self._thread = threading.Thread(
                        target=self._run, name="gtpu-otlp-export",
                        daemon=True)
                    self._thread.start()
                self._cv.notify_all()
        except Exception:  # noqa: BLE001 — telemetry must never hurt a query
            pass

    def _enqueue(self, spans) -> None:
        with self._cv:
            for s in spans:
                if len(self._q) >= self.queue_size:
                    OTLP_TRACE_SPANS.inc(event="dropped")
                    continue
                self._q.append(s)
            OTLP_TRACE_QUEUE_DEPTH.set(float(len(self._q)))
            if self._thread is None and not self._stop:
                self._thread = threading.Thread(
                    target=self._run, name="gtpu-otlp-export", daemon=True)
                self._thread.start()
            self._cv.notify_all()

    # -- worker side ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                # idle: block untimed — producers notify on enqueue and
                # flush/shutdown notify too, so there is no 20 Hz
                # wakeup loop on a quiet node
                while not self._stop and not self._q and not self._logq:
                    self._cv.wait()
                if self._stop and not self._q and not self._logq:
                    return
                # batch-accumulation window: give a bursting producer
                # up to flush_interval_s to fill the batch
                deadline = time.monotonic() + self.flush_interval_s
                while not self._stop and len(self._q) < self.batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                chunk = [self._q.popleft()
                         for _ in range(min(self.batch, len(self._q)))]
                logs = [self._logq.popleft()
                        for _ in range(min(self.batch, len(self._logq)))]
                self._busy = len(chunk) + len(logs)
                OTLP_TRACE_QUEUE_DEPTH.set(float(len(self._q)))
            if chunk:
                self._post(chunk)
            if logs:
                self._post_logs(logs)
            with self._cv:
                self._busy = 0
                self._cv.notify_all()

    def _post(self, spans) -> None:
        from greptimedb_tpu.fault import FAULTS

        try:
            # serialization INSIDE the guard: a surprise in one span's
            # attrs must count as a failed batch, never kill the worker
            # thread (it is the only one; _enqueue never respawns it)
            body = json.dumps(payload(spans, node=self.node)).encode()
            # chaos seam: the fault-injected-endpoint test arms this to
            # prove typed degradation (counted, logged, zero query
            # impact) without standing up a broken collector
            FAULTS.fire("otlp.export")
            req = urllib.request.Request(
                self.endpoint, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except Exception as e:  # noqa: BLE001 — export must degrade, not raise
            OTLP_TRACE_SPANS.inc(float(len(spans)), event="failed")
            OTLP_TRACE_EXPORTS.inc(event="error")
            self._fail_streak += 1
            if self._fail_streak == 1 or self._fail_streak % 100 == 0:
                _log.warning("OTLP trace export to %s failing (streak %d): %s",
                             self.endpoint, self._fail_streak, e)
            return
        self._fail_streak = 0
        OTLP_TRACE_SPANS.inc(float(len(spans)), event="exported")
        OTLP_TRACE_EXPORTS.inc(event="ok")

    def _post_logs(self, records) -> None:
        from greptimedb_tpu.fault import FAULTS

        try:
            body = json.dumps(log_payload(records, node=self.node)).encode()
            # same chaos seam + typed-degradation contract as span
            # export: an armed otlp.export fault fails this batch too
            FAULTS.fire("otlp.export")
            req = urllib.request.Request(
                self.log_endpoint, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except Exception as e:  # noqa: BLE001 — export must degrade, not raise
            OTLP_LOG_RECORDS.inc(float(len(records)), event="failed")
            self._log_fail_streak += 1
            if self._log_fail_streak == 1 or self._log_fail_streak % 100 == 0:
                _log.warning("OTLP log export to %s failing (streak %d): %s",
                             self.log_endpoint, self._log_fail_streak, e)
            return
        self._log_fail_streak = 0
        OTLP_LOG_RECORDS.inc(float(len(records)), event="exported")

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until both queues drain (tests / shutdown)."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            self._cv.notify_all()
            while self._q or self._logq or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.1))
        return True

    def shutdown(self, timeout_s: float = 2.0) -> None:
        self.flush(timeout_s)
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)

    def depth(self) -> int:
        with self._cv:
            return len(self._q)


class OtlpLogHandler(logging.Handler):
    """logging.Handler that ships warning+ records from the repo's own
    loggers (fault injections, slow queries, degradations) through the
    exporter's queue as OTLP logs — trace-correlated via the current
    trace id, throttled by the exporter's token bucket, and never
    raising (the logging contract and the telemetry contract agree)."""

    #: never re-export the exporter's own failure warnings: a dead
    #: collector would otherwise feed its own error log back into the
    #: queue it cannot drain
    _SKIP = ("greptimedb_tpu.otlp_trace",)

    def __init__(self, exporter: "OtlpTraceExporter"):
        super().__init__(level=logging.WARNING)
        self._exporter = exporter

    def emit(self, record: logging.LogRecord) -> None:
        try:
            if record.name in self._SKIP:
                return
            tid = getattr(record, "trace_id", None)
            if not tid or tid == "-":
                from greptimedb_tpu.utils import tracing
                tid = tracing.current_trace_id() or ""
            self._exporter.on_log({
                "ts": record.created,
                "levelno": record.levelno,
                "logger": record.name,
                "body": record.getMessage(),
                "trace_id": tid,
            })
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


# ---- module-level wiring ----------------------------------------------------

_EXPORTER: Optional[OtlpTraceExporter] = None
_LOG_HANDLER: Optional[OtlpLogHandler] = None
_install_lock = threading.Lock()


def exporter() -> Optional[OtlpTraceExporter]:
    return _EXPORTER


def configure(endpoint: Optional[str], **kwargs) -> Optional[OtlpTraceExporter]:
    """Install (endpoint set) or tear down (empty/None) the process
    exporter and hand it to tracing's span-completion hook."""
    global _EXPORTER, _LOG_HANDLER
    from greptimedb_tpu.utils import tracing

    with _install_lock:
        old, _EXPORTER = _EXPORTER, None
        tracing._exporter = None
        repo_logger = logging.getLogger("greptimedb_tpu")
        if _LOG_HANDLER is not None:
            repo_logger.removeHandler(_LOG_HANDLER)
            _LOG_HANDLER = None
        if old is not None:
            old.shutdown(timeout_s=0.5)
        if endpoint:
            _EXPORTER = OtlpTraceExporter(endpoint, **kwargs)
            tracing._exporter = _EXPORTER
            # log lane rides the same exporter: fault/slow-query/
            # degradation warnings under the repo's logger namespace
            # (gate: GTPU_OTLP_LOGS=off opts out)
            if os.environ.get("GTPU_OTLP_LOGS", "1").strip().lower() \
                    not in ("off", "0", "false", "no"):
                _LOG_HANDLER = OtlpLogHandler(_EXPORTER)
                repo_logger.addHandler(_LOG_HANDLER)
        return _EXPORTER


def maybe_install() -> Optional[OtlpTraceExporter]:
    """Env-driven install (GTPU_OTLP_ENDPOINT + GTPU_OTLP_* knobs) —
    idempotent; called by apply_observability and datanode bootstrap so
    every process in a cluster exports under one configuration. Any
    changed knob (not just the endpoint) reinstalls the exporter."""
    endpoint = os.environ.get("GTPU_OTLP_ENDPOINT", "")
    cur = _EXPORTER
    if not endpoint:
        if cur is not None:
            configure(None)
        return None

    def _f(name, default):
        try:
            return float(os.environ.get(name, default))
        except ValueError:
            return default

    cfg = (endpoint.rstrip("/"),
           _f("GTPU_OTLP_SAMPLE_RATIO", 1.0),
           int(_f("GTPU_OTLP_QUEUE", 2048)),
           _f("GTPU_OTLP_FLUSH_S", 2.0),
           os.environ.get("GTPU_OTLP_LOGS", "1"))
    if cur is not None and getattr(cur, "_env_cfg", None) == cfg:
        return cur
    exp = configure(cfg[0], sample_ratio=cfg[1], queue_size=cfg[2],
                    flush_interval_s=cfg[3])
    if exp is not None:
        exp._env_cfg = cfg
    return exp


def mark_keep(trace_id: str) -> None:
    """Module-level tail-keep hook (slow_query imports this lazily)."""
    exp = _EXPORTER
    if exp is not None:
        exp.mark_keep(trace_id)
