"""OTLP/HTTP trace export: completed spans leave the process.

The span ring (utils/tracing.py) answers "what happened recently on
THIS node"; fleet operators want the same trees in their tracing
backend (Jaeger/Tempo/anything OTLP). A bounded-queue background
exporter drains completed spans into OTLP/HTTP **JSON**
(`/v1/traces` ExportTraceServiceRequest) — no protobuf dependency, and
the payload builder is a pure function the golden-payload test pins.

Sampling is two-sided:

- **head**: a deterministic per-trace hash against `sample_ratio`
  decides at record time whether a trace's spans enter the queue;
- **tail keep**: spans from unsampled traces park in a bounded
  lookback ring, and `mark_keep(trace_id)` — called by the slow-query
  log for every slow or failed statement — promotes them after the
  fact, so the traces worth keeping survive even at 1% head sampling.

Failure contract: the exporter must NEVER impact a query. Enqueue past
the bound drops (counted), a dead endpoint counts `failed` and moves
on (log-throttled), and the chaos point `otlp.export` injects exactly
that failure in tests. Health is observable at /metrics:
`otlp_trace_queue_depth` + `otlp_trace_spans_total{event=...}`.

Configuration: `[tracing]` options (options.apply_observability) write
the `GTPU_OTLP_*` env knobs this module reads via `maybe_install()` —
env-is-truth layering, so child datanode processes inherit the
operator's endpoint and export their own spans too.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
import zlib
from collections import OrderedDict, deque
from typing import Optional

from greptimedb_tpu.utils.metrics import REGISTRY

OTLP_TRACE_SPANS = REGISTRY.counter(
    "greptimedb_tpu_otlp_trace_spans_total",
    "OTLP trace exporter span outcomes by event (exported = delivered, "
    "dropped = bounded queue was full, failed = endpoint error after "
    "the span was queued, kept = promoted from the unsampled lookback "
    "ring by a tail-based keep — slow/failed statements)")
OTLP_TRACE_QUEUE_DEPTH = REGISTRY.gauge(
    "greptimedb_tpu_otlp_trace_queue_depth",
    "Spans waiting in the bounded OTLP exporter queue")
OTLP_TRACE_EXPORTS = REGISTRY.counter(
    "greptimedb_tpu_otlp_trace_exports_total",
    "OTLP export batches by outcome (ok/error)")

_log = logging.getLogger("greptimedb_tpu.otlp_trace")

#: traces remembered as keep-worthy / recently-decided (bounded)
_KEEP_CAP = 512
_LOOKBACK_CAP = 2048


def _span_otlp(s) -> dict:
    """One tracing.Span -> OTLP JSON span."""
    start_ns = int(s.started_at * 1e9)
    out = {
        "traceId": (s.trace_id or "").rjust(32, "0"),
        "spanId": (s.span_id or "").rjust(16, "0"),
        "name": s.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(start_ns + int(s.duration_ms * 1e6)),
        "attributes": [
            {"key": str(k), "value": _attr_value(v)}
            for k, v in s.attrs.items()
        ],
    }
    if s.parent_id:
        out["parentSpanId"] = s.parent_id.rjust(16, "0")
    if s.node:
        out["attributes"].append(
            {"key": "gtpu.node", "value": {"stringValue": str(s.node)}})
    return out


def _attr_value(v) -> dict:
    if isinstance(v, bool):  # before int: bool subclasses int
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # proto3 JSON maps int64 to string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def payload(spans, service_name: str = "greptimedb_tpu",
            node: Optional[str] = None) -> dict:
    """ExportTraceServiceRequest JSON for one batch — pure, so the
    golden-payload test pins the wire shape without a live endpoint."""
    resource_attrs = [
        {"key": "service.name", "value": {"stringValue": service_name}},
    ]
    if node:
        resource_attrs.append(
            {"key": "service.instance.id", "value": {"stringValue": node}})
    return {
        "resourceSpans": [{
            "resource": {"attributes": resource_attrs},
            "scopeSpans": [{
                "scope": {"name": "greptimedb_tpu.tracing"},
                "spans": [_span_otlp(s) for s in spans],
            }],
        }],
    }


def _sampled(trace_id: str, ratio: float) -> bool:
    """Deterministic head sampling: the same trace decides the same way
    on every node (crc32 over the id, uniform in [0, 1))."""
    if ratio >= 1.0:
        return True
    if ratio <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) / 2**32 < ratio


class OtlpTraceExporter:
    """Bounded-queue background exporter. Thread starts lazily on the
    first enqueued span; `flush()` is for tests and shutdown."""

    def __init__(self, endpoint: str, sample_ratio: float = 1.0,
                 queue_size: int = 2048, batch: int = 256,
                 flush_interval_s: float = 2.0, timeout_s: float = 5.0,
                 node: Optional[str] = None):
        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.endswith("/v1/traces"):
            self.endpoint += "/v1/traces"
        self.sample_ratio = float(sample_ratio)
        self.queue_size = int(queue_size)
        self.batch = int(batch)
        self.flush_interval_s = float(flush_interval_s)
        self.timeout_s = float(timeout_s)
        self.node = node or os.environ.get("GTPU_NODE_ID") or ""
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._busy = 0          # spans taken off the queue, not yet posted
        self._keep: "OrderedDict[str, bool]" = OrderedDict()
        self._lookback: deque = deque(maxlen=_LOOKBACK_CAP)
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._fail_streak = 0

    # -- producer side (called from tracing._record; must never raise) -------

    def on_span(self, span) -> None:
        try:
            tid = span.trace_id
            if not tid:
                return  # background spans outside any request trace
            with self._cv:
                keep = tid in self._keep
            if keep or _sampled(tid, self.sample_ratio):
                self._enqueue([span])
            else:
                self._lookback.append(span)
        except Exception:  # noqa: BLE001 — telemetry must never hurt a query
            pass

    def mark_keep(self, trace_id: str) -> None:
        """Tail-based keep: promote an unsampled trace (the slow-query
        ring calls this for every slow or failed statement) — its parked
        spans enter the queue, and spans still being recorded follow."""
        if not trace_id:
            return
        try:
            with self._cv:
                already = trace_id in self._keep
                self._keep[trace_id] = True
                while len(self._keep) > _KEEP_CAP:
                    self._keep.popitem(last=False)
            if already or self.sample_ratio >= 1.0:
                return
            promoted = [s for s in list(self._lookback)
                        if s.trace_id == trace_id]
            if promoted:
                OTLP_TRACE_SPANS.inc(float(len(promoted)), event="kept")
                self._enqueue(promoted)
        except Exception:  # noqa: BLE001 — telemetry must never hurt a query
            pass

    def _enqueue(self, spans) -> None:
        with self._cv:
            for s in spans:
                if len(self._q) >= self.queue_size:
                    OTLP_TRACE_SPANS.inc(event="dropped")
                    continue
                self._q.append(s)
            OTLP_TRACE_QUEUE_DEPTH.set(float(len(self._q)))
            if self._thread is None and not self._stop:
                self._thread = threading.Thread(
                    target=self._run, name="gtpu-otlp-export", daemon=True)
                self._thread.start()
            self._cv.notify_all()

    # -- worker side ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                # idle: block untimed — producers notify on enqueue and
                # flush/shutdown notify too, so there is no 20 Hz
                # wakeup loop on a quiet node
                while not self._stop and not self._q:
                    self._cv.wait()
                if self._stop and not self._q:
                    return
                # batch-accumulation window: give a bursting producer
                # up to flush_interval_s to fill the batch
                deadline = time.monotonic() + self.flush_interval_s
                while not self._stop and len(self._q) < self.batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                chunk = [self._q.popleft()
                         for _ in range(min(self.batch, len(self._q)))]
                self._busy = len(chunk)
                OTLP_TRACE_QUEUE_DEPTH.set(float(len(self._q)))
            if chunk:
                self._post(chunk)
            with self._cv:
                self._busy = 0
                self._cv.notify_all()

    def _post(self, spans) -> None:
        from greptimedb_tpu.fault import FAULTS

        try:
            # serialization INSIDE the guard: a surprise in one span's
            # attrs must count as a failed batch, never kill the worker
            # thread (it is the only one; _enqueue never respawns it)
            body = json.dumps(payload(spans, node=self.node)).encode()
            # chaos seam: the fault-injected-endpoint test arms this to
            # prove typed degradation (counted, logged, zero query
            # impact) without standing up a broken collector
            FAULTS.fire("otlp.export")
            req = urllib.request.Request(
                self.endpoint, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except Exception as e:  # noqa: BLE001 — export must degrade, not raise
            OTLP_TRACE_SPANS.inc(float(len(spans)), event="failed")
            OTLP_TRACE_EXPORTS.inc(event="error")
            self._fail_streak += 1
            if self._fail_streak == 1 or self._fail_streak % 100 == 0:
                _log.warning("OTLP trace export to %s failing (streak %d): %s",
                             self.endpoint, self._fail_streak, e)
            return
        self._fail_streak = 0
        OTLP_TRACE_SPANS.inc(float(len(spans)), event="exported")
        OTLP_TRACE_EXPORTS.inc(event="ok")

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until the queue drains (tests / shutdown)."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            self._cv.notify_all()
            while self._q or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.1))
        return True

    def shutdown(self, timeout_s: float = 2.0) -> None:
        self.flush(timeout_s)
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)

    def depth(self) -> int:
        with self._cv:
            return len(self._q)


# ---- module-level wiring ----------------------------------------------------

_EXPORTER: Optional[OtlpTraceExporter] = None
_install_lock = threading.Lock()


def exporter() -> Optional[OtlpTraceExporter]:
    return _EXPORTER


def configure(endpoint: Optional[str], **kwargs) -> Optional[OtlpTraceExporter]:
    """Install (endpoint set) or tear down (empty/None) the process
    exporter and hand it to tracing's span-completion hook."""
    global _EXPORTER
    from greptimedb_tpu.utils import tracing

    with _install_lock:
        old, _EXPORTER = _EXPORTER, None
        tracing._exporter = None
        if old is not None:
            old.shutdown(timeout_s=0.5)
        if endpoint:
            _EXPORTER = OtlpTraceExporter(endpoint, **kwargs)
            tracing._exporter = _EXPORTER
        return _EXPORTER


def maybe_install() -> Optional[OtlpTraceExporter]:
    """Env-driven install (GTPU_OTLP_ENDPOINT + GTPU_OTLP_* knobs) —
    idempotent; called by apply_observability and datanode bootstrap so
    every process in a cluster exports under one configuration. Any
    changed knob (not just the endpoint) reinstalls the exporter."""
    endpoint = os.environ.get("GTPU_OTLP_ENDPOINT", "")
    cur = _EXPORTER
    if not endpoint:
        if cur is not None:
            configure(None)
        return None

    def _f(name, default):
        try:
            return float(os.environ.get(name, default))
        except ValueError:
            return default

    cfg = (endpoint.rstrip("/"),
           _f("GTPU_OTLP_SAMPLE_RATIO", 1.0),
           int(_f("GTPU_OTLP_QUEUE", 2048)),
           _f("GTPU_OTLP_FLUSH_S", 2.0))
    if cur is not None and getattr(cur, "_env_cfg", None) == cfg:
        return cur
    exp = configure(cfg[0], sample_ratio=cfg[1], queue_size=cfg[2],
                    flush_interval_s=cfg[3])
    if exp is not None:
        exp._env_cfg = cfg
    return exp


def mark_keep(trace_id: str) -> None:
    """Module-level tail-keep hook (slow_query imports this lazily)."""
    exp = _EXPORTER
    if exp is not None:
        exp.mark_keep(trace_id)
