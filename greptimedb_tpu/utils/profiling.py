"""On-demand profiling over HTTP — the pprof analog.

Mirrors the reference's `servers/src/http/pprof.rs` (CPU flamegraphs via
the pprof crate's sampling profiler) and `http/mem_prof.rs` (jemalloc heap
profiles): here a wall-clock stack sampler over `sys._current_frames()`
produces folded-stack output (the flamegraph.pl / speedscope "collapsed"
format), and tracemalloc snapshots provide allocation profiles. Both are
pull-style: hit the endpoint, get a self-contained text artifact."""

from __future__ import annotations

import sys
import threading
import time
import tracemalloc
from collections import Counter

#: thread idents of every live profiler/sampler thread — each sampler
#: (this module's on-demand one, utils/flame.py's continuous one)
#: registers itself so no flame is ever polluted by the instruments
#: observing each other. Plain set mutations are GIL-atomic.
_PROFILER_TIDS: set = set()


def register_profiler_thread(tid: int) -> None:
    _PROFILER_TIDS.add(tid)


def unregister_profiler_thread(tid: int) -> None:
    _PROFILER_TIDS.discard(tid)


def sample_cpu(seconds: float = 5.0, hz: float = 99.0,
               include_idle: bool = False) -> str:
    """Sample every thread's Python stack for `seconds` at `hz`.

    Returns folded stacks: `frame;frame;...;leaf count` per line, leaf
    last — feed to any flamegraph renderer. Threads blocked in epoll/GIL
    waits are skipped unless include_idle (matching pprof's on-CPU view
    as closely as a wall sampler can). Profiler threads — this one and
    any registered continuous sampler — are excluded: an earlier version
    counted its own sampling loop when invoked off the serving thread,
    so every flame carried a phantom `sample_cpu` tower."""
    deadline = time.monotonic() + seconds
    interval = 1.0 / hz
    stacks: Counter = Counter()
    me = threading.get_ident()
    register_profiler_thread(me)
    try:
        n_samples = _sample_loop(deadline, interval, stacks, include_idle)
    finally:
        unregister_profiler_thread(me)
    lines = [f"# sampler: {n_samples} samples @ {hz:g}Hz over {seconds:g}s"]
    for stack, count in stacks.most_common():
        lines.append(f"{stack} {count}")
    return "\n".join(lines) + "\n"


def _sample_loop(deadline: float, interval: float, stacks: Counter,
                 include_idle: bool) -> int:
    n_samples = 0
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid in _PROFILER_TIDS:
                continue
            parts = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            if not parts:
                continue
            leaf = parts[0]
            if not include_idle and (
                "wait" in leaf or "select" in leaf or "poll" in leaf
                or "accept" in leaf or "read (" in leaf
            ):
                continue
            stacks[";".join(reversed(parts))] += 1
        n_samples += 1
        time.sleep(interval)
    return n_samples


_mem_lock = threading.Lock()


def mem_profile(top: int = 50) -> str:
    """Allocation snapshot (jemalloc heap-profile analog). Starts
    tracemalloc on first call — the first snapshot covers allocations from
    then on; subsequent calls show current live allocations."""
    with _mem_lock:
        if not tracemalloc.is_tracing():
            tracemalloc.start(10)
            return ("# tracemalloc started; allocations recorded from now —"
                    " call again for a snapshot\n")
        snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")
    total = sum(s.size for s in stats)
    lines = [f"# live python allocations: {total / 1e6:.1f} MB "
             f"in {len(stats)} sites (top {top})"]
    for s in stats[:top]:
        fr = s.traceback[0]
        lines.append(f"{s.size / 1e3:.1f}kB x{s.count} "
                     f"{fr.filename.rsplit('/', 1)[-1]}:{fr.lineno}")
    return "\n".join(lines) + "\n"


def mem_profile_stop() -> str:
    with _mem_lock:
        if tracemalloc.is_tracing():
            tracemalloc.stop()
            return "# tracemalloc stopped\n"
        return "# tracemalloc was not running\n"
