"""Minimal protobuf wire-format codec (no codegen).

Hand-rolled varint/length-delimited encoding for the handful of external
message schemas the servers speak — Prometheus remote_write/read
(prometheus.WriteRequest/ReadRequest, reference src/servers/src/proto.rs)
and OTLP — without depending on generated stubs. Messages are represented
as dicts of field number -> list of raw values.
"""

from __future__ import annotations

import struct
from typing import Iterator


def read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def iter_fields(data: bytes) -> Iterator[tuple[int, int, object]]:
    """Yield (field_number, wire_type, value). Length-delimited values are
    raw bytes; varints are ints; fixed64/fixed32 are raw ints."""
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = read_varint(data, pos)
        field, wt = key >> 3, key & 0x07
        if wt == 0:
            v, pos = read_varint(data, pos)
            yield field, wt, v
        elif wt == 1:
            v = struct.unpack("<Q", data[pos:pos + 8])[0]
            pos += 8
            yield field, wt, v
        elif wt == 2:
            ln, pos = read_varint(data, pos)
            yield field, wt, data[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = struct.unpack("<I", data[pos:pos + 4])[0]
            pos += 4
            yield field, wt, v
        else:
            raise ValueError(f"unsupported wire type {wt}")


def fixed64_to_double(v: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", v))[0]


def varint_to_sint64(v: int) -> int:
    """Interpret a varint as two's-complement int64 (protobuf int64)."""
    return v - (1 << 64) if v >= (1 << 63) else v


# ---- encode helpers ----


def field_varint(field: int, v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    return write_varint(field << 3) + write_varint(v)


def field_bytes(field: int, data: bytes) -> bytes:
    return write_varint((field << 3) | 2) + write_varint(len(data)) + data


def field_str(field: int, s: str) -> bytes:
    return field_bytes(field, s.encode())


def field_double(field: int, v: float) -> bytes:
    return write_varint((field << 3) | 1) + struct.pack("<d", v)
