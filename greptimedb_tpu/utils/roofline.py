"""Roofline accountant: fold a query's resource ledger into bandwidth.

The per-query :mod:`ledger` already counts every byte a statement moves
(H2D/D2H transfers from device_telemetry, decoded scan bytes from the
storage plane) and every millisecond its device spans ran.  This module
folds those raw counts into the three numbers ROADMAP item 1 names as
the headline capture metric:

- ``achieved_gbps``  — bytes moved / device time, in GB/s.  The bytes
  are ``h2d_bytes + d2h_bytes + bytes_decoded`` (link traffic plus the
  decode read stream); the denominator prefers device span time
  (``device_ms``), falling back to aggregate time and finally to the
  caller-supplied wall duration.
- ``arithmetic_intensity`` — estimated FLOPs per byte.  The workloads
  here are streaming reductions (~one multiply-accumulate per scanned
  row), so intensity lands well under 1 FLOP/B: bandwidth-bound, which
  is exactly why achieved GB/s is the number that matters.
- ``roofline_fraction`` — achieved_gbps / the chip's peak memory
  bandwidth (819 GB/s for TPU v5e; overridable for golden tests and
  colocated captures via ``GTPU_ROOFLINE_PEAK_GBPS``).

Everything is a pure fold over a ledger snapshot dict — no sampling, no
probes at account() time — so the stamped numbers agree with the ledger
byte counts exactly, and golden tests can hand-compute fixtures.
"""

from __future__ import annotations

import os
from typing import Optional

#: chip peak memory bandwidth by backend, GB/s.  tpu = v5e HBM per
#: chip; gpu = H100 SXM HBM3; cpu = a typical dual-channel DDR5 host,
#: a stand-in so cpu-backend smoke runs still get a finite fraction.
_PEAKS = {"tpu": 819.0, "gpu": 3350.0, "cpu": 100.0}

#: estimated FLOPs per scanned row — one multiply-accumulate, the
#: honest floor for the streaming SUM/AVG reductions this engine runs
_EST_FLOPS_PER_ROW = 2.0

#: ledger keys folded into the byte numerator, in stamp order
BYTE_KEYS = ("h2d_bytes", "d2h_bytes", "bytes_decoded")


def _link() -> dict:
    try:
        from greptimedb_tpu.query.physical import accelerator_link
        return accelerator_link()
    except Exception:
        return {"backend": "cpu", "colocated": True}


def peak_gbps(backend: Optional[str] = None) -> float:
    """Attainable peak bandwidth in GB/s for the active backend.

    Chip HBM peak when co-located; over a network tunnel (remote chip)
    the *measured* D2H link rate from ``accelerator_link()`` is the
    real ceiling, so the roofline fraction reads ~1.0 when a query is
    tunnel-bound rather than a misleading ~0.001 of HBM it could never
    reach.  ``GTPU_ROOFLINE_PEAK_GBPS`` overrides everything — used by
    golden tests for determinism and by operators whose parts differ
    from the defaults.
    """
    env = os.environ.get("GTPU_ROOFLINE_PEAK_GBPS", "").strip()
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
    link = _link() if backend is None else None
    if backend is None:
        backend = str(link.get("backend", "cpu"))
    chip = _PEAKS.get(backend, _PEAKS["cpu"])
    if link is not None and not link.get("colocated", True):
        try:
            measured = float(link.get("d2h_mbps", 0.0)) / 1e3
            if 0 < measured < chip:
                return measured
        except (TypeError, ValueError):
            pass
    return chip


def account(led: dict, duration_ms: Optional[float] = None,
            peak: Optional[float] = None) -> Optional[dict]:
    """Fold a ledger snapshot/diff dict into roofline terms.

    Returns None when the ledger moved no bytes or recorded no usable
    time window — host-only statements (DDL, information_schema) have
    no meaningful bandwidth and must not stamp a misleading zero.
    """
    bytes_total = 0.0
    for k in BYTE_KEYS:
        try:
            bytes_total += float(led.get(k, 0) or 0)
        except (TypeError, ValueError):
            continue
    ms = led.get("device_ms") or led.get("agg_ms") or duration_ms
    try:
        ms = float(ms) if ms is not None else 0.0
    except (TypeError, ValueError):
        ms = 0.0
    if bytes_total <= 0 or ms <= 0:
        return None
    gbps = bytes_total / (ms / 1e3) / 1e9
    if peak is None:
        peak = peak_gbps()
    try:
        rows = float(led.get("rows_scanned", 0) or 0)
    except (TypeError, ValueError):
        rows = 0.0
    return {
        "achieved_gbps": gbps,
        "roofline_fraction": gbps / peak if peak > 0 else 0.0,
        "arithmetic_intensity": (_EST_FLOPS_PER_ROW * rows) / bytes_total,
        "bytes_total": int(bytes_total),
        "window_ms": ms,
        "peak_gbps": peak,
    }


def stamp(attrs: dict, led: dict,
          duration_ms: Optional[float] = None) -> Optional[dict]:
    """account() + write the two headline numbers into a span's attrs.

    The full fold is returned so callers (slow-query records, ANALYZE)
    can surface the supporting terms too.
    """
    rf = account(led, duration_ms)
    if rf is not None:
        attrs["achieved_gbps"] = round(rf["achieved_gbps"], 6)
        attrs["roofline_fraction"] = round(rf["roofline_fraction"], 9)
    return rf


def format_line(rf: dict) -> str:
    """One ANALYZE-style text line for a fold, stable for tooling."""
    return (f"achieved_gbps={rf['achieved_gbps']:.6g} "
            f"roofline_fraction={rf['roofline_fraction']:.6g} "
            f"arithmetic_intensity={rf['arithmetic_intensity']:.6g} "
            f"bytes={rf['bytes_total']} window_ms={rf['window_ms']:.6g} "
            f"peak_gbps={rf['peak_gbps']:g}")
