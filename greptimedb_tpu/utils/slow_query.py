"""Slow-query log: a bounded ring of structured records for statements
that crossed a configurable latency threshold.

Mirrors the reference's slow-query timer (servers register a slow query
threshold and log structured records; GreptimeDB additionally exposes
them as a system table). Here every SQL statement and PromQL evaluation
runs under `watch(...)`; when its wall time crosses the threshold the
record — trace id, query text, duration, rows, execution path, and the
per-stage span breakdown — lands in a process-wide ring surfaced three
ways:

- `information_schema.slow_queries` (SQL)
- `GET /v1/slow_queries` (HTTP debug route, auth-gated)
- `greptimedb_tpu_slow_queries_total` counter at /metrics

Configuration: `[slow_query]` options (options.py) write the
GTPU_SLOW_QUERY_MS / GTPU_SLOW_QUERY_RING env knobs this module reads —
same env-is-truth layering as config.py, so child datanode processes
inherit the operator's setting. Threshold <= 0 disables capture.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from greptimedb_tpu.utils import ledger, tracing
from greptimedb_tpu.utils.metrics import SLOW_QUERIES

#: default threshold (ms); the reference defaults its slow-query timer on
DEFAULT_THRESHOLD_MS = 1000.0
DEFAULT_RING = 128

#: re-entrancy guard: TQL runs PromQL INSIDE an execute_sql statement —
#: only the outermost watch records (the inner text is a substring of
#: the outer statement anyway)
_active: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "gtpu_slow_query_active", default=False)

def _ring_capacity() -> int:
    try:
        return max(1, int(os.environ.get("GTPU_SLOW_QUERY_RING",
                                         DEFAULT_RING)))
    except ValueError:
        return DEFAULT_RING


_lock = threading.Lock()
_ring: deque = deque(maxlen=_ring_capacity())


def threshold_ms() -> float:
    try:
        return float(os.environ.get("GTPU_SLOW_QUERY_MS",
                                    DEFAULT_THRESHOLD_MS))
    except ValueError:
        return DEFAULT_THRESHOLD_MS


def configure(threshold: Optional[float] = None,
              ring_size: Optional[int] = None) -> None:
    """Apply [slow_query] options: env is the store (children inherit
    both knobs), the ring is rebuilt only when its capacity changes."""
    global _ring
    if threshold is not None:
        os.environ["GTPU_SLOW_QUERY_MS"] = str(float(threshold))
    if ring_size is not None:
        os.environ["GTPU_SLOW_QUERY_RING"] = str(int(ring_size))
        if ring_size != _ring.maxlen:
            with _lock:
                _ring = deque(_ring, maxlen=max(1, int(ring_size)))


@dataclass
class SlowQuery:
    trace_id: str
    kind: str            # sql | promql
    query: str
    db: str
    duration_ms: float
    threshold_ms: float
    rows: int
    execution_path: Optional[str]
    started_at: float    # epoch seconds
    #: why the statement never reached the plan cache (join/cte/
    #: subquery/range_select/window) — uncacheable dashboard queries
    #: show up here instead of just being slow
    plan_cache_skip: Optional[str] = None
    #: how the deadline plane ended this statement, if it did
    #: (expired | cancelled | killed) — an expired statement is almost
    #: always a slow one, so the record says WHY it stopped
    deadline_event: Optional[str] = None
    stages: list = field(default_factory=list)  # (node, name, ms) triples
    #: the statement's slice of the per-query resource ledger (cache
    #: hits, H2D bytes, admission wait, rows scanned — utils/ledger.py)
    ledger: dict = field(default_factory=dict)
    #: roofline fold over that same ledger slice (utils/roofline.py) —
    #: None when the statement moved no bytes (host-only work)
    achieved_gbps: Optional[float] = None
    roofline_fraction: Optional[float] = None

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "kind": self.kind,
            "query": self.query, "db": self.db,
            "duration_ms": round(self.duration_ms, 3),
            "threshold_ms": self.threshold_ms, "rows": self.rows,
            "execution_path": self.execution_path,
            "plan_cache_skip": self.plan_cache_skip,
            "deadline_event": self.deadline_event,
            "started_at_ms": int(self.started_at * 1000),
            "stages": [
                {"node": n, "stage": s, "duration_ms": round(d, 3)}
                for n, s, d in self.stages
            ],
            "ledger": dict(self.ledger),
            "achieved_gbps": self.achieved_gbps,
            "roofline_fraction": self.roofline_fraction,
        }


class _Watch:
    """Mutable per-statement record the caller annotates after the run
    (rows, execution path) — only read if the statement turns out slow."""

    __slots__ = ("rows", "execution_path", "plan_cache_skip",
                 "deadline_event")

    def __init__(self):
        self.rows = 0
        self.execution_path = None
        self.plan_cache_skip = None
        self.deadline_event = None


#: the active watch, reachable from deep inside planning (the engine's
#: plan-cache skip annotation fires levels below execute_sql)
_current: contextvars.ContextVar = contextvars.ContextVar(
    "gtpu_slow_query_watch", default=None)


def annotate(**attrs) -> None:
    """Set fields on the current statement's watch (no-op outside one)."""
    w = _current.get()
    if w is None:
        return
    for k, v in attrs.items():
        if k in _Watch.__slots__:
            setattr(w, k, v)


@contextlib.contextmanager
def watch(kind: str, query: str, db: str = "public"):
    """Time the enclosed statement; record it if it crosses the
    threshold. Nested watches (TQL inside SQL) are no-ops. Records even
    when the statement RAISES — a slow failure is still a slow query."""
    thr = threshold_ms()
    if _active.get() or thr <= 0:
        yield _Watch()
        return
    token = _active.set(True)
    w = _Watch()
    w_token = _current.set(w)
    # entry points that bypass the SQL engine (direct PromQL HTTP) have
    # no trace yet — mint one so the record, the spans, and the log
    # lines of this evaluation still join on an id
    prev_tid = tracing.current_trace_id()
    if prev_tid is None:
        tracing.set_trace(None)
    started = time.time()
    t0 = time.perf_counter()
    try:
        # the statement's resource-ledger slice: attach one if the
        # server didn't (direct engine callers), and diff around the
        # run so multi-statement requests attribute per statement
        with ledger.attach() as led:
            led0 = led.snapshot() if led is not None else {}
            with tracing.collect_spans() as sink:
                yield w
    finally:
        _active.reset(token)
        _current.reset(w_token)
        dur_ms = (time.perf_counter() - t0) * 1000.0
        if dur_ms >= thr:
            led_slice = ledger.diff(led0, led.snapshot()) \
                if led is not None else {}
            _record(kind, query, db, dur_ms, thr, w, started, sink,
                    led_slice)
        if prev_tid is None:
            tracing.restore_trace(None)


def _record(kind, query, db, dur_ms, thr, w, started, sink,
            led_slice=None) -> None:
    rec = SlowQuery(
        trace_id=tracing.current_trace_id() or "-",
        kind=kind, query=query[:4096], db=db,
        duration_ms=dur_ms, threshold_ms=thr, rows=w.rows,
        execution_path=w.execution_path,
        plan_cache_skip=w.plan_cache_skip,
        deadline_event=w.deadline_event, started_at=started,
        stages=[(s.node or "local", s.name, s.duration_ms) for s in sink],
        ledger=led_slice or {},
    )
    if led_slice:
        from greptimedb_tpu.utils import roofline

        rf = roofline.account(led_slice, duration_ms=dur_ms)
        if rf is not None:
            rec.achieved_gbps = round(rf["achieved_gbps"], 6)
            rec.roofline_fraction = round(rf["roofline_fraction"], 9)
    with _lock:
        _ring.append(rec)
    SLOW_QUERIES.inc(kind=kind)
    # tail-based keep: a slow (or slow-failing) statement's trace is
    # worth exporting even when head sampling passed on it
    from greptimedb_tpu.utils import otlp_trace

    otlp_trace.mark_keep(rec.trace_id if rec.trace_id != "-" else "")
    import logging

    # log a bounded prefix: a multi-thousand-row INSERT VALUES is tens
    # of KB — the full statement lives in the ring (information_schema.
    # slow_queries), the log line only needs enough to identify it
    logging.getLogger("greptimedb_tpu.slow_query").warning(
        "slow query (%.1f ms >= %.0f ms) kind=%s rows=%d path=%s: %s",
        dur_ms, thr, kind, rec.rows, rec.execution_path,
        rec.query[:400] + ("..." if len(rec.query) > 400 else ""))


def records(n: Optional[int] = None) -> list[SlowQuery]:
    """Newest-first slice of the ring."""
    with _lock:
        out = list(_ring)
    out.reverse()
    return out[:n] if n is not None else out


def clear() -> None:
    with _lock:
        _ring.clear()
