"""Snappy block-format codec.

Prometheus remote write/read bodies are snappy-framed protobuf
(reference src/servers/src/prom_store.rs uses the snap crate). The fast
path is the native C++ codec (greptimedb_tpu/native, real back-reference
compression, the analog of the reference's snap crate); this module's
pure-Python implementation is the always-available fallback: decompress
covers the full block format (literals + copy-1/2/4), compress emits
valid literal-only snappy.

`compress`/`decompress` below transparently dispatch to native when the
toolchain built it.
"""

from __future__ import annotations


class SnappyError(Exception):
    pass


def _try_native():
    try:
        from greptimedb_tpu.native import try_load
        return try_load()
    except Exception:  # noqa: BLE001 — fallback must never fail
        return None


_NATIVE = _try_native()


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 35:
            raise SnappyError("varint too long")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    if _NATIVE is not None:
        try:
            return _NATIVE.snappy_decompress(data)
        except ValueError as e:
            raise SnappyError(str(e)) from None
    return _py_decompress(data)


def compress(data: bytes) -> bytes:
    if _NATIVE is not None:
        return _NATIVE.snappy_compress(data)
    return _py_compress(data)


def _py_decompress(data: bytes) -> bytes:
    expected, pos = _read_varint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos:pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise SnappyError("truncated literal")
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == 1:  # copy with 1-byte offset
            length = ((tag >> 2) & 0x07) + 4
            if pos >= n:
                raise SnappyError("truncated copy-1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy with 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise SnappyError("truncated copy-2")
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:  # copy with 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise SnappyError("truncated copy-4")
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError(f"bad copy offset {offset}")
        # overlapping copies are allowed and common (RLE-style)
        start = len(out) - offset
        for i in range(length):
            out.append(out[start + i])
    if len(out) != expected:
        raise SnappyError(f"length mismatch: got {len(out)}, want {expected}")
    return bytes(out)


def _py_compress(data: bytes) -> bytes:
    """Literal-only snappy encoding (valid per spec; no back-references)."""
    out = bytearray(_write_varint(len(data)))
    pos = 0
    n = len(data)
    while pos < n:
        chunk = data[pos: pos + 65536]
        pos += len(chunk)
        length = len(chunk) - 1
        if length < 60:
            out.append(length << 2)
        elif length < 1 << 8:
            out.append(60 << 2)
            out += length.to_bytes(1, "little")
        else:
            out.append(61 << 2)
            out += length.to_bytes(2, "little")
        out += chunk
    return bytes(out)
