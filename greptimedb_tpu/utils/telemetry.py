"""Opt-out anonymous usage telemetry (mirrors reference
src/common/greptimedb-telemetry/src/lib.rs:90-105 StatisticData + the
uuid-cache/RepeatedTask mechanics).

Reports {os, version, arch, mode, nodes, uuid} on an interval to a
configurable endpoint. Differences from the reference, deliberate:

- DISABLED by default (`telemetry.enable = false`): this build targets
  air-gapped TPU pods; phoning home must be an explicit choice
  (reference defaults on, lib.rs).
- The report is plain JSON POST via urllib; failures are swallowed and
  retried next interval — telemetry must never affect the server.

The installation uuid persists in `.greptimedb-telemetry-uuid` under
the data home (same filename as the reference, lib.rs:31) so restarts
report a stable anonymous identity.
"""

from __future__ import annotations

import json
import os
import platform
import threading
import uuid as uuidlib
from typing import Callable, Optional

from greptimedb_tpu import __version__

UUID_FILE_NAME = ".greptimedb-telemetry-uuid"
DEFAULT_INTERVAL_S = 30 * 60  # reference: 30 minutes


def load_or_create_uuid(working_home: str) -> Optional[str]:
    path = os.path.join(working_home, UUID_FILE_NAME)
    try:
        if os.path.exists(path):
            val = open(path).read().strip()
            if val:
                return val
        val = uuidlib.uuid4().hex
        os.makedirs(working_home, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(val)
        os.replace(tmp, path)
        return val
    except OSError:
        return None  # read-only home: report uuid-less like the reference


def statistic_data(mode: str, working_home: str,
                   nodes: Optional[int] = None) -> dict:
    """The StatisticData payload (lib.rs:90-105)."""
    return {
        "os": platform.system().lower(),
        "version": __version__,
        "arch": platform.machine(),
        "mode": mode,
        "git_commit": os.environ.get("GREPTIMEDB_TPU_GIT_COMMIT", ""),
        "nodes": nodes,
        "uuid": load_or_create_uuid(working_home),
    }


class TelemetryTask:
    """Periodic reporter (the RepeatedTask analog). `post` is injectable
    for tests; the default uses urllib with a short timeout."""

    def __init__(self, url: str, mode: str, working_home: str,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 nodes_fn: Optional[Callable[[], Optional[int]]] = None,
                 post: Optional[Callable[[str, bytes], None]] = None):
        self.url = url
        self.mode = mode
        self.working_home = working_home
        self.interval_s = interval_s
        self.nodes_fn = nodes_fn
        self.post = post or self._default_post
        self.reports_sent = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    @staticmethod
    def _default_post(url: str, body: bytes) -> None:
        import urllib.request

        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=5).read()

    def report_once(self) -> bool:
        nodes = self.nodes_fn() if self.nodes_fn is not None else None
        body = json.dumps(statistic_data(
            self.mode, self.working_home, nodes)).encode()
        try:
            self.post(self.url, body)
        except Exception:  # noqa: BLE001 — telemetry must never bite
            return False
        self.reports_sent += 1
        return True

    def _run(self) -> None:
        self.report_once()  # initial delay zero, like the reference
        while not self._stop.wait(self.interval_s):
            self.report_once()

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
