"""Timestamp algebra (mirrors reference src/common/time, ~5k LoC).

Internal representation is int64 in a column-specific unit; all parsing
lands in nanoseconds and converts down.
"""

from __future__ import annotations

import datetime as dt
import re
from typing import Optional

from greptimedb_tpu.datatypes.types import DataType, TimeUnit

_FORMATS = (
    "%Y-%m-%d %H:%M:%S.%f%z",
    "%Y-%m-%dT%H:%M:%S.%f%z",
    "%Y-%m-%d %H:%M:%S%z",
    "%Y-%m-%dT%H:%M:%S%z",
    "%Y-%m-%d %H:%M:%S.%f",
    "%Y-%m-%dT%H:%M:%S.%f",
    "%Y-%m-%d %H:%M:%S",
    "%Y-%m-%dT%H:%M:%S",
    "%Y-%m-%d %H:%M",
    "%Y-%m-%d",
)


def tzinfo_for(name: Optional[str]) -> dt.tzinfo:
    """Session timezone name → tzinfo: '+08:00'/'-05:30' fixed offsets,
    IANA names via zoneinfo, None/UTC → UTC (reference
    common/time timezone.rs parse precedence)."""
    if not name or name.upper() == "UTC":
        return dt.timezone.utc
    m = re.fullmatch(r"([+-])(\d{1,2}):?(\d{2})?", name.strip())
    if m:
        sign = 1 if m.group(1) == "+" else -1
        minutes = int(m.group(2)) * 60 + int(m.group(3) or 0)
        return dt.timezone(sign * dt.timedelta(minutes=minutes))
    try:
        from zoneinfo import ZoneInfo

        return ZoneInfo(name)
    except Exception as exc:  # noqa: BLE001 — bad tz name is a user error
        raise ValueError(f"unknown time zone {name!r}") from exc


def parse_timestamp_ns(text: str, tz: Optional[str] = None) -> int:
    """Parse an ISO-ish timestamp string to epoch nanoseconds. Naive
    strings are interpreted in `tz` (the session timezone), UTC when
    unset; an explicit offset in the string always wins."""
    t = text.strip().replace("Z", "+0000")
    for fmt in _FORMATS:
        try:
            d = dt.datetime.strptime(t, fmt)
            if d.tzinfo is None:
                d = d.replace(tzinfo=tzinfo_for(tz))
            epoch = d.timestamp()
            # avoid float precision loss: split seconds/micros
            whole = int(epoch // 1)
            micros = d.microsecond
            base = dt.datetime(d.year, d.month, d.day, d.hour, d.minute, d.second,
                               tzinfo=d.tzinfo)
            return int(base.timestamp()) * 10**9 + micros * 1000
        except ValueError:
            continue
    raise ValueError(f"cannot parse timestamp {text!r}")


def ns_to_unit(ns: int, unit: TimeUnit) -> int:
    return ns // unit.nanos_per_unit


def unit_to_ns(value: int, unit: TimeUnit) -> int:
    return value * unit.nanos_per_unit


def coerce_ts_literal(value, dtype: DataType,
                      tz: Optional[str] = None) -> int:
    """Coerce a SQL literal (string or int) to the storage unit of `dtype`.

    Integer literals are interpreted in the column's own unit (matching the
    reference's behavior for bare numeric timestamp comparisons); naive
    strings in the session timezone `tz`."""
    unit = dtype.time_unit
    if isinstance(value, str):
        return ns_to_unit(parse_timestamp_ns(value, tz), unit)
    if isinstance(value, dt.datetime):
        # Arrow timestamp columns round-trip as datetime objects
        tz = value if value.tzinfo else value.replace(tzinfo=dt.timezone.utc)
        delta = tz - dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)
        ns = (delta.days * 86_400 + delta.seconds) * 10**9 \
            + delta.microseconds * 1000
        return ns_to_unit(ns, unit)
    return int(value)


def format_ts(value: int, dtype: DataType) -> str:
    """Render an int timestamp for output (ISO, UTC)."""
    ns = unit_to_ns(int(value), dtype.time_unit)
    secs, rem = divmod(ns, 10**9)
    d = dt.datetime.fromtimestamp(secs, tz=dt.timezone.utc)
    if rem:
        frac = f".{rem // 10**6:03d}" if rem % 10**6 == 0 else f".{rem:09d}".rstrip("0")
        return d.strftime("%Y-%m-%dT%H:%M:%S") + frac
    return d.strftime("%Y-%m-%dT%H:%M:%S")
