"""Trace context + spans (mirrors reference common/telemetry tracing:
`TracingContext::to_w3c` rides region requests across process hops,
query/src/dist_plan/merge_scan.rs:185-201, re-attached server-side at
servers/src/grpc/region_server.rs:74).

A request's trace id lives in a contextvar; spans record wall-time per
stage into a bounded ring buffer. EXPLAIN ANALYZE and the region wire
protocol both ride this: the frontend's trace id crosses Flight inside
the scan spec, so one query's spans line up across processes.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

_current: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "gtpu_trace_id", default=None)

_SPANS: deque = deque(maxlen=4096)


@dataclass
class Span:
    trace_id: Optional[str]
    name: str
    duration_ms: float
    started_at: float
    attrs: dict = field(default_factory=dict)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def set_trace(trace_id: Optional[str] = None) -> str:
    """Install (or adopt) a trace id for the current context."""
    tid = trace_id or new_trace_id()
    _current.set(tid)
    return tid


def current_trace_id() -> Optional[str]:
    return _current.get()


def restore_trace(trace_id: Optional[str]) -> None:
    """Put back a previously-saved id verbatim (None clears — unlike
    set_trace, which would mint a fresh id)."""
    _current.set(trace_id)


@contextlib.contextmanager
def span(name: str, **attrs):
    t0 = time.perf_counter()
    started = time.time()
    try:
        yield
    finally:
        _SPANS.append(Span(_current.get(), name,
                           (time.perf_counter() - t0) * 1000.0,
                           started, attrs))


def spans_for(trace_id: str) -> list[Span]:
    return [s for s in _SPANS if s.trace_id == trace_id]


def recent_spans(n: int = 100) -> list[Span]:
    return list(_SPANS)[-n:]
