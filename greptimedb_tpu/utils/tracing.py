"""Trace context + spans (mirrors reference common/telemetry tracing:
`TracingContext::to_w3c` rides region requests across process hops,
query/src/dist_plan/merge_scan.rs:185-201, re-attached server-side at
servers/src/grpc/region_server.rs:74).

A request's trace id lives in a contextvar; spans record wall-time per
stage into a bounded ring buffer. EXPLAIN ANALYZE and the region wire
protocol both ride this: the frontend's trace id crosses Flight inside
the scan spec, so one query's spans line up across processes — and the
datanode's spans ride BACK on the Flight response (the RecordBatchMetrics
piggyback, merge_scan.rs:245-259 analog), tagged with the source node,
so a distributed EXPLAIN ANALYZE renders the whole per-process span tree
instead of only frontend-local time.

Logs join the same id: `TraceIdFilter` stamps every log record with the
current trace id (`trace_id=<id>`), so logs, metrics, and spans correlate
on one key.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

_current: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "gtpu_trace_id", default=None)

#: request-scoped span sink (see collect_spans): lets a server handler
#: capture exactly the spans ITS request produced, concurrency-safe,
#: without diffing the shared ring
_collector: contextvars.ContextVar[Optional[list]] = contextvars.ContextVar(
    "gtpu_span_collector", default=None)

_SPANS: deque = deque(maxlen=4096)


@dataclass
class Span:
    trace_id: Optional[str]
    name: str
    duration_ms: float
    started_at: float
    attrs: dict = field(default_factory=dict)
    #: source process for piggybacked remote spans (None = this process)
    node: Optional[str] = None


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def set_trace(trace_id: Optional[str] = None) -> str:
    """Install (or adopt) a trace id for the current context."""
    tid = trace_id or new_trace_id()
    _current.set(tid)
    return tid


def current_trace_id() -> Optional[str]:
    return _current.get()


def restore_trace(trace_id: Optional[str]) -> None:
    """Put back a previously-saved id verbatim (None clears — unlike
    set_trace, which would mint a fresh id)."""
    _current.set(trace_id)


def _record(span: Span) -> None:
    _SPANS.append(span)
    sink = _collector.get()
    if sink is not None:
        sink.append(span)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Record a timed span. Yields the (mutable) attrs dict so the body
    can attach result stats it only knows at the end (rows, bytes,
    pruning counts) — they land on the recorded span."""
    t0 = time.perf_counter()
    started = time.time()
    try:
        yield attrs
    finally:
        _record(Span(_current.get(), name,
                     (time.perf_counter() - t0) * 1000.0,
                     started, attrs))


@contextlib.contextmanager
def collect_spans():
    """Yield a list that receives every span recorded in this context
    (on top of the shared ring). Used by the Flight region service to
    piggyback exactly ITS request's spans on the response, and by the
    slow-query log to capture a statement's per-stage breakdown. Nesting
    installs the innermost sink only — the outer one resumes on exit."""
    sink: list[Span] = []
    token = _collector.set(sink)
    try:
        yield sink
    finally:
        _collector.reset(token)


def propagate(fn):
    """Carry the caller's trace id AND span sink across a thread-pool
    boundary (contextvars don't cross threads): the returned wrapper
    re-installs both around each invocation. The sink is appended from
    worker threads — list.append is atomic, so concurrent region RPCs
    interleave safely."""
    tid = _current.get()
    sink = _collector.get()

    def wrapper(*args, **kwargs):
        t1 = _current.set(tid)
        t2 = _collector.set(sink)
        try:
            return fn(*args, **kwargs)
        finally:
            _collector.reset(t2)
            _current.reset(t1)
    return wrapper


# ---- cross-process piggyback ------------------------------------------------


def spans_to_wire(spans: list[Span]) -> list[dict]:
    """JSON-serializable span records for the Flight response metadata
    (the RecordBatchMetrics payload analog)."""
    return [
        {"name": s.name, "duration_ms": round(s.duration_ms, 4),
         "started_at": s.started_at, "attrs": _wire_attrs(s.attrs)}
        for s in spans
    ]


def _wire_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        out[str(k)] = v if isinstance(v, (int, float, bool, str,
                                          type(None))) else str(v)
    return out


def merge_spans(wire: list[dict], node: Optional[str] = None,
                trace_id: Optional[str] = None) -> list[Span]:
    """Merge piggybacked remote spans into the local ring, tagged with
    their source node and attributed to the CURRENT trace (the remote
    process recorded them under the same propagated id; using the local
    id keeps them joined even if the peer was mid-rollout and dropped
    it). When the 'remote' service actually shares this process (the
    in-process wire-mode cluster), its handler already recorded the
    same spans into this ring — those piggybacked copies are skipped,
    not double-reported. Returns the merged spans."""
    tid = trace_id or _current.get()
    # snapshot first: concurrent region RPC workers append to the ring
    # while this merge runs, and iterating a deque under mutation
    # raises (list(deque) is a single C-level copy, safe under the GIL)
    existing = {(s.name, s.started_at, round(s.duration_ms, 4))
                for s in list(_SPANS) if s.trace_id == tid}
    merged = []
    for w in wire:
        try:
            s = Span(tid, str(w["name"]), float(w["duration_ms"]),
                     float(w.get("started_at", 0.0)),
                     dict(w.get("attrs") or {}), node=node)
        except (KeyError, TypeError, ValueError):
            continue  # a mangled record must not kill the query
        if (s.name, s.started_at, s.duration_ms) in existing:
            continue
        _record(s)
        merged.append(s)
    return merged


def spans_for(trace_id: str) -> list[Span]:
    # list() snapshot: see merge_spans — readers race ring appends
    return [s for s in list(_SPANS) if s.trace_id == trace_id]


def recent_spans(n: int = 100) -> list[Span]:
    return list(_SPANS)[-n:]


# ---- log correlation --------------------------------------------------------


class TraceIdFilter(logging.Filter):
    """Stamp every record with the context's trace id so log lines join
    metrics and spans on one key (reference: its tracing subscriber puts
    the trace id on every event)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.trace_id = _current.get() or "-"
        return True


#: format fragment including the trace id (used by install_trace_logging
#: and any service that builds its own handler)
TRACE_LOG_FORMAT = ("%(asctime)s %(levelname)s %(name)s "
                    "trace_id=%(trace_id)s %(message)s")


def install_trace_logging(level: Optional[int] = None) -> TraceIdFilter:
    """Attach a TraceIdFilter to the root logger's handlers (creating a
    basicConfig handler with TRACE_LOG_FORMAT if none exist yet) so every
    log record carries `trace_id=`. Idempotent."""
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(format=TRACE_LOG_FORMAT,
                            level=level if level is not None else logging.INFO)
    elif level is not None:
        root.setLevel(level)
    filt = None
    for h in root.handlers:
        existing = [f for f in h.filters if isinstance(f, TraceIdFilter)]
        if existing:
            filt = existing[0]
            continue
        filt = filt or TraceIdFilter()
        h.addFilter(filt)
    return filt or TraceIdFilter()
