"""Trace context + hierarchical spans (mirrors reference common/telemetry
tracing: `TracingContext::to_w3c` rides region requests across process
hops, query/src/dist_plan/merge_scan.rs:185-201, re-attached server-side
at servers/src/grpc/region_server.rs:74).

A request's trace id lives in a contextvar; spans carry a `span_id` and
a `parent_id` maintained by a contextvar parent stack inside `span()`,
so EXPLAIN ANALYZE / TQL ANALYZE and `/v1/traces/<id>` render true
nested trees with per-span self-time. The wire protocols speak W3C
trace context: HTTP accepts and emits a `traceparent` header,
MySQL/Postgres accept one in a leading SQL comment, and the Flight
piggyback ships parent linkage both ways — a datanode's `region_scan`
span re-parents under the frontend span that issued the RPC, so one
tree covers every process the query touched.

The span ring is indexed by trace id (bounded dict-of-lists evicted
with the ring) so `spans_for`/`merge_spans` on a busy frontend never
walk thousands of foreign spans. Completed spans also feed the OTLP
exporter (utils/otlp_trace.py) and the per-query resource ledger
(utils/ledger.py) when either is active. `GTPU_TRACING=off` turns span
recording (and the ledger) into a no-op for A/B overhead runs.

Logs join the same id: `TraceIdFilter` stamps every log record with the
current trace id (`trace_id=<id>`), so logs, metrics, and spans
correlate on one key — and histogram exemplars (utils/metrics.py) close
the metrics→trace direction.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from greptimedb_tpu.utils import flame as _flame
from greptimedb_tpu.utils import ledger, roofline

_current: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "gtpu_trace_id", default=None)

#: innermost open span's id — the parent of the next span opened in this
#: context (and the span id a traceparent/Flight request propagates)
_parent: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "gtpu_span_parent", default=None)

#: request-scoped span sink (see collect_spans): lets a server handler
#: capture exactly the spans ITS request produced, concurrency-safe,
#: without diffing the shared ring
_collector: contextvars.ContextVar[Optional[list]] = contextvars.ContextVar(
    "gtpu_span_collector", default=None)

_RING_CAP = 4096
_SPANS: deque = deque()
#: trace_id -> spans, evicted in lockstep with the ring: spans_for is
#: one dict lookup instead of an O(ring) scan over foreign spans
_BY_TRACE: dict[str, list] = {}
_ring_lock = threading.Lock()

#: OTLP exporter hook — otlp_trace.configure() installs the live
#: exporter here (attribute handoff, no import cycle); None = disabled
_exporter = None


def enabled() -> bool:
    """Span recording master switch (GTPU_TRACING). The single env
    parse lives in ledger.enabled() — tracing imports ledger, never the
    other way — so the two halves of the observability plane can never
    drift apart on what "off" means. Trace-ID minting/propagation stays
    on either way — log correlation is too cheap to gate."""
    return ledger.enabled()


@dataclass
class Span:
    trace_id: Optional[str]
    name: str
    duration_ms: float
    started_at: float
    attrs: dict = field(default_factory=dict)
    #: source process for piggybacked remote spans (None = this process)
    node: Optional[str] = None
    #: 16-hex span identity + parent linkage (None = a root span)
    span_id: str = ""
    parent_id: Optional[str] = None


def new_trace_id() -> str:
    # os.urandom(8).hex() is ~3x cheaper than uuid4 and ids are minted
    # per request AND per span — this is hot-path cost (the <3% bench
    # overhead budget)
    return os.urandom(8).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def set_trace(trace_id: Optional[str] = None) -> str:
    """Install (or adopt) a trace id for the current context."""
    tid = trace_id or new_trace_id()
    _current.set(tid)
    return tid


def current_trace_id() -> Optional[str]:
    return _current.get()


def current_span_id() -> Optional[str]:
    """The innermost open span's id (what an outgoing RPC propagates as
    the remote side's parent)."""
    return _parent.get()


def restore_trace(trace_id: Optional[str]) -> None:
    """Put back a previously-saved id verbatim (None clears — unlike
    set_trace, which would mint a fresh id)."""
    _current.set(trace_id)


def _record(span: Span) -> None:
    sink = _collector.get()
    if sink is not None:
        sink.append(span)
    with _ring_lock:
        _SPANS.append(span)
        if span.trace_id:
            _BY_TRACE.setdefault(span.trace_id, []).append(span)
        while len(_SPANS) > _RING_CAP:
            old = _SPANS.popleft()
            if old.trace_id:
                lst = _BY_TRACE.get(old.trace_id)
                if lst is not None:
                    try:
                        lst.remove(old)
                    except ValueError:
                        pass
                    if not lst:
                        del _BY_TRACE[old.trace_id]
    led = ledger.active()
    if led is not None:
        led.note_span(span)
    exp = _exporter
    # merged remote copies (node set) are NOT re-exported: the peer that
    # recorded them exports its own spans under the same ids — the
    # frontend re-exporting would duplicate every datanode span at the
    # collector (head sampling decides identically on both sides)
    if exp is not None and span.node is None:
        exp.on_span(span)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Record a timed span nested under the innermost open one. Yields
    the (mutable) attrs dict so the body can attach result stats it only
    knows at the end (rows, bytes, pruning counts) — they land on the
    recorded span."""
    # the continuous profiler's stage attribution rides span entry/exit
    # (a thread-id-keyed registry the sampler thread can read — the
    # contextvar stack is invisible cross-thread); guarded by flame's
    # fast flag so the cost with profiling off is one attribute read,
    # and kept alive even with GTPU_TRACING=off so flames stay staged
    # during tracing A/B runs
    prof = _flame._ENABLED
    if prof:
        _flame.push_stage(name)
    try:
        if not enabled():
            yield attrs
            return
        sid = new_span_id()
        parent = _parent.get()
        token = _parent.set(sid)
        t0 = time.perf_counter()
        started = time.time()
        try:
            yield attrs
        finally:
            _parent.reset(token)
            _record(Span(_current.get(), name,
                         (time.perf_counter() - t0) * 1000.0,
                         started, attrs, span_id=sid, parent_id=parent))
    finally:
        if prof:
            _flame.pop_stage()


@contextlib.contextmanager
def request_span(name: str, traceparent: Optional[str] = None, **attrs):
    """Wire-ingress scaffold: adopt the caller's W3C trace context (or
    mint a fresh trace), open the request's root span, and attach the
    resource ledger — then restore the connection thread's previous
    context so keep-alive reuse can't leak one request's trace into the
    next. Every protocol front door (HTTP, MySQL, Postgres, Flight SQL)
    enters through here; the span_coverage lint checker enforces it."""
    parsed = parse_traceparent(traceparent) if traceparent else None
    tid, remote_parent = parsed if parsed else (new_trace_id(), None)
    tok_tid = _current.set(tid)
    tok_par = _parent.set(remote_parent)
    try:
        with ledger.attach() as led:
            with span(name, **attrs) as a:
                try:
                    yield a
                finally:
                    # stamp INSIDE the span block: the span is recorded
                    # (and handed to the OTLP exporter) at __exit__, so
                    # a later mutation would race the export serializer
                    # and leave the exported copy ledger-less
                    if led is not None:
                        counts = ledger.derive(led.snapshot())
                        if counts:
                            a["ledger"] = ledger.format_dict(counts)
                            # roofline fold on the request root: same
                            # ledger dict, so the stamped numbers agree
                            # with the byte counts by construction
                            roofline.stamp(a, counts)
    finally:
        _parent.reset(tok_par)
        _current.reset(tok_tid)


@contextlib.contextmanager
def adopt_remote(trace_id: Optional[str], parent_id: Optional[str] = None):
    """Server side of a cross-process hop (region_server.rs:74 analog):
    adopt the caller's trace AND parent span so spans recorded inside
    re-parent under the frontend span that issued the RPC. Restores the
    worker thread's previous context on exit."""
    tok_tid = _current.set(trace_id or _current.get())
    tok_par = _parent.set(parent_id)
    try:
        yield
    finally:
        _parent.reset(tok_par)
        _current.reset(tok_tid)


@contextlib.contextmanager
def collect_spans():
    """Yield a list that receives every span recorded in this context
    (on top of the shared ring). Used by the Flight region service to
    piggyback exactly ITS request's spans on the response, and by the
    slow-query log to capture a statement's per-stage breakdown. Nesting
    installs the innermost sink only — the outer one resumes on exit."""
    sink: list[Span] = []
    token = _collector.set(sink)
    try:
        yield sink
    finally:
        _collector.reset(token)


def propagate(fn):
    """Carry the caller's trace id, open-span parent, span sink, AND
    resource ledger across a thread-pool boundary (contextvars don't
    cross threads): the returned wrapper re-installs all four around
    each invocation. The sink is appended from worker threads —
    list.append is atomic, so concurrent region RPCs interleave
    safely; the ledger takes its own lock."""
    tid = _current.get()
    parent = _parent.get()
    sink = _collector.get()
    led = ledger.active()

    def wrapper(*args, **kwargs):
        t1 = _current.set(tid)
        t2 = _collector.set(sink)
        t3 = _parent.set(parent)
        t4 = ledger._current.set(led)
        try:
            return fn(*args, **kwargs)
        finally:
            ledger._current.reset(t4)
            _parent.reset(t3)
            _collector.reset(t2)
            _current.reset(t1)
    return wrapper


# ---- W3C trace context ------------------------------------------------------

_TRACEPARENT_RE = re.compile(
    r"^(?P<ver>[0-9a-f]{2})-(?P<tid>[0-9a-f]{32})-"
    r"(?P<sid>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$")

#: leading-comment carrier for header-less wire protocols (MySQL/
#: Postgres text): /* traceparent='00-...-...-01' */ SELECT ...
_COMMENT_TP_RE = re.compile(
    r"/\*\s*traceparent\s*[=:]\s*'?"
    r"(?P<tp>[0-9a-f]{2}-[0-9a-f]{32}-[0-9a-f]{16}-[0-9a-f]{2})"
    r"'?\s*\*/", re.IGNORECASE)


def pad32(trace_id: str) -> str:
    """Our internal ids are 16 hex chars; W3C wants 32 — left-pad with
    zeros (an adopted 32-char id passes through unchanged)."""
    return trace_id.rjust(32, "0")


def parse_traceparent(header: str) -> Optional[tuple[str, Optional[str]]]:
    """(trace_id, parent_span_id) from a W3C `traceparent`, or None on
    anything malformed (a bad header must never fail the request). A
    zero-padded id we emitted earlier round-trips back to its internal
    16-char form."""
    m = _TRACEPARENT_RE.match((header or "").strip().lower())
    if not m or m.group("ver") == "ff":
        return None
    tid, sid = m.group("tid"), m.group("sid")
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    if tid.startswith("0" * 16):
        tid = tid[16:]
    return tid, sid


def to_traceparent(trace_id: Optional[str] = None,
                   span_id: Optional[str] = None) -> Optional[str]:
    """W3C header for the current (or given) context — what HTTP egress
    emits and what a client would hand the next hop."""
    tid = trace_id or _current.get()
    if not tid:
        return None
    sid = (span_id or _parent.get() or new_span_id()).rjust(16, "0")[-16:]
    return f"00-{pad32(tid)}-{sid}-01"


def traceparent_from_sql(sql: str) -> Optional[str]:
    """Extract a traceparent carried in a leading SQL comment (the
    MySQL/Postgres ingress carrier — those wires have no headers)."""
    m = _COMMENT_TP_RE.search(sql[:256])
    return m.group("tp") if m else None


# ---- cross-process piggyback ------------------------------------------------


def spans_to_wire(spans: list[Span]) -> list[dict]:
    """JSON-serializable span records for the Flight response metadata
    (the RecordBatchMetrics payload analog). span_id/parent_id ride
    along so the frontend's merged tree keeps the nesting."""
    return [
        {"name": s.name, "duration_ms": round(s.duration_ms, 4),
         "started_at": s.started_at, "attrs": _wire_attrs(s.attrs),
         "span_id": s.span_id, "parent_id": s.parent_id}
        for s in spans
    ]


def _wire_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        out[str(k)] = v if isinstance(v, (int, float, bool, str,
                                          type(None))) else str(v)
    return out


def merge_spans(wire: list[dict], node: Optional[str] = None,
                trace_id: Optional[str] = None) -> list[Span]:
    """Merge piggybacked remote spans into the local ring, tagged with
    their source node and attributed to the CURRENT trace (the remote
    process recorded them under the same propagated id; using the local
    id keeps them joined even if the peer was mid-rollout and dropped
    it). When the 'remote' service actually shares this process (the
    in-process wire-mode cluster), its handler already recorded the
    same spans into this ring — those piggybacked copies are skipped,
    not double-reported. Returns the merged spans."""
    tid = trace_id or _current.get()
    local = spans_for(tid) if tid else []
    existing_ids = {s.span_id for s in local if s.span_id}
    # legacy dedup key for peers that predate span ids
    existing = {(s.name, s.started_at, round(s.duration_ms, 4))
                for s in local}
    merged = []
    for w in wire:
        try:
            s = Span(tid, str(w["name"]), float(w["duration_ms"]),
                     float(w.get("started_at", 0.0)),
                     dict(w.get("attrs") or {}), node=node,
                     span_id=str(w.get("span_id") or ""),
                     parent_id=w.get("parent_id") or None)
        except (KeyError, TypeError, ValueError):
            continue  # a mangled record must not kill the query
        if s.span_id and s.span_id in existing_ids:
            continue
        if (s.name, s.started_at, s.duration_ms) in existing:
            continue
        _record(s)
        merged.append(s)
    return merged


def spans_for(trace_id: str) -> list[Span]:
    with _ring_lock:
        return list(_BY_TRACE.get(trace_id, ()))


def recent_spans(n: int = 100) -> list[Span]:
    with _ring_lock:
        return list(_SPANS)[-n:]


# ---- tree rendering ---------------------------------------------------------


def span_tree(spans: list[Span]) -> list[tuple[int, Span, float]]:
    """(depth, span, self_ms) rows in tree order. Children sort by start
    time under their parent; spans whose parent never landed in the ring
    (evicted, or a peer that predates linkage) surface as roots. Self
    time is the span's duration minus its direct children's — the
    'where did the 50 ms actually go' number."""
    by_id = {s.span_id: s for s in spans if s.span_id}
    children: dict[Optional[str], list[Span]] = {}
    roots: list[Span] = []
    for s in spans:
        if s.parent_id and s.parent_id in by_id:
            children.setdefault(s.parent_id, []).append(s)
        else:
            roots.append(s)
    roots.sort(key=lambda s: s.started_at)
    out: list[tuple[int, Span, float]] = []

    def walk(s: Span, depth: int, seen: set) -> None:
        if s.span_id and s.span_id in seen:
            return  # defensive: a mangled piggyback must not loop
        seen = seen | ({s.span_id} if s.span_id else set())
        kids = sorted(children.get(s.span_id, ()),
                      key=lambda c: c.started_at)
        # self = duration minus the WALL-CLOCK UNION of the children:
        # parallel children (scan-pool fan-out re-parents per-file
        # decode under one scan span) overlap, and a plain sum would
        # print negative self-time for exactly those spans
        covered = 0.0
        cur_lo = cur_hi = None
        for c in kids:
            lo, hi = c.started_at, c.started_at + c.duration_ms / 1000.0
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    covered += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        if cur_hi is not None:
            covered += cur_hi - cur_lo
        self_ms = max(s.duration_ms - covered * 1000.0, 0.0)
        out.append((depth, s, self_ms))
        for c in kids:
            walk(c, depth + 1, seen)

    for r in roots:
        walk(r, 0, set())
    return out


def render_tree(spans: list[Span], indent: str = "  ") -> list[str]:
    """Human lines for one trace's span tree (EXPLAIN ANALYZE,
    /v1/slow_queries rendering, tools/trace_dump.py). A `[node]` marker
    line precedes the first span of each remote process at its nesting
    depth, so cross-process hops stay visually attributable."""
    lines: list[str] = []
    rows = span_tree(spans)
    prev_node: Optional[str] = None
    for depth, s, self_ms in rows:
        pad = indent * (depth + 1)
        if s.node != prev_node and s.node is not None:
            lines.append(f"{pad}[{s.node}]")
        prev_node = s.node
        attrs = " ".join(f"{k}={v}" for k, v in s.attrs.items())
        has_kids = any(d == depth + 1 and p.parent_id == s.span_id
                       for d, p, _ in rows)
        self_part = f" (self {self_ms:.2f} ms)" if has_kids else ""
        lines.append(f"{pad}{s.name}: {s.duration_ms:.2f} ms{self_part}"
                     + (f" [{attrs}]" if attrs else ""))
    return lines


# ---- log correlation --------------------------------------------------------


class TraceIdFilter(logging.Filter):
    """Stamp every record with the context's trace id so log lines join
    metrics and spans on one key (reference: its tracing subscriber puts
    the trace id on every event)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.trace_id = _current.get() or "-"
        return True


#: format fragment including the trace id (used by install_trace_logging
#: and any service that builds its own handler)
TRACE_LOG_FORMAT = ("%(asctime)s %(levelname)s %(name)s "
                    "trace_id=%(trace_id)s %(message)s")


def install_trace_logging(level: Optional[int] = None) -> TraceIdFilter:
    """Attach a TraceIdFilter to the root logger's handlers (creating a
    basicConfig handler with TRACE_LOG_FORMAT if none exist yet) so every
    log record carries `trace_id=`. Idempotent."""
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(format=TRACE_LOG_FORMAT,
                            level=level if level is not None else logging.INFO)
    elif level is not None:
        root.setLevel(level)
    filt = None
    for h in root.handlers:
        existing = [f for f in h.filters if isinstance(f, TraceIdFilter)]
        if existing:
            filt = existing[0]
            continue
        filt = filt or TraceIdFilter()
        h.addFilter(filt)
    return filt or TraceIdFilter()
