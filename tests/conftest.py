"""Test harness: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's test strategy (SURVEY.md §4): every distributed
component runs single-process against in-memory fakes; multi-chip sharding
is validated on virtual devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon sitecustomize overrides JAX_PLATFORMS at interpreter start;
# force CPU again post-import (must happen before any device use)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (schedule + jitter "
        "seeded by GTPU_CHAOS_SEED; the seed is printed on failure)")
    config.addinivalue_line(
        "markers",
        "slow: excluded from tier-1 (-m 'not slow') — the full "
        "compound-fault scenario matrix; run via pytest -m slow or "
        "tools/run_scenarios.py")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if rep.failed and item.get_closest_marker("chaos") is not None:
        # any red chaos run must be replayable: surface the seed that
        # drove this run's fault schedule
        seed = os.environ.get("GTPU_CHAOS_SEED", "0")
        rep.sections.append(
            ("chaos seed",
             f"replay this failure with GTPU_CHAOS_SEED={seed}"))
