"""Grammar-based SQL fuzzing IR (reference tests-fuzz/src/{ir,generator,
translator,validator}: random DDL/DML generators over a typed IR, executed
against the real engine and validated against an independent oracle).

The IR is a `TableModel` the generator mutates in lockstep with the DDL it
emits; DML/queries generated from the model are always schema-valid, so
every statement must SUCCEED — an error is a finding, not noise. A pandas
shadow copy of all inserted rows is the differential oracle for SELECTs
(the validator role)."""

from __future__ import annotations

import random
import string
from dataclasses import dataclass, field

TAG_TYPES = ["STRING"]
FIELD_TYPES = ["DOUBLE", "FLOAT", "BIGINT", "INT", "SMALLINT", "BOOLEAN"]
TS_TYPES = ["TIMESTAMP(3)", "TIMESTAMP(0)", "TIMESTAMP(6)"]


@dataclass
class Col:
    name: str
    sql_type: str
    semantic: str  # tag | field | ts


@dataclass
class TableModel:
    name: str
    cols: list[Col] = field(default_factory=list)
    append_mode: bool = False
    next_ts: int = 1_600_000_000_000

    @property
    def tags(self):
        return [c for c in self.cols if c.semantic == "tag"]

    @property
    def fields(self):
        return [c for c in self.cols if c.semantic == "field"]

    @property
    def ts_col(self):
        return next(c for c in self.cols if c.semantic == "ts")


class Generator:
    """Deterministic per-seed statement generator."""

    def __init__(self, seed: int):
        self.rng = random.Random(seed)
        self.n_names = 0

    def name(self, prefix: str) -> str:
        self.n_names += 1
        suffix = "".join(self.rng.choices(string.ascii_lowercase, k=4))
        return f"{prefix}_{self.n_names}_{suffix}"

    # ---- DDL ---------------------------------------------------------------

    def gen_create_table(self) -> tuple[TableModel, str]:
        rng = self.rng
        model = TableModel(self.name("t"), append_mode=rng.random() < 0.3)
        n_tags = rng.randint(0, 3)
        n_fields = rng.randint(1, 6)
        for _ in range(n_tags):
            model.cols.append(Col(self.name("tag"), rng.choice(TAG_TYPES),
                                  "tag"))
        ts_type = rng.choice(TS_TYPES)
        model.cols.append(Col(self.name("ts"), ts_type, "ts"))
        for _ in range(n_fields):
            model.cols.append(Col(self.name("f"), rng.choice(FIELD_TYPES),
                                  "field"))
        rng.shuffle(model.cols)
        defs = []
        for c in model.cols:
            if c.semantic == "ts":
                defs.append(f"{c.name} {c.sql_type} NOT NULL")
            else:
                defs.append(f"{c.name} {c.sql_type}")
        defs.append(f"TIME INDEX ({model.ts_col.name})")
        if model.tags:
            defs.append(
                "PRIMARY KEY (" + ", ".join(c.name for c in model.tags) + ")")
        with_clause = " WITH (append_mode = 'true')" if model.append_mode \
            else ""
        sql = f"CREATE TABLE {model.name} ({', '.join(defs)}){with_clause}"
        return model, sql

    def gen_add_column(self, model: TableModel) -> str:
        col = Col(self.name("f"), self.rng.choice(FIELD_TYPES), "field")
        model.cols.append(col)
        return f"ALTER TABLE {model.name} ADD COLUMN {col.name} {col.sql_type}"

    def gen_rename(self, model: TableModel) -> str:
        new = self.name("t")
        sql = f"ALTER TABLE {model.name} RENAME TO {new}"
        model.name = new
        return sql

    # ---- DML ---------------------------------------------------------------

    def _value(self, c: Col, model: TableModel):
        rng = self.rng
        if c.semantic == "ts":
            # bare integer literals are interpreted in the column's own
            # unit (utils/time.py coerce_ts_literal), so a monotonically
            # increasing int is valid for every TIMESTAMP precision
            model.next_ts += rng.randint(1, 10_000)
            return model.next_ts
        if c.semantic == "tag":
            if rng.random() < 0.1:
                return None
            return f"v{rng.randint(0, 5)}"
        if rng.random() < 0.1:
            return None
        if c.sql_type in ("DOUBLE", "FLOAT"):
            v = round(rng.uniform(-1e6, 1e6), 3)
            return v
        if c.sql_type == "BOOLEAN":
            return rng.random() < 0.5
        if c.sql_type == "SMALLINT":
            return rng.randint(-32768, 32767)
        if c.sql_type == "INT":
            return rng.randint(-2**31, 2**31 - 1)
        return rng.randint(-2**40, 2**40)

    def gen_insert(self, model: TableModel, max_rows: int = 20) \
            -> tuple[str, list[dict]]:
        rng = self.rng
        n = rng.randint(1, max_rows)
        rows = []
        for _ in range(n):
            rows.append({c.name: self._value(c, model) for c in model.cols})
        cols = [c.name for c in model.cols]

        def lit(v):
            if v is None:
                return "NULL"
            if isinstance(v, bool):
                return "TRUE" if v else "FALSE"
            if isinstance(v, str):
                return "'" + v.replace("'", "''") + "'"
            return repr(v)

        values = ", ".join(
            "(" + ", ".join(lit(r[c]) for c in cols) + ")" for r in rows)
        sql = f"INSERT INTO {model.name} ({', '.join(cols)}) VALUES {values}"
        return sql, rows

    # ---- queries -----------------------------------------------------------

    def gen_count_query(self, model: TableModel) -> str:
        return f"SELECT count(*) FROM {model.name}"

    def gen_agg_query(self, model: TableModel):
        """Aggregate over one numeric field, optionally grouped by one tag.
        Returns (sql, field, tag|None, agg)."""
        rng = self.rng
        numeric = [c for c in model.fields
                   if c.sql_type in ("DOUBLE", "FLOAT", "BIGINT", "INT",
                                     "SMALLINT")]
        if not numeric:
            return None
        f = rng.choice(numeric)
        agg = rng.choice(["sum", "min", "max", "count", "avg"])
        tag = rng.choice(model.tags) if model.tags and rng.random() < 0.7 \
            else None
        if tag is not None:
            sql = (f"SELECT {tag.name}, {agg}({f.name}) FROM {model.name} "
                   f"GROUP BY {tag.name} ORDER BY {tag.name}")
        else:
            sql = f"SELECT {agg}({f.name}) FROM {model.name}"
        return sql, f, tag, agg

    def gen_filter_query(self, model: TableModel):
        """Point lookup on a tag (exercises index pruning). Returns
        (sql, tag, value)."""
        if not model.tags:
            return None
        tag = self.rng.choice(model.tags)
        v = f"v{self.rng.randint(0, 5)}"
        sql = (f"SELECT count(*) FROM {model.name} "
               f"WHERE {tag.name} = '{v}'")
        return sql, tag, v
