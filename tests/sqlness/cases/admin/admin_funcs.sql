-- ADMIN maintenance functions: flush + compact survive re-query
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1.0, 1000), ('b', 2.0, 2000);

ADMIN flush_table('m');

INSERT INTO m VALUES ('c', 3.0, 3000);

ADMIN flush_table('m');

ADMIN compact_table('m');

SELECT host, v FROM m ORDER BY host;

SELECT count(*) FROM m;
