-- ADMIN maintenance-plane job flow: every maintenance ADMIN returns the
-- submitted job id; queries stay correct while jobs run in background
CREATE TABLE mj (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO mj VALUES ('a', 1.0, 60000), ('b', 2.0, 61000), ('a', 3.0, 121000);

ADMIN flush_table('mj');

ADMIN rollup_table('mj', '1m');

ADMIN expire_table('mj', '100000d');

ADMIN compact_table('mj');

SELECT host, count(*) FROM mj GROUP BY host ORDER BY host;

SELECT count(*) FROM mj;
