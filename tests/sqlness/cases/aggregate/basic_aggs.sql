-- Aggregates without GROUP BY
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1.0, 1000), ('b', 2.0, 2000), ('c', 3.0, 3000), ('d', 4.0, 4000);

SELECT count(*), sum(v), avg(v), min(v), max(v) FROM m;

SELECT stddev(v), variance(v) FROM m;

SELECT sum(v) FROM m WHERE v > 2.0;

SELECT count(*) FROM m WHERE v > 100.0;

SELECT median(v), percentile(v, 50) FROM m;
