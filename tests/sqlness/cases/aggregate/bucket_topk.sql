-- bucket-top-k narrowing: ORDER BY <time bucket> LIMIT k scans only the
-- newest/oldest k buckets (physical.py::_bucket_topk_ranges); results
-- must be indistinguishable from the full aggregate
CREATE TABLE bt (host STRING, v DOUBLE, ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host));

INSERT INTO bt VALUES ('a', 1.0, 0), ('a', 2.0, 30000), ('a', 3.0, 60000), ('b', 4.0, 90000), ('b', 5.0, 150000), ('a', 6.0, 210000), ('b', 7.0, 211000), ('a', 8.0, 330000);

-- newest 3 minute-buckets (bucket 5 = 330000, 3 = 210000/211000, 2 = 150000)
SELECT date_bin(INTERVAL '1 minute', ts) AS minute, max(v), count(*) FROM bt GROUP BY minute ORDER BY minute DESC LIMIT 3;

-- oldest 2 buckets
SELECT date_bin(INTERVAL '1 minute', ts) AS minute, min(v) FROM bt GROUP BY minute ORDER BY minute ASC LIMIT 2;

-- with an upper ts bound and an offset
SELECT date_bin(INTERVAL '1 minute', ts) AS minute, avg(v) FROM bt WHERE ts < 300000 GROUP BY minute ORDER BY minute DESC LIMIT 2 OFFSET 1;

-- limit beyond the bucket count returns everything
SELECT date_bin(INTERVAL '2 minutes', ts) AS b, count(*) FROM bt GROUP BY b ORDER BY b DESC LIMIT 50;

DROP TABLE bt;
