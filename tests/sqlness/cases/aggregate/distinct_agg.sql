-- COUNT(DISTINCT x) and grouped variants
CREATE TABLE da (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO da VALUES ('a', 1.0, 1), ('a', 1.0, 2), ('a', 2.0, 3), ('b', 1.0, 1);

SELECT count(DISTINCT v) AS dv FROM da;

SELECT host, count(DISTINCT v) AS dv FROM da GROUP BY host ORDER BY host;

DROP TABLE da;
