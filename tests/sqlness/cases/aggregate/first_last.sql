-- first/last by time order, incl. last_value(x ORDER BY ts)
CREATE TABLE fl (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO fl VALUES ('a', 1.0, 100), ('a', 9.0, 300), ('a', 5.0, 200), ('b', 7.0, 100);

SELECT host, first(v) AS f, last(v) AS l FROM fl GROUP BY host ORDER BY host;

SELECT host, last_value(v ORDER BY ts) AS lv FROM fl GROUP BY host ORDER BY host;

DROP TABLE fl;
