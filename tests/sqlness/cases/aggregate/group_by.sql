-- GROUP BY: multi-key, HAVING, group by expression and position
CREATE TABLE m (host STRING, idc STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, idc));

INSERT INTO m VALUES
    ('a', 'east', 1.0, 1000), ('a', 'west', 2.0, 2000),
    ('b', 'east', 3.0, 3000), ('b', 'west', 4.0, 4000),
    ('a', 'east', 5.0, 5000);

SELECT host, sum(v) FROM m GROUP BY host ORDER BY host;

SELECT host, idc, avg(v) FROM m GROUP BY host, idc ORDER BY host, idc;

SELECT idc, count(*) AS n FROM m GROUP BY idc HAVING n > 2 ORDER BY idc;

SELECT host, max(v) - min(v) AS spread FROM m GROUP BY host ORDER BY host;

SELECT date_bin('2 seconds', ts) AS bucket, sum(v) FROM m GROUP BY bucket ORDER BY bucket;
