-- GROUP BY expressions and positional-style aliases
CREATE TABLE ge (host STRING, v DOUBLE, ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host));

INSERT INTO ge VALUES ('web-1', 1.0, 0), ('web-2', 2.0, 0), ('db-1', 4.0, 0);

SELECT CASE WHEN host LIKE 'web%' THEN 'web' ELSE 'db' END AS tier, sum(v) AS s FROM ge GROUP BY tier ORDER BY tier;

SELECT date_bin(INTERVAL '1 hour', ts) AS h, count(*) AS n FROM ge GROUP BY h;

DROP TABLE ge;
