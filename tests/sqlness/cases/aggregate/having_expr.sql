-- HAVING over aggregate expressions
CREATE TABLE hv (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO hv VALUES ('a', 1.0, 1), ('a', 2.0, 2), ('b', 10.0, 1), ('c', 3.0, 1);

SELECT host, sum(v) AS s FROM hv GROUP BY host HAVING sum(v) > 2.5 ORDER BY host;

SELECT host, count(*) AS n FROM hv GROUP BY host HAVING n = 1 ORDER BY host;

DROP TABLE hv;
