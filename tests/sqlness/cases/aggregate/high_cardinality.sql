-- Group-by where the dense key product is large but observed groups few
-- (exercises the sparse sort-compact path)
CREATE TABLE wide (t1 STRING, t2 STRING, t3 STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(t1, t2, t3));

INSERT INTO wide VALUES
    ('a1', 'b1', 'c1', 1.0, 1000),
    ('a2', 'b2', 'c2', 2.0, 2000),
    ('a3', 'b3', 'c3', 3.0, 3000),
    ('a1', 'b1', 'c1', 4.0, 4000);

SELECT t1, t2, t3, sum(v) FROM wide GROUP BY t1, t2, t3 ORDER BY t1;

SELECT count(*) FROM wide;

SELECT t1, count(*) FROM wide GROUP BY t1 ORDER BY t1;
