-- TSBS lastpoint shape (last_value ORDER BY) and stddev/variance
CREATE TABLE cpu (host STRING, u DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO cpu VALUES ('a', 1.0, 1000), ('a', 3.0, 3000), ('b', 10.0, 1000), ('b', 20.0, 2000);

SELECT host, last_value(u ORDER BY ts) FROM cpu GROUP BY host ORDER BY host;

SELECT host, last_value(u ORDER BY ts DESC) FROM cpu GROUP BY host ORDER BY host;

SELECT host, first_value(u) FROM cpu GROUP BY host ORDER BY host;

SELECT host, variance(u), stddev(u) FROM cpu GROUP BY host ORDER BY host;
