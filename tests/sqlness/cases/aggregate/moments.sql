-- stddev / variance, incl. single-sample NULL semantics
CREATE TABLE mo (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO mo VALUES ('a', 2.0, 1), ('a', 4.0, 2), ('a', 6.0, 3), ('b', 9.0, 1);

SELECT host, variance(v) AS var, stddev(v) AS sd FROM mo GROUP BY host ORDER BY host;

DROP TABLE mo;
