-- Order-statistic aggregates: median, percentile, argmax/argmin
CREATE TABLE m (host STRING, v DOUBLE, w DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES
    ('a', 1.0, 10.0, 1000), ('a', 2.0, 20.0, 2000), ('a', 3.0, 5.0, 3000),
    ('b', 10.0, 1.0, 1000), ('b', 30.0, 2.0, 2000);

SELECT median(v) FROM m;

SELECT host, median(v) FROM m GROUP BY host ORDER BY host;

SELECT percentile(v, 90) FROM m;

SELECT host, argmax(w, v) FROM m GROUP BY host ORDER BY host;

SELECT argmin(w, v) FROM m;
