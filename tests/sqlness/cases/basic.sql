-- Basic end-to-end: create, insert, scan, aggregate
-- (reference tests/cases/standalone/common/basic.sql shape)
CREATE TABLE system_metrics (
    host STRING,
    idc STRING,
    cpu_util DOUBLE,
    memory_util DOUBLE,
    disk_util DOUBLE,
    ts TIMESTAMP,
    PRIMARY KEY(host, idc),
    TIME INDEX(ts)
);

INSERT INTO system_metrics
VALUES
    ('host1', 'idc_a', 11.8, 10.3, 10.3, 1667446797450),
    ('host2', 'idc_a', 80.0, 70.3, 90.0, 1667446797450),
    ('host1', 'idc_b', 50.0, 66.7, 40.6, 1667446797450);

SELECT * FROM system_metrics ORDER BY host, idc;

SELECT count(*) FROM system_metrics;

SELECT avg(cpu_util) FROM system_metrics;

SELECT idc, avg(memory_util) FROM system_metrics GROUP BY idc ORDER BY idc;

DROP TABLE system_metrics;
