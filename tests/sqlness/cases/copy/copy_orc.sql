-- COPY TO / FROM round-trip in ORC (reference file_format.rs:57-61)
CREATE TABLE src_orc (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO src_orc VALUES ('a', 1.5, 1000), ('b', 2.5, 2000);

COPY src_orc TO '/tmp/sqlness_copy_src.orc';

CREATE TABLE dst_orc (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

COPY dst_orc FROM '/tmp/sqlness_copy_src.orc' WITH (format = 'orc');

SELECT host, v FROM dst_orc ORDER BY host;
