-- COPY TO / FROM round-trip through server-side files
CREATE TABLE src (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO src VALUES ('a', 1.5, 1000), ('b', 2.5, 2000);

COPY src TO '/tmp/sqlness_copy_src.parquet';

CREATE TABLE dst (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

COPY dst FROM '/tmp/sqlness_copy_src.parquet';

SELECT host, v FROM dst ORDER BY host;
