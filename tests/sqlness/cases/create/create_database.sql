-- Databases: create, show, duplicate error, use via qualified names
CREATE DATABASE metrics;

CREATE DATABASE metrics;

SHOW DATABASES;

CREATE TABLE metrics.cpu (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO metrics.cpu VALUES ('a', 1.0, 1000);

SELECT * FROM metrics.cpu;

DROP TABLE metrics.cpu;
