-- Views: create, select, replace, show, drop
CREATE TABLE src (h STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(h));

INSERT INTO src VALUES ('a', 1.0, 1000), ('b', 9.0, 2000);

CREATE VIEW big AS SELECT h, v FROM src WHERE v > 5;

SELECT * FROM big;

SELECT count(*) FROM big;

SHOW VIEWS;

SHOW CREATE VIEW big;

CREATE OR REPLACE VIEW big AS SELECT h FROM src;

SELECT count(*) FROM big;

DROP VIEW big;

SHOW VIEWS;
