-- WITH: common table expressions (reference: DataFusion CTEs)
CREATE TABLE cpu (host STRING, usage_user DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO cpu VALUES ('a', 10.0, 1000), ('a', 20.0, 2000), ('b', 5.0, 1000), ('b', 50.0, 2000), ('c', 7.0, 1000);

WITH hot AS (SELECT host, usage_user FROM cpu WHERE usage_user > 9)
SELECT host, count(*) AS c FROM hot GROUP BY host ORDER BY host;

-- a CTE can rename columns and reference an earlier CTE
WITH t(h, u) AS (SELECT host, usage_user FROM cpu WHERE ts = 1000),
     m AS (SELECT max(u) AS mu FROM t)
SELECT mu FROM m;

-- CTEs shadow real tables
WITH cpu AS (SELECT 1 AS one) SELECT * FROM cpu;

-- CTE joined against a base table
WITH agg AS (SELECT host, max(usage_user) AS mx FROM cpu GROUP BY host)
SELECT agg.host, agg.mx FROM agg JOIN cpu ON agg.host = cpu.host AND agg.mx = cpu.usage_user ORDER BY agg.host;

DROP TABLE cpu;
