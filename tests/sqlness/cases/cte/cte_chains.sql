-- multi-stage CTE pipelines
CREATE TABLE cc (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO cc VALUES ('a', 1.0, 1), ('a', 9.0, 2), ('b', 5.0, 1), ('c', 2.0, 1);

WITH sums AS (SELECT host, sum(v) AS s FROM cc GROUP BY host),
     ranked AS (SELECT host, s, rank() OVER (ORDER BY s DESC) AS r FROM sums)
SELECT host, s, r FROM ranked WHERE r <= 2 ORDER BY r, host;

WITH a AS (SELECT 1 AS x), b AS (SELECT x + 1 AS y FROM a)
SELECT a.x, b.y FROM a CROSS JOIN b;

DROP TABLE cc;
