-- DELETE rows; deletes tombstone under LWW semantics
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1.0, 1000), ('b', 2.0, 2000), ('c', 3.0, 3000);

DELETE FROM m WHERE host = 'b';

SELECT host FROM m ORDER BY host;

DELETE FROM m WHERE v > 2.5;

SELECT host FROM m ORDER BY host;

-- re-insert after delete resurrects the key with the new value
INSERT INTO m VALUES ('b', 20.0, 2000);

SELECT host, v FROM m ORDER BY host;
