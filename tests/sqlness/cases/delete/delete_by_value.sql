-- DELETE by field predicate resolves key rows first
CREATE TABLE dv (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO dv VALUES ('a', 1.0, 1), ('b', 99.0, 1), ('c', 2.0, 1);

DELETE FROM dv WHERE v > 50;

SELECT host, v FROM dv ORDER BY host;

DROP TABLE dv;
