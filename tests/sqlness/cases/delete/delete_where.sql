-- DELETE with predicates; tombstones hold across flush
CREATE TABLE dw (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO dw VALUES ('a', 1.0, 1), ('a', 2.0, 2), ('b', 3.0, 1);

DELETE FROM dw WHERE host = 'a' AND ts = 1;

SELECT host, v FROM dw ORDER BY host, ts;

ADMIN flush_table('dw');

SELECT host, v FROM dw ORDER BY host, ts;

DELETE FROM dw;

SELECT count(*) AS n FROM dw;

DROP TABLE dw;
