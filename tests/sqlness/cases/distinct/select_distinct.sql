-- SELECT DISTINCT over rows and expressions
CREATE TABLE sd (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO sd VALUES ('a', 1.0, 1), ('a', 1.0, 2), ('b', 1.0, 1), ('b', 2.0, 2);

SELECT DISTINCT host FROM sd ORDER BY host;

SELECT DISTINCT host, v FROM sd ORDER BY host, v;

SELECT DISTINCT v * 10 AS x FROM sd ORDER BY x;

DROP TABLE sd;
