-- Continuous aggregation flows: the sink table is derived from the
-- flow query's column names on first tick
CREATE TABLE events (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

CREATE FLOW rollup SINK TO sink AS SELECT host, sum(v) AS total, date_bin('1 second', ts) AS bucket FROM events GROUP BY host, bucket;

SHOW FLOWS;

INSERT INTO events VALUES ('a', 1.0, 100), ('a', 2.0, 200), ('b', 5.0, 100);

ADMIN flush_flow('rollup');

SELECT host, total FROM sink ORDER BY host;

-- late data dirties the bucket; next flush recomputes it
INSERT INTO events VALUES ('a', 10.0, 300);

ADMIN flush_flow('rollup');

SELECT host, total FROM sink ORDER BY host;

DROP FLOW rollup;

SHOW FLOWS;
