-- NULL-handling scalars
CREATE TABLE cn (a DOUBLE, b DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO cn VALUES (NULL, 2.0, 1), (1.0, NULL, 2), (3.0, 4.0, 3);

SELECT coalesce(a, b) AS c FROM cn ORDER BY ts;

SELECT coalesce(a, b, 0.0) AS c FROM cn ORDER BY ts;

DROP TABLE cn;
