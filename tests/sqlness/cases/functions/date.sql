-- Date/time scalar functions over a time-series table
CREATE TABLE e (k STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(k));

INSERT INTO e VALUES
    ('x', 1.0, 1667446797450),
    ('x', 2.0, 1667450397450),
    ('y', 3.0, 1667446797450);

SELECT k, date_bin('1 hour', ts) AS hour_bucket, sum(v) FROM e GROUP BY k, hour_bucket ORDER BY k, hour_bucket;

SELECT k, date_trunc('hour', ts) AS h, count(*) FROM e GROUP BY k, h ORDER BY k, h;

SELECT k, to_unixtime(ts) AS unix_s FROM e WHERE k = 'y' ORDER BY unix_s;

SELECT time_bucket('30 minutes', ts) AS b, avg(v) FROM e GROUP BY b ORDER BY b;
