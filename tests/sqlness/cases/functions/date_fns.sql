-- date/time scalar functions
CREATE TABLE df (v DOUBLE, ts TIMESTAMP(3) TIME INDEX);

INSERT INTO df VALUES (1.0, '2024-03-15 13:45:30');

SELECT date_trunc('hour', ts) AS h FROM df;

SELECT extract(year FROM ts) AS y, extract(month FROM ts) AS m FROM df;

DROP TABLE df;
