-- Math scalar functions
SELECT abs(-3.5), ceil(1.2), floor(1.8), round(2.5);

SELECT sqrt(16.0), pow(2, 10), mod(10, 3);

SELECT exp(0.0), ln(1.0), log10(100.0), log2(8.0);

SELECT sin(0.0), cos(0.0), atan2(0.0, 1.0);

SELECT greatest(1, 5, 3), least(1, 5, 3), clamp(10, 0, 5);

SELECT signum(-2.5), trunc(3.9), degrees(0.0), radians(0.0);
