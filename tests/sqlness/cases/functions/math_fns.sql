-- math scalar functions
SELECT abs(-3.5) AS a, ceil(1.2) AS c, floor(1.8) AS f, round(2.567, 2) AS r;

SELECT sqrt(16.0) AS sq, power(2, 10) AS p, ln(1.0) AS l;

SELECT greatest(1, 5, 3) AS g, least(1, 5, 3) AS ls;

SELECT CASE WHEN 1 < 2 THEN 'yes' ELSE 'no' END AS c;
