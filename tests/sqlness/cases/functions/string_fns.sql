-- string scalar functions over a table
CREATE TABLE sf (host STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO sf VALUES ('Web-01', 1), ('db-02', 2);

SELECT lower(host) AS lo, upper(host) AS up FROM sf ORDER BY host;

SELECT length(host) AS n FROM sf ORDER BY host;

SELECT concat(host, ':9090') AS addr FROM sf ORDER BY host;

DROP TABLE sf;
