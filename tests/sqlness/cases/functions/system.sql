-- System/session functions
SELECT database();

SELECT current_schema();

SELECT version();

SELECT timezone();
