-- key_column_usage / table_constraints / character_sets / collations / build_info
CREATE TABLE kt (host STRING, az STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, az));

SELECT constraint_name, column_name, ordinal_position FROM information_schema.key_column_usage WHERE table_name = 'kt' ORDER BY constraint_name, ordinal_position;

SELECT constraint_name, constraint_type FROM information_schema.table_constraints WHERE table_name = 'kt' ORDER BY constraint_name;

SELECT * FROM information_schema.character_sets;

SELECT collation_name, character_set_name, is_default FROM information_schema.collations;

SELECT pkg_version FROM information_schema.build_info;

DROP TABLE kt;
