-- information_schema virtual tables
CREATE TABLE cpu (host STRING, usage DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

SELECT table_name, table_type FROM information_schema.tables WHERE table_schema = 'public' ORDER BY table_name;

SELECT column_name, data_type, semantic_type FROM information_schema.columns WHERE table_name = 'cpu' ORDER BY column_name;

SELECT schema_name FROM information_schema.schemata ORDER BY schema_name;

SELECT engine, support FROM information_schema.engines ORDER BY engine;
