-- malformed inserts error cleanly
CREATE TABLE ae (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO ae VALUES (1.0);

INSERT INTO ae (v, ts, nope) VALUES (1.0, 1, 2);

INSERT INTO ae VALUES (1.0, 1);

SELECT count(*) AS n FROM ae;

DROP TABLE ae;
