-- column defaults and NULL fills on partial inserts
CREATE TABLE dn (host STRING, v DOUBLE DEFAULT 7.5, note STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO dn (host, ts) VALUES ('a', 1);

INSERT INTO dn (host, v, ts) VALUES ('b', 2.5, 2);

SELECT host, v, note FROM dn ORDER BY host;

DROP TABLE dn;
