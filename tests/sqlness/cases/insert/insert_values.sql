-- INSERT forms: multi-row, explicit column list, NULL values
CREATE TABLE cpu (host STRING, usage DOUBLE, idle DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO cpu VALUES ('a', 10.5, 89.5, 1000), ('b', 20.0, 80.0, 2000);

INSERT INTO cpu (host, usage, ts) VALUES ('c', 30.0, 3000);

INSERT INTO cpu (host, usage, idle, ts) VALUES ('d', NULL, NULL, 4000);

SELECT host, usage, idle FROM cpu ORDER BY host;

SELECT count(*), count(usage), count(idle) FROM cpu;
