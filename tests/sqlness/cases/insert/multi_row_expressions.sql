-- expression values and negative numbers in VALUES
CREATE TABLE mre (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO mre VALUES (1 + 2.5, 1), (-4.5, 2), (2 * 3, 3);

SELECT v FROM mre ORDER BY ts;

DROP TABLE mre;
