-- Last-write-wins upsert on (primary key, timestamp)
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1.0, 1000);

INSERT INTO m VALUES ('a', 99.0, 1000);

SELECT host, v FROM m;

INSERT INTO m VALUES ('a', 2.0, 2000);

SELECT host, v, ts FROM m ORDER BY ts;

-- flush between writes must not change LWW resolution
ADMIN flush_table('m');

INSERT INTO m VALUES ('a', 123.0, 1000);

SELECT host, v FROM m WHERE ts = 1000;
