-- INNER/LEFT joins with aliases, bare-column resolution, and aggregates
CREATE TABLE metrics (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

CREATE TABLE hosts (host STRING, dc STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO metrics VALUES ('a', 1.0, 1000), ('a', 3.0, 2000), ('b', 10.0, 1000), ('c', 99.0, 1000);

INSERT INTO hosts VALUES ('a', 'east', 0), ('b', 'west', 0);

SELECT metrics.host, metrics.v, hosts.dc FROM metrics JOIN hosts ON metrics.host = hosts.host ORDER BY metrics.v;

SELECT m.host, h.dc FROM metrics m LEFT JOIN hosts h ON m.host = h.host ORDER BY m.host, m.ts;

SELECT dc, sum(v), count(*) FROM metrics JOIN hosts ON metrics.host = hosts.host GROUP BY dc ORDER BY dc;

SELECT v, dc FROM metrics JOIN hosts ON metrics.host = hosts.host WHERE v > 5;
