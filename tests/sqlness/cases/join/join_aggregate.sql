-- GROUP BY over joined relations
CREATE TABLE jm (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

CREATE TABLE jd (host STRING, dc STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO jm VALUES ('a', 1.0, 1), ('a', 3.0, 2), ('b', 10.0, 1), ('c', 5.0, 1);

INSERT INTO jd VALUES ('a', 'east', 0), ('b', 'west', 0), ('c', 'east', 0);

SELECT jd.dc, sum(jm.v) AS s FROM jm JOIN jd ON jm.host = jd.host GROUP BY jd.dc ORDER BY jd.dc;

SELECT jd.dc, count(*) AS n FROM jm LEFT JOIN jd ON jm.host = jd.host GROUP BY jd.dc ORDER BY jd.dc;

DROP TABLE jm;

DROP TABLE jd;
