-- RIGHT / FULL / CROSS joins
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

CREATE TABLE d (host STRING, dc STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1.0, 1000), ('c', 3.0, 1000);

INSERT INTO d VALUES ('a', 'east', 0), ('z', 'north', 0);

SELECT m.host, d.host, d.dc FROM m RIGHT JOIN d ON m.host = d.host ORDER BY d.host;

SELECT m.host, d.host FROM m FULL OUTER JOIN d ON m.host = d.host ORDER BY m.v;

SELECT count(*) AS n FROM m CROSS JOIN d;

-- anti-join: rows on the right with no left match
SELECT d.host FROM m RIGHT JOIN d ON m.host = d.host WHERE m.host IS NULL;

DROP TABLE m;

DROP TABLE d;
