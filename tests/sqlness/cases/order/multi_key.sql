-- multi-key ordering with mixed directions
CREATE TABLE mk (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO mk VALUES ('a', 2.0, 1), ('a', 1.0, 2), ('b', 2.0, 1), ('b', 1.0, 2);

SELECT host, v FROM mk ORDER BY host ASC, v DESC;

SELECT host, v FROM mk ORDER BY v DESC, host DESC;

DROP TABLE mk;
