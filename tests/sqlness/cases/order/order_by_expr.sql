-- ORDER BY an expression and an unprojected column
CREATE TABLE oe (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO oe VALUES ('a', 3.0, 1), ('b', 1.0, 2), ('c', 2.0, 3);

SELECT host FROM oe ORDER BY v * -1;

SELECT host, v * 2 AS d FROM oe ORDER BY d;

DROP TABLE oe;
