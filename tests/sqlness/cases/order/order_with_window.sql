-- ORDER BY a window expression
CREATE TABLE ow (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO ow VALUES ('a', 3.0, 1), ('b', 1.0, 1), ('c', 2.0, 1);

SELECT host, rank() OVER (ORDER BY v DESC) AS r FROM ow ORDER BY r;

DROP TABLE ow;
