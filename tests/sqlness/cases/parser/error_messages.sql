-- parse errors surface cleanly, not as crashes
SELEKT 1;

SELECT FROM nothing;

SELECT 1 +;

CREATE TABLE no_time_index (v DOUBLE);

SELECT * FROM does_not_exist;
