-- Error surfaces: each statement's error text is part of the contract
SELECT nocol FROM nosuchtable;

SELEKT 1;

CREATE TABLE bad (v DOUBLE);

CREATE TABLE t (k STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(k));

SELECT unknown_col FROM t;

SELECT k, avg(ts) FROM t;

INSERT INTO t VALUES ('only-one-value');

SELECT percentile(ts) FROM t;

DROP TABLE t;
