-- ALIGN ... BY grouping and BY () across-series form
CREATE TABLE ab (host STRING, v DOUBLE, ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host));

INSERT INTO ab VALUES ('a', 2.0, 0), ('b', 4.0, 0), ('a', 6.0, 10000), ('b', 8.0, 10000);

SELECT ts, host, max(v) RANGE '10s' FROM ab ALIGN '10s' BY (host) ORDER BY ts, host;

SELECT ts, sum(v) RANGE '10s' FROM ab ALIGN '10s' BY () ORDER BY ts;

DROP TABLE ab;
