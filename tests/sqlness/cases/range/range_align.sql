-- RANGE ... ALIGN queries (the reference's range_select)
CREATE TABLE sensor (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO sensor VALUES
    ('a', 1.0, 0), ('a', 2.0, 5000), ('a', 3.0, 10000), ('a', 4.0, 15000),
    ('b', 10.0, 0), ('b', 20.0, 5000), ('b', 30.0, 10000);

SELECT ts, host, avg(v) RANGE '10s' FROM sensor ALIGN '10s' ORDER BY host, ts;

SELECT ts, host, max(v) RANGE '10s' FROM sensor ALIGN '5s' ORDER BY host, ts;

SELECT ts, host, sum(v) RANGE '5s' FROM sensor ALIGN '5s' BY (host) ORDER BY host, ts;
