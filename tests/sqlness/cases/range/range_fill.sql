-- RANGE with FILL policies over a sparse series
CREATE TABLE s (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO s VALUES ('a', 1.0, 0), ('a', 5.0, 20000), ('b', 7.0, 10000);

SELECT ts, host, avg(v) RANGE '5s' FROM s ALIGN '5s' ORDER BY host, ts;

SELECT ts, host, avg(v) RANGE '5s' FILL NULL FROM s ALIGN '5s' ORDER BY host, ts;

SELECT ts, host, avg(v) RANGE '5s' FILL PREV FROM s ALIGN '5s' ORDER BY host, ts;

SELECT ts, host, avg(v) RANGE '5s' FILL LINEAR FROM s WHERE host = 'a' ALIGN '5s' ORDER BY ts;

SELECT ts, host, avg(v) RANGE '5s' FILL 0 FROM s ALIGN '5s' ORDER BY host, ts;
