-- ALIGN TO origin shifting and BY () (no keys)
CREATE TABLE s (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO s VALUES
    ('a', 1.0, 1000), ('a', 2.0, 6000), ('b', 3.0, 11000), ('b', 4.0, 16000);

SELECT ts, host, sum(v) RANGE '10s' FROM s ALIGN '10s' ORDER BY host, ts;

SELECT ts, host, sum(v) RANGE '10s' FROM s ALIGN '10s' TO 1000 ORDER BY host, ts;

SELECT ts, sum(v) RANGE '10s' FROM s ALIGN '10s' BY () ORDER BY ts;

SELECT ts, count(v) RANGE '20s' FROM s ALIGN '10s' BY () ORDER BY ts;
