-- String predicates: LIKE patterns, IN lists, BETWEEN on strings
CREATE TABLE m (host STRING, dc STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, dc));

INSERT INTO m VALUES
    ('web-01', 'east', 1.0, 1000), ('web-02', 'west', 2.0, 2000),
    ('db-01', 'east', 3.0, 3000), ('cache-01', 'west', 4.0, 4000);

SELECT host FROM m WHERE host LIKE 'web-%' ORDER BY host;

SELECT host FROM m WHERE host LIKE '%-01' ORDER BY host;

SELECT host FROM m WHERE dc IN ('east') ORDER BY host;

SELECT host FROM m WHERE host BETWEEN 'a' AND 'e' ORDER BY host;

SELECT host FROM m WHERE host NOT LIKE 'web-%' ORDER BY host;
