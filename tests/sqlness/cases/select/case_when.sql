-- CASE in projection and WHERE
CREATE TABLE cw (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO cw VALUES (10.0, 1), (55.0, 2), (91.0, 3);

SELECT v, CASE WHEN v > 90 THEN 'high' WHEN v > 50 THEN 'mid' ELSE 'low' END AS band FROM cw ORDER BY v;

SELECT count(*) AS n FROM cw WHERE CASE WHEN v > 50 THEN true ELSE false END;

DROP TABLE cw;
