-- DISTINCT, aliases, arithmetic in the projection
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1.0, 1000), ('a', 1.0, 2000), ('b', 2.0, 3000);

SELECT DISTINCT host FROM m ORDER BY host;

SELECT host AS h, v * 2 AS doubled, v + 1 AS plus_one FROM m ORDER BY h, doubled;

SELECT 1 + 2;

SELECT 'hello' AS greeting;
