-- ORDER BY asc/desc, multi-key, LIMIT and OFFSET
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 3.0, 1000), ('b', 1.0, 2000), ('c', 2.0, 3000), ('d', 1.0, 4000);

SELECT host, v FROM m ORDER BY v, host;

SELECT host, v FROM m ORDER BY v DESC, host DESC;

SELECT host FROM m ORDER BY host LIMIT 2;

SELECT host FROM m ORDER BY host LIMIT 2 OFFSET 1;
