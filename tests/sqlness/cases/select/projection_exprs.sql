-- Scalar expressions over aggregate results and columns
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 4.0, 1000), ('b', 9.0, 2000), ('c', 16.0, 3000);

SELECT host, sqrt(v) AS root, v * v AS squared FROM m ORDER BY host;

SELECT max(v) - min(v) AS spread FROM m;

SELECT avg(v) * 2 AS doubled_avg, round(avg(v), 1) AS rounded FROM m;

SELECT host, CASE WHEN v > 8.0 THEN 'big' ELSE 'small' END AS size FROM m ORDER BY host;

SELECT sum(v) + count(*) FROM m;
