-- db-qualified and aliased table references
CREATE DATABASE qdb;

CREATE TABLE qdb.qt (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO qdb.qt VALUES (5.0, 1);

SELECT v FROM qdb.qt;

SELECT q.v FROM qdb.qt AS q;

USE qdb;

SELECT v FROM qt;

USE public;

DROP TABLE qdb.qt;
