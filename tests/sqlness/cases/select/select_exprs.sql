-- projection arithmetic, aliases, literals
CREATE TABLE se (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO se VALUES (2.0, 1), (4.0, 2);

SELECT v, v * 2 AS dbl, v + v AS ss, 100 AS k FROM se ORDER BY v;

SELECT 1 + 1;

SELECT 'text' AS t, 3.14 AS pi;

SELECT v % 3 AS m, -v AS neg FROM se ORDER BY v;

DROP TABLE se;
