-- Time-index predicates: range pruning must not change results
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES
    ('a', 1.0, 1000), ('a', 2.0, 2000), ('a', 3.0, 3000),
    ('a', 4.0, 4000), ('a', 5.0, 5000);

SELECT v FROM m WHERE ts > 2000 ORDER BY v;

SELECT v FROM m WHERE ts >= 2000 AND ts < 4000 ORDER BY v;

SELECT v FROM m WHERE ts = 3000;

SELECT sum(v) FROM m WHERE ts BETWEEN 2000 AND 4000;

ADMIN flush_table('m');

SELECT v FROM m WHERE ts >= 2000 AND ts < 4000 ORDER BY v;
