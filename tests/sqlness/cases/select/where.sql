-- WHERE: comparisons, boolean operators, IN, BETWEEN, tag and time filters
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1.0, 1000), ('b', 2.0, 2000), ('c', 3.0, 3000), ('d', 4.0, 4000);

SELECT host, v FROM m WHERE v > 2.0 ORDER BY host;

SELECT host FROM m WHERE v >= 2.0 AND v < 4.0 ORDER BY host;

SELECT host FROM m WHERE host = 'a' OR host = 'd' ORDER BY host;

SELECT host FROM m WHERE host IN ('a', 'c') ORDER BY host;

SELECT host FROM m WHERE v BETWEEN 2.0 AND 3.0 ORDER BY host;

SELECT host FROM m WHERE ts >= 2000 AND ts <= 3000 ORDER BY host;

SELECT host FROM m WHERE host != 'b' AND NOT (v > 3.0) ORDER BY host;
