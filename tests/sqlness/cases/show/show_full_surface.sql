-- SHOW surface: databases / tables / views / flows
CREATE DATABASE showdb;

CREATE TABLE showdb.s1 (v DOUBLE, ts TIMESTAMP TIME INDEX);

SHOW TABLES FROM showdb;

SHOW DATABASES;

SHOW VIEWS;

SHOW FLOWS;

DROP TABLE showdb.s1;
