-- derived tables + scalar/IN/EXISTS subqueries
CREATE TABLE cpu (host STRING, usage_user DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO cpu VALUES ('a', 10.0, 1000), ('a', 20.0, 2000), ('b', 5.0, 1000), ('b', 50.0, 2000), ('c', 7.0, 1000);

-- FROM (SELECT ...) alias: TSBS groupby-orderby-limit shape
SELECT * FROM (SELECT host, avg(usage_user) AS au FROM cpu GROUP BY host) x ORDER BY au DESC LIMIT 2;

-- scalar subquery in WHERE
SELECT host, usage_user FROM cpu WHERE usage_user = (SELECT max(usage_user) FROM cpu);

-- scalar subquery in projection
SELECT (SELECT min(usage_user) FROM cpu) + 1 AS lo;

-- IN / NOT IN subqueries
SELECT DISTINCT host FROM cpu WHERE host IN (SELECT host FROM cpu WHERE usage_user > 15) ORDER BY host;

SELECT DISTINCT host FROM cpu WHERE host NOT IN (SELECT host FROM cpu WHERE usage_user > 15) ORDER BY host;

-- EXISTS
SELECT count(*) AS n FROM cpu WHERE EXISTS (SELECT 1 FROM cpu WHERE usage_user > 40);

-- scalar subquery with more than one row is an error
SELECT 1 WHERE 1 = (SELECT usage_user FROM cpu);

DROP TABLE cpu;
