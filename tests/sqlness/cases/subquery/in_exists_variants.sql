-- IN/EXISTS/scalar subqueries in more positions
CREATE TABLE sv (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO sv VALUES ('a', 1.0, 1), ('b', 5.0, 1), ('c', 9.0, 1);

SELECT host FROM sv WHERE v > (SELECT avg(v) FROM sv) ORDER BY host;

SELECT host, v >= (SELECT max(v) FROM sv) AS is_max FROM sv ORDER BY host;

SELECT count(*) AS n FROM sv WHERE NOT EXISTS (SELECT 1 FROM sv WHERE v > 100);

SELECT host FROM sv WHERE host IN (SELECT host FROM sv WHERE v < 6) AND v > 2;

DROP TABLE sv;
