-- date_bin bucketing at several widths
CREATE TABLE db (v DOUBLE, ts TIMESTAMP(3) TIME INDEX);

INSERT INTO db VALUES (1.0, 0), (2.0, 30000), (3.0, 60000), (4.0, 90000), (5.0, 3600000);

SELECT date_bin(INTERVAL '1 minute', ts) AS m, sum(v) AS s FROM db GROUP BY m ORDER BY m;

SELECT date_bin(INTERVAL '1 hour', ts) AS h, count(*) AS n FROM db GROUP BY h ORDER BY h;

DROP TABLE db;
