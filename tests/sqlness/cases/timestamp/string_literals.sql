-- timestamp string literals coerce on insert and in predicates
CREATE TABLE tl (v DOUBLE, ts TIMESTAMP(3) TIME INDEX);

INSERT INTO tl VALUES (1.0, '2024-01-01 00:00:00'), (2.0, '2024-01-01 00:01:00');

SELECT count(*) AS n FROM tl WHERE ts >= '2024-01-01 00:00:30';

SELECT v FROM tl WHERE ts = '2024-01-01 00:00:00';

DROP TABLE tl;
