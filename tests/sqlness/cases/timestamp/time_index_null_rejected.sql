-- the time index cannot be NULL
CREATE TABLE tn (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO tn VALUES (1.0, NULL);

SELECT count(*) AS n FROM tn;

DROP TABLE tn;
