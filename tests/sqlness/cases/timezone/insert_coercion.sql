-- timestamp-string inserts honor the session timezone
CREATE TABLE tic (v DOUBLE, ts TIMESTAMP(3) TIME INDEX);

SET TIME ZONE '+00:00';

INSERT INTO tic VALUES (1.0, '2024-01-01 00:00:00');

SET TIME ZONE '+02:00';

INSERT INTO tic VALUES (2.0, '2024-01-01 02:00:00');

SET TIME ZONE DEFAULT;

SELECT count(DISTINCT ts) AS distinct_instants FROM tic;

DROP TABLE tic;
