-- SET TIME ZONE round-trips; HTTP JSON returns epoch ms (rendering is client-side)
CREATE TABLE tz (v DOUBLE, ts TIMESTAMP(3) TIME INDEX);

INSERT INTO tz VALUES (1.0, '2024-06-01 12:00:00');

SELECT ts FROM tz;

SET TIME ZONE '+08:00';

SELECT ts FROM tz;

SET TIME ZONE DEFAULT;

SELECT ts FROM tz;

DROP TABLE tz;
