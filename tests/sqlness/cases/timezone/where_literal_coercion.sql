-- the session timezone applies to WHERE/BETWEEN literals, not just INSERT
CREATE TABLE wl (v DOUBLE, ts TIMESTAMP(3) TIME INDEX);

SET TIME ZONE '+08:00';

INSERT INTO wl VALUES (1.0, '2024-01-01 08:00:00');

-- the same literal that inserted the row must find it again
SELECT v FROM wl WHERE ts = '2024-01-01 08:00:00';

SELECT count(*) AS n FROM wl WHERE ts BETWEEN '2024-01-01 07:59:00' AND '2024-01-01 08:01:00';

SET TIME ZONE DEFAULT;

-- in UTC the stored instant is 2024-01-01T00:00:00Z
SELECT v FROM wl WHERE ts = '2024-01-01 00:00:00';

SELECT count(*) AS n FROM wl WHERE ts = '2024-01-01 08:00:00';

-- a typo'd zone fails at SET, not on a later statement
SET TIME ZONE 'Nope/Zone';

DROP TABLE wl;
