-- TQL binary operations between vectors and scalars
CREATE TABLE g2 (job STRING, val DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(job));

INSERT INTO g2 VALUES ('a', 4, 10000), ('b', 9, 10000);

TQL EVAL (10, 10, '10s') g2 * 2;

TQL EVAL (10, 10, '10s') g2 > 5;

TQL EVAL (10, 10, '10s') g2 + g2;

DROP TABLE g2;
