-- TQL EVAL: PromQL embedded in SQL
CREATE TABLE http_requests (job STRING, instance STRING, val DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(job, instance));

INSERT INTO http_requests VALUES
    ('api', 'i1', 10, 0), ('api', 'i1', 20, 10000), ('api', 'i1', 30, 20000),
    ('api', 'i2', 5, 0), ('api', 'i2', 15, 10000), ('api', 'i2', 25, 20000),
    ('web', 'i3', 100, 0), ('web', 'i3', 110, 10000), ('web', 'i3', 120, 20000);

TQL EVAL (0, 20, '10s') http_requests;

TQL EVAL (20, 20, '10s') sum(http_requests);

TQL EVAL (20, 20, '10s') sum by (job) (http_requests);

TQL EVAL (20, 20, '10s') rate(http_requests[20s]);

TQL EVAL (20, 20, '10s') topk(1, http_requests);
