-- TQL: PromQL function coverage through the SQL gateway
CREATE TABLE latency (job STRING, le STRING, val DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(job, le));

INSERT INTO latency VALUES
    ('api', '0.1', 10, 10000), ('api', '0.5', 30, 10000),
    ('api', '1', 40, 10000), ('api', '+Inf', 50, 10000);

TQL EVAL (10, 10, '10s') histogram_quantile(0.9, latency);

CREATE TABLE g (job STRING, val DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(job));

INSERT INTO g VALUES ('a', 1, 0), ('a', 4, 10000), ('a', 9, 20000), ('b', 2, 0), ('b', 2, 10000), ('b', 2, 20000);

TQL EVAL (20, 20, '10s') sqrt(g);

TQL EVAL (20, 20, '10s') clamp_max(g, 4);

TQL EVAL (20, 20, '10s') delta(g[20s]);

TQL EVAL (20, 20, '10s') avg_over_time(g[20s]);

TQL EVAL (20, 20, '10s') sort_desc(g);

TQL EVAL (20, 20, '10s') absent(nonexistent_metric);
