-- TQL rate + aggregation over a counter-shaped series
CREATE TABLE reqs (job STRING, val DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(job));

INSERT INTO reqs VALUES ('a', 0, 0), ('a', 60, 60000), ('a', 120, 120000), ('b', 0, 0), ('b', 30, 60000), ('b', 60, 120000);

TQL EVAL (120, 120, '60s') rate(reqs[2m]);

TQL EVAL (120, 120, '60s') sum(rate(reqs[2m]));

TQL EVAL (120, 120, '60s') avg_over_time(reqs[2m]);

DROP TABLE reqs;
