-- TRUNCATE drops rows, keeps schema
CREATE TABLE tt (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO tt VALUES ('a', 1.0, 1), ('b', 2.0, 2);

TRUNCATE TABLE tt;

SELECT count(*) AS n FROM tt;

INSERT INTO tt VALUES ('c', 3.0, 3);

SELECT host, v FROM tt;

DROP TABLE tt;
