-- BOOLEAN columns
CREATE TABLE bt (ok BOOLEAN, ts TIMESTAMP TIME INDEX);

INSERT INTO bt VALUES (true, 1), (false, 2), (true, 3);

SELECT ok FROM bt ORDER BY ts;

SELECT count(*) AS n FROM bt WHERE ok;

DROP TABLE bt;
