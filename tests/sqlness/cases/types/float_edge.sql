-- float specials: NULL vs NaN handling in aggregates
CREATE TABLE fe (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO fe VALUES (1.5, 1), (NULL, 2), (2.5, 3);

SELECT count(*) AS rows_n, count(v) AS vals_n FROM fe;

SELECT sum(v) AS s, avg(v) AS a, min(v) AS lo, max(v) AS hi FROM fe;

SELECT v IS NULL AS isn FROM fe ORDER BY ts;

DROP TABLE fe;
