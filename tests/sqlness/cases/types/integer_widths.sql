-- integer column widths store and round-trip
CREATE TABLE iw (a TINYINT, b SMALLINT, c INT, d BIGINT, ts TIMESTAMP TIME INDEX);

INSERT INTO iw VALUES (1, 300, 70000, 5000000000, 1);

INSERT INTO iw VALUES (-1, -300, -70000, -5000000000, 2);

SELECT a, b, c, d FROM iw ORDER BY ts;

SELECT sum(d) AS s FROM iw;

DROP TABLE iw;
