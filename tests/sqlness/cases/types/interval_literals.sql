-- interval literal forms
CREATE TABLE il (v DOUBLE, ts TIMESTAMP(3) TIME INDEX);

INSERT INTO il VALUES (1.0, 0), (2.0, 90000);

SELECT date_bin(INTERVAL '1 minute', ts) AS m, count(*) AS n FROM il GROUP BY m ORDER BY m;

SELECT date_bin(INTERVAL '90 seconds', ts) AS m, count(*) AS n FROM il GROUP BY m ORDER BY m;

SELECT date_bin(INTERVAL '1h30m', ts) AS m, count(*) AS n FROM il GROUP BY m ORDER BY m;

DROP TABLE il;
