-- NULL semantics in filters, aggregates, and sorting
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO m (host, v, ts) VALUES ('a', 1.0, 1000), ('b', NULL, 2000), ('c', 3.0, 3000);

SELECT host, v FROM m ORDER BY host;

SELECT count(*), count(v) FROM m;

SELECT sum(v), avg(v), min(v), max(v) FROM m;

SELECT host FROM m WHERE v IS NULL;

SELECT host FROM m WHERE v IS NOT NULL ORDER BY host;

SELECT coalesce(v, -1.0) AS v2, host FROM m ORDER BY host;
