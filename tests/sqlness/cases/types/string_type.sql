-- STRING fields (not tags): store, filter, NULL
CREATE TABLE st (msg STRING, ts TIMESTAMP TIME INDEX);

INSERT INTO st VALUES ('hello', 1), (NULL, 2), ('world', 3);

SELECT msg FROM st ORDER BY ts;

SELECT count(msg) AS n FROM st;

SELECT msg FROM st WHERE msg LIKE 'w%';

DROP TABLE st;
