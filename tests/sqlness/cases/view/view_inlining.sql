-- simple views inline: RANGE/device path work against the base table
CREATE TABLE vm (host STRING, v DOUBLE, ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host));

INSERT INTO vm VALUES ('a', 1.0, 0), ('a', 3.0, 5000), ('b', 2.0, 0), ('b', 4.0, 5000);

CREATE VIEW vs AS SELECT host AS h, v * 2 AS dbl, ts FROM vm WHERE v > 1;

SELECT h, dbl FROM vs ORDER BY h, dbl;

SELECT h, max(dbl) AS mx FROM vs GROUP BY h ORDER BY h;

SELECT ts, sum(dbl) RANGE '5s' FROM vs ALIGN '5s' BY () ORDER BY ts;

DROP VIEW vs;

DROP TABLE vm;
