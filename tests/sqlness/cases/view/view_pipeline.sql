-- views over aggregates, views over views, SHOW/replace/drop
CREATE TABLE vt (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO vt VALUES ('a', 1.0, 1), ('a', 3.0, 2), ('b', 10.0, 1);

CREATE VIEW v_sum AS SELECT host, sum(v) AS s FROM vt GROUP BY host;

SELECT * FROM v_sum ORDER BY host;

CREATE VIEW v_top AS SELECT * FROM v_sum WHERE s > 2;

SELECT * FROM v_top ORDER BY host;

CREATE OR REPLACE VIEW v_top AS SELECT * FROM v_sum WHERE s > 5;

SELECT * FROM v_top;

SHOW VIEWS;

DROP VIEW v_top;

DROP VIEW v_sum;

DROP TABLE vt;
