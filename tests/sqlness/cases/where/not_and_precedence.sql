-- NOT binding and parenthesized boolean logic
CREATE TABLE np (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO np VALUES (1.0, 1), (2.0, 2), (3.0, 3);

SELECT v FROM np WHERE NOT v = 2 ORDER BY v;

SELECT v FROM np WHERE NOT (v = 1 OR v = 2);

SELECT v FROM np WHERE v = 1 OR v = 2 AND v = 3 ORDER BY v;

DROP TABLE np;
