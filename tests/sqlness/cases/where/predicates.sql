-- BETWEEN / IN / LIKE / IS NULL / boolean combinations
CREATE TABLE wp (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO wp VALUES ('web-1', 1.0, 1), ('web-2', 5.0, 2), ('db-1', 9.0, 3), ('db-2', NULL, 4);

SELECT host FROM wp WHERE v BETWEEN 2 AND 9 ORDER BY host;

SELECT host FROM wp WHERE v NOT BETWEEN 2 AND 9 ORDER BY host;

SELECT host FROM wp WHERE host IN ('web-1', 'db-1') ORDER BY host;

SELECT host FROM wp WHERE host LIKE 'web-%' ORDER BY host;

SELECT host FROM wp WHERE v IS NULL;

SELECT host FROM wp WHERE v IS NOT NULL AND (v < 2 OR v > 8) ORDER BY host;

DROP TABLE wp;
