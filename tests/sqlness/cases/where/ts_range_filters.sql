-- time-index range predicates prune correctly
CREATE TABLE tr (v DOUBLE, ts TIMESTAMP(3) TIME INDEX);

INSERT INTO tr VALUES (1.0, 1000), (2.0, 2000), (3.0, 3000), (4.0, 4000);

SELECT v FROM tr WHERE ts > 1000 AND ts < 4000 ORDER BY ts;

SELECT v FROM tr WHERE ts >= 2000 AND ts <= 3000 ORDER BY ts;

SELECT count(*) AS n FROM tr WHERE ts >= 5000;

DROP TABLE tr;
