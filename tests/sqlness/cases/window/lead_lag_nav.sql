-- navigation windows: lead/lag offsets, first/nth value
CREATE TABLE nv (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO nv VALUES ('a', 1.0, 1), ('a', 2.0, 2), ('a', 3.0, 3), ('a', 4.0, 4);

SELECT ts, lag(v, 2) OVER (ORDER BY ts) AS l2, lead(v, 1, -1.0) OVER (ORDER BY ts) AS ld FROM nv ORDER BY ts;

SELECT ts, first_value(v) OVER (ORDER BY ts) AS fv, nth_value(v, 2) OVER (ORDER BY ts) AS n2 FROM nv ORDER BY ts;

DROP TABLE nv;
