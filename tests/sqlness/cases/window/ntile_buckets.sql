-- ntile bucketing
CREATE TABLE nt (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO nt VALUES (1.0, 1), (2.0, 2), (3.0, 3), (4.0, 4), (5.0, 5);

SELECT v, ntile(2) OVER (ORDER BY v) AS b FROM nt ORDER BY v;

SELECT v, ntile(3) OVER (ORDER BY v) AS b FROM nt ORDER BY v;

DROP TABLE nt;
