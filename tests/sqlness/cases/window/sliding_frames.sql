-- sliding window frames: ROWS/RANGE k PRECEDING (moving aggregates),
-- INTERVAL offsets over the time index, frame-positional navigation
CREATE TABLE sf (host STRING, v DOUBLE, ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host));

INSERT INTO sf VALUES ('a', 1.0, 1000), ('a', 2.0, 2000), ('a', 3.0, 3000), ('a', 4.0, 4000), ('b', 10.0, 1000), ('b', 20.0, 3000), ('b', 30.0, 6000);

-- moving average over the last 3 rows per host
SELECT host, ts, avg(v) OVER (PARTITION BY host ORDER BY ts ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS ma FROM sf ORDER BY host, ts;

-- moving sum over a 2-second value window (RANGE, numeric offset in ms)
SELECT host, ts, sum(v) OVER (PARTITION BY host ORDER BY ts RANGE BETWEEN 2000 PRECEDING AND CURRENT ROW) AS s2 FROM sf ORDER BY host, ts;

-- same window via INTERVAL against the timestamp order key
SELECT host, ts, sum(v) OVER (PARTITION BY host ORDER BY ts RANGE BETWEEN INTERVAL '2 seconds' PRECEDING AND CURRENT ROW) AS s2 FROM sf ORDER BY host, ts;

-- sliding min/max (sparse-table range queries)
SELECT ts, min(v) OVER (ORDER BY ts ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS mn, max(v) OVER (ORDER BY ts ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS mx FROM sf WHERE host = 'a' ORDER BY ts;

-- navigation reads the frame bounds
SELECT ts, first_value(v) OVER (ORDER BY ts ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS fv FROM sf WHERE host = 'b' ORDER BY ts;

-- windows over GROUP BY output: rank hosts by grouped average
SELECT host, avg(v) AS a, rank() OVER (ORDER BY avg(v) DESC) AS rk FROM sf GROUP BY host ORDER BY host;

-- moving average over grouped time buckets
SELECT date_bin(INTERVAL '2 seconds', ts) AS b, avg(v) AS a, avg(avg(v)) OVER (ORDER BY date_bin(INTERVAL '2 seconds', ts) ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS ma FROM sf GROUP BY b ORDER BY b;

-- unsupported shapes error instead of silently degrading
SELECT sum(v) OVER (ORDER BY ts ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) FROM sf;

SELECT sum(v) OVER (ORDER BY ts GROUPS BETWEEN 1 PRECEDING AND CURRENT ROW) FROM sf;

DROP TABLE sf;
