-- window functions (reference: DataFusion WindowAggExec)
CREATE TABLE cpu (host STRING, usage_user DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO cpu VALUES ('a', 10.0, 1000), ('a', 20.0, 2000), ('a', 30.0, 3000), ('b', 5.0, 1000), ('b', 50.0, 2000);

SELECT host, usage_user, row_number() OVER (PARTITION BY host ORDER BY ts) AS rn FROM cpu ORDER BY host, rn;

-- lastpoint via row_number in a derived table
SELECT host, usage_user FROM (
  SELECT host, usage_user, row_number() OVER (PARTITION BY host ORDER BY ts DESC) AS rn FROM cpu
) t WHERE rn = 1 ORDER BY host;

-- running sum and whole-partition average
SELECT ts, sum(usage_user) OVER (PARTITION BY host ORDER BY ts) AS rs FROM cpu WHERE host = 'a' ORDER BY ts;

SELECT DISTINCT host, avg(usage_user) OVER (PARTITION BY host) AS pa FROM cpu ORDER BY host;

-- lag / lead navigation
SELECT ts, lag(usage_user) OVER (PARTITION BY host ORDER BY ts) AS prev,
       lead(usage_user) OVER (PARTITION BY host ORDER BY ts) AS nxt
FROM cpu WHERE host = 'a' ORDER BY ts;

-- rank with ties
CREATE TABLE s (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO s VALUES (10.0, 1), (10.0, 2), (20.0, 3);

SELECT v, rank() OVER (ORDER BY v) AS rk, dense_rank() OVER (ORDER BY v) AS dr FROM s ORDER BY ts;

-- window over GROUP BY output (SQL evaluation order)
SELECT host, row_number() OVER (ORDER BY host) FROM cpu GROUP BY host;

DROP TABLE s;

DROP TABLE cpu;
