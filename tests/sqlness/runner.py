"""sqlness: golden-file SQL conformance harness.

Mirrors the reference's sqlness runner (tests/runner/src/main.rs +
tests/cases/standalone/): each `cases/**/*.sql` file is a sequence of SQL
statements; the runner replays them through the REAL HTTP server
(`/v1/sql`, the same path a user hits) and renders every result as an
ASCII table / "Affected Rows: N" / "Error: ..." block. The rendered
transcript is compared byte-for-byte against the sibling `.result` file.

Regenerate goldens after an intentional behavior change with:
    SQLNESS_REGEN=1 python -m pytest tests/test_sqlness.py
then review the `.result` diff like any other code change.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request


def split_statements(text: str) -> list[str]:
    """Split a .sql file into statements on top-level ';', respecting
    quotes and `--` comments. Comment-only fragments are dropped;
    comments attached to a statement are preserved (they document the
    case in the transcript)."""
    stmts = []
    buf: list[str] = []
    in_str: str | None = None
    in_comment = False
    i = 0
    while i < len(text):
        c = text[i]
        if in_comment:
            buf.append(c)
            if c == "\n":
                in_comment = False
        elif in_str is not None:
            buf.append(c)
            if c == in_str:
                if i + 1 < len(text) and text[i + 1] == in_str:
                    buf.append(text[i + 1])
                    i += 1
                else:
                    in_str = None
        elif c == "-" and text[i:i + 2] == "--":
            in_comment = True
            buf.append(c)
        elif c in ("'", '"'):
            in_str = c
            buf.append(c)
        elif c == ";":
            stmt = "".join(buf).strip()
            if _has_sql(stmt):
                stmts.append(stmt)
            buf = []
        else:
            buf.append(c)
        i += 1
    tail = "".join(buf).strip()
    if _has_sql(tail):
        stmts.append(tail)
    return stmts


def _has_sql(stmt: str) -> bool:
    return any(
        line.strip() and not line.strip().startswith("--")
        for line in stmt.splitlines()
    )


def _fmt_cell(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if v != v:  # NaN renders like NULL, matching engine semantics
            return ""
        return repr(v)
    return str(v)


def render_table(names: list[str], rows: list[list]) -> str:
    cells = [[_fmt_cell(v) for v in row] for row in rows]
    widths = [
        max(len(n), *(len(r[i]) for r in cells)) if cells else len(n)
        for i, n in enumerate(names)
    ]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep]
    out.append("| " + " | ".join(n.ljust(w) for n, w in zip(names, widths)) + " |")
    out.append(sep)
    for r in cells:
        out.append("| " + " | ".join(c.ljust(w) for c, w in zip(r, widths)) + " |")
    out.append(sep)
    return "\n".join(out)


class HttpSqlClient:
    """Drives the real HTTP server's /v1/sql endpoint."""

    def __init__(self, port: int, db: str = "public"):
        self.port = port
        self.db = db
        self.timezone = None

    def run(self, sql: str) -> str:
        """Execute one statement; return its rendered transcript block.
        USE <db> and SET TIME ZONE are session state (the reference
        runner holds a connection); HTTP is stateless, so the runner
        tracks them and pins each later request via the ?db= parameter /
        X-Greptime-Timezone header."""
        code_lines = [ln for ln in sql.splitlines()
                      if ln.strip() and not ln.strip().startswith("--")]
        bare = " ".join(code_lines).strip().rstrip(";").split()
        if len(bare) == 2 and bare[0].lower() == "use":
            self.db = bare[1].strip('"`')
            return "Affected Rows: 0"
        low = [w.lower() for w in bare]
        tz_val = None
        if low[:3] == ["set", "time", "zone"] and len(bare) == 4:
            tz_val = bare[3]
        elif len(low) >= 2 and low[0] == "set" \
                and low[1].split("=")[0] in ("time_zone", "timezone"):
            # MySQL spelling: SET time_zone = '+08:00'
            tz_val = bare[-1].split("=")[-1]
        if tz_val is not None:
            # run the SET through the server (its validation + transcript
            # are part of the case), and only keep the zone for later
            # statements when it was accepted
            out = self._post(sql)
            if not out.startswith("Error"):
                val = tz_val.strip("'\"")
                self.timezone = None if val.lower() == "default" else val
            return out
        return self._post(sql)

    def _post(self, sql: str) -> str:
        data = urllib.parse.urlencode({"sql": sql, "db": self.db}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/v1/sql", data=data, method="POST"
        )
        if self.timezone:
            req.add_header("X-Greptime-Timezone", self.timezone)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                payload = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode())
            except Exception:  # noqa: BLE001
                return f"Error: HTTP {e.code}"
            msg = payload.get("error", f"HTTP {e.code}")
            return f"Error: {msg}"
        outputs = payload.get("output", [])
        blocks = []
        for out in outputs:
            if "records" in out:
                rec = out["records"]
                names = [c["name"] for c in rec["schema"]["column_schemas"]]
                blocks.append(render_table(names, rec["rows"]))
            else:
                blocks.append(f"Affected Rows: {out.get('affectedrows', 0)}")
        return "\n\n".join(blocks) if blocks else "Affected Rows: 0"


def run_case(sql_text: str, client: HttpSqlClient) -> str:
    """Replay a case file; return the full rendered transcript."""
    parts = []
    for stmt in split_statements(sql_text):
        parts.append(stmt + ";")
        parts.append("")
        parts.append(client.run(stmt))
        parts.append("")
    return "\n".join(parts).rstrip() + "\n"
