"""Auth + session tests: provider parsing, per-protocol credential
verification (HTTP Basic, MySQL native-password scramble, Postgres
cleartext), and the coarse permission checker."""

import base64
import json
import socket
import struct
import urllib.request

import pytest

from greptimedb_tpu.auth import (
    AuthError,
    PermissionChecker,
    StaticUserProvider,
    UserInfo,
    mysql_native_scramble,
    user_provider_from_option,
)
from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.session import Channel, QueryContext
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE cpu (host STRING, usage DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY(host))"
    )
    yield q
    engine.close()


PROVIDER = StaticUserProvider({"alice": "s3cret", "bob": ""})


class TestProvider:
    def test_authenticate(self):
        assert PROVIDER.authenticate("alice", "s3cret").username == "alice"
        with pytest.raises(AuthError):
            PROVIDER.authenticate("alice", "wrong")
        with pytest.raises(AuthError):
            PROVIDER.authenticate("nobody", "x")

    def test_from_option_cmd(self):
        p = user_provider_from_option("static_user_provider:cmd:u1=p1,u2=p2")
        assert p.authenticate("u2", "p2").username == "u2"

    def test_from_option_file(self, tmp_path):
        f = tmp_path / "users"
        f.write_text("# users\nalice = pw1\nbob=pw2\n")
        p = user_provider_from_option(f"static_user_provider:file:{f}")
        assert p.authenticate("alice", "pw1").username == "alice"
        assert p.authenticate("bob", "pw2").username == "bob"

    def test_bad_option(self):
        with pytest.raises(AuthError):
            user_provider_from_option("ldap:whatever")

    def test_basic_auth(self):
        hdr = "Basic " + base64.b64encode(b"alice:s3cret").decode()
        assert PROVIDER.authenticate_basic(hdr).username == "alice"
        with pytest.raises(AuthError):
            PROVIDER.authenticate_basic("Bearer token")
        with pytest.raises(AuthError):
            PROVIDER.authenticate_basic(
                "Basic " + base64.b64encode(b"alice:nope").decode())

    def test_mysql_scramble(self):
        salt = bytes(range(1, 21))
        resp = mysql_native_scramble("s3cret", salt)
        assert PROVIDER.authenticate_mysql("alice", resp, salt).username == "alice"
        with pytest.raises(AuthError):
            PROVIDER.authenticate_mysql("alice", b"\x00" * 20, salt)

    def test_mysql_empty_password(self):
        # empty stored password ⇒ zero-length client auth response
        salt = bytes(range(1, 21))
        assert PROVIDER.authenticate_mysql("bob", b"", salt).username == "bob"
        with pytest.raises(AuthError):
            PROVIDER.authenticate_mysql("bob", b"x" * 20, salt)


class TestPermission:
    def test_grants(self, qe):
        from greptimedb_tpu.sql import parse_sql

        checker = PermissionChecker()
        reader = UserInfo("r", grants=frozenset({"read"}))
        select = parse_sql("SELECT * FROM cpu")[0]
        insert = parse_sql("INSERT INTO cpu (host, usage, ts) VALUES ('a',1,1)")[0]
        checker.check(reader, select, "public")
        with pytest.raises(AuthError):
            checker.check(reader, insert, "public")
        checker.check(UserInfo("w"), insert, "public")  # no grants = all

    def test_protected_schema(self):
        """greptime_private: writes denied for everyone but the admin user
        (including anonymous contexts); reads allowed (ADVICE r1)."""
        from greptimedb_tpu.sql import parse_sql

        checker = PermissionChecker()
        select = parse_sql("SELECT * FROM t")[0]
        insert = parse_sql("INSERT INTO t (a) VALUES (1)")[0]
        checker.check(UserInfo("alice"), select, "greptime_private")
        checker.check(None, select, "greptime_private")
        with pytest.raises(AuthError):
            checker.check(UserInfo("alice"), insert, "greptime_private")
        with pytest.raises(AuthError):
            checker.check(None, insert, "greptime_private")
        checker.check(UserInfo("greptime"), insert, "greptime_private")

    def test_copy_requires_write(self):
        """COPY moves data in/out — read-only grants must not allow it
        (ADVICE r1: ingest/exfil via COPY with only 'read')."""
        from greptimedb_tpu.sql import parse_sql

        checker = PermissionChecker()
        reader = UserInfo("r", grants=frozenset({"read"}))
        copy_from = parse_sql("COPY t FROM '/tmp/x.parquet'")[0]
        copy_to = parse_sql("COPY t TO '/tmp/x.parquet'")[0]
        for stmt in (copy_from, copy_to):
            with pytest.raises(AuthError):
                checker.check(reader, stmt, "public")

    def test_enforced_in_engine(self, qe):
        """The engine itself rejects writes from read-only users
        (regression: the checker must actually be wired into dispatch)."""
        ctx = QueryContext(user=UserInfo("r", grants=frozenset({"read"})))
        qe.execute_one("SELECT * FROM cpu", ctx)
        with pytest.raises(AuthError):
            qe.execute_one(
                "INSERT INTO cpu (host, usage, ts) VALUES ('x',1,1)", ctx)

    def test_string_interval_device_path(self, qe):
        """date_bin with a string interval works through the full
        aggregate (device) path, and bad intervals fail as PlanError."""
        from greptimedb_tpu.query.expr import PlanError

        qe.execute_one(
            "INSERT INTO cpu (host, usage, ts) VALUES ('a',1,1000),('a',3,61000)")
        r = qe.execute_one(
            "SELECT host, date_bin('1 minute', ts) AS m, avg(usage) "
            "FROM cpu GROUP BY host, m ORDER BY m")
        assert r.rows() == [["a", 0, 1.0], ["a", 60000, 3.0]]
        with pytest.raises(PlanError):
            qe.execute_one(
                "SELECT date_bin('bogus', ts), avg(usage) FROM cpu GROUP BY 1")


class TestQueryContext:
    def test_channel_and_user(self):
        ctx = QueryContext(db="d", channel=Channel.MYSQL,
                           user=UserInfo("alice"))
        assert ctx.current_schema == "d"
        assert ctx.with_db("e").channel is Channel.MYSQL


class TestHttpAuth:
    @pytest.fixture
    def server(self, qe):
        from greptimedb_tpu.servers.http import HttpServer

        srv = HttpServer(qe, port=0, user_provider=PROVIDER)
        srv.start()
        yield srv
        srv.stop()

    def _get(self, port, path, auth=None):
        req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
        if auth:
            req.add_header(
                "Authorization",
                "Basic " + base64.b64encode(auth.encode()).decode())
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    def test_health_open(self, server):
        status, _ = self._get(server.port, "/health")
        assert status == 200

    def test_sql_requires_auth(self, server):
        status, body = self._get(server.port, "/v1/sql?sql=SELECT%201")
        assert status == 401
        status, body = self._get(server.port, "/v1/sql?sql=SELECT%201",
                                 auth="alice:wrong")
        assert status == 401
        status, body = self._get(server.port, "/v1/sql?sql=SELECT%201",
                                 auth="alice:s3cret")
        assert status == 200
        assert body["output"]


class TestMysqlAuth:
    def _connect(self, port, user, password):
        sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        buf = b""
        header = self._read(sock, 4)
        n = header[0] | (header[1] << 8) | (header[2] << 16)
        greeting = self._read(sock, n)
        assert greeting[0] == 0x0A
        # server version is NUL-terminated after the protocol byte
        ver_end = greeting.index(b"\x00", 1)
        pos = ver_end + 1 + 4  # thread id
        salt1 = greeting[pos:pos + 8]
        pos += 8 + 1  # filler
        pos += 2 + 1 + 2 + 2 + 1 + 10  # caps lo, charset, status, caps hi, len, reserved
        salt2 = greeting[pos:pos + 12]
        salt = salt1 + salt2
        scramble = mysql_native_scramble(password, salt) if password else b""
        caps = 0x0200 | 0x8000  # protocol 41 | secure connection
        resp = (struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
                + bytes([0x21]) + b"\x00" * 23
                + user.encode() + b"\x00"
                + bytes([len(scramble)]) + scramble)
        sock.sendall(struct.pack("<I", len(resp))[:3] + bytes([header[3] + 1]) + resp)
        header = self._read(sock, 4)
        n = header[0] | (header[1] << 8) | (header[2] << 16)
        pkt = self._read(sock, n)
        sock.close()
        return pkt[0]

    def _read(self, sock, n):
        buf = b""
        while len(buf) < n:
            c = sock.recv(n - len(buf))
            assert c, "closed"
            buf += c
        return buf

    def test_scramble_auth(self, qe):
        from greptimedb_tpu.servers.mysql import MysqlServer

        srv = MysqlServer(qe, port=0, user_provider=PROVIDER)
        srv.start()
        try:
            assert self._connect(srv.port, "alice", "s3cret") == 0x00  # OK
            assert self._connect(srv.port, "alice", "wrong") == 0xFF  # ERR
            assert self._connect(srv.port, "nobody", "x") == 0xFF
        finally:
            srv.shutdown()


class TestPostgresAuth:
    def test_cleartext_auth(self, qe):
        from greptimedb_tpu.servers.postgres import PostgresServer

        srv = PostgresServer(qe, port=0, user_provider=PROVIDER)
        srv.start()
        try:
            assert self._login(srv.port, "alice", "s3cret")
            assert not self._login(srv.port, "alice", "wrong")
        finally:
            srv.shutdown()

    def _login(self, port, user, password) -> bool:
        sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        body = struct.pack("!I", 196608)
        body += b"user\x00" + user.encode() + b"\x00"
        body += b"database\x00public\x00\x00"
        sock.sendall(struct.pack("!I", len(body) + 4) + body)
        # expect AuthenticationCleartextPassword
        tag = sock.recv(1)
        assert tag == b"R"
        (length,) = struct.unpack("!I", self._read(sock, 4))
        (code,) = struct.unpack("!I", self._read(sock, length - 4))
        assert code == 3
        pwd = password.encode() + b"\x00"
        sock.sendall(b"p" + struct.pack("!I", len(pwd) + 4) + pwd)
        tag = sock.recv(1)
        ok = False
        if tag == b"R":
            (length,) = struct.unpack("!I", self._read(sock, 4))
            (code,) = struct.unpack("!I", self._read(sock, length - 4))
            ok = code == 0
        sock.close()
        return ok

    def _read(self, sock, n):
        buf = b""
        while len(buf) < n:
            c = sock.recv(n - len(buf))
            assert c, "closed"
            buf += c
        return buf
