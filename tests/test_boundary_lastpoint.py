"""Lastpoint boundary fast path: first/last aggregates gather per-series
run-boundary rows from the (tags, ts, seq)-sorted SST segments instead of
reducing the whole scan (physical.py::_boundary_firstlast).

Every test cross-checks the fast path against the general segment kernel
(fast path monkeypatched off), the strategy the prepared-plane work used
(SURVEY.md §4: differential oracles)."""

import numpy as np
import pytest

from greptimedb_tpu.catalog import Catalog, MemoryKv
from greptimedb_tpu.query import QueryEngine
from greptimedb_tpu.query.physical import PhysicalExecutor
from greptimedb_tpu.storage import RegionEngine
from greptimedb_tpu.storage.engine import EngineConfig


@pytest.fixture
def db(tmp_path, monkeypatch):
    # tiny tables: every row is a boundary candidate, which the benefit
    # threshold would veto — force the path on so correctness is tested
    monkeypatch.setattr(
        "greptimedb_tpu.query.physical._BOUNDARY_MAX_FRACTION", 1.01)
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "data")))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    yield qe
    engine.close()


def _mk(db, append_mode=False, two_tags=False):
    tags = "host STRING, dc STRING," if two_tags else "host STRING,"
    pk = "PRIMARY KEY (host, dc)" if two_tags else "PRIMARY KEY (host)"
    opts = " WITH (append_mode = 'true')" if append_mode else ""
    db.execute_one(
        f"CREATE TABLE t ({tags} v DOUBLE, w DOUBLE, ts TIMESTAMP(3) "
        f"NOT NULL, TIME INDEX (ts), {pk}){opts}")


def _ins(db, rows, two_tags=False):
    cols = "(host, dc, v, w, ts)" if two_tags else "(host, v, w, ts)"
    vals = ", ".join(
        "(" + ", ".join(
            f"'{x}'" if isinstance(x, str) else str(x) for x in r) + ")"
        for r in rows)
    db.execute_one(f"INSERT INTO t {cols} VALUES {vals}")


def _flush(db):
    info = db.catalog.table("public", "t")
    db.region_engine.flush(info.region_ids[0])


SQL = ("SELECT host, last_value(v ORDER BY ts) AS lv, "
       "first_value(w ORDER BY ts) AS fw FROM t GROUP BY host "
       "ORDER BY host")


def _run_both(db, sql):
    """(fast-path rows, general-kernel rows, fast path actually used)."""
    fast = db.execute_one(sql)
    used = "boundary+" in (db.executor.last_path or "")
    orig = PhysicalExecutor._boundary_firstlast
    PhysicalExecutor._boundary_firstlast = (
        lambda self, *a, **k: None)
    try:
        slow = db.execute_one(sql)
    finally:
        PhysicalExecutor._boundary_firstlast = orig
    return fast.rows(), slow.rows(), used


def test_multi_file_and_memtable(db):
    """Winners spread over two SSTs and an unsorted memtable tail."""
    _mk(db)
    _ins(db, [("a", 1.0, 10.0, 1000), ("a", 2.0, 20.0, 2000),
              ("b", 3.0, 30.0, 1500)])
    _flush(db)
    _ins(db, [("a", 4.0, 40.0, 3000), ("b", 5.0, 50.0, 500),
              ("c", 6.0, 60.0, 100)])
    _flush(db)
    # memtable rows deliberately out of time order within a series
    _ins(db, [("b", 7.0, 70.0, 4000), ("b", 8.0, 80.0, 200),
              ("c", 9.0, 90.0, 5000)])
    fast, slow, used = _run_both(db, SQL)
    assert used
    assert fast == slow
    assert fast == [["a", 4.0, 10.0], ["b", 7.0, 80.0], ["c", 9.0, 60.0]]


def test_lww_duplicate_instants_across_files(db):
    """Same (series, ts) written in both files: max seq must win, for
    both the max-ts instant (last) and the min-ts instant (first)."""
    _mk(db)
    _ins(db, [("a", 1.0, 10.0, 1000), ("a", 2.0, 20.0, 5000)])
    _flush(db)
    # overwrite both instants with newer versions in a later file
    _ins(db, [("a", 11.0, 110.0, 1000), ("a", 12.0, 120.0, 5000)])
    _flush(db)
    fast, slow, used = _run_both(db, SQL)
    assert used
    assert fast == slow
    assert fast == [["a", 12.0, 110.0]]


def test_duplicate_instants_within_one_file(db):
    """Two versions of one instant inside a single sorted segment: the
    sub-run end (max seq) is the candidate, not the run start."""
    _mk(db)
    _ins(db, [("a", 1.0, 10.0, 1000)])
    _ins(db, [("a", 2.0, 20.0, 1000)])  # newer version, same instant
    _ins(db, [("a", 3.0, 30.0, 2000)])
    _flush(db)
    fast, slow, used = _run_both(db, SQL)
    assert used
    assert fast == slow
    assert fast == [["a", 3.0, 20.0]]


def test_delete_tombstone_disables_path(db):
    """A tombstone can shadow the newest row; the fast path must bow out
    and the general kernel must produce the pre-delete answer."""
    _mk(db)
    _ins(db, [("a", 1.0, 10.0, 1000), ("a", 2.0, 20.0, 2000)])
    _flush(db)
    db.execute_one("DELETE FROM t WHERE host = 'a' AND ts = 2000")
    _flush(db)
    fast, slow, used = _run_both(db, SQL)
    assert not used
    assert fast == slow
    assert fast == [["a", 1.0, 10.0]]


def test_where_disables_path(db):
    """Any residual WHERE can unseat boundary rows — general kernel."""
    _mk(db)
    _ins(db, [("a", 1.0, 10.0, 1000), ("a", 2.0, 20.0, 2000),
              ("a", 3.0, 30.0, 3000)])
    _flush(db)
    sql = ("SELECT host, last_value(v ORDER BY ts) AS lv FROM t "
           "WHERE v < 2.5 GROUP BY host")
    fast, slow, used = _run_both(db, sql)
    assert not used
    assert fast == slow
    assert fast == [["a", 2.0]]


def test_mixed_agg_disables_path(db):
    """count(*) alongside last_value needs true row counts."""
    _mk(db)
    _ins(db, [("a", 1.0, 10.0, 1000), ("a", 2.0, 20.0, 2000)])
    _flush(db)
    sql = ("SELECT host, last_value(v ORDER BY ts) AS lv, count(*) AS c "
           "FROM t GROUP BY host")
    fast, slow, used = _run_both(db, sql)
    assert not used
    assert fast == slow
    assert fast == [["a", 2.0, 2]]


def test_group_by_tag_subset(db):
    """Group by one tag of a two-tag primary key: winners still sit on
    full-pk run boundaries."""
    _mk(db, two_tags=True)
    _ins(db, [("a", "x", 1.0, 10.0, 1000), ("a", "y", 2.0, 20.0, 5000),
              ("a", "x", 3.0, 30.0, 4000), ("b", "x", 4.0, 40.0, 100)],
         two_tags=True)
    _flush(db)
    fast, slow, used = _run_both(db, SQL)
    assert used
    assert fast == slow
    assert fast == [["a", 2.0, 10.0], ["b", 4.0, 40.0]]


def test_append_mode_large_random(db):
    """Randomized differential: 20k rows, 50 series, three flushes plus a
    memtable tail, append mode (no dedup)."""
    _mk(db, append_mode=True)
    rng = np.random.default_rng(42)
    info = db.catalog.table("public", "t")
    rid = info.region_ids[0]
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    names = np.asarray([f"h{i:02d}" for i in range(50)], dtype=object)
    for part in range(4):  # 3 flushed + 1 memtable
        n = 5000
        codes = rng.integers(0, 50, n).astype(np.int32)
        # distinct ts per row (no ties): ties have no defined winner in
        # append mode, so the two paths could legitimately differ
        ts = rng.permutation(n).astype(np.int64) * 7 + part * 40000
        batch = RecordBatch(info.schema, {
            "host": DictVector(codes, names),
            "v": rng.uniform(0, 100, n),
            "w": rng.uniform(0, 100, n),
            "ts": ts,
        })
        db.region_engine.put(rid, batch)
        if part < 3:
            db.region_engine.flush(rid)
    fast, slow, used = _run_both(db, SQL)
    assert used
    assert fast == slow


def test_global_first_last_no_group(db):
    _mk(db)
    _ins(db, [("a", 1.0, 10.0, 1000), ("b", 2.0, 20.0, 9000),
              ("c", 3.0, 30.0, 500)])
    _flush(db)
    sql = ("SELECT last_value(v ORDER BY ts) AS lv, "
           "first_value(w ORDER BY ts) AS fw FROM t")
    fast, slow, used = _run_both(db, sql)
    assert used
    assert fast == slow
    assert fast == [[2.0, 30.0]]
