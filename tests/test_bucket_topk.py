"""Bucket-top-k scan narrowing (physical.py::_bucket_topk_ranges):
`GROUP BY date_bin(...) ORDER BY <bucket> DESC LIMIT k` scans only the
newest k buckets, widening geometrically when data is sparse. Every case
cross-checks against the un-narrowed execution."""

import numpy as np
import pytest

from greptimedb_tpu.catalog import Catalog, MemoryKv
from greptimedb_tpu.query import QueryEngine
from greptimedb_tpu.query.physical import PhysicalExecutor
from greptimedb_tpu.storage import RegionEngine
from greptimedb_tpu.storage.engine import EngineConfig


@pytest.fixture
def db(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "data")))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    yield qe
    engine.close()


def _seed(db, minutes=120, per_min=20, gap=None):
    db.execute_one(
        "CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP(3) NOT NULL, "
        "TIME INDEX (ts), PRIMARY KEY (host)) WITH (append_mode='true')")
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    info = db.catalog.table("public", "m")
    rng = np.random.default_rng(2)
    rows = []
    for mi in range(minutes):
        if gap and gap[0] <= mi < gap[1]:
            continue  # sparse stretch: no data at all
        for p in range(per_min):
            rows.append((mi * 60000 + p * 2000,
                         round(float(rng.uniform(0, 100)), 6)))
    ts = np.asarray([r[0] for r in rows], dtype=np.int64)
    v = np.asarray([r[1] for r in rows])
    codes = np.zeros(len(rows), dtype=np.int32)
    db.region_engine.put(info.region_ids[0], RecordBatch(info.schema, {
        "host": DictVector(codes, np.asarray(["h0"], dtype=object)),
        "v": v, "ts": ts}))
    db.region_engine.flush(info.region_ids[0])
    return rows


def _run_both(db, sql):
    fast = db.execute_one(sql)
    used = (db.executor.last_path or "").startswith("bucket_topk+")
    orig = PhysicalExecutor._bucket_topk_ranges
    PhysicalExecutor._bucket_topk_ranges = lambda self, *a, **k: None
    try:
        slow = db.execute_one(sql)
    finally:
        PhysicalExecutor._bucket_topk_ranges = orig
    return fast.rows(), slow.rows(), used


DESC_SQL = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, max(v), "
            "count(*) FROM m GROUP BY minute ORDER BY minute DESC LIMIT 5")


def test_desc_limit_matches_full(db):
    _seed(db)
    fast, slow, used = _run_both(db, DESC_SQL)
    assert used
    assert fast == slow
    assert len(fast) == 5
    assert fast[0][0] == 119 * 60000  # newest bucket first


def test_with_ts_upper_bound(db):
    _seed(db)
    cutoff = 90 * 60000
    sql = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, max(v) "
           f"FROM m WHERE ts < {cutoff} GROUP BY minute "
           "ORDER BY minute DESC LIMIT 5")
    fast, slow, used = _run_both(db, sql)
    assert used
    assert fast == slow
    assert fast[0][0] == 89 * 60000


def test_sparse_data_widens(db):
    # newest 40 minutes empty: the first narrow attempt finds nothing
    # and the widening loop must still produce the right 5 buckets
    _seed(db, minutes=120, gap=(80, 120))
    fast, slow, used = _run_both(db, DESC_SQL)
    assert fast == slow
    assert len(fast) == 5
    assert fast[0][0] == 79 * 60000


def test_asc_limit(db):
    _seed(db)
    sql = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, avg(v) "
           "FROM m GROUP BY minute ORDER BY minute ASC LIMIT 3")
    fast, slow, used = _run_both(db, sql)
    assert used
    assert fast == slow
    assert [r[0] for r in fast] == [0, 60000, 120000]


def test_offset_counts_toward_k(db):
    _seed(db)
    sql = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, max(v) "
           "FROM m GROUP BY minute ORDER BY minute DESC LIMIT 4 OFFSET 3")
    fast, slow, used = _run_both(db, sql)
    assert fast == slow
    assert fast[0][0] == (119 - 3) * 60000


def test_fewer_buckets_than_limit(db):
    _seed(db, minutes=3)
    fast, slow, used = _run_both(db, DESC_SQL)
    assert fast == slow
    assert len(fast) == 3  # all of them, full range covered


def test_non_bucket_order_not_narrowed(db):
    _seed(db, minutes=20)
    sql = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, max(v) AS "
           "mx FROM m GROUP BY minute ORDER BY mx DESC LIMIT 5")
    fast, slow, used = _run_both(db, sql)
    assert not used  # ordering by the aggregate needs every bucket
    assert fast == slow


def test_having_disables(db):
    _seed(db, minutes=20)
    sql = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, count(*) "
           "AS c FROM m GROUP BY minute HAVING c > 0 "
           "ORDER BY minute DESC LIMIT 5")
    fast, slow, used = _run_both(db, sql)
    assert not used
    assert fast == slow


@pytest.mark.parametrize("seed", range(8))
def test_randomized_differential(db, seed):
    """Random irregular timestamps (incl. negatives), random bucket step,
    limit/offset, direction, and ts bounds — fast path vs full execution
    must agree exactly. Regular grids hide bucket-alignment bugs."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    rng = np.random.default_rng(1000 + seed)
    db.execute_one(
        "CREATE TABLE r (host STRING, v DOUBLE, ts TIMESTAMP(3) NOT NULL, "
        "TIME INDEX (ts), PRIMARY KEY (host)) WITH (append_mode='true')")
    info = db.catalog.table("public", "r")
    n = int(rng.integers(200, 2000))
    # irregular, possibly negative, heavily clustered timestamps
    ts = np.unique(rng.choice(
        rng.integers(-(10 ** 7), 10 ** 7, 40), n)
        + rng.integers(0, 50000, n)).astype(np.int64)
    n = len(ts)
    codes = rng.integers(0, 3, n).astype(np.int32)
    names = np.asarray(["a", "b", "c"], dtype=object)
    db.region_engine.put(info.region_ids[0], RecordBatch(info.schema, {
        "host": DictVector(codes, names),
        "v": rng.uniform(0, 100, n), "ts": ts}))
    db.region_engine.flush(info.region_ids[0])

    any_used = False
    for _ in range(6):
        step_ms = int(rng.choice([1000, 7000, 60000, 3600000]))
        k = int(rng.integers(1, 8))
        off = int(rng.integers(0, 4)) if rng.random() < 0.4 else 0
        desc = rng.random() < 0.7
        where = ""
        if rng.random() < 0.5:
            lo, hi = sorted(rng.integers(-(10 ** 7), 2 * 10 ** 7, 2))
            where = f"WHERE ts >= {lo} AND ts < {hi} "
        agg = rng.choice(["max(v)", "min(v)", "count(*)", "avg(v)"])
        sql = (f"SELECT date_bin(INTERVAL '{step_ms // 1000} seconds', ts)"
               f" AS b, {agg} FROM r {where}GROUP BY b "
               f"ORDER BY b {'DESC' if desc else 'ASC'} LIMIT {k}"
               + (f" OFFSET {off}" if off else ""))
        fast, slow, used = _run_both(db, sql)
        any_used = any_used or used
        assert fast == slow, sql
    # the differential is vacuous if narrowing never engages
    assert any_used
