"""Seeded, replayable chaos suite over the fault-injection layer
(greptimedb_tpu/fault): deterministic schedules at the I/O seams, the
shared retry/backoff policy, graceful router degradation, and the
Jepsen-style cluster scenarios — datanode death mid-write, dropped
heartbeats until phi fires, injected object-store errors mid-scan —
asserting zero acknowledged-write loss and correct post-recovery query
results.

Every test is marked `chaos`; a failing run prints the GTPU_CHAOS_SEED
that drove its schedule (tests/conftest.py) so any red run replays
exactly."""

import os
import time

import numpy as np
import pytest

from greptimedb_tpu.cluster import Cluster
from greptimedb_tpu.datatypes import (
    ColumnSchema,
    DataType,
    DictVector,
    RecordBatch,
    Schema,
    SemanticType,
)
from greptimedb_tpu.fault import (
    FAULTS,
    Fault,
    FaultError,
    FaultRegistry,
    Unavailable,
    retry_call,
)
from greptimedb_tpu.meta.metasrv import MetasrvOptions
from greptimedb_tpu.objectstore import MemoryStore, ObjectStoreError
from greptimedb_tpu.partition.rule import PartitionBound, RangePartitionRule
from greptimedb_tpu.utils.metrics import (
    DEGRADED,
    FAULT_INJECTIONS,
    REGISTRY,
    RETRY_ATTEMPTS,
    RETRY_EXHAUSTED,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def clean_faults():
    """Chaos schedules must never leak across tests."""
    FAULTS.reset()
    yield
    FAULTS.reset()


# ---- schedule determinism + env arming --------------------------------------


class TestFaultSchedules:
    def test_same_seed_same_schedule(self):
        a = Fault(kind="fail", prob=0.3, seed=1234)
        b = Fault(kind="fail", prob=0.3, seed=1234)
        sa = [a.should_fire() for _ in range(200)]
        sb = [b.should_fire() for _ in range(200)]
        assert sa == sb
        assert any(sa) and not all(sa)
        # a different seed produces a different schedule
        c = Fault(kind="fail", prob=0.3, seed=1235)
        assert [c.should_fire() for _ in range(200)] != sa

    def test_fail_nth_window(self):
        f = Fault(kind="fail", nth=3, times=2)
        assert [f.should_fire() for _ in range(6)] == \
            [False, False, True, True, False, False]

    def test_env_grammar(self):
        r = FaultRegistry()
        r.arm_from_env(
            "objectstore.read=fail,nth:3,times:2;"
            "flight.do_get=latency,arg:0.05,prob:0.5,seed:7;"
            "heartbeat.send=fail,@node:dn-1")
        assert r.armed("objectstore.read")
        assert r.armed("flight.do_get")
        assert r._points["heartbeat.send"].match == {"node": "dn-1"}
        with pytest.raises(ValueError):
            r.arm_from_env("no.such.point=fail")
        with pytest.raises(ValueError):
            r.arm_from_env("wal.append=fail,bogus:1")

    def test_match_labels_do_not_consume_schedule(self):
        FAULTS.arm("heartbeat.send",
                   Fault(kind="fail", nth=1, match={"node": "dn-1"}))
        FAULTS.fire("heartbeat.send", node="dn-0")  # no match: no draw
        with pytest.raises(FaultError):
            FAULTS.fire("heartbeat.send", node="dn-1")

    def test_unarmed_point_is_free(self):
        FAULTS.fire("objectstore.read")  # no-op, no counter
        data, fail_after = FAULTS.mangle("objectstore.write", b"x")
        assert data == b"x" and not fail_after

    def test_match_applies_to_data_path_too(self):
        # a @node matcher on a data point must not fire for unlabeled
        # (or differently-labeled) calls — and must not consume the draw
        FAULTS.arm("wal.append", Fault(kind="fail", nth=1,
                                       match={"node": "dn-1"}))
        data, fail_after = FAULTS.mangle("wal.append", b"x")
        assert data == b"x" and not fail_after
        with pytest.raises(FaultError):
            FAULTS.mangle("wal.append", b"x", node="dn-1")


# ---- retry policy + object store seam ---------------------------------------


class TestRetryAndObjectStore:
    def test_fail_nth_is_absorbed_by_retry(self):
        store = MemoryStore()
        store.write("k", b"payload")
        before = RETRY_ATTEMPTS.get(point="objectstore.read")
        FAULTS.arm("objectstore.read", Fault(kind="fail", nth=1))
        assert store.read("k") == b"payload"
        assert RETRY_ATTEMPTS.get(point="objectstore.read") == before + 1

    def test_persistent_failure_exhausts_and_counts(self):
        store = MemoryStore()
        store.write("k", b"payload")
        before = RETRY_EXHAUSTED.get(point="objectstore.read")
        FAULTS.arm("objectstore.read", Fault(kind="fail"))
        with pytest.raises(FaultError):
            store.read("k")
        assert RETRY_EXHAUSTED.get(point="objectstore.read") == before + 1

    def test_not_found_is_not_retried(self):
        store = MemoryStore()
        before = RETRY_ATTEMPTS.get(point="objectstore.read")
        with pytest.raises(ObjectStoreError):
            store.read("missing")
        assert RETRY_ATTEMPTS.get(point="objectstore.read") == before

    def test_torn_write_persists_partial_and_raises(self):
        store = MemoryStore()
        FAULTS.arm("objectstore.write", Fault(kind="torn", arg=0.4, nth=1))
        with pytest.raises(FaultError) as ei:
            store.write("t", b"0123456789")
        assert not ei.value.transient
        FAULTS.reset()
        assert store.read("t") == b"0123"  # the torn object is real

    def test_torn_read_surfaces_error_never_truncated_bytes(self):
        store = MemoryStore()
        store.write("k", b"0123456789")
        FAULTS.arm("objectstore.read", Fault(kind="torn", arg=0.5, nth=1))
        with pytest.raises(FaultError) as ei:
            store.read("k")
        assert not ei.value.transient
        FAULTS.reset()
        assert store.read("k") == b"0123456789"  # backing data untouched

    def test_every_counter_renders_at_metrics(self):
        store = MemoryStore()
        store.write("k", b"v")
        FAULTS.arm("objectstore.read", Fault(kind="fail", nth=1))
        store.read("k")
        text = REGISTRY.render()
        assert 'greptimedb_tpu_fault_injections_total{' \
            'kind="fail",point="objectstore.read"}' in text
        assert 'greptimedb_tpu_retry_attempts_total{' \
            'point="objectstore.read"}' in text

    def test_retry_call_deadline(self):
        from greptimedb_tpu.fault import RetryPolicy

        calls = []

        def op():
            calls.append(1)
            raise FaultError("flight.do_get")
        t0 = time.monotonic()
        with pytest.raises(FaultError):
            retry_call(op, point="flight.do_get",
                       policy=RetryPolicy(max_attempts=100, base_s=0.05,
                                          cap_s=0.05, deadline_s=0.2))
        assert time.monotonic() - t0 < 2.0
        assert 2 <= len(calls) < 100

    def test_exhaustion_raises_the_last_typed_error(self):
        """Each attempt may fail differently (fault, then timeout, then
        connection refused); the exhausted call must surface the LAST
        error — the one describing the state the caller actually hit."""
        from greptimedb_tpu.fault import RetryPolicy

        errors = [FaultError("flight.do_get", kind="fail"),
                  FaultError("flight.do_get", kind="latency"),
                  FaultError("flight.do_get", kind="partition")]
        it = iter(errors)

        def op():
            raise next(it)

        with pytest.raises(FaultError) as ei:
            retry_call(op, point="flight.do_get",
                       policy=RetryPolicy(max_attempts=3, base_s=0.0,
                                          cap_s=0.0, deadline_s=5.0))
        assert ei.value is errors[-1], \
            "exhaustion must re-raise the final attempt's error"

    def test_jitter_stays_within_bounds_seeded(self):
        """Full-jitter backoff: sleep_i = U(0, min(cap, base*2^i)),
        bit-replayable under a seeded RNG."""
        import random as _random

        from greptimedb_tpu.fault import RetryPolicy

        policy = RetryPolicy(max_attempts=10, base_s=0.02, cap_s=0.5,
                             deadline_s=10.0)
        a = [policy.backoff_s(i, _random.Random(99)) for i in range(12)]
        b = [policy.backoff_s(i, _random.Random(99)) for i in range(12)]
        assert a == b, "seeded jitter must replay exactly"
        for i, delay in enumerate(a):
            assert 0.0 <= delay <= min(policy.cap_s,
                                       policy.base_s * (2 ** i))
        # the cap binds once base*2^i crosses it
        assert all(d <= policy.cap_s for d in a)

    def test_zero_budget_policy_fails_fast(self):
        """max_attempts=1 is a no-retry policy: one call, immediate
        raise, exhaustion counted, no sleeping."""
        from greptimedb_tpu.fault import RetryPolicy

        calls = []

        def op():
            calls.append(1)
            raise FaultError("wal.append")

        before = RETRY_EXHAUSTED.get(point="wal.append")
        t0 = time.monotonic()
        with pytest.raises(FaultError):
            retry_call(op, point="wal.append",
                       policy=RetryPolicy(max_attempts=1, base_s=1.0,
                                          cap_s=1.0, deadline_s=10.0))
        assert len(calls) == 1
        assert time.monotonic() - t0 < 0.5, "zero-budget call slept"
        assert RETRY_EXHAUSTED.get(point="wal.append") == before + 1


# ---- WAL seams --------------------------------------------------------------


def _wal_schema():
    return Schema([
        ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND,
                     SemanticType.TIMESTAMP),
        ColumnSchema("hostname", DataType.STRING, SemanticType.TAG),
        ColumnSchema("v", DataType.FLOAT64),
    ])


def _wal_batch(schema, i):
    return RecordBatch(schema, {
        "ts": np.asarray([i], dtype=np.int64),
        "hostname": DictVector.encode(["h"]),
        "v": np.asarray([float(i)], dtype=np.float64)})


class TestWalChaos:
    def test_torn_append_unacked_and_later_writes_survive(self, tmp_path):
        """A torn local-WAL append must NOT be acknowledged, and must not
        orphan later acknowledged frames at replay (self-repair)."""
        from greptimedb_tpu.storage.wal import Wal

        s = _wal_schema()
        w = Wal(str(tmp_path), sync=False)
        w.append(1, 0, 0, _wal_batch(s, 0))
        FAULTS.arm("wal.append", Fault(kind="torn", arg=0.5, nth=1))
        with pytest.raises(FaultError):
            w.append(1, 1, 0, _wal_batch(s, 1))
        FAULTS.reset()
        w.append(1, 1, 0, _wal_batch(s, 2))  # acked after the torn one
        entries = list(w.replay(1))
        assert [e.seq for e in entries] == [0, 1]
        assert entries[1].batch.columns["v"].tolist() == [2.0]

    def test_replay_short_read_is_retried_not_truncated(self, tmp_path):
        """An injected short read during replay must not be mistaken for
        a torn tail: durable frames survive and replay retries."""
        from greptimedb_tpu.storage.wal import Wal

        s = _wal_schema()
        w = Wal(str(tmp_path), sync=False)
        for i in range(4):
            w.append(1, i, 0, _wal_batch(s, i))
        w.close()
        w2 = Wal(str(tmp_path), sync=False)
        FAULTS.arm("wal.replay", Fault(kind="short_read", arg=0.3, nth=1))
        assert [e.seq for e in w2.replay(1)] == [0, 1, 2, 3]

    def test_remote_wal_torn_segment_isolated(self):
        """Remote-WAL segments are separate immutable objects: a torn
        (unacked) segment never shadows later acknowledged segments."""
        from greptimedb_tpu.storage.remote_wal import RemoteWal

        s = _wal_schema()
        rw = RemoteWal(MemoryStore())
        rw.append(5, 0, 0, _wal_batch(s, 0))
        FAULTS.arm("wal.append", Fault(kind="torn", arg=0.5, nth=1))
        with pytest.raises(FaultError):
            rw.append(5, 1, 0, _wal_batch(s, 1))
        FAULTS.reset()
        rw.append(5, 1, 0, _wal_batch(s, 2))
        assert [e.seq for e in rw.replay(5)] == [0, 1]


# ---- flow tick errors (satellite) -------------------------------------------


class TestFlowTickErrors:
    def test_incremental_tick_failure_is_counted_not_printed(self):
        from types import SimpleNamespace

        from greptimedb_tpu.catalog.kv import MemoryKv
        from greptimedb_tpu.flow.engine import FlowEngine, FlowInfo
        from greptimedb_tpu.utils.metrics import FLOW_TICK_ERRORS

        eng = FlowEngine.__new__(FlowEngine)
        eng.kv = MemoryKv()
        src = SimpleNamespace(region_ids=[1], append_mode=True)
        eng.qe = SimpleNamespace(
            _table=lambda name, ctx: src,
            region_engine=SimpleNamespace(
                region=lambda rid: SimpleNamespace(data_version=1)))
        info = FlowInfo(name="chaos_flow", db="public", sink_table="s",
                        source_table="t", sql="SELECT v FROM t",
                        incremental=True)
        before = FLOW_TICK_ERRORS.get(flow="chaos_flow")
        assert eng._tick_flow(info) == 0  # failure deferred to next tick
        assert FLOW_TICK_ERRORS.get(flow="chaos_flow") == before + 1
        # the fold boundary did NOT advance: next tick retries
        assert info.last_version == -1


# ---- in-process cluster scenarios -------------------------------------------

CREATE = (
    "CREATE TABLE cpu (host STRING, region STRING, usage_user DOUBLE, "
    "usage_system DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, region))"
)


def _make_cluster(tmp_path, n=3):
    return Cluster(str(tmp_path), num_datanodes=n, opts=MetasrvOptions())


def _host_rule(*splits):
    bounds = [PartitionBound((s,)) for s in splits] + [PartitionBound(())]
    return RangePartitionRule(["host"], bounds)


def _seed_rows(cluster, n_hosts=6, points_per_host=4):
    rows = []
    for h in range(n_hosts):
        for t in range(points_per_host):
            rows.append(f"('host{h}', 'us-west', {10.0 + h}, {1.0 * t}, "
                        f"{1000 * (t + 1)})")
    cluster.sql(
        "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
        "VALUES " + ", ".join(rows))


class TestClusterChaos:
    def test_scan_survives_injected_sst_read_errors(self, tmp_path):
        """Object-store errors mid-scan are absorbed by the retry layer:
        the query answers correctly and the retries are observable."""
        c = _make_cluster(tmp_path)
        try:
            info = c.create_partitioned_table(CREATE,
                                              _host_rule("host2", "host4"))
            _seed_rows(c)
            for rid in info.region_ids:
                c.router.flush(rid)  # data must come back from SSTs
            before = RETRY_ATTEMPTS.get(point="objectstore.read")
            FAULTS.arm("objectstore.read", Fault(kind="fail", nth=1))
            res = c.sql("SELECT count(*) FROM cpu")
            assert res.rows()[0][0] == 24
            assert RETRY_ATTEMPTS.get(point="objectstore.read") == before + 1
        finally:
            c.close()

    def test_dropped_heartbeats_until_phi_fires_failover(self, tmp_path):
        """Nemesis-targeted heartbeat drops: ONE node's beats vanish, phi
        climbs, failover moves its regions, data stays queryable —
        without killing the process (the asymmetric-partition shape)."""
        c = _make_cluster(tmp_path)
        try:
            info = c.create_partitioned_table(CREATE,
                                              _host_rule("host2", "host4"))
            _seed_rows(c)
            for rid in info.region_ids:
                c.router.flush(rid)
            t = 0.0
            for _ in range(10):
                c.beat_all(t)
                t += 3000.0
            rid0 = info.region_ids[0]
            victim = c.metasrv.routes.get(
                str(rid0 >> 32)).region(rid0).leader_node
            FAULTS.arm("heartbeat.send",
                       Fault(kind="fail", match={"node": victim}))
            for _ in range(20):
                c.beat_all(t)
                t += 3000.0
            assert FAULT_INJECTIONS.total(point="heartbeat.send",
                                          kind="fail") >= 20
            started = c.tick(t)
            assert started, "phi should fire for the silenced node"
            c.beat_all(t)  # deliver OPEN_REGION to the survivors
            route = c.metasrv.routes.get(str(rid0 >> 32))
            assert route.region(rid0).leader_node != victim
            assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 24
        finally:
            c.close()

    def test_stale_route_degrades_and_recovers(self, tmp_path):
        """A stale route (engine no longer owns the region) re-resolves
        transparently instead of surfacing a KeyError."""
        c = _make_cluster(tmp_path)
        try:
            info = c.create_partitioned_table(CREATE,
                                              _host_rule("host2", "host4"))
            _seed_rows(c)
            rid = info.region_ids[0]
            owner = c.metasrv.routes.get(str(rid >> 32)).region(rid).leader_node
            wrong = next(n for n in c.datanodes if n != owner)
            before = DEGRADED.get(point="router.scan")
            with c.router._lock:
                c.router._region_node[rid] = wrong
            scan = c.router.scan(rid)
            assert scan is not None and scan.num_rows == 8
            assert DEGRADED.get(point="router.scan") == before + 1
        finally:
            c.close()

    def test_no_live_datanode_surfaces_typed_unavailable(self, tmp_path):
        c = _make_cluster(tmp_path, n=2)
        try:
            info = c.create_partitioned_table(CREATE, _host_rule("host2"))
            _seed_rows(c)
            rid = info.region_ids[0]
            for dn in c.datanodes.values():
                dn.kill()
            with pytest.raises(Unavailable):
                c.router.scan(rid)
        finally:
            c.close()

    def test_seeded_datanode_crash_schedule(self, tmp_path):
        """`datanode.crash` armed with a deterministic schedule kills a
        node at a chosen beat; failover restores full query results."""
        c = _make_cluster(tmp_path)
        try:
            info = c.create_partitioned_table(CREATE,
                                              _host_rule("host2", "host4"))
            _seed_rows(c)
            for rid in info.region_ids:
                c.router.flush(rid)
            t = 0.0
            for _ in range(10):
                c.beat_all(t)
                t += 3000.0
            # beat_all visits dn-0, dn-1, dn-2 per round: call 31 is the
            # first node of round 11 — exactly one node dies, chosen by
            # the schedule, not the test
            FAULTS.arm("datanode.crash", Fault(kind="fail", nth=31))
            dead = None
            for _ in range(20):
                c.beat_all(t)
                t += 3000.0
                if dead is None:
                    dead = next((n for n, d in c.datanodes.items()
                                 if not d.alive), None)
            assert dead is not None, "the crash schedule should have fired"
            c.tick(t)
            c.beat_all(t)
            assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 24
        finally:
            c.close()


# ---- procedure crash-recovery (satellite) -----------------------------------


class TestFailoverProcedureCrashRecovery:
    """Crash the coordinator after EACH persisted step of a
    RegionFailoverProcedure and re-drive from the stored state via the
    procedure runner: completion must be idempotent — route swapped
    exactly once, no orphan region routes."""

    N_PHASES = 5  # start→select→activate→update_metadata→invalidate→end

    def _seeded_metasrv(self):
        from greptimedb_tpu.catalog.kv import MemoryKv
        from greptimedb_tpu.meta.metasrv import HeartbeatRequest, Metasrv
        from greptimedb_tpu.meta.route import RegionRoute, TableRoute

        kv = MemoryKv()
        ms = Metasrv(kv, MetasrvOptions())
        rid = (7 << 32) | 1
        ms.routes.put_new(TableRoute(table="7", regions=[
            RegionRoute(region_id=rid, leader_node="dn-0")]))
        t = 0.0
        for _ in range(5):
            for n in ("dn-0", "dn-1", "dn-2"):
                ms.handle_heartbeat(HeartbeatRequest(node_id=n, now_ms=t))
            t += 3000.0
        return kv, ms, rid, t - 3000.0

    @pytest.mark.parametrize("crash_after", range(6))
    def test_crash_after_each_persisted_step(self, crash_after):
        from greptimedb_tpu.meta.metasrv import (
            HeartbeatRequest,
            Metasrv,
            RegionFailoverProcedure,
        )
        from greptimedb_tpu.procedure.procedure import (
            ProcedureContext,
            ProcedureRecord,
        )

        kv, ms, rid, t = self._seeded_metasrv()
        proc = RegionFailoverProcedure(ms, state={
            "table": "7", "region_id": rid, "from_node": "dn-0",
            "now_ms": t})
        pid = ms.procedures.next_id()
        rec = ProcedureRecord(procedure_id=pid, type_name=proc.type_name,
                              state=proc.state, status="running")
        ms.procedures.store.save(rec)
        ctx = ProcedureContext(procedure_id=pid, manager=ms.procedures)
        for _ in range(crash_after):
            status = proc.step(ctx)
            rec.state = proc.state
            ms.procedures.store.save(rec)  # the crash-recovery point
            if status.done:
                break
        # CRASH: a new coordinator over the same shared KV; survivors
        # keep heartbeating it, then it recovers in-flight procedures
        ms2 = Metasrv(kv, MetasrvOptions())
        for n in ("dn-1", "dn-2"):
            ms2.handle_heartbeat(HeartbeatRequest(node_id=n, now_ms=t))
        recovered = {r.procedure_id: r for r in ms2.procedures.recover()}
        assert recovered[pid].status == "done"
        route = ms2.routes.get("7")
        entries = [r for r in route.regions if r.region_id == rid]
        assert len(entries) == 1, "exactly one route entry — no orphans"
        leader = entries[0].leader_node
        assert leader in ("dn-1", "dn-2") and leader != "dn-0"
        # idempotent completion: recovering again re-drives nothing and
        # the route does not swap a second time
        assert all(r.procedure_id != pid for r in ms2.procedures.recover())
        assert ms2.routes.get("7").region(rid).leader_node == leader


# ---- the full seeded scenario over real OS processes ------------------------


class TestProcessClusterChaos:
    def test_seeded_chaos_zero_acked_write_loss(self, tmp_path, monkeypatch):
        """The acceptance scenario: a 3-datanode ProcessCluster under a
        seeded schedule — datanode SIGKILL mid-write-stream, a fraction
        of heartbeats dropped, object-store read errors injected inside
        every child (via GTPU_CHAOS env inheritance) — finishes with
        zero acknowledged-write loss and correct post-recovery results."""
        from greptimedb_tpu.cluster.process_cluster import ProcessCluster

        seed = int(os.environ.get("GTPU_CHAOS_SEED", "0")) or 1234
        monkeypatch.setenv("GTPU_CHAOS_SEED", str(seed))
        # children arm from env at import: transient read errors under
        # every SST/WAL/manifest object read, absorbed by their retries
        monkeypatch.setenv(
            "GTPU_CHAOS",
            f"objectstore.read=fail,prob:0.02,seed:{seed}")
        # parent-side nemesis: drop a tenth of all heartbeats
        FAULTS.arm("heartbeat.send",
                   Fault(kind="fail", prob=0.1, seed=seed))
        c = ProcessCluster(str(tmp_path), num_datanodes=3,
                           opts=MetasrvOptions())
        try:
            t = 0.0
            for _ in range(5):
                c.beat_all(t)
                t += 3000.0
            c.sql("CREATE TABLE m (host STRING, v DOUBLE, "
                  "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")
            rid = c.catalog.table("public", "m").region_ids[0]
            owner = c.metasrv.routes.get(
                str(rid >> 32)).regions[0].leader_node
            for _ in range(3):
                c.beat_all(t)
                t += 3000.0
            acked = []
            for i in range(12):
                if i == 6:
                    # SIGKILL the owner in the middle of the write
                    # stream: rows 0..5 are acknowledged and unflushed —
                    # they exist ONLY in the shared remote WAL
                    c.kill_datanode(owner)
                try:
                    c.sql(f"INSERT INTO m VALUES ('h{i:02d}', {float(i)}, "
                          f"{1000 * (i + 1)})")
                    acked.append(i)
                except Exception:  # noqa: BLE001 — unacked writes may fail
                    pass
            assert 6 <= len(acked) < 12, "kill must land mid-stream"
            # survivors keep beating (minus the dropped ones); the dead
            # node's silence drives phi over the threshold
            for _ in range(30):
                c.beat_all(t)
                t += 3000.0
            assert c.tick(t), "failover should start"
            c.beat_all(t)  # deliver OPEN_REGION to the failover target
            rows = c.sql("SELECT host, v FROM m ORDER BY host").rows()
            got = {r[0]: r[1] for r in rows}
            for i in acked:
                assert got.get(f"h{i:02d}") == float(i), \
                    f"acknowledged write h{i:02d} lost"
            new_owner = c.metasrv.routes.get(
                str(rid >> 32)).regions[0].leader_node
            assert new_owner != owner
            # the run was observable: injected heartbeat drops counted
            assert FAULT_INJECTIONS.total(point="heartbeat.send",
                                          kind="fail") > 0
        finally:
            c.close()
