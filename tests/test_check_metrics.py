"""tools/check_metrics.py as a tier-1 gate: every registered metric is
prefixed, documented, and charted (the dashboard ships with the repo
like the reference's grafana/greptimedb.json)."""

import json
import os
import subprocess
import sys
from types import SimpleNamespace


def _load():
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "check_metrics.py")
    spec = importlib.util.spec_from_file_location("check_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_live_registry_is_clean():
    mod = _load()
    with open(mod.DASHBOARD) as f:
        text = f.read()
    json.loads(text)
    problems = mod.check(mod.registered_metrics(), text)
    assert problems == []


def test_detects_violations():
    mod = _load()
    bad = [
        SimpleNamespace(name="unprefixed_total", help="x"),
        SimpleNamespace(name="greptimedb_tpu_undocumented_total", help=" "),
        SimpleNamespace(name="greptimedb_tpu_uncharted_total", help="y"),
    ]
    problems = mod.check(bad, dashboard_text="{}")
    joined = "\n".join(problems)
    assert "prefix" in joined and "help" in joined and "panel" in joined
    # a clean set stays clean
    ok = [SimpleNamespace(name="greptimedb_tpu_fine_total", help="doc")]
    assert mod.check(ok, "greptimedb_tpu_fine_total") == []


def test_cli_exit_code_zero():
    mod = _load()
    out = subprocess.run(
        [sys.executable, mod.__file__], capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ok" in out.stdout
