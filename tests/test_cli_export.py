"""CLI backup tooling: export (schemas + parquet) then import into a
fresh data home restores everything (reference cmd/src/cli/export.rs:
CREATE TABLE dump + COPY TO parquet)."""

import argparse
import os


def test_export_import_roundtrip(tmp_path, capsys):
    from greptimedb_tpu import cli

    home1 = str(tmp_path / "h1")
    home2 = str(tmp_path / "h2")
    dump = str(tmp_path / "dump")

    engine, qe = cli.build_standalone(home1)
    qe.execute_one("CREATE DATABASE IF NOT EXISTS metricsdb")
    qe.execute_one(
        "CREATE TABLE cpu (host STRING, ts TIMESTAMP(3) NOT NULL, v DOUBLE,"
        " TIME INDEX (ts), PRIMARY KEY (host))")
    qe.execute_one(
        "INSERT INTO cpu VALUES ('a', 1000, 1.5), ('b', 2000, 2.5)")
    from greptimedb_tpu.query.engine import QueryContext

    qe.execute_one(
        "CREATE TABLE mem (ts TIMESTAMP(3) NOT NULL, used DOUBLE,"
        " TIME INDEX (ts))", QueryContext(db="metricsdb"))
    qe.execute_one("INSERT INTO mem VALUES (500, 9.0)",
                   QueryContext(db="metricsdb"))
    engine.close()

    cli.cmd_export(argparse.Namespace(data_home=home1, output_dir=dump,
                                      db=None))
    out = capsys.readouterr().out
    assert "exported public" in out and "exported metricsdb" in out
    assert os.path.exists(os.path.join(dump, "public", "create_tables.sql"))
    assert any(f.endswith(".parquet")
               for f in os.listdir(os.path.join(dump, "public")))

    cli.cmd_import(argparse.Namespace(data_home=home2, input_dir=dump))
    engine, qe = cli.build_standalone(home2)
    try:
        r = qe.execute_one("SELECT host, v FROM cpu ORDER BY ts")
        assert r.rows() == [["a", 1.5], ["b", 2.5]]
        r = qe.execute_one("SELECT used FROM mem",
                           QueryContext(db="metricsdb"))
        assert r.rows() == [[9.0]]
    finally:
        engine.close()
