"""Cluster tests: distributed DDL/insert/query, heartbeats, failover,
migration — the tests-integration/{cluster,region_failover,region_migration}
analog, single-process over shared storage (SURVEY.md §4)."""

import numpy as np
import pytest

from greptimedb_tpu.cluster import Cluster
from greptimedb_tpu.meta.metasrv import MetasrvOptions
from greptimedb_tpu.partition.rule import PartitionBound, RangePartitionRule

CREATE = (
    "CREATE TABLE cpu (host STRING, region STRING, usage_user DOUBLE, "
    "usage_system DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, region))"
)


def make_cluster(tmp_path, n=3):
    return Cluster(str(tmp_path), num_datanodes=n, opts=MetasrvOptions())


def host_rule(*splits):
    bounds = [PartitionBound((s,)) for s in splits] + [PartitionBound(())]
    return RangePartitionRule(["host"], bounds)


def seed(cluster, n_hosts=6, points_per_host=4):
    rows = []
    for h in range(n_hosts):
        for t in range(points_per_host):
            rows.append(
                f"('host{h}', 'us-west', {10.0 + h}, {1.0 * t}, {1000 * (t + 1)})"
            )
    cluster.sql(
        "INSERT INTO cpu (host, region, usage_user, usage_system, ts) VALUES "
        + ", ".join(rows)
    )


class TestClusterBasics:
    def test_partitioned_create_places_regions_across_nodes(self, tmp_path):
        c = make_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        assert len(info.region_ids) == 3
        placed_nodes = set()
        for rid in info.region_ids:
            route = c.metasrv.routes.get(str(rid >> 32))
            placed_nodes.add(route.region(rid).leader_node)
        assert len(placed_nodes) == 3  # round-robin spread
        c.close()

    def test_distributed_insert_and_query(self, tmp_path):
        c = make_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        res = c.sql("SELECT count(*) FROM cpu")
        assert res.rows()[0][0] == 24
        res = c.sql(
            "SELECT host, avg(usage_user) FROM cpu GROUP BY host ORDER BY host"
        )
        rows = res.rows()
        assert len(rows) == 6
        assert rows[0][0] == "host0"
        assert rows[0][1] == pytest.approx(10.0)
        assert rows[5][1] == pytest.approx(15.0)
        c.close()

    def test_rows_land_on_rule_regions(self, tmp_path):
        c = make_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        # host0,host1 -> region 0; host2,host3 -> region 1; rest -> region 2
        sizes = []
        for rid in info.region_ids:
            scan = c.router.scan(rid)
            sizes.append(0 if scan is None else scan.num_rows)
        assert sizes == [8, 8, 8]
        c.close()


class TestHeartbeatAndLease:
    def test_heartbeats_mark_nodes_alive(self, tmp_path):
        c = make_cluster(tmp_path)
        t = 0.0
        for _ in range(5):
            c.beat_all(t)
            t += 3000.0
        assert c.metasrv.alive_nodes(t) == ["dn-0", "dn-1", "dn-2"]
        c.close()

    def test_lease_expiry_closes_regions(self, tmp_path):
        c = make_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2"))
        seed(c)
        t = 0.0
        for _ in range(3):
            c.beat_all(t)
            t += 3000.0
        dn = next(d for d in c.datanodes.values() if d.engine.regions)
        # no heartbeats for a long time -> lease lapses -> self-close
        expired = dn.enforce_leases(t + 60_000)
        assert expired
        assert not dn.engine.regions
        c.close()


class TestFailover:
    def test_region_failover_moves_leader_and_data_survives(self, tmp_path):
        c = make_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        c.sql("ADMIN flush_table('cpu')") if False else None
        for rid in info.region_ids:
            c.router.flush(rid)  # persist SSTs to the shared store
        t = 0.0
        for _ in range(10):
            c.beat_all(t)
            t += 3000.0
        # kill the node owning region 0
        rid0 = info.region_ids[0]
        victim_id = c.metasrv.routes.get(str(rid0 >> 32)).region(rid0).leader_node
        victim = c.datanodes[victim_id]
        victim_regions = list(victim.engine.regions)
        victim.kill()
        # time passes; survivors keep beating; metasrv detects the death
        for _ in range(20):
            c.beat_all(t)
            t += 3000.0
        started = c.tick(t)
        assert started, "failover should start for the dead node's regions"
        # deliver OpenRegion instructions via the survivors' next heartbeat
        c.beat_all(t)
        # all the victim's regions now have a live leader
        for rid in victim_regions:
            route = c.metasrv.routes.get(str(rid >> 32))
            new_leader = route.region(rid).leader_node
            assert new_leader != victim_id
            assert c.datanodes[new_leader].engine.regions.get(rid) is not None
        # and the data is still queryable through the frontend
        res = c.sql("SELECT count(*) FROM cpu")
        assert res.rows()[0][0] == 24
        c.close()


    def test_dead_route_raises_typed_unavailable(self, tmp_path):
        """Between a leader's death and failover landing, routing to
        its region must degrade TYPED (Unavailable — retryable), never
        leak a bare KeyError out of the routing table. Found by the
        chaos explorer (seed 18, datanode.crash@dn-0)."""
        from greptimedb_tpu.fault import Unavailable

        c = make_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        rid0 = info.region_ids[0]
        victim_id = (c.metasrv.routes.get(str(rid0 >> 32))
                     .region(rid0).leader_node)
        c.datanodes[victim_id].kill()
        # failover has NOT run: the stale route points at a dead node
        with pytest.raises(Unavailable, match="no live datanode"):
            c.router.region(rid0)
        c.close()


class TestMigration:
    def test_manual_region_migration(self, tmp_path):
        c = make_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, host_rule("host2"))
        seed(c)
        rid = info.region_ids[0]
        table_key = str(rid >> 32)
        from_node = c.metasrv.routes.get(table_key).region(rid).leader_node
        to_node = next(n for n in c.datanodes if n != from_node)
        c.router.flush(rid)
        rec = c.metasrv.migrate_region(table_key, rid, to_node)
        assert rec.status == "done"
        # instructions flow on next heartbeats
        c.beat_all()
        route = c.metasrv.routes.get(table_key)
        assert route.region(rid).leader_node == to_node
        assert rid in c.datanodes[to_node].engine.regions
        assert rid not in c.datanodes[from_node].engine.regions
        # data still queryable
        res = c.sql("SELECT count(*) FROM cpu WHERE host < 'host2'")
        assert res.rows()[0][0] == 8
        c.close()


class TestPartitionSQL:
    def test_create_table_partition_on_columns(self, tmp_path):
        c = make_cluster(tmp_path)
        c.sql(
            "CREATE TABLE cpu (host STRING, region STRING, usage_user DOUBLE, "
            "usage_system DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, region)) "
            "PARTITION ON COLUMNS (host) (host < 'host2', "
            "host >= 'host2' AND host < 'host4', host >= 'host4')"
        )
        info = c.catalog.table("public", "cpu")
        assert len(info.region_ids) == 3
        seed(c)
        sizes = [
            (0 if (s := c.router.scan(rid)) is None else s.num_rows)
            for rid in info.region_ids
        ]
        assert sizes == [8, 8, 8]
        assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 24
        c.close()

    def test_influx_writes_respect_partitions(self, tmp_path):
        from greptimedb_tpu.servers.influx import parse_line_protocol, write_points

        c = make_cluster(tmp_path)
        c.sql(
            "CREATE TABLE mem (host STRING, used DOUBLE, ts TIMESTAMP TIME INDEX, "
            "PRIMARY KEY(host)) PARTITION ON COLUMNS (host) "
            "(host < 'm', host >= 'm')"
        )
        lines = "\n".join(
            [
                "mem,host=alpha used=1.0 1465839830100000000",
                "mem,host=zulu used=2.0 1465839830100000000",
            ]
        )
        pts = parse_line_protocol(lines)
        write_points(c.frontend, "public", pts, precision="ns")
        info = c.catalog.table("public", "mem")
        sizes = [
            (0 if (s := c.router.scan(rid)) is None else s.num_rows)
            for rid in info.region_ids
        ]
        assert sizes == [1, 1]
        # exact integer ns -> ms conversion
        res = c.sql("SELECT ts FROM mem WHERE host = 'alpha'")
        assert res.rows()[0][0] == 1465839830100
        c.close()


class TestWireTransport:
    """Same cluster flows with every region request crossing a real Flight
    serialization boundary (VERDICT r1 item 2: the round-1 cluster routed
    in-process Python calls; reference always crosses gRPC,
    datanode/src/region_server.rs:623-660)."""

    def _wire_cluster(self, tmp_path, n=3):
        return Cluster(str(tmp_path), num_datanodes=n, opts=MetasrvOptions(),
                       wire_transport=True)

    def test_remote_engine_in_use(self, tmp_path):
        from greptimedb_tpu.servers.flight import RemoteRegionEngine

        c = self._wire_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        eng = c.router._engine_for(info.region_ids[0])
        assert isinstance(eng, RemoteRegionEngine)
        c.close()

    def test_distributed_insert_and_query_over_wire(self, tmp_path):
        c = self._wire_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 24
        rows = c.sql(
            "SELECT host, avg(usage_user) FROM cpu GROUP BY host ORDER BY host"
        ).rows()
        assert len(rows) == 6
        assert rows[0][1] == pytest.approx(10.0)
        c.close()

    def test_flush_and_requery_over_wire(self, tmp_path):
        c = self._wire_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        for rid in info.region_ids:
            c.router.flush(rid)
        assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 24
        c.close()

    def test_failover_over_wire(self, tmp_path):
        c = self._wire_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        for rid in info.region_ids:
            c.router.flush(rid)
        t = 0.0
        for _ in range(10):
            c.beat_all(t)
            t += 3000.0
        rid0 = info.region_ids[0]
        victim_id = c.metasrv.routes.get(str(rid0 >> 32)).region(rid0).leader_node
        c.datanodes[victim_id].kill()
        for _ in range(20):
            c.beat_all(t)
            t += 3000.0
        assert c.tick(t)
        c.beat_all(t)
        assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 24
        c.close()

    def test_delete_over_wire(self, tmp_path):
        c = self._wire_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        c.sql("DELETE FROM cpu WHERE host = 'host0'")
        assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 20
        c.close()


class TestTracingAnalyze:
    def test_explain_analyze_reports_stages(self, tmp_path):
        c = make_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        r = c.sql("EXPLAIN ANALYZE SELECT host, avg(usage_user) FROM cpu "
                  "GROUP BY host")
        text = "\n".join(row[0] for row in r.rows())
        assert "ANALYZE trace=" in text
        # decomposable multi-region aggregate takes the pushdown path
        assert "fragment_pushdown:" in text
        assert "execution path: pushdown" in text
        # a host order-statistic is not decomposable, but its INPUT
        # commutes: filtered-row pushdown + frontend aggregation
        # (mode=rows_agg), never a raw scan gather
        r = c.sql("EXPLAIN ANALYZE SELECT host, median(usage_user) FROM cpu "
                  "GROUP BY host")
        text = "\n".join(row[0] for row in r.rows())
        assert "mode=rows_agg" in text
        assert "device_agg:" in text
        c.close()

    def test_trace_id_crosses_the_wire(self, tmp_path):
        """The frontend trace id rides the Flight scan spec and is adopted
        by the datanode-side span (W3C propagation analog)."""
        from greptimedb_tpu.utils import tracing

        c = Cluster(str(tmp_path), num_datanodes=2, opts=MetasrvOptions(),
                    wire_transport=True)
        c.create_partitioned_table(CREATE, host_rule("host2"))
        seed(c)
        from greptimedb_tpu.query.engine import QueryContext
        ctx = QueryContext(trace_id="feedbeefcafe0001")
        c.frontend.execute_one("SELECT count(*) FROM cpu", ctx)
        spans = tracing.spans_for("feedbeefcafe0001")
        names = {s.name for s in spans}
        # pushdown path: fragment client span + server-side span
        assert "remote_region_frag" in names
        assert "region_frag" in names
        # a full projection with no WHERE/LIMIT has nothing to reduce
        # region-side (even median rides rows_agg pushdown now) — it
        # exercises the raw scan transport
        ctx2 = QueryContext(trace_id="feedbeefcafe0002")
        c.frontend.execute_one(
            "SELECT host, region, usage_user, usage_system, ts FROM cpu",
            ctx2)
        names2 = {s.name for s in tracing.spans_for("feedbeefcafe0002")}
        assert "remote_region_scan" in names2
        assert "region_scan" in names2  # server-side span, same trace
        c.close()
