"""Cluster-mode rollup substitution, lastpoint pruning, vmapped member
batches, and partition scatter (ISSUE 12): the distributed frontend must
ship partial-aggregate planes — never raw rows — and return bit-for-bit
what the raw path returns."""

import numpy as np
import pytest

from greptimedb_tpu.cluster import Cluster
from greptimedb_tpu.meta.metasrv import MetasrvOptions
from greptimedb_tpu.partition.rule import (
    HashPartitionRule,
    PartitionBound,
    RangePartitionRule,
    rule_from_json,
)

CREATE = (
    "CREATE TABLE cpu (host STRING, v DOUBLE, ts TIMESTAMP(3) NOT NULL, "
    "TIME INDEX (ts), PRIMARY KEY(host))"
)


def host_rule(*splits):
    bounds = [PartitionBound((s,)) for s in splits] + [PartitionBound(())]
    return RangePartitionRule(["host"], bounds)


def make_cluster(tmp_path, n=3, wire=False):
    return Cluster(str(tmp_path), num_datanodes=n, opts=MetasrvOptions(),
                   wire_transport=wire)


def seed_minutes(cluster, hosts=6, minutes=3, per_minute=20):
    """Integer-valued rows spanning `minutes` one-minute buckets; the
    last bucket stays the ACTIVE window after a rollup."""
    rng = np.random.default_rng(3)
    rows = []
    for h in range(hosts):
        for m in range(minutes):
            for i in range(per_minute):
                ts = m * 60_000 + i * (60_000 // per_minute)
                rows.append(
                    f"('host{h}', {int(rng.integers(0, 1000))}, {ts})")
    cluster.sql("INSERT INTO cpu (host, v, ts) VALUES " + ", ".join(rows))


def roll_all(cluster, resolution_ms=60_000):
    """Give every datanode the rollup rule and roll every raw region —
    what the maintenance plane does on its tick, driven synchronously."""
    from greptimedb_tpu.maintenance.rollup import (
        ROLLUP_RID_FLAG,
        RollupRule,
        rule_slot,
        run_rollup_job,
    )

    rule = RollupRule(resolution_ms=resolution_ms)
    for dn in cluster.datanodes.values():
        dn.engine.maintenance.rollup_rules = [rule]
        for rid in list(dn.engine.regions):
            if rid & ROLLUP_RID_FLAG:
                continue
            run_rollup_job(dn.engine, rid, rule_slot(resolution_ms), rule)


ROLLUP_SQL = ("SELECT host, min(v), max(v), sum(v), count(v), avg(v) "
              "FROM cpu WHERE ts >= 0 AND ts < 120000 "
              "GROUP BY host ORDER BY host")


class TestClusterRollupSubstitution:
    def _run(self, c, monkeypatch):
        got = c.sql(ROLLUP_SQL).rows()
        path = c.frontend.executor.last_path
        # raw oracle: substitution disabled, same cluster
        monkeypatch.setenv("GTPU_ROLLUP_SUBSTITUTE", "0")
        try:
            want = c.sql(ROLLUP_SQL).rows()
        finally:
            monkeypatch.delenv("GTPU_ROLLUP_SUBSTITUTE")
        return got, want, path

    def test_substitution_ships_plane_fragments(self, tmp_path,
                                                monkeypatch):
        c = make_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed_minutes(c)
        roll_all(c)
        got, want, path = self._run(c, monkeypatch)
        # served from the companion plane regions THROUGH the fragment
        # pushdown: partial [G, F] planes crossed the frontend boundary,
        # not raw rows — and bit-for-bit equal to the raw path
        assert path == "pushdown+rollup", path
        assert got == want
        assert len(got) == 6
        c.close()

    @pytest.mark.slow
    def test_substitution_over_wire(self, tmp_path, monkeypatch):
        c = make_cluster(tmp_path, n=2, wire=True)
        c.create_partitioned_table(CREATE, host_rule("host3"))
        seed_minutes(c, hosts=4)
        roll_all(c)
        got, want, path = self._run(c, monkeypatch)
        assert path == "pushdown+rollup", path
        assert got == want
        c.close()

    def test_late_write_disables_substitution(self, tmp_path,
                                              monkeypatch):
        """An out-of-order write into the covered span must flip the
        probe ineligible — the raw path serves (correctness beats the
        plane win) until the next roll re-covers."""
        c = make_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed_minutes(c)
        roll_all(c)
        assert c.sql(ROLLUP_SQL)  # warm: substitution works
        assert c.frontend.executor.last_path == "pushdown+rollup"
        # a vacant instant inside the covered span (LWW must not merge it)
        c.sql("INSERT INTO cpu (host, v, ts) VALUES ('host0', 500, 30001)")
        got = c.sql(ROLLUP_SQL).rows()
        path = c.frontend.executor.last_path
        assert "rollup" not in (path or ""), path
        # the late row is IN the result (raw path sees it)
        by_host = {r[0]: r for r in got}
        assert by_host["host0"][4] == 41  # count picked up the new row
        c.close()

    def test_uncovered_window_falls_back(self, tmp_path, monkeypatch):
        c = make_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed_minutes(c)
        roll_all(c)
        # window reaches into the active (raw-only) bucket
        sql = ("SELECT host, sum(v) FROM cpu WHERE ts >= 0 AND "
               "ts < 180000 GROUP BY host ORDER BY host")
        got = c.sql(sql).rows()
        assert "rollup" not in (c.frontend.executor.last_path or "")
        assert len(got) == 6
        c.close()


class TestClusterLastpoint:
    def test_lastpoint_fragment_prunes_and_matches(self, tmp_path,
                                                   monkeypatch):
        """Cluster lastpoint: the fragment carries the pruning hint,
        every region serves its partial from scan_last (spied), the
        frontend's last_path proves no raw-row gather, and the result is
        bit-for-bit the raw aggregate."""
        from greptimedb_tpu.storage.region import Region

        c = make_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE,
                                          host_rule("host2", "host4"))
        # several files per region so newest-first pruning has work
        for gen in range(3):
            rows = [f"('host{h}', {100 * gen + h}, {gen * 10_000 + h})"
                    for h in range(6)]
            c.sql("INSERT INTO cpu (host, v, ts) VALUES " + ", ".join(rows))
            for rid in info.region_ids:
                c.router.flush(rid)
        calls = {"n": 0}
        orig = Region.scan_last

        def spy(self, *a, **k):
            calls["n"] += 1
            return orig(self, *a, **k)

        monkeypatch.setattr(Region, "scan_last", spy)
        sql = "SELECT host, last(v) FROM cpu GROUP BY host ORDER BY host"
        got = c.sql(sql).rows()
        assert c.frontend.executor.last_path == "lastfrag+pushdown"
        assert calls["n"] == len(info.region_ids)
        assert got == [(f"host{h}", float(200 + h)) for h in range(6)] or \
            [list(r) for r in got] == [[f"host{h}", float(200 + h)]
                                       for h in range(6)]
        # raw oracle: strip the hint by disabling scan_last
        monkeypatch.setattr(Region, "scan_last",
                            lambda self, *a, **k: None)
        want = c.sql(sql).rows()
        assert got == want
        c.close()


@pytest.mark.slow
class TestProcessClusterPushdown:
    def test_lastpoint_pushdown_across_processes(self, tmp_path):
        """Real child-process datanodes over Flight: cluster lastpoint
        returns exactly the per-series newest rows, and the frontend's
        last_path proves the partial-agg fragment (with the scan_last
        hint) served it — no raw-row gather."""
        from greptimedb_tpu.cluster.process_cluster import ProcessCluster

        c = ProcessCluster(str(tmp_path), num_datanodes=2)
        try:
            c.sql(
                "CREATE TABLE cpu (host STRING, v DOUBLE, ts TIMESTAMP(3) "
                "NOT NULL, TIME INDEX (ts), PRIMARY KEY(host)) "
                "PARTITION ON COLUMNS (host) (host < 'host3', "
                "host >= 'host3')")
            for gen in range(3):
                rows = [f"('host{h}', {100 * gen + h}, {gen * 10_000 + h})"
                        for h in range(6)]
                c.sql("INSERT INTO cpu (host, v, ts) VALUES "
                      + ", ".join(rows))
                c.sql("ADMIN flush_table('cpu')")
            sql = ("SELECT host, last(v) FROM cpu GROUP BY host "
                   "ORDER BY host")
            got = [list(r) for r in c.sql(sql).rows()]
            assert got == [[f"host{h}", float(200 + h)] for h in range(6)]
            assert c.frontend.executor.last_path == "lastfrag+pushdown"
        finally:
            c.close()


class TestPartitionScatter:
    def test_hash_rule_vectorized_and_stable(self):
        rule = HashPartitionRule(["host"], 4)
        hosts = np.asarray([f"h{i}" for i in range(1000)], dtype=object)
        r1 = rule.find_regions([hosts])
        r2 = rule.find_regions([hosts])
        assert (r1 == r2).all()
        assert r1.dtype == np.int32
        assert set(np.unique(r1)) <= set(range(4))
        # reasonable spread over 1000 distinct series
        counts = np.bincount(r1, minlength=4)
        assert counts.min() > 150, counts
        # split partitions the row set exactly
        parts = rule.split([hosts])
        all_rows = np.sort(np.concatenate(list(parts.values())))
        assert (all_rows == np.arange(1000)).all()
        # JSON round trip preserves assignment
        clone = rule_from_json(rule.to_json())
        assert (clone.find_regions([hosts]) == r1).all()

    def test_hash_rule_multi_column_and_numeric(self):
        rule = HashPartitionRule(["host", "dev"], 3)
        hosts = np.asarray(["a", "a", "b", "b"], dtype=object)
        devs = np.asarray([1, 2, 1, 2], dtype=np.int64)
        r = rule.find_regions([hosts, devs])
        assert len(r) == 4
        # same tuple -> same region (whole series stay together)
        r2 = rule.find_regions([hosts[:1], devs[:1]])
        assert r2[0] == r[0]

    def test_cluster_rows_land_where_find_regions_says(self, tmp_path):
        rule = HashPartitionRule(["host"], 3)
        c = make_cluster(tmp_path)
        info = c.create_partitioned_table(CREATE, rule)
        hosts = [f"host{h}" for h in range(12)]
        rows = [f"('{h}', 1, {i * 1000})"
                for i, h in enumerate(hosts) for _ in (0,)]
        c.sql("INSERT INTO cpu (host, v, ts) VALUES " + ", ".join(rows))
        expect = rule.find_regions(
            [np.asarray(hosts, dtype=object)])
        for idx, rid in enumerate(info.region_ids):
            scan = c.router.scan(rid)
            got_hosts = set()
            if scan is not None:
                d = scan.tag_dicts["host"]
                got_hosts = {d[code] for code in scan.columns["host"]}
            want_hosts = {h for h, r in zip(hosts, expect) if r == idx}
            assert got_hosts == want_hosts, (idx, got_hosts, want_hosts)
        # the aggregate over the scattered table is whole
        assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 12
        c.close()

    def test_default_hash_regions_auto_partitions(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("GREPTIMEDB_TPU_DEFAULT_HASH_REGIONS", "3")
        c = make_cluster(tmp_path)
        c.sql(CREATE)
        info = c.catalog.table("public", "cpu")
        assert len(info.region_ids) == 3
        assert info.partition_rules["type"] == "hash"
        assert info.partition_rules["columns"] == ["host"]
        rows = [f"('host{h}', {h}, {h * 1000})" for h in range(9)]
        c.sql("INSERT INTO cpu (host, v, ts) VALUES " + ", ".join(rows))
        assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 9
        # more than one region actually holds rows
        occupied = sum(
            1 for rid in info.region_ids
            if c.router.scan(rid) is not None)
        assert occupied > 1
        c.close()

    def test_standalone_create_stays_single_region(self, tmp_path,
                                                   monkeypatch):
        """The [partition] default must not touch standalone engines."""
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.storage import RegionEngine
        from greptimedb_tpu.storage.engine import EngineConfig

        monkeypatch.setenv("GREPTIMEDB_TPU_DEFAULT_HASH_REGIONS", "3")
        engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "d")))
        qe = QueryEngine(Catalog(MemoryKv()), engine)
        qe.execute_one(CREATE)
        assert len(qe.catalog.table("public", "cpu").region_ids) == 1
        engine.close()


class TestVmappedFragments:
    # the selector tag must stay OUT of the projection/group keys (the
    # batcher's shape contract); members differ in host + window
    DASH = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, max(v), "
            "sum(v), count(v) FROM cpu WHERE host = '{h}' AND "
            "ts >= {lo} AND ts < {hi} GROUP BY minute")

    def _group(self, qe, sqls):
        from greptimedb_tpu.concurrency import batcher as batcher_mod
        from greptimedb_tpu.query.engine import QueryContext
        from greptimedb_tpu.sql.parser import parse_sql

        ctx = QueryContext()
        info = qe._table("cpu", ctx)
        shapes = []
        for sql in sqls:
            sel = parse_sql(sql)[0]
            sh = batcher_mod.analyze(sel, info)
            assert sh is not None, sql
            shapes.append((sel, sh))
        assert len({sh.masked for _, sh in shapes}) == 1
        order = []
        for _, sh in shapes:
            if sh.values not in order:
                order.append(sh.values)
        return info, shapes[0][0], shapes[0][1], order

    def test_multi_region_members_ride_fragments(self, tmp_path):
        """Cluster frontends used to decline vmapped batches (IN-list/
        serial fallback); members must now execute as one vmapped_agg
        fragment per region, bit-for-bit with serial."""
        from greptimedb_tpu.query.vmapped import run_vmapped

        c = make_cluster(tmp_path)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed_minutes(c)
        qe = c.frontend
        sqls = [self.DASH.format(h=f"host{i % 6}",
                                 lo=(i % 2) * 30_000,
                                 hi=90_000 + (i % 2) * 30_000)
                for i in range(8)]
        info, leader, shape, order = self._group(qe, sqls)
        results = run_vmapped(qe.executor, leader, info, shape.params,
                              order)
        assert qe.executor.last_path == "vmapped_fragments"
        for sql in sqls:
            vals = self._values_of(qe, sql)
            got = results[order.index(vals)]
            # serial oracle through the same cluster frontend
            with qe.concurrency.suppress_batching():
                want = qe.execute_one(sql)
            assert got.names == want.names
            assert got.rows() == want.rows(), sql
        c.close()

    def _values_of(self, qe, sql):
        from greptimedb_tpu.concurrency import batcher as batcher_mod
        from greptimedb_tpu.query.engine import QueryContext
        from greptimedb_tpu.sql.parser import parse_sql

        info = qe._table("cpu", QueryContext())
        return batcher_mod.analyze(parse_sql(sql)[0], info).values

    def test_vmapped_first_last_members(self, tmp_path):
        """Satellite: first/last ride the stacked axis (single region,
        ts-paired combine) — lastpoint-class dashboards batch too."""
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.query.vmapped import run_vmapped
        from greptimedb_tpu.storage import RegionEngine
        from greptimedb_tpu.storage.engine import EngineConfig

        engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "d"),
                                           maintenance_workers=0))
        qe = QueryEngine(Catalog(MemoryKv()), engine)
        qe.execute_one(CREATE)
        rng = np.random.default_rng(9)
        for gen in range(2):  # two SSTs + memtable tail
            rows = [f"('host{h}', {int(rng.integers(0, 100))}, "
                    f"{(gen * 50 + i) * 1000})"
                    for h in range(4) for i in range(50)]
            qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                           + ",".join(rows))
            qe.execute_one("ADMIN flush_table('cpu')")
        qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                       "('host0', 777, 200000)")
        sql = ("SELECT date_bin(INTERVAL '30 seconds', ts) AS b, "
               "first(v), last(v) FROM cpu "
               "WHERE host = '{h}' AND ts >= {lo} AND ts < {hi} "
               "GROUP BY b")
        sqls = [sql.format(h=f"host{i % 4}", lo=(i % 2) * 20_000,
                           hi=80_000 + (i % 2) * 60_000 + 70_000)
                for i in range(6)]
        info, leader, shape, order = self._group(qe, sqls)
        results = run_vmapped(qe.executor, leader, info, shape.params,
                              order)
        assert qe.executor.last_path == "dense_vmapped"
        for sql_i, vals in zip(sqls, [self._values_of(qe, s)
                                      for s in sqls]):
            got = results[order.index(vals)]
            with qe.concurrency.suppress_batching():
                want = qe.execute_one(sql_i)
            assert got.rows() == want.rows(), sql_i
        engine.close()
