"""TWCS compaction + inverted index tests (reference compaction/twcs.rs and
index/inverted_index tests analog)."""

import numpy as np
import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.storage.compaction import TwcsOptions, TwcsPicker, infer_time_window_ms
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine
from greptimedb_tpu.storage.index import IndexApplier, extract_tag_predicates
from greptimedb_tpu.storage.sst import FileMeta
from greptimedb_tpu.sql import parse_sql

HOUR_MS = 3_600_000


def fm(i, ts_min, ts_max, level=0):
    return FileMeta(file_id=f"f{i}", num_rows=100, ts_min=ts_min, ts_max=ts_max,
                    max_seq=i, level=level)


class TestTwcsPicker:
    def test_no_compaction_under_limits(self):
        picker = TwcsPicker(TwcsOptions(time_window_ms=HOUR_MS))
        files = [fm(1, 0, 100), fm(2, 100, 200)]  # 2 files, active window, limit 4
        assert picker.pick(files) == []

    def test_active_window_compacts_over_limit(self):
        picker = TwcsPicker(TwcsOptions(time_window_ms=HOUR_MS,
                                        max_active_window_files=2))
        files = [fm(i, 0, 1000 + i) for i in range(4)]
        groups = picker.pick(files)
        assert len(groups) == 1
        assert len(groups[0]) == 4

    def test_inactive_window_compacts_at_two(self):
        picker = TwcsPicker(TwcsOptions(time_window_ms=HOUR_MS))
        old = [fm(1, 0, 100), fm(2, 50, 200)]  # window 0
        active = [fm(3, 2 * HOUR_MS, 2 * HOUR_MS + 10)]  # window 2
        groups = picker.pick(old + active)
        assert len(groups) == 1
        assert {f.file_id for f in groups[0]} == {"f1", "f2"}

    def test_window_inference(self):
        files = [fm(1, 0, 30 * 60 * 1000)]  # 30min span -> 1h bucket
        assert infer_time_window_ms(files) == HOUR_MS
        files = [fm(1, 0, 5 * 24 * HOUR_MS)]  # 5d span -> 7d bucket
        assert infer_time_window_ms(files) == 7 * 24 * HOUR_MS


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE cpu (host STRING, usage DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY(host))"
    )
    yield q
    engine.close()


def region_of(qe, name="cpu"):
    info = qe.catalog.table("public", name)
    return qe.region_engine.region(info.region_ids[0])


class TestRegionCompaction:
    def test_twcs_merges_same_window(self, qe):
        # 3 flushes in the same hour window + overflow threshold
        for i in range(5):
            qe.execute_one(
                f"INSERT INTO cpu (host, usage, ts) VALUES ('h{i}', {i}.0, {1000 + i})"
            )
            region_of(qe).flush()
        region = region_of(qe)
        assert len(region.files) == 5
        out = region.compact()
        assert len(out) == 1
        assert len(region.files) == 1
        assert list(region.files.values())[0].level == 1
        res = qe.execute_one("SELECT count(*) FROM cpu")
        assert res.rows()[0][0] == 5

    def test_windowed_compaction_preserves_lww(self, qe):
        # same key written twice across files: winner must survive the merge
        qe.execute_one("INSERT INTO cpu (host, usage, ts) VALUES ('a', 1.0, 1000)")
        region_of(qe).flush()
        qe.execute_one("INSERT INTO cpu (host, usage, ts) VALUES ('a', 9.0, 1000)")
        region_of(qe).flush()
        for i in range(3):
            qe.execute_one(
                f"INSERT INTO cpu (host, usage, ts) VALUES ('b', {i}.0, {2000 + i})"
            )
            region_of(qe).flush()
        region_of(qe).compact()
        res = qe.execute_one("SELECT usage FROM cpu WHERE host = 'a'")
        assert res.rows() == [[9.0]]

    def test_partial_compaction_keeps_tombstones(self, qe):
        # put in file A (old window), delete in file B+C (new window);
        # compacting only B+C must not lose the tombstone
        qe.execute_one("INSERT INTO cpu (host, usage, ts) VALUES ('a', 1.0, 1000)")
        region = region_of(qe)
        region.flush()
        qe.execute_one("DELETE FROM cpu WHERE host = 'a'")
        region.flush()
        qe.execute_one("INSERT INTO cpu (host, usage, ts) VALUES ('b', 2.0, 2000)")
        region.flush()
        # merge only the last two files (partial group)
        group = sorted(region.files.values(), key=lambda f: f.max_seq)[1:]
        region._merge_files(group)
        res = qe.execute_one("SELECT host FROM cpu ORDER BY host")
        assert res.rows() == [["b"]]  # 'a' stays deleted

    def test_full_compaction_drops_tombstones(self, qe):
        qe.execute_one("INSERT INTO cpu (host, usage, ts) VALUES ('a', 1.0, 1000)")
        region = region_of(qe)
        region.flush()
        qe.execute_one("DELETE FROM cpu WHERE host = 'a'")
        region.flush()
        region.compact(strategy="full")
        assert len(region.files) == 1
        res = qe.execute_one("SELECT count(*) FROM cpu")
        assert res.rows()[0][0] == 0
        # the merged file physically contains no tombstone rows
        meta = list(region.files.values())[0]
        assert meta.num_rows == 0 or meta.num_rows == 1  # winner-only content


class TestInvertedIndex:
    def test_index_prunes_row_groups(self, tmp_path):
        engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        qe = QueryEngine(Catalog(MemoryKv()), engine)
        qe.execute_one(
            "CREATE TABLE t (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
            "PRIMARY KEY(host))"
        )
        region = region_of(qe, "t")
        region.sst_writer.row_group_size = 8  # force multiple row groups
        rows = []
        for h in range(4):
            for i in range(8):
                rows.append(f"('host{h}', 1.0, {h * 1_000_000 + i})")
        qe.execute_one("INSERT INTO t (host, v, ts) VALUES " + ",".join(rows))
        region.flush()
        meta = list(region.files.values())[0]
        applier = region.sst_reader.index_applier
        # host0 lives in exactly one of 4 row groups (data sorted by host)
        groups = applier.apply(meta.file_id, {"host": {"host0"}})
        assert groups == [0]
        assert applier.apply(meta.file_id, {"host": {"host3"}}) == [3]
        assert applier.apply(meta.file_id, {"host": {"nope"}}) == []
        # scan path returns the pruned subset but correct results
        scan = region.scan(tag_predicates={"host": {"host0"}})
        assert scan.num_rows == 8
        res = qe.execute_one("SELECT count(*) FROM t WHERE host = 'host0'")
        assert res.rows()[0][0] == 8
        engine.close()

    def test_extract_tag_predicates(self, qe):
        info = qe.catalog.table("public", "cpu")
        from greptimedb_tpu.storage.index import InSet

        sel = parse_sql("SELECT * FROM cpu WHERE host = 'a' AND ts > 5")[0]
        preds = extract_tag_predicates(sel.where, info.schema)
        assert preds == {"host": (InSet.of(["a"]),)}
        sel = parse_sql("SELECT * FROM cpu WHERE host IN ('a', 'b')")[0]
        preds = extract_tag_predicates(sel.where, info.schema)
        assert preds == {"host": (InSet.of(["a", "b"]),)}
        # OR is not restrictive -> no predicates
        sel = parse_sql("SELECT * FROM cpu WHERE host = 'a' OR usage > 1")[0]
        assert extract_tag_predicates(sel.where, info.schema) == {}
