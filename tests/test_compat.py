"""Cross-version compatibility (reference tests/compat/test-compat.sh:
old-version data dirs must open under new code; incompatible versions
must refuse loudly, never corrupt).

`tests/fixtures/compat_r3` is a committed golden data dir written by the
ROUND-3 build (commit 26ec8be): zstd-compressed SST + inverted index +
manifest without format stamps + WAL holding unflushed rows and a DELETE
tombstone. Round-4+ code must replay all of it bit-correctly."""

import json
import os
import shutil

import pytest

from greptimedb_tpu.catalog import Catalog, FileKv
from greptimedb_tpu.query import QueryEngine
from greptimedb_tpu.storage import RegionEngine
from greptimedb_tpu.storage.engine import EngineConfig
from greptimedb_tpu.storage.format import FORMAT_VERSIONS, FormatError

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "compat_r3")

# what the round-3 build printed for:
#   SELECT host, region, usage FROM cpu ORDER BY host, ts
R3_ROWS = [["a", "us", 1.5], ["a", "us", 2.5], ["a", "us", 3.5],
           ["c", "ap", 4.0]]


@pytest.fixture
def old_dir(tmp_path):
    # opens mutate (WAL replay state, format stamp): work on a copy
    dst = tmp_path / "compat_r3"
    shutil.copytree(FIXTURE, dst)
    return str(dst)


def _open(d):
    engine = RegionEngine(EngineConfig(data_dir=os.path.join(d, "data")))
    qe = QueryEngine(Catalog(FileKv(os.path.join(d, "catalog.json"))),
                     engine)
    return engine, qe


def test_open_r3_dir_and_read(old_dir):
    engine, qe = _open(old_dir)
    try:
        r = qe.execute_one(
            "SELECT host, region, usage FROM cpu ORDER BY host, ts")
        assert r.rows() == R3_ROWS
        # the WAL-resident delete must still hide host b
        r = qe.execute_one("SELECT count(*) FROM cpu WHERE host = 'b'")
        assert r.rows() == [[0]]
    finally:
        engine.close()


def test_write_new_into_r3_dir(old_dir):
    engine, qe = _open(old_dir)
    try:
        qe.execute_one("INSERT INTO cpu VALUES ('d', 'sa', 7.0, 70.0, 9000)")
        qe.execute_one("ADMIN flush_table('cpu')")  # new lz4 SST beside zstd
        r = qe.execute_one(
            "SELECT host, usage FROM cpu ORDER BY host, ts")
        assert r.rows() == [["a", 1.5], ["a", 2.5], ["a", 3.5],
                            ["c", 4.0], ["d", 7.0]]
    finally:
        engine.close()
    # reopen: mixed-codec SSTs + fresh manifest actions replay clean
    engine, qe = _open(old_dir)
    try:
        r = qe.execute_one("SELECT count(*) FROM cpu")
        assert r.rows() == [[5]]
    finally:
        engine.close()


def test_r3_dir_gets_stamped_on_open(old_dir):
    data = os.path.join(old_dir, "data")
    assert not os.path.exists(os.path.join(data, "FORMAT.json"))
    engine, qe = _open(old_dir)
    engine.close()
    with open(os.path.join(data, "FORMAT.json")) as f:
        assert json.load(f)["versions"] == FORMAT_VERSIONS


def test_newer_stamp_refuses_open(tmp_path):
    d = tmp_path / "data"
    d.mkdir()
    (d / "FORMAT.json").write_text(json.dumps(
        {"versions": dict(FORMAT_VERSIONS, sst=FORMAT_VERSIONS["sst"] + 1)}))
    with pytest.raises(FormatError, match="newer build"):
        RegionEngine(EngineConfig(data_dir=str(d)))


def test_newer_manifest_action_refuses(tmp_path):
    from greptimedb_tpu.storage.manifest import RegionManifestState

    st = RegionManifestState()
    with pytest.raises(FormatError, match="manifest action format"):
        st.apply({"format": 99, "kind": "truncate"})
