"""Write/scan/flush/compact under concurrency — the worker-model
discipline (reference mito2 region worker, worker.rs:110-650): mutations
serialize on the region lock, scans snapshot consistently, compacted
SSTs are purged on a grace delay so in-flight scans can finish."""

import threading

import numpy as np
import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY(host))")
    yield q
    engine.close()


def _run_threads(fns, timeout=120):
    errors = []

    def wrap(fn):
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(fn,)) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not errors, errors[:3]


class TestWriteScanRaces:
    ROUNDS = 30

    def test_writes_during_scans(self, qe):
        """Scans racing writes must never crash and every scan must see a
        consistent snapshot (full rows, monotonic count)."""
        counts = []

        def writer():
            for i in range(self.ROUNDS):
                qe.execute_one(
                    "INSERT INTO m VALUES " + ", ".join(
                        f"('h{j}', {i}.0, {i * 100 + j})" for j in range(20)))

        def scanner():
            for _ in range(self.ROUNDS):
                r = qe.execute_one("SELECT count(*), count(v) FROM m")
                total, non_null = r.rows()[0]
                # a torn scan would show count(*) != count(v) (a row with
                # ts appended but v missing) — snapshots forbid that
                assert total == non_null, (total, non_null)
                counts.append(total)

        _run_threads([writer, scanner, scanner])
        assert qe.execute_one("SELECT count(*) FROM m").rows()[0][0] == \
            self.ROUNDS * 20
        # each scanner saw monotonically non-decreasing counts
        # (counts interleave between scanners; global sortedness isn't
        # required — only that nothing went backwards catastrophically
        # below zero or above the final total)
        assert all(0 <= c <= self.ROUNDS * 20 for c in counts)

    def test_concurrent_writers_unique_seqs(self, qe):
        """Parallel INSERTs must not collide on WAL sequences (lost
        updates); every row must survive a restart replay."""
        def writer(base):
            def run():
                for i in range(self.ROUNDS):
                    qe.execute_one(
                        f"INSERT INTO m VALUES ('w{base}', {i}.0, "
                        f"{base * 1_000_000 + i})")
            return run

        _run_threads([writer(b) for b in range(4)])
        assert qe.execute_one("SELECT count(*) FROM m").rows()[0][0] == \
            4 * self.ROUNDS
        info = qe.catalog.table("public", "m")
        rid = info.region_ids[0]
        region = qe.region_engine.region(rid)
        # WAL seqs must be unique: replay and count
        seqs = [e.seq for e in region.wal.replay(rid)]
        assert len(seqs) == len(set(seqs))

    def test_scans_during_flush_and_compact(self, qe):
        """Flush + compaction racing scans: file swaps must not break an
        in-flight scan (grace-deferred purge)."""
        qe.execute_one(
            "INSERT INTO m VALUES " + ", ".join(
                f"('h{j}', 1.0, {j})" for j in range(50)))

        stop = threading.Event()

        def maintainer():
            for i in range(10):
                qe.execute_one(
                    "INSERT INTO m VALUES " + ", ".join(
                        f"('h{j}', 2.0, {10_000 + i * 100 + j})"
                        for j in range(20)))
                qe.execute_one("ADMIN flush_table('m')")
                qe.execute_one("ADMIN compact_table('m')")
            stop.set()

        def scanner():
            while not stop.is_set():
                r = qe.execute_one(
                    "SELECT host, count(*) FROM m GROUP BY host "
                    "ORDER BY host")
                assert r.num_rows >= 1

        _run_threads([maintainer, scanner, scanner])
        assert qe.execute_one("SELECT count(*) FROM m").rows()[0][0] == \
            50 + 10 * 20

    def test_compacted_files_purged_on_close(self, qe, tmp_path):
        import glob

        qe.execute_one("INSERT INTO m VALUES ('a', 1.0, 1000)")
        qe.execute_one("ADMIN flush_table('m')")
        qe.execute_one("INSERT INTO m VALUES ('b', 2.0, 2000)")
        qe.execute_one("ADMIN flush_table('m')")
        r = qe.execute_one("ADMIN compact_table('m')")
        # ADMIN is async job submission now — wait for the compact job
        # before asserting its side effects
        maint = qe.region_engine.maintenance
        for row in r.rows():
            maint.wait(int(row[0]), timeout=30)
        info = qe.catalog.table("public", "m")
        region = qe.region_engine.region(info.region_ids[0])
        # old files grace-held, not yet deleted
        assert region._purge_queue
        region.close()
        assert not region._purge_queue
        live = set(region.files)
        on_disk = {p.split("/")[-1].replace(".parquet", "")
                   for p in glob.glob(str(tmp_path) + "/**/sst/*.parquet",
                                      recursive=True)}
        assert on_disk == live
