import numpy as np
import pyarrow as pa
import pytest

from greptimedb_tpu.datatypes import (
    ColumnSchema,
    DataType,
    DictVector,
    RecordBatch,
    Schema,
    SemanticType,
)
from greptimedb_tpu.datatypes.types import parse_sql_type


def make_cpu_schema():
    return Schema(
        [
            ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND, SemanticType.TIMESTAMP),
            ColumnSchema("hostname", DataType.STRING, SemanticType.TAG),
            ColumnSchema("usage_user", DataType.FLOAT64),
            ColumnSchema("usage_system", DataType.FLOAT64),
        ]
    )


def test_schema_canonical_order():
    s = make_cpu_schema()
    # tags, time index, fields — the storage sort-key order
    assert s.names == ["hostname", "ts", "usage_user", "usage_system"]
    assert s.time_index.name == "ts"
    assert [c.name for c in s.tag_columns] == ["hostname"]
    assert [c.name for c in s.field_columns] == ["usage_user", "usage_system"]


def test_schema_requires_single_time_index():
    with pytest.raises(ValueError):
        Schema([ColumnSchema("x", DataType.FLOAT64)])
    with pytest.raises(ValueError):
        Schema(
            [
                ColumnSchema("a", DataType.TIMESTAMP_SECOND, SemanticType.TIMESTAMP),
                ColumnSchema("b", DataType.TIMESTAMP_SECOND, SemanticType.TIMESTAMP),
            ]
        )


def test_schema_arrow_roundtrip():
    s = make_cpu_schema()
    s2 = Schema.from_arrow(s.to_arrow())
    assert s2 == s


def test_schema_dict_roundtrip():
    s = make_cpu_schema()
    assert Schema.from_dict(s.to_dict()) == s


def test_dict_vector_encode_decode():
    v = DictVector.encode(["a", "b", None, "a", "c"])
    assert v.codes.tolist() == [0, 1, -1, 0, 2]
    assert v.decode().tolist() == ["a", "b", None, "a", "c"]


def test_dict_vector_arrow_roundtrip():
    v = DictVector.encode(["x", None, "y", "x"])
    arr = v.to_arrow()
    v2 = DictVector.from_arrow(arr)
    assert v2.decode().tolist() == ["x", None, "y", "x"]


def test_recordbatch_arrow_roundtrip():
    s = make_cpu_schema()
    rb = RecordBatch(
        s,
        {
            "ts": np.array([1000, 2000, 3000], dtype=np.int64),
            "hostname": DictVector.encode(["h0", "h1", "h0"]),
            "usage_user": np.array([1.0, 2.0, 3.0]),
            "usage_system": np.array([0.5, np.nan, 1.5]),
        },
    )
    arrow = rb.to_arrow()
    assert arrow.num_rows == 3
    rb2 = RecordBatch.from_arrow(arrow, s)
    assert rb2.columns["ts"].tolist() == [1000, 2000, 3000]
    assert rb2.columns["hostname"].decode().tolist() == ["h0", "h1", "h0"]
    np.testing.assert_allclose(rb2.columns["usage_user"], [1.0, 2.0, 3.0])


def test_recordbatch_concat_merges_dicts():
    s = make_cpu_schema()

    def mk(hosts, ts0):
        n = len(hosts)
        return RecordBatch(
            s,
            {
                "ts": np.arange(ts0, ts0 + n, dtype=np.int64),
                "hostname": DictVector.encode(hosts),
                "usage_user": np.ones(n),
                "usage_system": np.zeros(n),
            },
        )

    merged = RecordBatch.concat([mk(["a", "b"], 0), mk(["c", "a"], 10)])
    assert merged.num_rows == 4
    assert merged.columns["hostname"].decode().tolist() == ["a", "b", "c", "a"]
    # codes must index a single merged dictionary
    assert merged.columns["hostname"].codes.tolist() == [0, 1, 2, 0]


def test_parse_sql_type():
    assert parse_sql_type("DOUBLE") == DataType.FLOAT64
    assert parse_sql_type("BIGINT") == DataType.INT64
    assert parse_sql_type("TIMESTAMP(3)") == DataType.TIMESTAMP_MILLISECOND
    assert parse_sql_type("String") == DataType.STRING
    with pytest.raises(ValueError):
        parse_sql_type("geometry")
