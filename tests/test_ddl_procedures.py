"""Distributed DDL as journaled procedures (reference
common/meta/src/ddl_manager.rs + ddl/{create_table,drop_table,
alter_table}.rs): crash mid-DDL must resume or roll back cleanly, and
readers must never observe a half-created table."""

import pytest

from greptimedb_tpu.cluster.cluster import Cluster
from greptimedb_tpu.meta.ddl import (
    CreateTableProcedure,
    DdlError,
)
from greptimedb_tpu.procedure import ProcedureRecord


@pytest.fixture()
def cluster(tmp_path):
    c = Cluster(str(tmp_path), num_datanodes=3)
    yield c
    c.close()


CREATE = ("CREATE TABLE t (host STRING, ts TIMESTAMP(3) NOT NULL, v DOUBLE,"
          " TIME INDEX (ts), PRIMARY KEY (host))")


class TestHappyPath:
    def test_create_insert_drop_via_procedures(self, cluster):
        cluster.sql(CREATE)
        # the DDL left a journaled done procedure behind
        recs = cluster.metasrv.procedures.store.list()
        assert any(r.type_name == "ddl/create_table" and r.status == "done"
                   for r in recs)
        cluster.sql("INSERT INTO t VALUES ('a', 1000, 1.0)")
        assert cluster.sql("SELECT count(*) FROM t").rows()[0][0] == 1
        cluster.sql("DROP TABLE t")
        assert any(r.type_name == "ddl/drop_table" and r.status == "done"
                   for r in cluster.metasrv.procedures.store.list())
        with pytest.raises(Exception, match="not found"):
            cluster.sql("SELECT * FROM t")
        # recreate under the same name: fresh table id, no leftovers
        cluster.sql(CREATE)
        assert cluster.sql("SELECT count(*) FROM t").rows()[0][0] == 0

    def test_create_if_not_exists(self, cluster):
        cluster.sql(CREATE)
        cluster.sql(CREATE.replace("CREATE TABLE t",
                                   "CREATE TABLE IF NOT EXISTS t"))
        with pytest.raises(Exception, match="already exists"):
            cluster.sql(CREATE)

    def test_alter_via_procedure(self, cluster):
        cluster.sql(CREATE)
        cluster.sql("INSERT INTO t VALUES ('a', 1000, 1.0)")
        cluster.sql("ALTER TABLE t ADD COLUMN w DOUBLE")
        assert any(r.type_name == "ddl/alter_table" and r.status == "done"
                   for r in cluster.metasrv.procedures.store.list())
        cluster.sql("INSERT INTO t VALUES ('a', 2000, 2.0, 9.0)")
        r = cluster.sql("SELECT v, w FROM t ORDER BY ts")
        rows = r.rows()
        assert rows[0][0] == 1.0 and rows[1] == [2.0, 9.0]

    def test_partitioned_create_places_across_nodes(self, cluster):
        from greptimedb_tpu.partition.rule import (
            PartitionBound,
            RangePartitionRule,
        )

        rule = RangePartitionRule(
            ["host"],
            [PartitionBound(("h",)), PartitionBound(("p",)),
             PartitionBound(())])
        info = cluster.create_partitioned_table(
            "CREATE TABLE pt (host STRING, ts TIMESTAMP(3) NOT NULL, "
            "v DOUBLE, TIME INDEX (ts), PRIMARY KEY (host))", rule)
        assert len(info.region_ids) == 3
        # route table covers every region
        route = cluster.metasrv.routes.get(str(info.table_id))
        assert {r.region_id for r in route.regions} == set(info.region_ids)


class TestCrashResume:
    def _crash_after(self, cluster, crash_phase):
        """Run a CreateTableProcedure but 'crash' (stop driving) after the
        given phase persisted; return the procedure id."""
        from greptimedb_tpu.datatypes import (
            ColumnSchema,
            DataType,
            Schema,
            SemanticType,
        )

        schema = Schema([
            ColumnSchema("host", DataType.STRING, SemanticType.TAG),
            ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND,
                         SemanticType.TIMESTAMP),
            ColumnSchema("v", DataType.FLOAT64),
        ])
        ddl = cluster.router.ddl_manager
        pm = cluster.metasrv.procedures
        proc = CreateTableProcedure(ddl, {
            "db": "public", "name": "crash_t", "schema": schema.to_dict(),
            "options": {}, "num_regions": 2,
        })
        pid = pm.next_id()
        rec = ProcedureRecord(procedure_id=pid, type_name=proc.type_name,
                              state=proc.state, status="running")
        pm.store.save(rec)
        ctx = None
        while proc.state.get("phase") != crash_phase:
            status = proc.step(ctx)
            rec.state = proc.state
            pm.store.save(rec)
            assert not status.done, "reached the end before the crash point"
        return pid

    def test_resume_after_crash_before_commit(self, cluster):
        """Crash after regions exist but before the catalog commit: the
        table is invisible; recovery completes it."""
        self._crash_after(cluster, "commit_metadata")
        assert not cluster.catalog.table_exists("public", "crash_t")
        done = cluster.metasrv.procedures.recover()
        assert [r.status for r in done
                if r.type_name == "ddl/create_table"] == ["done"]
        assert cluster.catalog.table_exists("public", "crash_t")
        cluster.sql("INSERT INTO crash_t VALUES ('a', 1000, 1.0)")
        assert cluster.sql(
            "SELECT count(*) FROM crash_t").rows()[0][0] == 1

    def test_resume_after_crash_before_regions(self, cluster):
        """Crash right after id allocation: recovery creates the regions
        and commits."""
        self._crash_after(cluster, "create_regions")
        cluster.metasrv.procedures.recover()
        assert cluster.catalog.table_exists("public", "crash_t")
        cluster.sql("INSERT INTO crash_t VALUES ('a', 1000, 1.0)")
        assert cluster.sql(
            "SELECT count(*) FROM crash_t").rows()[0][0] == 1

    def test_leader_failover_resumes_ddl(self, tmp_path):
        """A second metasrv taking over the shared KV resumes the DDL
        (reference: procedures live in the shared store; the new leader's
        recover() drives them)."""
        c = Cluster(str(tmp_path), num_datanodes=2)
        try:
            # crash the 'leader' mid-DDL (state persisted in shared kv)
            self._crash_after(c, "commit_metadata")
            # a fresh coordinator over the same KV + datanodes: loaders
            # re-registered, then recover() drives the in-flight DDL
            from greptimedb_tpu.meta.ddl import DdlManager

            DdlManager(c.metasrv.procedures, c.router, c.catalog)
            c.metasrv.procedures.recover()
            assert c.catalog.table_exists("public", "crash_t")
        finally:
            c.close()


class TestDropOnDeadNode:
    def test_drop_table_cleans_route_when_node_dead(self, cluster):
        """DROP TABLE while the owning datanode is down must still remove
        the route — a stale route would let failover resurrect the
        dropped region (code-review regression)."""
        cluster.sql(CREATE)
        info = cluster.catalog.table("public", "t")
        rid = info.region_ids[0]
        node = cluster.router._region_node.get(rid) or \
            next(iter(cluster.datanodes))
        cluster.datanodes[node].kill()
        cluster.sql("DROP TABLE t")
        route = cluster.metasrv.routes.get(str(info.table_id))
        assert route is None or all(r.region_id != rid
                                    for r in route.regions)
        assert not cluster.catalog.table_exists("public", "t")


class TestRollback:
    def test_failed_create_rolls_back_regions(self, cluster):
        """A create whose region step keeps failing rolls back: no catalog
        entry, no orphan routes."""
        ddl = cluster.router.ddl_manager
        pm = cluster.metasrv.procedures
        pm._max_retries = 1
        pm._retry_delay_s = 0

        orig = cluster.router.create_region_on
        calls = []

        def failing(node, rid, schema):
            calls.append(rid)
            if len(calls) >= 2:
                raise RuntimeError("datanode unreachable")
            return orig(node, rid, schema)

        cluster.router.create_region_on = failing
        from greptimedb_tpu.datatypes import (
            ColumnSchema,
            DataType,
            Schema,
            SemanticType,
        )

        schema = Schema([
            ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND,
                         SemanticType.TIMESTAMP),
            ColumnSchema("v", DataType.FLOAT64),
        ])
        with pytest.raises(DdlError):
            ddl.create_table("public", "rb_t", schema, num_regions=3)
        cluster.router.create_region_on = orig
        assert not cluster.catalog.table_exists("public", "rb_t")
        # first region (created before the failure) was rolled back
        recs = [r for r in pm.store.list()
                if r.type_name == "ddl/create_table"
                and r.status == "rolled_back"]
        assert recs, "expected a rolled_back record"
        rid0 = calls[0]
        with pytest.raises(KeyError):
            cluster.router.region(rid0)
