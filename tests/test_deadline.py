"""End-to-end query deadlines, cooperative cancellation, and hedged
region requests (the tail-tolerance plane, utils/deadline.py +
cluster/cluster.py).

The acceptance scenario: a datanode stalled far beyond the query's
budget still yields a TYPED DeadlineExceeded in bounded time — with
every admission slot released and the running-queries registry empty —
because each wait a query can park on (admission, scan pool gathers,
injected latency, the Flight wire itself) re-checks the statement's
CancelToken. KILL QUERY and client-disconnect ride the same token;
hedged fragment reads race a backup attempt and cancel the loser
without ever touching the outer statement's token."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from greptimedb_tpu.cluster import Cluster
from greptimedb_tpu.fault import FAULTS, Fault
from greptimedb_tpu.fault.retry import Cancelled, DeadlineExceeded
from greptimedb_tpu.meta.metasrv import MetasrvOptions
from greptimedb_tpu.partition.rule import PartitionBound, RangePartitionRule
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.utils import deadline as dl
from greptimedb_tpu.utils.metrics import DEADLINE_EVENTS, HEDGE_EVENTS

CREATE = (
    "CREATE TABLE cpu (host STRING, region STRING, usage_user DOUBLE, "
    "usage_system DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, region))"
)


@pytest.fixture(autouse=True)
def clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _host_rule(*splits):
    bounds = [PartitionBound((s,)) for s in splits] + [PartitionBound(())]
    return RangePartitionRule(["host"], bounds)


def _seed_rows(cluster, n_hosts=6, points_per_host=4):
    rows = []
    for h in range(n_hosts):
        for t in range(points_per_host):
            rows.append(f"('host{h}', 'us-west', {10.0 + h}, {1.0 * t}, "
                        f"{1000 * (t + 1)})")
    cluster.sql(
        "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
        "VALUES " + ", ".join(rows))


# ---- token/unit surface -----------------------------------------------------


class TestTokenUnit:
    def test_parse_timeout_ms(self):
        assert dl.parse_timeout_ms(500) == 500.0
        assert dl.parse_timeout_ms("500") == 500.0
        assert dl.parse_timeout_ms("'250ms'") == 250.0
        assert dl.parse_timeout_ms("2s") == 2000.0
        assert dl.parse_timeout_ms("1min") == 60000.0
        assert dl.parse_timeout_ms(None) is None
        assert dl.parse_timeout_ms("garbage") is None

    def test_expired_token_counts_exactly_once(self):
        before = DEADLINE_EVENTS.get(event="expired")
        tok = dl.CancelToken(timeout_ms=1)
        time.sleep(0.01)
        for _ in range(3):  # every checkpoint raises, ONE counted event
            with pytest.raises(DeadlineExceeded):
                tok.check("unit")
        assert DEADLINE_EVENTS.get(event="expired") == before + 1

    def test_uncounted_cancel_is_metric_silent(self):
        """Hedge losers are infrastructure churn: their cancel must not
        inflate the user-facing deadline-events counter."""
        before = DEADLINE_EVENTS.get(event="cancelled")
        tok = dl.CancelToken()
        tok.cancel("hedge loser", kind="cancelled", count=False)
        with pytest.raises(Cancelled):
            tok.check("unit")
        assert DEADLINE_EVENTS.get(event="cancelled") == before

    def test_wait_future_unwinds_typed_on_deadline(self):
        pool = ThreadPoolExecutor(max_workers=1)
        gate = threading.Event()
        try:
            fut = pool.submit(gate.wait, 30.0)
            tok = dl.CancelToken(timeout_ms=50)
            t0 = time.monotonic()
            with dl.activate(tok):
                with pytest.raises(DeadlineExceeded):
                    dl.wait_future(fut, "unit")
            assert time.monotonic() - t0 < 2.0
        finally:
            gate.set()
            pool.shutdown(wait=True)

    def test_wait_future_without_token_returns_value(self):
        pool = ThreadPoolExecutor(max_workers=1)
        try:
            assert dl.wait_future(pool.submit(lambda: 7)) == 7
        finally:
            pool.shutdown(wait=True)

    def test_running_queries_register_kill_unregister(self):
        tok = dl.CancelToken()
        qid = dl.RUNNING.register(tok, "SELECT 1", db="public",
                                  channel="http")
        assert any(e["id"] == qid for e in dl.RUNNING.list())
        assert dl.RUNNING.kill(qid)
        with pytest.raises(Cancelled):
            tok.check("unit")
        dl.RUNNING.unregister(qid)
        assert not any(e["id"] == qid for e in dl.RUNNING.list())
        assert not dl.RUNNING.kill(qid)  # already gone

    def test_client_disconnect_cancels_token(self):
        import socket

        a, b = socket.socketpair()
        tok = dl.CancelToken()
        stop = dl.watch_disconnect(a, tok)
        try:
            b.close()  # the client goes away mid-statement
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline and not tok.cancelled:
                time.sleep(0.02)
            with pytest.raises(Cancelled):
                tok.check("unit")
        finally:
            stop()
            a.close()


# ---- the straggler matrix ---------------------------------------------------


class TestStragglerDeadline:
    def test_stalled_scan_unwinds_typed_within_budget(self, tmp_path):
        """A 5 s object-store stall under a 500 ms budget: the query
        answers typed DeadlineExceeded in well under the stall, the
        admission slots drain, the registry empties, and the SAME query
        succeeds once the stall clears — nothing leaked or wedged."""
        c = Cluster(str(tmp_path), num_datanodes=3, opts=MetasrvOptions())
        try:
            info = c.create_partitioned_table(CREATE,
                                              _host_rule("host2", "host4"))
            _seed_rows(c)
            for rid in info.region_ids:
                c.router.flush(rid)  # the scan must hit the object store
            before = DEADLINE_EVENTS.get(event="expired")
            FAULTS.arm("objectstore.read", Fault(kind="latency", arg=5.0))
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                c.frontend.execute_one(
                    "SELECT count(*) FROM cpu",
                    QueryContext(db="public", timeout_ms=500))
            elapsed = time.monotonic() - t0
            assert elapsed < 3.0, f"typed unwind took {elapsed:.2f}s"
            assert DEADLINE_EVENTS.get(event="expired") == before + 1
            # resource postconditions: nothing stays admitted/registered
            adm = c.frontend.concurrency.admission
            assert adm.active == 0 and adm.queued == 0
            assert dl.RUNNING.list() == []
            # the stall clears: the identical query now answers correctly
            FAULTS.reset()
            res = c.frontend.execute_one(
                "SELECT count(*) FROM cpu",
                QueryContext(db="public", timeout_ms=5000))
            assert res.rows()[0][0] == 24
        finally:
            c.close()

    def test_kill_query_mid_scan(self, tmp_path):
        """KILL QUERY <id> while the victim is parked inside a stalled
        scan: the victim unwinds typed Cancelled promptly (not after the
        stall), the killed event is counted, the registry empties."""
        c = Cluster(str(tmp_path), num_datanodes=3, opts=MetasrvOptions())
        try:
            info = c.create_partitioned_table(CREATE,
                                              _host_rule("host2", "host4"))
            _seed_rows(c)
            for rid in info.region_ids:
                c.router.flush(rid)
            before = DEADLINE_EVENTS.get(event="killed")
            FAULTS.arm("objectstore.read", Fault(kind="latency", arg=30.0))
            victim_sql = "SELECT count(*) FROM cpu"
            outcome: list = []

            def run():
                try:
                    outcome.append(c.frontend.execute_one(
                        victim_sql, QueryContext(db="public")))
                except BaseException as e:  # noqa: BLE001 — asserted below
                    outcome.append(e)

            th = threading.Thread(target=run, daemon=True)
            th.start()
            qid = None
            poll_until = time.monotonic() + 5.0
            while time.monotonic() < poll_until and qid is None:
                for e in dl.RUNNING.list():
                    if e["query"] == victim_sql:
                        qid = e["id"]
                time.sleep(0.02)
            assert qid is not None, "victim never registered"
            t0 = time.monotonic()
            assert c.sql(f"KILL QUERY {qid}").rows() is not None
            th.join(timeout=8.0)
            assert not th.is_alive(), "victim still parked after KILL"
            assert time.monotonic() - t0 < 8.0
            assert outcome and isinstance(outcome[0], Cancelled), outcome
            assert DEADLINE_EVENTS.get(event="killed") == before + 1
            assert dl.RUNNING.list() == []
        finally:
            c.close()


class TestProcessClusterStraggler:
    def test_stalled_datanode_typed_deadline_over_the_wire(
            self, tmp_path, monkeypatch):
        """The cross-process acceptance case: a child datanode stalled
        5 s inside its Flight do_get handler, frontend budget 500 ms.
        Typed DeadlineExceeded must come back in bounded time — via the
        ticket's budget unwinding server-side, the per-call gRPC
        deadline, or both racing — and a follow-up query on the SAME
        cluster must succeed (no slot, pin, or route left wedged)."""
        from greptimedb_tpu.cluster.process_cluster import ProcessCluster

        monkeypatch.setenv(
            "GTPU_CHAOS",
            "flight.do_get=latency,arg:5,times:1,@side:server")
        monkeypatch.setenv("GTPU_HEDGE", "off")  # isolate the deadline path
        c = ProcessCluster(str(tmp_path), num_datanodes=2,
                           opts=MetasrvOptions())
        try:
            c.beat_all(time.time() * 1000)
            c.sql("CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP "
                  "TIME INDEX, PRIMARY KEY(host))")
            c.sql("INSERT INTO m VALUES ('a', 1.0, 1000), ('b', 2.0, 2000)")
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                c.frontend.execute_one(
                    "SELECT host, v FROM m ORDER BY host",
                    QueryContext(db="public", timeout_ms=500))
            elapsed = time.monotonic() - t0
            assert elapsed < 3.0, f"typed unwind took {elapsed:.2f}s"
            adm = c.frontend.concurrency.admission
            assert adm.active == 0 and adm.queued == 0
            assert dl.RUNNING.list() == []
            # the schedule is consumed (times:1): same query now answers
            r = c.frontend.execute_one(
                "SELECT host, v FROM m ORDER BY host",
                QueryContext(db="public", timeout_ms=10000))
            assert r.rows() == [["a", 1.0], ["b", 2.0]]
        finally:
            c.close()


# ---- hedged region requests -------------------------------------------------


class TestHedging:
    def _bare_router(self):
        from greptimedb_tpu.cluster.cluster import RegionRouter, _HedgePlane

        router = object.__new__(RegionRouter)
        router._hedge = _HedgePlane()
        router._region_node = {}
        return router

    def test_hedge_wins_and_loser_is_cancelled(self, monkeypatch):
        """Stalled primary, fast hedge: the hedge's value comes back,
        fired/won are counted, the primary's token is cancelled (it
        stops burning the stalled path) — and the loser's cancel never
        shows up in the user-facing deadline-events counter."""
        monkeypatch.setenv("GTPU_HEDGE_DELAY_MS", "10")
        router = self._bare_router()
        fired0 = HEDGE_EVENTS.get(event="fired")
        won0 = HEDGE_EVENTS.get(event="won")
        cancelled0 = DEADLINE_EVENTS.get(event="cancelled")
        lock = threading.Lock()
        calls: list = []
        primary_cancelled = threading.Event()

        def call(eng):
            with lock:
                calls.append(1)
                first = len(calls) == 1
            if first:
                try:
                    dl.sleep(30.0, "stalled primary")
                except Cancelled:
                    primary_cancelled.set()
                    raise
                return "slow"
            return 42

        t0 = time.monotonic()
        assert router._hedged_call(1 << 32, None, call) == 42
        assert time.monotonic() - t0 < 5.0
        assert HEDGE_EVENTS.get(event="fired") == fired0 + 1
        assert HEDGE_EVENTS.get(event="won") == won0 + 1
        assert primary_cancelled.wait(5.0), "loser never cancelled"
        assert DEADLINE_EVENTS.get(event="cancelled") == cancelled0

    def test_primary_win_cancels_hedge_and_counts_lost(self, monkeypatch):
        monkeypatch.setenv("GTPU_HEDGE_DELAY_MS", "10")
        router = self._bare_router()
        lost0 = HEDGE_EVENTS.get(event="lost")
        lock = threading.Lock()
        calls: list = []

        def call(eng):
            with lock:
                calls.append(1)
                first = len(calls) == 1
            if first:
                time.sleep(0.1)  # slow enough for the hedge to fire
                return "primary"
            dl.sleep(30.0, "stalled hedge")
            return "hedge"

        assert router._hedged_call(1 << 32, None, call) == "primary"
        assert HEDGE_EVENTS.get(event="lost") == lost0 + 1

    def test_budget_denied_when_bucket_empty(self, monkeypatch):
        monkeypatch.setenv("GTPU_HEDGE_DELAY_MS", "1")
        router = self._bare_router()
        router._hedge._credit = 0.0  # drained token bucket
        denied0 = HEDGE_EVENTS.get(event="budget_denied")

        def call(eng):
            time.sleep(0.05)
            return "only"

        assert router._hedged_call(1 << 32, None, call) == "only"
        assert HEDGE_EVENTS.get(event="budget_denied") == denied0 + 1

    def test_hedged_read_bit_identical_over_the_wire(
            self, tmp_path, monkeypatch):
        """Hedging forced on every remote fragment (delay 0): results
        are identical to the unhedged run and hedge events are
        observable — first-response-wins changes tail latency, never
        answers."""
        from greptimedb_tpu.cluster.process_cluster import ProcessCluster

        c = ProcessCluster(str(tmp_path), num_datanodes=2,
                           opts=MetasrvOptions())
        try:
            c.beat_all(time.time() * 1000)
            c.sql("CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP "
                  "TIME INDEX, PRIMARY KEY(host))")
            c.sql("INSERT INTO m VALUES ('a', 1.0, 1000), ('b', 2.0, 2000),"
                  " ('c', 3.0, 3000)")
            agg = "SELECT count(*), sum(v) FROM m"
            monkeypatch.setenv("GTPU_HEDGE", "off")
            baseline = c.sql(agg).rows()
            monkeypatch.delenv("GTPU_HEDGE", raising=False)
            monkeypatch.setenv("GTPU_HEDGE_DELAY_MS", "0")
            fired0 = HEDGE_EVENTS.get(event="fired")
            for _ in range(3):
                assert c.sql(agg).rows() == baseline
            assert HEDGE_EVENTS.get(event="fired") > fired0
            done0 = (HEDGE_EVENTS.get(event="won")
                     + HEDGE_EVENTS.get(event="lost"))
            assert done0 > 0  # every fired hedge resolved won-or-lost
        finally:
            c.close()


# ---- the lint checker (satellite a) -----------------------------------------


class TestDeadlineLintChecker:
    def _check(self, path, src):
        from greptimedb_tpu.lint import Repo, SourceFile
        from greptimedb_tpu.lint.deadline import check

        return check(Repo(root="",
                          files=[SourceFile.from_text(path, src)]))

    def test_unbounded_wait_in_serving_scope_fires(self):
        found = self._check("greptimedb_tpu/servers/foo.py", """
def handler(ev):
    ev.wait()
""")
        assert len(found) == 1 and "ev.wait" in found[0].message

    def test_timeout_clears_the_finding(self):
        found = self._check("greptimedb_tpu/servers/foo.py", """
def handler(ev, fut, q):
    ev.wait(1.0)
    fut.result(timeout=2.0)
    q.get(timeout=0.1)
""")
        assert found == []

    def test_blocking_queue_get_fires(self):
        found = self._check("greptimedb_tpu/query/foo.py", """
def drain(work_queue):
    return work_queue.get()
""")
        assert len(found) == 1

    def test_outside_serving_scope_is_free(self):
        found = self._check("greptimedb_tpu/cli/foo.py", """
def offline(ev):
    ev.wait()
""")
        assert found == []
