"""CLI-deployed distributed cluster: metasrv + datanodes + frontend as
REAL OS processes wired over HTTP/Flight — no parent-proxy heartbeats.

The round-4 verdict's missing #1/#2/#4: separate-role service processes
(reference src/cmd/src/bin/greptime.rs:35-55), a networked metadata KV
(kv_backend/etcd.rs analog), and datanode-owned heartbeats
(datanode/src/heartbeat.rs:47-183). Every control-plane interaction here
crosses a process boundary: datanodes heartbeat the metasrv themselves
over HTTP, the frontend discovers routes/addresses from the networked
KV, and kill -9 failover is driven end-to-end by the metasrv's own tick
loop with instructions delivered on the surviving datanodes' heartbeats.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request

import pytest

FAST = ["--heartbeat-interval", "0.25"]


def _spawn(tmp_path, name, *args):
    log = open(os.path.join(tmp_path, f"{name}.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "greptimedb_tpu", *args],
        stdout=log, stderr=subprocess.STDOUT,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "GREPTIMEDB_TPU_PLATFORM": "cpu"},
    )
    return proc, log


def _wait_port(path, proc, name, timeout_s=90.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            log = path.replace(".port", ".log")
            tail = ""
            base = os.path.dirname(path)
            lp = os.path.join(base, f"{name}.log")
            if os.path.exists(lp):
                tail = open(lp, "rb").read()[-2000:].decode(errors="replace")
            raise RuntimeError(f"{name} died at startup:\n{tail}")
        if os.path.exists(path):
            return int(open(path).read().strip())
        time.sleep(0.05)
    raise TimeoutError(f"{name} did not write {path}")


def _sql(port, sql, timeout=30):
    q = urllib.parse.urlencode({"sql": sql})
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/sql?{q}", timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        # surface the server's error body — a bare "HTTP Error 400"
        # is undiagnosable when the failure is load-dependent
        body = e.read().decode(errors="replace")[:500]
        raise AssertionError(
            f"HTTP {e.code} for {sql!r}: {body}") from None


@pytest.fixture
def cluster(tmp_path):
    """metasrv + 2 datanodes + frontend, all via the CLI."""
    tmp = str(tmp_path)
    shared = os.path.join(tmp, "shared")
    os.makedirs(shared, exist_ok=True)
    procs = []
    logs = []
    try:
        ms_port_file = os.path.join(tmp, "ms.port")
        p, lg = _spawn(
            tmp, "metasrv", "metasrv", "start",
            "--data-home", os.path.join(tmp, "meta"),
            "--bind-addr", "127.0.0.1:0",
            "--port-file", ms_port_file,
            "--region-lease", "1.5", "--failure-threshold", "4.0",
            *FAST)
        procs.append(p)
        logs.append(lg)
        ms_port = _wait_port(ms_port_file, p, "metasrv")
        metasrv = f"127.0.0.1:{ms_port}"

        dns = {}
        for i in range(2):
            pf = os.path.join(tmp, f"dn-{i}.port")
            p, lg = _spawn(
                tmp, f"dn-{i}", "datanode", "start",
                "--node-id", f"dn-{i}", "--metasrv", metasrv,
                "--data-home", shared, "--rpc-addr", "127.0.0.1:0",
                "--port-file", pf, *FAST)
            procs.append(p)
            logs.append(lg)
            dns[f"dn-{i}"] = p
        for i in range(2):
            _wait_port(os.path.join(tmp, f"dn-{i}.port"), dns[f"dn-{i}"],
                       f"dn-{i}")

        fe_pf = os.path.join(tmp, "fe.port")
        p, lg = _spawn(
            tmp, "frontend", "frontend", "start",
            "--metasrv", metasrv, "--http-addr", "127.0.0.1:0",
            "--port-file", fe_pf)
        procs.append(p)
        logs.append(lg)
        fe_port = _wait_port(fe_pf, p, "frontend")
        yield {"fe_port": fe_port, "metasrv": metasrv, "dns": dns,
               "tmp": tmp, "metasrv_proc": procs[0]}
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 10
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for lg in logs:
            lg.close()


def test_cli_cluster_sql_and_failover(cluster):
    fe = cluster["fe_port"]
    # DDL + writes route over Flight to a datanode chosen by the
    # frontend's selector from heartbeat-registered nodes
    out = _sql(fe, "CREATE TABLE cpu (host STRING, val DOUBLE, "
                   "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host))")
    assert out["code"] == 0, out
    out = _sql(fe, "INSERT INTO cpu VALUES ('a', 1.0, 1000), "
                   "('b', 2.0, 2000), ('a', 3.0, 61000)")
    assert out["output"][0]["affectedrows"] == 3
    out = _sql(fe, "SELECT host, sum(val) FROM cpu GROUP BY host "
                   "ORDER BY host")
    rows = out["output"][0]["records"]["rows"]
    assert rows == [["a", 4.0], ["b", 2.0]]

    # find the datanode OS process serving the region and kill -9 it
    owner, _rid = _region_owner(cluster["metasrv"])
    assert owner in cluster["dns"], owner
    victim = cluster["dns"][owner]
    victim.kill()
    victim.wait()

    # failover: the metasrv's own ticker detects death, the failover
    # procedure instructs the survivor on ITS next heartbeat, the
    # frontend re-resolves the route — all over the wire. WAL is shared
    # (remote backend), so the un-flushed rows must survive.
    deadline = time.monotonic() + 60
    rows = None
    while time.monotonic() < deadline:
        try:
            out = _sql(fe, "SELECT host, sum(val) FROM cpu GROUP BY host "
                           "ORDER BY host")
            if out.get("code") == 0:
                rows = out["output"][0]["records"]["rows"]
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert rows == [["a", 4.0], ["b", 2.0]], rows

    # the failed-over table accepts writes again
    out = _sql(fe, "INSERT INTO cpu VALUES ('c', 9.0, 120000)")
    assert out["output"][0]["affectedrows"] == 1
    out = _sql(fe, "SELECT count(*) FROM cpu")
    assert out["output"][0]["records"]["rows"][0][0] == 4


def _region_owner(metasrv_addr):
    """(leader_node, region_id) of the single test table, read from the
    networked KV the way a frontend reads routes."""
    import http.client

    host, _, port = metasrv_addr.partition(":")
    c = http.client.HTTPConnection(host, int(port), timeout=5)
    c.request("POST", "/kv/range",
              json.dumps({"prefix": "__meta/table_route/"}).encode(),
              {"Content-Type": "application/json"})
    raw = json.loads(c.getresponse().read())
    c.close()
    owner = rid = None
    for _, v in raw["items"]:
        route = json.loads(v)
        for rr in route.get("regions", []):
            if rr.get("leader_node"):
                owner, rid = rr["leader_node"], rr["region_id"]
    return owner, rid


def test_flownode_process_ticks_flows(cluster):
    """A CLI-spawned flownode process picks flows up from the shared
    metadata KV and keeps the sink current — the reference's flownode
    role (cmd/src/flownode.rs + adapter.rs run_available)."""
    fe = cluster["fe_port"]
    out = _sql(fe, "CREATE TABLE fsrc (host STRING, v DOUBLE, "
                   "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
                   "WITH (append_mode = 'true')")
    assert out["code"] == 0, out
    out = _sql(fe, "CREATE FLOW ftot SINK TO fsink AS "
                   "SELECT host, sum(v) AS s FROM fsrc GROUP BY host")
    assert out["code"] == 0, out
    fn_pf = os.path.join(cluster["tmp"], "fn.port")
    p, lg = _spawn(cluster["tmp"], "flownode", "flownode", "start",
                   "--metasrv", cluster["metasrv"],
                   "--tick-interval", "0.3", "--port-file", fn_pf)
    try:
        _wait_port(fn_pf, p, "flownode")
        _sql(fe, "INSERT INTO fsrc VALUES ('a', 1.0, 1000), "
                 "('a', 2.0, 2000), ('b', 5.0, 1000)")
        deadline = time.monotonic() + 45
        rows = None
        while time.monotonic() < deadline:
            out = _sql(fe, "SELECT host, s FROM fsink ORDER BY host")
            if out.get("code") == 0:
                rows = out["output"][0]["records"]["rows"]
                if rows == [["a", 3.0], ["b", 5.0]]:
                    break
            time.sleep(0.4)
        assert rows == [["a", 3.0], ["b", 5.0]], rows
        # second batch folds incrementally on the flownode
        _sql(fe, "INSERT INTO fsrc VALUES ('a', 10.0, 3000)")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            out = _sql(fe, "SELECT s FROM fsink WHERE host = 'a'")
            rows = out["output"][0]["records"]["rows"]
            if rows == [[13.0]]:
                break
            time.sleep(0.4)
        assert rows == [[13.0]], rows
    finally:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        lg.close()


def test_region_migration_over_the_wire(cluster):
    """migrate_region through the metasrv admin API: the
    downgrade→open-candidate→upgrade→swap-route handshake runs across
    real processes, instructions delivered on datanode heartbeats, and
    the frontend follows the swapped route."""
    fe = cluster["fe_port"]
    out = _sql(fe, "CREATE TABLE m (host STRING, v DOUBLE, "
                   "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
                   "WITH (append_mode = 'true')")
    assert out["code"] == 0, out
    out = _sql(fe, "INSERT INTO m VALUES ('a', 1.0, 1000), "
                   "('b', 2.0, 2000)")
    assert out["output"][0]["affectedrows"] == 2
    owner, rid = _region_owner(cluster["metasrv"])
    target = next(n for n in cluster["dns"] if n != owner)

    from greptimedb_tpu.meta.kv_service import MetaClient

    proc_id = MetaClient(cluster["metasrv"]).migrate_region(
        str(rid >> 32), rid, target)
    assert proc_id

    # instructions flow on heartbeats; wait for the route to swap and
    # the data to serve from the new owner — tracked separately so a
    # failure names the subsystem that actually stalled
    deadline = time.monotonic() + 45
    route_swapped = data_served = False
    last = None
    while time.monotonic() < deadline:
        now_owner, _ = _region_owner(cluster["metasrv"])
        if now_owner == target:
            route_swapped = True
            try:
                # transient during handover: the old owner may have
                # closed the region before the frontend's watch-driven
                # invalidation lands
                last = _sql(fe, "SELECT host, sum(v) FROM m GROUP BY "
                                "host ORDER BY host")
            except Exception as e:  # noqa: BLE001 — retried
                last = {"error": repr(e)}
            if last.get("code") == 0 and \
                    last["output"][0]["records"]["rows"] == \
                    [["a", 1.0], ["b", 2.0]]:
                data_served = True
                break
        time.sleep(0.4)
    assert route_swapped, f"route never moved to {target}"
    assert data_served, f"route moved but data never served: {last}"
    # writes land on the new owner
    out = _sql(fe, "INSERT INTO m VALUES ('c', 3.0, 3000)")
    assert out["output"][0]["affectedrows"] == 1
    out = _sql(fe, "SELECT count(*) FROM m")
    assert out["output"][0]["records"]["rows"][0][0] == 3


def test_datanode_self_close_on_lease_expiry(cluster):
    """Split-brain guard: SIGSTOP the metasrv so leases stop renewing —
    the datanode's OWN alive-keeper must close its regions, observed
    directly on the datanode's Flight port (no frontend, no parent)."""
    from greptimedb_tpu.servers.flight import RemoteRegionEngine

    fe = cluster["fe_port"]
    out = _sql(fe, "CREATE TABLE g (host STRING, v DOUBLE, "
                   "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host))")
    assert out["code"] == 0, out
    _sql(fe, "INSERT INTO g VALUES ('x', 1.0, 1000)")
    owner, rid = _region_owner(cluster["metasrv"])
    dn_port = int(open(os.path.join(cluster["tmp"],
                                    f"{owner}.port")).read())
    remote = RemoteRegionEngine(f"127.0.0.1:{dn_port}")
    assert remote.scan(rid) is not None  # serving before the freeze

    cluster["metasrv_proc"].send_signal(signal.SIGSTOP)
    try:
        deadline = time.monotonic() + 30  # lease 1.5s; allow margin
        closed = False
        while time.monotonic() < deadline:
            try:
                remote.scan(rid)
            except Exception:
                closed = True
                break
            time.sleep(0.25)
        assert closed, "region still serving after lease expiry"
    finally:
        cluster["metasrv_proc"].send_signal(signal.SIGCONT)
        remote.close()
