"""Device tier (ISSUE 7): the fused scan→filter→bucket→aggregate Pallas
kernel differentially against the XLA scatter path, the HBM-resident
columnar hot set under the storage mutation matrix
(flush/compaction/expiry/DROP), buffer donation on the chunked
accumulator loops, the mid-query kernel-failure degradation latch, and
measured (history-driven) tier routing."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import greptimedb_tpu.query.physical as ph  # noqa: E402
from greptimedb_tpu.catalog import Catalog, MemoryKv  # noqa: E402
from greptimedb_tpu.ops.pallas_segment import (  # noqa: E402
    MAX_FUSED_FIELDS,
    MAX_SEGMENTS,
    fused_eligible,
    pallas_fused_segment_agg,
)
from greptimedb_tpu.query import QueryEngine  # noqa: E402
from greptimedb_tpu.storage import RegionEngine  # noqa: E402
from greptimedb_tpu.storage.engine import EngineConfig  # noqa: E402


# ---- fused kernel vs oracle (interpret mode on CPU) ------------------------


def _oracle(vals, ids, g):
    """Reference masked segment aggregation: NaN = SQL NULL, empty/
    all-NULL groups -> 0 counts and ±inf extremes (kernel contract)."""
    n, f = vals.shape
    out = {
        "sum": np.zeros((g, f)),
        "count": np.zeros((g, f)),
        "rows": np.zeros(g),
        "min": np.full((g, f), np.inf),
        "max": np.full((g, f), -np.inf),
    }
    for i in range(n):
        s = ids[i]
        out["rows"][s] += 1
        for j in range(f):
            v = vals[i, j]
            if np.isnan(v):
                continue
            out["sum"][s, j] += v
            out["count"][s, j] += 1
            out["min"][s, j] = min(out["min"][s, j], v)
            out["max"][s, j] = max(out["max"][s, j], v)
    return out


@pytest.mark.parametrize("n,f,g,seed", [
    (1000, 10, 61, 1),    # the double-groupby shape class
    (777, 1, 9, 2),       # single column, ragged rows
    (513, 56, 64, 3),     # full fused field width
    (3, 4, 8, 4),         # tiny
])
def test_fused_kernel_matches_oracle(n, f, g, seed):
    rng = np.random.default_rng(seed)
    vals = rng.uniform(-100, 100, (n, f))
    vals[rng.uniform(0, 1, (n, f)) < 0.15] = np.nan  # NULL sprinkle
    # segment g-1 is the DEAD segment (padding rows land there — the
    # caller's masked-row contract): live ids stay below it and only
    # the live slice is compared
    ids = rng.integers(0, g - 1, n).astype(np.int32)
    got = pallas_fused_segment_agg(
        jnp.asarray(vals), jnp.asarray(ids), g,
        want_min=True, want_max=True, interpret=True)
    want = _oracle(vals, ids, g)
    live = g - 1
    np.testing.assert_allclose(np.asarray(got["sum"])[:live],
                               want["sum"][:live], rtol=1e-9, atol=1e-9)
    np.testing.assert_array_equal(np.asarray(got["count"])[:live],
                                  want["count"][:live])
    np.testing.assert_array_equal(np.asarray(got["rows"])[:live],
                                  want["rows"][:live])
    np.testing.assert_array_equal(np.asarray(got["min"])[:live],
                                  want["min"][:live])
    np.testing.assert_array_equal(np.asarray(got["max"])[:live],
                                  want["max"][:live])


def test_fused_integer_planes_bit_exact():
    """Integer-valued planes: matmul-summed sums and counts are EXACT
    (< 2^53, every partial is an integer), matching the scatter path
    bit for bit — the differential-suite contract."""
    rng = np.random.default_rng(7)
    n, f, g = 2048, 6, 33
    vals = rng.integers(-1000, 1000, (n, f)).astype(np.float64)
    ids = rng.integers(0, g, n).astype(np.int32)
    got = pallas_fused_segment_agg(jnp.asarray(vals), jnp.asarray(ids), g,
                                   interpret=True)
    want_sum = np.asarray(jax.ops.segment_sum(
        jnp.asarray(vals), jnp.asarray(ids), num_segments=g))
    np.testing.assert_array_equal(np.asarray(got["sum"]), want_sum)
    ones = np.ones((n, f))
    want_cnt = np.asarray(jax.ops.segment_sum(
        jnp.asarray(ones), jnp.asarray(ids), num_segments=g))
    np.testing.assert_array_equal(np.asarray(got["count"]), want_cnt)


def test_fused_f32_tolerance():
    rng = np.random.default_rng(11)
    n, f, g = 4096, 10, 128
    vals = rng.uniform(0, 100, (n, f)).astype(np.float32)
    ids = rng.integers(0, g, n).astype(np.int32)
    got = pallas_fused_segment_agg(
        jnp.asarray(vals), jnp.asarray(ids), g,
        want_min=True, want_max=True, interpret=True)
    want = _oracle(vals.astype(np.float64), ids, g)
    np.testing.assert_allclose(np.asarray(got["sum"]), want["sum"],
                               rtol=2e-5)
    # extremes are selections, not accumulations: exact even in f32
    np.testing.assert_array_equal(np.asarray(got["min"]),
                                  want["min"].astype(np.float32))
    np.testing.assert_array_equal(np.asarray(got["max"]),
                                  want["max"].astype(np.float32))


def test_fused_dead_segment_rows_excluded():
    """Masked rows arrive encoded into the dead segment (the caller's
    contract): their values must not leak into live segments."""
    vals = np.asarray([[1.0], [2.0], [1e9]])
    ids = np.asarray([0, 0, 2], dtype=np.int32)  # row 2 -> dead seg
    got = pallas_fused_segment_agg(jnp.asarray(vals), jnp.asarray(ids), 3,
                                   want_min=True, want_max=True,
                                   interpret=True)
    assert float(got["sum"][0, 0]) == 3.0
    assert float(got["rows"][0]) == 2.0
    assert float(got["max"][0, 0]) == 2.0
    assert float(got["sum"][1, 0]) == 0.0
    assert float(got["min"][1, 0]) == np.inf


def test_fused_eligibility_envelope():
    assert fused_eligible(10, 61)
    assert fused_eligible(MAX_FUSED_FIELDS, MAX_SEGMENTS)
    assert not fused_eligible(MAX_FUSED_FIELDS + 1, 61)
    assert not fused_eligible(10, MAX_SEGMENTS + 1)
    assert not fused_eligible(0, 61)


def test_finite_proof_runs_in_compute_dtype():
    """A finite f64 value that overflows the f64->f32 cast reaches the
    one-hot matmul as Inf all the same — the fused-route finite proof
    must run post-cast, or the f32 chip path NaN-poisons every group."""
    from types import SimpleNamespace

    has = ph.PhysicalExecutor._scan_has_inf
    scan = SimpleNamespace(columns={"v": np.array([1.0, 1e40])})
    assert not has(None, scan, ("v",))                  # finite in f64
    assert has(None, scan, ("v",), dtype=np.float32)    # Inf after cast
    # memoization is per-dtype: the f64 verdict is not clobbered
    assert not has(None, scan, ("v",), dtype=np.float64)
    # a genuinely infinite column is flagged under every dtype
    scan2 = SimpleNamespace(columns={"v": np.array([np.inf, 1.0])})
    assert has(None, scan2, ("v",))
    assert has(None, scan2, ("v",), dtype=np.float32)
    # integer columns can never go infinite
    scan3 = SimpleNamespace(columns={"v": np.array([1, 2], dtype=np.int64)})
    assert not has(None, scan3, ("v",), dtype=np.float32)


# ---- engine-level fixtures -------------------------------------------------


@pytest.fixture
def db(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "d"),
                                       maintenance_workers=0))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    yield engine, qe
    engine.close()


def _fill(qe, files=3, hosts=5, points=40):
    qe.execute_one(
        "CREATE TABLE t (host STRING, v DOUBLE, ts TIMESTAMP(3) "
        "TIME INDEX, PRIMARY KEY(host)) WITH (append_mode = 'true')")
    rng = np.random.default_rng(5)
    i = 0
    for f in range(files):
        rows = []
        for p in range(points):
            for h in range(hosts):
                rows.append(f"('h{h}', {rng.uniform(0, 100):.6f}, "
                            f"{(f * points + p) * 1000})")
                i += 1
        qe.execute_one("INSERT INTO t (host, v, ts) VALUES "
                       + ",".join(rows))
        qe.execute_one("ADMIN flush_table('t')")
    return qe.catalog.table("public", "t").region_ids[0]


AGG_SQL = ("SELECT host, sum(v), count(v), min(v), max(v), avg(v) "
           "FROM t GROUP BY host ORDER BY host")


def _h2d():
    from greptimedb_tpu.utils.metrics import DEVICE_TRANSFER_BYTES

    return DEVICE_TRANSFER_BYTES.get(direction="h2d")


def rows_close(a, b):
    """Row-set equality with float tolerance: compaction/merges reorder
    the physical rows, so float sums differ in the last ulps."""
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra[0] == rb[0]
        np.testing.assert_allclose([float(x) for x in ra[1:]],
                                   [float(y) for y in rb[1:]],
                                   rtol=1e-9)


# ---- hot-set residency + invalidation matrix -------------------------------


class TestHotSet:
    def test_warm_repeat_pays_zero_h2d(self, db):
        engine, qe = db
        rid = _fill(qe)
        want = qe.execute_one(AGG_SQL).rows()
        cache = qe.executor.cache
        assert cache.file_keys(rid), "no file-anchored blocks resident"
        before = _h2d()
        got = qe.execute_one(AGG_SQL).rows()
        assert _h2d() == before, "hot-set-warm repeat re-uploaded blocks"
        assert got == want

    def test_flush_keeps_old_files_uploads_only_new(self, db):
        engine, qe = db
        rid = _fill(qe)
        qe.execute_one(AGG_SQL)
        cache = qe.executor.cache
        old_keys = set(cache.file_keys(rid))
        assert old_keys
        qe.execute_one(
            "INSERT INTO t (host, v, ts) VALUES ('h0', 1.0, 999000)")
        qe.execute_one("ADMIN flush_table('t')")
        want = qe.execute_one(AGG_SQL).rows()
        keys = set(cache.file_keys(rid))
        # every pre-flush upload survived the data-version bump...
        assert old_keys <= keys
        # ...and the new file's blocks joined them
        assert len(keys) > len(old_keys)
        # correctness across the incremental upload
        assert qe.execute_one(AGG_SQL).rows() == want

    def test_compaction_swap_kills_input_blocks(self, db):
        engine, qe = db
        rid = _fill(qe)
        want = qe.execute_one(AGG_SQL).rows()
        cache = qe.executor.cache
        old_ids = {k[2] for k in cache.file_keys(rid)}
        assert old_ids
        engine.compact(rid)  # full merge -> every input file dies
        live = set(engine.region(rid).files)
        assert not ({k[2] for k in cache.file_keys(rid)} - live)
        rows_close(qe.execute_one(AGG_SQL).rows(), want)

    def test_retention_expiry_kills_expired_blocks(self, db):
        from greptimedb_tpu.maintenance.retention import run_expiry

        engine, qe = db
        rid = _fill(qe)
        qe.execute_one(AGG_SQL)
        cache = qe.executor.cache
        assert cache.file_keys(rid)
        region = engine.region(rid)
        newest = max(m.ts_max for m in region.files.values())
        res = run_expiry(region, ttl_ms=1, now_ms=newest + 2)
        assert res["removed"] >= 1
        live = set(region.files)
        assert not ({k[2] for k in cache.file_keys(rid)} - live)

    def test_drop_clears_region_blocks(self, db):
        engine, qe = db
        rid = _fill(qe)
        # unflushed rows too, so snapshot-anchored entries exist
        qe.execute_one(
            "INSERT INTO t (host, v, ts) VALUES ('h0', 7.0, 888000)")
        qe.execute_one(AGG_SQL)
        cache = qe.executor.cache
        assert cache.file_keys(rid)
        qe.execute_one("DROP TABLE t")
        assert not cache.file_keys(rid)
        # snap-anchored entries die with the region as well: TRUNCATE
        # reuses the region_id AND resets data_version, so a survivor
        # could collide with a post-truncate re-ingest
        with cache._lock:
            assert not [k for k in cache._lru
                        if k[0] == "snap" and k[1] == rid]

    def test_truncate_reingest_serves_fresh_data(self, db):
        """TRUNCATE + same-shaped re-ingest must never serve a
        pre-truncate HBM block. Memtable-only on both sides ON PURPOSE:
        the recreated region restarts data_version, so the snapshot key
        ("snap", rid, 1, fingerprint, ...) COLLIDES exactly — without
        the drop-seam region invalidation this query returns the old
        table's sums (verified: sum 50.0 instead of 10.0)."""
        engine, qe = db
        qe.execute_one(
            "CREATE TABLE t (host STRING, v DOUBLE, ts TIMESTAMP(3) "
            "TIME INDEX, PRIMARY KEY(host)) WITH (append_mode = 'true')")
        rows = [f"('h{h}', 5.0, {p * 1000})"
                for p in range(10) for h in range(3)]
        qe.execute_one("INSERT INTO t (host, v, ts) VALUES "
                       + ",".join(rows))
        sql = ("SELECT host, sum(v), count(v) FROM t GROUP BY host "
               "ORDER BY host")
        qe.execute_one(sql)  # uploads memtable blocks under version 1
        qe.execute_one("TRUNCATE TABLE t")
        rows = [f"('h{h}', 1.0, {p * 1000})"
                for p in range(10) for h in range(3)]
        qe.execute_one("INSERT INTO t (host, v, ts) VALUES "
                       + ",".join(rows))
        got = qe.execute_one(sql).rows()
        for r in got:
            assert float(r[1]) == 10.0, got  # 10 x 1.0, not stale 50.0
            assert int(r[2]) == 10

    def test_dead_file_tombstone_blocks_racing_insert(self, db):
        """invalidate_files racing an in-flight build: the late insert
        for a dead file must be refused, not pinned into HBM."""
        engine, qe = db
        rid = _fill(qe)
        qe.execute_one(AGG_SQL)
        cache = qe.executor.cache
        key = cache.file_keys(rid)[0]
        arr = cache._lru[key]
        cache.invalidate_files(rid, [key[2]])
        assert key not in cache._lru
        cache._store(key, arr)  # the racing build landing late
        assert key not in cache._lru, "dead-file block re-entered HBM"
        # a LIVE file's insert still lands
        live = [k for k in cache.file_keys(rid) if k[2] != key[2]]
        assert live

    def test_region_epoch_blocks_racing_snap_insert(self, db):
        """invalidate_region (TRUNCATE/DROP) racing an in-flight snap
        build: data_versions ARE reused after a truncate, so the late
        insert must be refused by the epoch check — otherwise the
        pre-truncate block serves once the recreated region's
        data_version climbs back to the colliding value."""
        engine, qe = db
        rid = _fill(qe, files=1)
        # unflushed rows -> the scan has a memtable tail (snap-keyed)
        qe.execute_one(
            "INSERT INTO t (host, v, ts) VALUES ('h1', 2.0, 500000)")
        qe.execute_one(AGG_SQL)
        cache = qe.executor.cache
        with cache._lock:
            key = next(k for k in cache._lru
                       if k[0] == "snap" and k[1] == rid)
            arr = cache._lru[key]
            epoch = cache._key_epoch_locked(key)  # build starts here
        cache.invalidate_region(rid)              # ...TRUNCATE lands...
        assert key not in cache._lru
        cache._store(key, arr, epoch=epoch)       # ...build lands late
        assert key not in cache._lru, "stale snap block re-entered HBM"
        # a post-invalidation build (fresh epoch) still lands
        with cache._lock:
            fresh = cache._key_epoch_locked(key)
        assert fresh != epoch
        cache._store(key, arr, epoch=fresh)
        assert key in cache._lru

    def test_newer_snapshot_generation_retires_older(self, db):
        """Memtable-tail (snapshot-anchored) uploads of an older data
        version die on the first newer insert instead of lingering."""
        engine, qe = db
        rid = _fill(qe, files=1)
        # unflushed rows -> the scan has a memtable tail (snap-keyed)
        qe.execute_one(
            "INSERT INTO t (host, v, ts) VALUES ('h1', 2.0, 500000)")
        qe.execute_one(AGG_SQL)
        cache = qe.executor.cache

        def snap_versions():
            with cache._lock:
                return {k[2] for k in cache._lru
                        if k[0] == "snap" and k[1] == rid}

        v1 = snap_versions()
        qe.execute_one(
            "INSERT INTO t (host, v, ts) VALUES ('h1', 3.0, 501000)")
        qe.execute_one(AGG_SQL)
        v2 = snap_versions()
        assert v2 and not (v1 & v2), (v1, v2)


# ---- donation on the chunked accumulator loops -----------------------------


class TestDonation:
    def _fill_and_query(self, tmp_path, monkeypatch, donate):
        monkeypatch.setenv("GREPTIMEDB_TPU_STREAM_THRESHOLD_ROWS", "1")
        monkeypatch.setenv("GREPTIMEDB_TPU_STREAM_BLOCK_ROWS", "1024")
        monkeypatch.setenv("GREPTIMEDB_TPU_DONATE", donate)
        engine = RegionEngine(EngineConfig(
            data_dir=str(tmp_path / f"don_{donate}"),
            maintenance_workers=0))
        qe = QueryEngine(Catalog(MemoryKv()), engine)
        try:
            _fill(qe, files=3, hosts=6, points=300)
            assert qe.executor.tier_for(object(), 10, streaming=True)
            out = qe.execute_one(AGG_SQL).rows()
            path = qe.executor.last_path
            return out, path
        finally:
            engine.close()

    def test_donated_fold_matches_copying_fold(self, tmp_path,
                                               monkeypatch):
        """The donate_argnums accumulator loop must be value-identical
        to the copying loop (aliasing bug = wrong numbers, not a
        crash)."""
        import warnings

        with warnings.catch_warnings():
            # CPU backend can't honor donation; the fallback copy is
            # exactly what this parity test measures
            warnings.simplefilter("ignore", UserWarning)
            on, path_on = self._fill_and_query(tmp_path, monkeypatch, "1")
            off, path_off = self._fill_and_query(tmp_path, monkeypatch,
                                                 "off")
        assert path_on.startswith("stream"), path_on
        assert path_off.startswith("stream"), path_off
        assert on == off

    def test_donate_default_tracks_backend(self, monkeypatch):
        # auto: on for accelerator backends, off on CPU (XLA:CPU can't
        # alias these buffers and would warn per trace)
        monkeypatch.delenv("GREPTIMEDB_TPU_DONATE", raising=False)
        assert ph._donate_stream_buffers() == (
            jax.default_backend() != "cpu")
        monkeypatch.setenv("GREPTIMEDB_TPU_DONATE", "on")
        assert ph._donate_stream_buffers()
        monkeypatch.setenv("GREPTIMEDB_TPU_DONATE", "off")
        assert not ph._donate_stream_buffers()


# ---- chaos: fused kernel failure mid-query ---------------------------------


@pytest.fixture
def fused_latch_reset():
    yield
    ph._FUSED_DISABLED["flag"] = False


class TestFusedDegradation:
    @pytest.fixture(autouse=True)
    def _classic_paths(self, monkeypatch):
        # these tests pin the fused-vs-scatter machinery; the partial-
        # aggregate cache would intercept the shape before it reaches it
        monkeypatch.setenv("GREPTIMEDB_TPU_PARTIAL_CACHE", "off")

    def test_kernel_failure_degrades_to_scatter(self, db, monkeypatch,
                                                fused_latch_reset):
        """A fused-kernel failure mid-query must answer THAT query via
        the XLA scatter path, latch the kernel off for later queries,
        and count the degradation."""
        from greptimedb_tpu.utils.metrics import PALLAS_DISPATCHES

        engine, qe = db
        _fill(qe)
        want = qe.execute_one(AGG_SQL).rows()  # normal (scatter) path
        monkeypatch.setattr(ph.PhysicalExecutor, "_fused_ok",
                            lambda self, *a, **k: True)

        def boom(*a, **k):
            raise RuntimeError("injected Mosaic failure")

        monkeypatch.setattr(ph, "_agg_scan_fused", boom)
        before = PALLAS_DISPATCHES.get(kernel="fused_agg_failed")
        got = qe.execute_one(AGG_SQL).rows()
        assert got == want  # the query still answered
        assert qe.executor.last_path == "dense_prepared"
        assert ph._FUSED_DISABLED["flag"] is True
        assert PALLAS_DISPATCHES.get(
            kernel="fused_agg_failed") == before + 1
        # latched: later queries skip the fused attempt outright
        qe.execute_one(AGG_SQL)
        assert qe.executor.last_path == "dense_prepared"

    def test_fused_serves_after_latch_reset(self, db, monkeypatch,
                                            fused_latch_reset):
        """With the latch clear and the kernel healthy, the same query
        runs the fused path (interpret mode on CPU) and matches the
        scatter result."""
        engine, qe = db
        _fill(qe)
        want = qe.execute_one(AGG_SQL).rows()
        assert qe.executor.last_path == "dense_prepared"
        monkeypatch.setattr(ph.PhysicalExecutor, "_fused_ok",
                            lambda self, *a, **k: True)
        got = qe.execute_one(AGG_SQL).rows()
        assert qe.executor.last_path == "dense_fused"
        for a, b in zip(want, got):
            assert a[0] == b[0]
            np.testing.assert_allclose(
                [float(x) for x in a[1:]], [float(y) for y in b[1:]],
                rtol=1e-9)


# ---- measured tier routing -------------------------------------------------


@pytest.fixture
def remote_executor(tmp_path, monkeypatch):
    """A remote-link-shaped executor (the static heuristic routes small
    aggregates to host) with no mesh interference."""
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "r")))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    ex = qe.executor
    monkeypatch.setattr(ph, "_LINK", {
        "backend": "tpu", "rtt_ms": 66.0, "d2h_mbps": 11.0,
        "colocated": False})
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(ex, "mesh", None)
    yield ex
    ph._LINK = None
    engine.close()


class TestMeasuredRouting:
    N = 1000

    def _feed(self, ex, device_s, host_s, n=3):
        for _ in range(n):
            ex._note_tier("device", self.N, device_s)
            ex._note_tier("host", self.N, host_s)

    def test_measured_winner_overrides_heuristic(self, remote_executor):
        ex = remote_executor
        # static heuristic for a small aggregate over a slow link: host
        assert ex.tier_for(object(), self.N) == "host"
        # but the DEVICE measures faster -> routing follows the numbers
        self._feed(ex, device_s=0.05, host_s=0.40)
        assert ex.tier_for(object(), self.N) == "device"

    def test_losing_tier_stops_being_chosen(self, remote_executor):
        ex = remote_executor
        self._feed(ex, device_s=0.61, host_s=0.40)  # the r05 anchor shape
        assert ex.tier_for(object(), self.N) == "host"

    def test_insufficient_history_falls_back(self, remote_executor):
        ex = remote_executor
        ex._note_tier("device", self.N, 0.1)  # one sample only
        assert ex.tier_for(object(), self.N) == "host"  # heuristic

    def test_env_override_pins_heuristic(self, remote_executor,
                                         monkeypatch):
        ex = remote_executor
        self._feed(ex, device_s=0.05, host_s=0.40)
        monkeypatch.setenv("GREPTIMEDB_TPU_TIER_ADAPTIVE", "off")
        assert ex.tier_for(object(), self.N) == "host"  # heuristic wins

    def test_periodic_exploration_revisits_loser(self, remote_executor):
        ex = remote_executor
        self._feed(ex, device_s=0.05, host_s=0.40)
        seen = {ex.tier_for(object(), self.N) for _ in range(16)}
        assert seen == {"device", "host"}  # the 16th decision explores

    def test_size_classes_are_independent(self, remote_executor):
        ex = remote_executor
        self._feed(ex, device_s=0.05, host_s=0.40)
        # a different size class has no samples -> heuristic
        assert ex.tier_for(object(), 20_000_000) == "device"
        assert ex.tier_for(object(), 1000) == "device"  # same bucket as N
