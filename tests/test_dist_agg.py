"""Distributed aggregation pushdown: Partial on region owners, Final
combine at the frontend (reference query/src/dist_plan/analyzer.rs:35 +
merge_scan.rs:122). Oracle = the same query against a single-node
engine holding all the rows."""

import numpy as np
import pytest

from greptimedb_tpu.cluster import Cluster
from greptimedb_tpu.meta.metasrv import MetasrvOptions
from greptimedb_tpu.partition.rule import PartitionBound, RangePartitionRule
from greptimedb_tpu.query.plan_ser import PlanFragment, expr_from_json, expr_to_json
from greptimedb_tpu.sql import ast
from greptimedb_tpu.sql.parser import parse_sql

CREATE = (
    "CREATE TABLE cpu (host STRING, region STRING, usage_user DOUBLE, "
    "usage_system DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, region))"
)


def host_rule(*splits):
    bounds = [PartitionBound((s,)) for s in splits] + [PartitionBound(())]
    return RangePartitionRule(["host"], bounds)


def seed(cluster, n_hosts=6, points=5):
    rng = np.random.default_rng(42)
    rows = []
    for h in range(n_hosts):
        for t in range(points):
            rows.append(
                f"('host{h}', 'r{h % 2}', {rng.uniform(0, 100):.4f}, "
                f"{rng.uniform(0, 50):.4f}, {1000 * (t + 1)})")
    cluster.sql(
        "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
        "VALUES " + ", ".join(rows))


QUERIES = [
    "SELECT count(*) FROM cpu",
    "SELECT sum(usage_user), avg(usage_user), min(usage_user), "
    "max(usage_user) FROM cpu",
    "SELECT host, avg(usage_user) FROM cpu GROUP BY host ORDER BY host",
    "SELECT host, region, sum(usage_user), count(usage_system) FROM cpu "
    "GROUP BY host, region ORDER BY host, region",
    "SELECT host, stddev(usage_user) FROM cpu GROUP BY host ORDER BY host",
    "SELECT host, first(usage_user), last(usage_user) FROM cpu "
    "GROUP BY host ORDER BY host",
    "SELECT date_bin('2 seconds', ts) AS b, sum(usage_user) FROM cpu "
    "GROUP BY b ORDER BY b",
    "SELECT host, avg(usage_user) FROM cpu WHERE usage_user > 30.0 "
    "GROUP BY host ORDER BY host",
    "SELECT host, count(*) AS n FROM cpu GROUP BY host HAVING n > 3 "
    "ORDER BY host",
    "SELECT host, max(usage_user) - min(usage_user) AS spread FROM cpu "
    "GROUP BY host ORDER BY host LIMIT 3",
]


def _rows_close(a, b):
    assert len(a) == len(b), (a, b)
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                assert va == pytest.approx(vb, rel=1e-9, abs=1e-9), (ra, rb)
            else:
                assert va == vb, (ra, rb)


class TestPushdownMatchesOracle:
    @pytest.mark.parametrize("wire", [False, True],
                             ids=["inproc", "wire"])
    def test_queries(self, tmp_path, wire):
        c = Cluster(str(tmp_path / "c"), num_datanodes=3,
                    opts=MetasrvOptions(), wire_transport=wire)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        # oracle: single-node engine with identical data
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.storage import RegionEngine
        from greptimedb_tpu.storage.engine import EngineConfig

        oracle_engine = RegionEngine(
            EngineConfig(data_dir=str(tmp_path / "oracle")))
        oracle = QueryEngine(Catalog(MemoryKv()), oracle_engine)
        oracle.execute_one(CREATE)
        seed_sql = []
        rng = np.random.default_rng(42)
        for h in range(6):
            for t in range(5):
                seed_sql.append(
                    f"('host{h}', 'r{h % 2}', {rng.uniform(0, 100):.4f}, "
                    f"{rng.uniform(0, 50):.4f}, {1000 * (t + 1)})")
        oracle.execute_one(
            "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
            "VALUES " + ", ".join(seed_sql))
        for q in QUERIES:
            got = c.sql(q).rows()
            want = oracle.execute_one(q).rows()
            _rows_close(got, want)
            assert c.frontend.executor.last_path == "pushdown", q
        # non-decomposable aggregate falls back to the gather path and
        # still matches
        q = "SELECT host, median(usage_user) FROM cpu GROUP BY host ORDER BY host"
        _rows_close(c.sql(q).rows(), oracle.execute_one(q).rows())
        assert c.frontend.executor.last_path != "pushdown"
        oracle_engine.close()
        c.close()

    def test_pushdown_survives_flush(self, tmp_path):
        c = Cluster(str(tmp_path), num_datanodes=3, opts=MetasrvOptions())
        info = c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        before = c.sql(
            "SELECT host, sum(usage_user) FROM cpu GROUP BY host "
            "ORDER BY host").rows()
        for rid in info.region_ids:
            c.router.flush(rid)
        after = c.sql(
            "SELECT host, sum(usage_user) FROM cpu GROUP BY host "
            "ORDER BY host").rows()
        _rows_close(before, after)
        c.close()

    def test_lww_dedup_respected_across_pushdown(self, tmp_path):
        """An overwrite of the same (pk, ts) must resolve before the
        Partial step reduces — the partial runs the same dedup kernel."""
        c = Cluster(str(tmp_path), num_datanodes=2, opts=MetasrvOptions())
        c.create_partitioned_table(CREATE, host_rule("host1"))
        c.sql("INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
              "VALUES ('host0', 'r0', 1.0, 1.0, 1000)")
        c.sql("INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
              "VALUES ('host0', 'r0', 99.0, 1.0, 1000)")
        rows = c.sql("SELECT host, sum(usage_user) FROM cpu GROUP BY host").rows()
        assert rows == [["host0", 99.0]]
        c.close()


class TestStringArguments:
    def test_count_of_string_column_pushes_down(self, tmp_path):
        c = Cluster(str(tmp_path), num_datanodes=2, opts=MetasrvOptions())
        c.create_partitioned_table(CREATE, host_rule("host1"))
        c.sql("INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
              "VALUES ('host0', 'r0', 1.0, 1.0, 1000), "
              "('host0', NULL, 2.0, 1.0, 2000), "
              "('host2', 'r1', 3.0, 1.0, 1000)")
        rows = c.sql("SELECT host, count(region) FROM cpu GROUP BY host "
                     "ORDER BY host").rows()
        assert rows == [["host0", 1], ["host2", 1]]
        assert c.frontend.executor.last_path == "pushdown"
        c.close()

    def test_first_of_string_column_falls_back(self, tmp_path):
        """first(tag) needs raw values — must fall back, not crash."""
        c = Cluster(str(tmp_path), num_datanodes=2, opts=MetasrvOptions())
        c.create_partitioned_table(CREATE, host_rule("host1"))
        c.sql("INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
              "VALUES ('host0', 'r0', 1.0, 1.0, 1000), "
              "('host0', 'r9', 2.0, 1.0, 2000)")
        rows = c.sql("SELECT host, last(region) FROM cpu GROUP BY host").rows()
        assert rows == [["host0", "r9"]]
        assert c.frontend.executor.last_path != "pushdown"
        c.close()


class TestNullGroupKeys:
    @pytest.mark.parametrize("wire", [False, True], ids=["inproc", "wire"])
    def test_null_tag_group_survives_pushdown(self, tmp_path, wire):
        """NULL group keys form their own group, same as single-node."""
        c = Cluster(str(tmp_path), num_datanodes=2, opts=MetasrvOptions(),
                    wire_transport=wire)
        c.create_partitioned_table(CREATE, host_rule("host1"))
        c.sql("INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
              "VALUES ('host0', 'r0', 10.0, 1.0, 1000), "
              "('host0', NULL, 20.0, 1.0, 2000), "
              "('host2', NULL, 30.0, 1.0, 1000)")
        rows = c.sql(
            "SELECT region, sum(usage_user) FROM cpu GROUP BY region "
            "ORDER BY region").rows()
        assert c.frontend.executor.last_path == "pushdown"
        by_key = {r[0]: r[1] for r in rows}
        assert by_key["r0"] == pytest.approx(10.0)
        # the NULL group combines across regions
        assert by_key.get(None) == pytest.approx(50.0)
        c.close()


class TestFragmentSerialization:
    def test_expr_roundtrip_covers_grammar(self):
        sel = parse_sql(
            "SELECT host FROM t WHERE (v > 3.5 AND host != 'x') "
            "OR ts BETWEEN 10 AND 20 OR host IN ('a', 'b') "
            "AND v IS NOT NULL AND host LIKE 'web-%'")[0]
        j = expr_to_json(sel.where)
        assert expr_from_json(j) == sel.where

    def test_fragment_roundtrip(self):
        frag = PlanFragment(
            stages=[
                {"op": "filter",
                 "expr": ast.BinaryOp(">", ast.Column("v"),
                                      ast.Literal(1.5))},
                {"op": "prune", "columns": ["host", "v", "ts"]},
                {"op": "sort", "keys": [(ast.Column("v"), False)]},
                {"op": "limit", "k": 7},
                {"op": "partial_agg",
                 "keys": [("host", ast.Column("host"))],
                 "args": [ast.Column("v"),
                          ast.BinaryOp("*", ast.Column("v"),
                                       ast.Literal(2))],
                 "ops": ["sum", "count"]},
            ],
            ts_range=(0, 99), append_mode=True, tz="UTC")
        back = PlanFragment.from_json(frag.to_json())
        assert back.stages == frag.stages
        assert back.ts_range == (0, 99)
        assert back.append_mode is True
        assert back.tz == "UTC"
        assert back.stage("limit")["k"] == 7

    def test_unknown_node_type_rejected(self):
        with pytest.raises(ValueError, match="unknown plan node"):
            expr_from_json({"_t": "os_system", "cmd": "rm -rf /"})


class TestTopkPushdown:
    """Sort/limit pushdown for raw scans (TopkFragment): each region
    returns only k candidates; the frontend merges and re-sorts."""

    @pytest.mark.parametrize("wire", [False, True], ids=["inproc", "wire"])
    def test_topk_matches_oracle(self, tmp_path, wire):
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.storage import RegionEngine
        from greptimedb_tpu.storage.engine import EngineConfig

        c = Cluster(str(tmp_path / "c"), num_datanodes=3,
                    opts=MetasrvOptions(), wire_transport=wire)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        oracle_engine = RegionEngine(
            EngineConfig(data_dir=str(tmp_path / "oracle")))
        oracle = QueryEngine(Catalog(MemoryKv()), oracle_engine)
        oracle.execute_one(CREATE)
        rng = np.random.default_rng(42)
        rows = []
        for h in range(6):
            for t in range(5):
                rows.append(
                    f"('host{h}', 'r{h % 2}', {rng.uniform(0, 100):.4f}, "
                    f"{rng.uniform(0, 50):.4f}, {1000 * (t + 1)})")
        oracle.execute_one(
            "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
            "VALUES " + ", ".join(rows))
        queries = [
            "SELECT host, ts, usage_user FROM cpu "
            "ORDER BY ts DESC, host LIMIT 5",
            "SELECT host, usage_user FROM cpu "
            "ORDER BY usage_user DESC LIMIT 3",
            "SELECT host, usage_user FROM cpu WHERE usage_user > 20.0 "
            "ORDER BY usage_user LIMIT 4 OFFSET 2",
            "SELECT host, ts FROM cpu ORDER BY host, ts LIMIT 7",
        ]
        for q in queries:
            got = c.sql(q).rows()
            want = oracle.execute_one(q).rows()
            _rows_close(got, want)
            assert c.frontend.executor.last_path == "topk_pushdown", q
        # NULLS FIRST can't be replicated region-side: falls back, matches
        c.frontend.executor.last_path = None
        q = ("SELECT host, usage_user FROM cpu "
             "ORDER BY usage_user DESC NULLS LAST LIMIT 3")
        _rows_close(c.sql(q).rows(), oracle.execute_one(q).rows())
        assert c.frontend.executor.last_path != "topk_pushdown"
        oracle_engine.close()
        c.close()


class TestCombineVectorized:
    def test_combine_scales_without_python_loop(self):
        """48k-group x 4-region combine must be vectorized: the former
        per-group dict loop took seconds at this scale (round-2 VERDICT
        weak #5); the np.unique merge takes well under a second."""
        import time

        from greptimedb_tpu.query.dist_agg import combine_partials

        rng = np.random.default_rng(0)
        G, F, R = 48000, 10, 4
        partials = []
        for r in range(R):
            keys = [
                np.asarray([f"h{(i * 7 + r) % (G * 2)}" for i in range(G)],
                           dtype=object),
                np.arange(G, dtype=np.int64) % 12,
            ]
            partials.append({
                "keys": keys,
                "planes": {
                    "sum": rng.uniform(0, 1, (G, F)),
                    "count": np.ones((G, F)),
                    "rows": np.ones((G, 1)),
                },
            })
        t0 = time.perf_counter()
        out = combine_partials(partials, 2, ("sum", "count", "rows"))
        dt = time.perf_counter() - t0
        assert out is not None
        assert len(out["keys"][0]) >= G
        assert dt < 2.0, f"combine took {dt:.2f}s — not vectorized?"

    def test_combine_first_last_across_regions(self):
        from greptimedb_tpu.query.dist_agg import combine_partials

        def part(key, val, ts_first, ts_last):
            return {
                "keys": [np.asarray([key], dtype=object)],
                "planes": {
                    "first": np.asarray([[val]]),
                    "first_ts": np.asarray([[ts_first]], dtype=np.int64),
                    "last": np.asarray([[val]]),
                    "last_ts": np.asarray([[ts_last]], dtype=np.int64),
                },
            }

        out = combine_partials(
            [part("a", 1.0, 100, 100), part("a", 2.0, 50, 150),
             part("b", 9.0, 10, 10)],
            1, ("first", "last"))
        keys = list(out["keys"][0])
        ia, ib = keys.index("a"), keys.index("b")
        assert out["planes"]["first"][ia, 0] == 2.0   # ts 50 oldest
        assert out["planes"]["last"][ia, 0] == 2.0    # ts 150 newest
        assert out["planes"]["first"][ib, 0] == 9.0

    def test_combine_first_last_multi_arg(self):
        """first/last over 2+ argument columns: the ts plane is [R, 1]
        (one ts per group) while value planes are [R, F] — the combine
        must broadcast, not index ts per field."""
        from greptimedb_tpu.query.dist_agg import combine_partials

        def part(key, vals, ts):
            return {
                "keys": [np.asarray([key], dtype=object)],
                "planes": {
                    "first": np.asarray([vals]),
                    "first_ts": np.asarray([ts], dtype=np.int64),
                },
            }

        out = combine_partials(
            [part("a", [1.0, 10.0], 100), part("a", [2.0, 20.0], 50)],
            1, ("first",))
        np.testing.assert_allclose(out["planes"]["first"][0], [2.0, 20.0])


class TestRowsPushdown:
    """Filter/prune fragment pushdown (mode "rows"): WHERE runs
    region-side and only the matching rows cross the wire — never the
    raw region scans (commutativity.rs: Filter/Projection are
    Commutative)."""

    @pytest.mark.parametrize("wire", [False, True], ids=["inproc", "wire"])
    def test_filtered_rows_match_oracle(self, tmp_path, wire):
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.storage import RegionEngine
        from greptimedb_tpu.storage.engine import EngineConfig

        c = Cluster(str(tmp_path / "c"), num_datanodes=3,
                    opts=MetasrvOptions(), wire_transport=wire)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        oracle_engine = RegionEngine(
            EngineConfig(data_dir=str(tmp_path / "oracle")))
        oracle = QueryEngine(Catalog(MemoryKv()), oracle_engine)
        oracle.execute_one(CREATE)
        rng = np.random.default_rng(42)
        rows = []
        for h in range(6):
            for t in range(5):
                rows.append(
                    f"('host{h}', 'r{h % 2}', {rng.uniform(0, 100):.4f}, "
                    f"{rng.uniform(0, 50):.4f}, {1000 * (t + 1)})")
        oracle.execute_one(
            "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
            "VALUES " + ", ".join(rows))

        # spy on the fragment RPC: record how many rows each region ships
        shipped = []
        orig = c.frontend.executor.engine.execute_fragment

        def spy(rid, frag):
            out = orig(rid, frag)
            if out is not None and "cols" in out:
                shipped.append(len(next(iter(out["cols"].values()))))
            return out

        c.frontend.executor.engine.execute_fragment = spy
        queries = [
            "SELECT host, usage_user, ts FROM cpu WHERE usage_user > 70.0 "
            "ORDER BY host, ts",
            "SELECT host, usage_user FROM cpu WHERE usage_user > 50.0 "
            "AND region = 'r1' ORDER BY usage_user",
            "SELECT host, ts FROM cpu WHERE usage_user > 95.0",
        ]
        for q in queries:
            shipped.clear()
            got = c.sql(q).rows()
            want = oracle.execute_one(q).rows()
            _rows_close(sorted(map(tuple, got)), sorted(map(tuple, want)))
            assert c.frontend.executor.last_path == "rows_pushdown", q
            # the wire carried exactly the filtered rows, not the scans
            assert sum(shipped) == len(want), q
            assert sum(shipped) < 30  # seeded rows = 6 hosts x 5 points
        # bare LIMIT without sort: regions pre-truncate
        shipped.clear()
        got = c.sql("SELECT host, ts FROM cpu LIMIT 4").rows()
        assert len(got) == 4
        assert c.frontend.executor.last_path == "rows_pushdown"
        assert sum(shipped) <= 3 * 4  # <= k per region
        # no WHERE and no LIMIT: nothing to reduce -> gather path
        c.frontend.executor.last_path = None
        c.sql("SELECT host, ts, usage_user FROM cpu")
        assert c.frontend.executor.last_path != "rows_pushdown"
        oracle_engine.close()
        c.close()


class TestRowsAggPushdown:
    """Non-decomposable aggregates (order statistics): the aggregate is
    NonCommutative but its input commutes — regions ship filtered,
    projected rows and the frontend re-enters the device aggregation
    over the union (mode "rows_agg"), never gathering raw scans
    (commutativity.rs:27-52; round-4 verdict #7)."""

    @pytest.mark.parametrize("wire", [False, True], ids=["inproc", "wire"])
    def test_percentile_matches_oracle(self, tmp_path, wire):
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.storage import RegionEngine
        from greptimedb_tpu.storage.engine import EngineConfig

        c = Cluster(str(tmp_path / "c"), num_datanodes=3,
                    opts=MetasrvOptions(), wire_transport=wire)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        oracle_engine = RegionEngine(
            EngineConfig(data_dir=str(tmp_path / "oracle")))
        oracle = QueryEngine(Catalog(MemoryKv()), oracle_engine)
        oracle.execute_one(CREATE)
        rng = np.random.default_rng(42)
        rows = []
        for h in range(6):
            for t in range(5):
                rows.append(
                    f"('host{h}', 'r{h % 2}', {rng.uniform(0, 100):.4f}, "
                    f"{rng.uniform(0, 50):.4f}, {1000 * (t + 1)})")
        oracle.execute_one(
            "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
            "VALUES " + ", ".join(rows))

        shipped = []
        orig = c.frontend.executor.engine.execute_fragment

        def spy(rid, frag):
            out = orig(rid, frag)
            if out is not None and "cols" in out:
                shipped.append(len(next(iter(out["cols"].values()))))
            return out

        c.frontend.executor.engine.execute_fragment = spy
        queries = [
            "SELECT host, percentile(usage_user, 50) FROM cpu "
            "WHERE usage_user > 20.0 GROUP BY host ORDER BY host",
            "SELECT host, median(usage_user) FROM cpu "
            "WHERE region = 'r1' GROUP BY host ORDER BY host",
            "SELECT median(usage_user) FROM cpu WHERE usage_user < 80.0",
            # NOT argmax: it returns scan-order row indices, which are
            # legitimately different between physical plans
            "SELECT host, percentile(usage_system, 90) FROM cpu "
            "WHERE usage_user > 10.0 GROUP BY host ORDER BY host",
        ]
        for q in queries:
            shipped.clear()
            got = c.sql(q).rows()
            want = oracle.execute_one(q).rows()
            _rows_close(got, want)
            assert c.frontend.executor.last_path.startswith("rows_agg+"), q
            # the wire carried only rows surviving WHERE, not raw scans
            n_match = oracle.execute_one(
                "SELECT count(*) FROM cpu WHERE " + q.split("WHERE ")[1]
                .split(" GROUP")[0]).rows()[0][0]
            assert sum(shipped) == n_match, q
        # last(tag) takes the same route: raw string values needed
        shipped.clear()
        got = c.sql("SELECT host, last(region) FROM cpu "
                    "WHERE usage_user > 0.0 GROUP BY host "
                    "ORDER BY host").rows()
        want = oracle.execute_one(
            "SELECT host, last(region) FROM cpu WHERE usage_user > 0.0 "
            "GROUP BY host ORDER BY host").rows()
        _rows_close(got, want)
        assert c.frontend.executor.last_path.startswith("rows_agg+")
        oracle_engine.close()
        c.close()

    def test_projection_only_rows_agg_without_where(self, tmp_path):
        """No WHERE but the aggregate touches a column subset: the
        pruned-column row union still beats gathering full scans."""
        c = Cluster(str(tmp_path), num_datanodes=2, opts=MetasrvOptions())
        c.create_partitioned_table(CREATE, host_rule("host1"))
        seed(c, n_hosts=4)
        got = c.sql("SELECT host, median(usage_user) FROM cpu "
                    "GROUP BY host ORDER BY host").rows()
        assert len(got) == 4
        assert c.frontend.executor.last_path.startswith("rows_agg+")
        c.close()


class TestWindowPushdown:
    """Window-partition pushdown: OVER (PARTITION BY <rule cols> ...)
    computes region-side (partitions never span regions); the wire
    carries filtered rows + window columns. Non-covering windows fall
    back to the gather path and still match."""

    @pytest.mark.parametrize("wire", [False, True], ids=["inproc", "wire"])
    def test_partitioned_windows_match_oracle(self, tmp_path, wire):
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.storage import RegionEngine
        from greptimedb_tpu.storage.engine import EngineConfig

        c = Cluster(str(tmp_path / "c"), num_datanodes=3,
                    opts=MetasrvOptions(), wire_transport=wire)
        c.create_partitioned_table(CREATE, host_rule("host2", "host4"))
        seed(c)
        oracle_engine = RegionEngine(
            EngineConfig(data_dir=str(tmp_path / "oracle")))
        oracle = QueryEngine(Catalog(MemoryKv()), oracle_engine)
        oracle.execute_one(CREATE)
        rng = np.random.default_rng(42)
        rows = []
        for h in range(6):
            for t in range(5):
                rows.append(
                    f"('host{h}', 'r{h % 2}', {rng.uniform(0, 100):.4f}, "
                    f"{rng.uniform(0, 50):.4f}, {1000 * (t + 1)})")
        oracle.execute_one(
            "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
            "VALUES " + ", ".join(rows))
        queries = [
            # running sum per host (rule column partitions the window)
            "SELECT host, ts, sum(usage_user) OVER (PARTITION BY host "
            "ORDER BY ts) AS rs FROM cpu ORDER BY host, ts",
            # moving average + filter shipped region-side
            "SELECT host, ts, avg(usage_user) OVER (PARTITION BY host "
            "ORDER BY ts ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS ma "
            "FROM cpu WHERE usage_user > 20.0 ORDER BY host, ts",
            # extra partition key beyond the rule column still covers it
            "SELECT host, region, ts, row_number() OVER (PARTITION BY "
            "host, region ORDER BY ts) AS rn FROM cpu ORDER BY host, ts",
        ]
        for q in queries:
            got = c.sql(q).rows()
            want = oracle.execute_one(q).rows()
            _rows_close(got, want)
            assert c.frontend.executor.last_path == "window_pushdown", q
        # alias-qualified references ride the pushdown too
        q = ("SELECT c.host, c.ts, sum(c.usage_user) OVER (PARTITION BY "
             "c.host ORDER BY c.ts) AS rs FROM cpu c ORDER BY c.host, c.ts")
        _rows_close(c.sql(q).rows(), oracle.execute_one(q).rows())
        assert c.frontend.executor.last_path == "window_pushdown", q
        # window WITHOUT the rule column in PARTITION BY: global window —
        # cannot push; must fall back and still match
        q = ("SELECT host, ts, rank() OVER (ORDER BY usage_user DESC) rk "
             "FROM cpu ORDER BY host, ts")
        c.frontend.executor.last_path = None
        _rows_close(c.sql(q).rows(), oracle.execute_one(q).rows())
        assert c.frontend.executor.last_path != "window_pushdown"
        oracle_engine.close()
        c.close()
