"""Randomized chaos explorer (greptimedb_tpu/fault/explorer.py):
generative schedule/workload sampling, ddmin shrinking, repro-line
round-trips, and live randomized runs against ProcessClusters.

Tier-1 keeps a small always-on budget: the deterministic sampler units,
the ddmin machinery, the explore→catch→shrink→repro pipeline against a
test-only injected invariant bug (dry mode — no clusters spawned), and
3 live randomized single-datanode runs. The deep matrix (2-datanode
kill/crash runs, live outcome-determinism double runs) is slow-marked:
`pytest -m slow tests/test_explorer.py`."""

import logging
import random

import pytest

from greptimedb_tpu.fault import FAULTS, Fault, FaultRegistry
from greptimedb_tpu.fault import explorer as ex
from greptimedb_tpu.fault.scenarios import InvariantViolation
from greptimedb_tpu.utils.metrics import CHAOS_RUNS, CHAOS_SHRINK_STEPS

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# ---- samplers: determinism + validity ---------------------------------------


class TestSamplers:
    def test_schedule_is_seed_deterministic(self):
        topo = ex.Topology.cluster(2)
        for seed in range(20):
            a = ex.sample_schedule(random.Random(f"schedule:{seed}"),
                                   topo)
            b = ex.sample_schedule(random.Random(f"schedule:{seed}"),
                                   topo)
            assert [e.to_env() for e in a] == [e.to_env() for e in b]

    def test_different_seeds_diverge(self):
        topo = ex.Topology.cluster(1)
        envs = {ex.compile_env(ex.sample_schedule(
            random.Random(f"schedule:{s}"), topo)) for s in range(30)}
        assert len(envs) > 20, "sampler barely varies across seeds"

    def test_workload_is_seed_deterministic_and_replayable(self):
        topo = ex.Topology.cluster(3)
        a = ex.sample_workload(random.Random("workload:5"), 40, topo)
        b = ex.sample_workload(random.Random("workload:5"), 40, topo)
        assert a == b
        assert a[0] == ("create",)
        kills = [op for op in a if op[0] == "kill"]
        # never kills dn-0 (the failover candidate) and never the same
        # node twice
        assert all(op[1] != "dn-0" for op in kills)
        assert len({op[1] for op in kills}) == len(kills)

    def test_workload_kills_suppressed_when_crash_scheduled(self):
        topo = ex.Topology.cluster(3)
        ops = ex.sample_workload(random.Random("workload:5"), 40, topo,
                                 allow_kill=False)
        assert not [op for op in ops if op[0] == "kill"]

    def test_single_datanode_schedules_never_kill(self):
        topo = ex.Topology.cluster(1)
        for seed in range(40):
            entries = ex.sample_schedule(
                random.Random(f"schedule:{seed}"), topo)
            assert all(e.point != "datanode.crash" for e in entries)
        ops = ex.sample_workload(random.Random("workload:9"), 40, topo)
        assert not [op for op in ops if op[0] == "kill"]

    def test_sampled_schedules_arm_cleanly(self):
        """Every sampled schedule must pass the registry's arm-time
        validation — points exist, kinds legal, edges in topology."""
        for num_dn in (1, 2, 3):
            topo = ex.Topology.cluster(num_dn)
            for seed in range(25):
                env = ex.compile_env(ex.sample_schedule(
                    random.Random(f"schedule:{seed}"), topo))
                ex._validate_schedule(env, topo)  # raises on any flaw

    def test_sampled_election_schedules_arm_cleanly(self):
        topo = ex.Topology.election(3)
        for seed in range(25):
            env = ex.compile_env(ex.sample_election_schedule(
                random.Random(f"schedule:{seed}"), topo))
            ex._validate_schedule(env, topo)
            assert "election.lease" in env

    def test_schedule_kinds_stay_oracle_compatible(self):
        """torn/short_read on WAL/objectstore seams corrupt bytes the
        strict checkers would flag without a bug — the sampler must
        never emit them."""
        for seed in range(40):
            for e in ex.sample_schedule(
                    random.Random(f"schedule:{seed}"),
                    ex.Topology.cluster(2)):
                if e.point in ("partition", "datanode.crash"):
                    continue
                assert e.kind in ex.CLUSTER_KIND_POOL[e.point]
                assert e.kind not in ("torn", "short_read")

    def test_entry_env_round_trips_through_registry(self):
        """to_env() → arm_from_env() → fingerprint() preserves every
        knob: the repro line IS the schedule, bit for bit."""
        topo = ex.Topology.cluster(2)
        for seed in range(15):
            entries = ex.sample_schedule(
                random.Random(f"schedule:{seed}"), topo)
            env = ex.compile_env(entries)
            r1, r2 = FaultRegistry(), FaultRegistry()
            r1.arm_from_env(env)
            r2.arm_from_env(ex.compile_env(ex.split_env(env)))
            assert r1.fingerprint() == r2.fingerprint()
            for e in entries:
                if e.point == "partition":
                    continue
                fp = r1.fingerprint()["points"][e.point]
                assert fp["kind"] == e.kind
                assert fp["nth"] == e.nth
                assert fp["prob"] == (e.prob or 0.0)

    def test_skew_sampler_is_seeded_and_bounded(self):
        topo = ex.Topology.election(3)
        for seed in range(20):
            a = ex.sample_skews(random.Random(f"skew:{seed}"), topo, 9.0)
            b = ex.sample_skews(random.Random(f"skew:{seed}"), topo, 9.0)
            assert a == b
            for node, ms in a.items():
                assert node in topo.metasrvs
                assert 0 < ms <= 0.4 * 9000.0


# ---- ddmin -------------------------------------------------------------------


class TestDdmin:
    def test_shrinks_to_single_culprit(self):
        entries = [f"e{i}" for i in range(8)]
        probes = []

        def still_fails(subset):
            probes.append(list(subset))
            return "e5" in subset

        before = CHAOS_SHRINK_STEPS.get()
        assert ex.ddmin(entries, still_fails) == ["e5"]
        assert CHAOS_SHRINK_STEPS.get() == before + len(probes)

    def test_shrinks_to_interacting_pair(self):
        entries = [f"e{i}" for i in range(9)]

        def still_fails(subset):
            return "e1" in subset and "e7" in subset

        minimal = ex.ddmin(entries, still_fails)
        assert set(minimal) == {"e1", "e7"}

    def test_probe_budget_bounds_the_spend(self):
        entries = [f"e{i}" for i in range(64)]
        probes = []

        def still_fails(subset):
            probes.append(1)
            return "e63" in subset

        ex.ddmin(entries, still_fails, max_probes=5)
        assert len(probes) <= 5

    def test_unshrinkable_failure_returns_input(self):
        entries = ["a", "b"]
        assert ex.ddmin(entries, lambda s: len(s) >= 2) == ["a", "b"]


# ---- the catch → shrink → repro pipeline (dry: no clusters) -----------------


class TestBugHookPipeline:
    def test_injected_bug_is_caught_shrunk_and_reproducible(
            self, monkeypatch):
        """The acceptance loop: a deliberately injected invariant bug
        (test-only hook) must be caught by exploration, shrunk to <=3
        entries, and the resulting repro line must re-trigger it."""
        monkeypatch.setenv("GTPU_CHAOS_BUG", "point:wal.append")
        report = ex.explore(runs=10, seed=100, shrink=True)
        fails = [r for r in report["runs"] if r["outcome"] == "fail"]
        assert fails, "no sampled schedule armed wal.append in 10 runs"
        for rec in fails:
            assert rec["shrunk_entries"] <= 3
            assert "wal.append=" in rec["shrunk_env"]
            assert rec["repro"] and "GTPU_CHAOS" in rec["repro"]
            # the repro line re-triggers: re-run its exact schedule
            # under the same seed and the same bug hook
            with pytest.raises(InvariantViolation):
                ex.run_schedule(ex.split_env(rec["shrunk_env"]),
                                rec["seed"])
        # clean schedules stay green under a hook they never arm
        passes = [r for r in report["runs"] if r["outcome"] == "pass"]
        assert passes, "every sampled schedule armed wal.append?!"

    def test_same_seed_same_outcome(self, monkeypatch):
        monkeypatch.setenv("GTPU_CHAOS_BUG", "env:heartbeat")
        a = ex.explore(runs=6, seed=300, shrink=False)
        b = ex.explore(runs=6, seed=300, shrink=False)
        assert [(r["chaos_env"], r["outcome"]) for r in a["runs"]] \
            == [(r["chaos_env"], r["outcome"]) for r in b["runs"]]

    def test_outcome_metrics_count_by_outcome(self, monkeypatch):
        monkeypatch.setenv("GTPU_CHAOS_BUG", "env:partition")
        p0 = CHAOS_RUNS.get(outcome="pass")
        f0 = CHAOS_RUNS.get(outcome="fail")
        report = ex.explore(runs=6, seed=40, shrink=False)
        assert CHAOS_RUNS.get(outcome="pass") - p0 == report["passed"]
        assert CHAOS_RUNS.get(outcome="fail") - f0 == report["failed"]
        assert report["passed"] + report["failed"] == 6

    def test_election_mode_bug_hook(self, monkeypatch):
        monkeypatch.setenv("GTPU_CHAOS_BUG", "point:election.lease")
        report = ex.explore(runs=3, seed=0, shrink=True, election=True)
        # every election schedule carries election.lease by design
        assert report["failed"] == 3
        for rec in report["runs"]:
            assert rec["shrunk_entries"] <= 3
            assert "--election" in (rec["repro"] or "")

    def test_bad_hook_spec_is_loud(self, monkeypatch):
        monkeypatch.setenv("GTPU_CHAOS_BUG", "bogus")
        report = ex.explore(runs=1, seed=0, shrink=False)
        assert report["errors"] == 1


# ---- satellite: fault log lines carry the active trace id -------------------


class TestFaultLogTraceId:
    def test_injection_log_carries_trace_id(self, caplog, monkeypatch):
        from greptimedb_tpu.utils import tracing

        monkeypatch.setenv("GTPU_CHAOS_LOG_THROTTLE_S", "0")
        r = FaultRegistry()
        r.arm("wal.append", Fault(kind="fail", nth=1))
        tid = tracing.set_trace()
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="greptimedb_tpu.fault"):
                with pytest.raises(Exception):
                    r.fire("wal.append")
        finally:
            tracing.restore_trace(None)
        line = next(rec.getMessage() for rec in caplog.records
                    if "fault injected" in rec.getMessage())
        assert f"trace_id={tid}" in line
        assert "point=wal.append" in line and "kind=fail" in line

    def test_injection_log_is_throttled(self, caplog, monkeypatch):
        monkeypatch.setenv("GTPU_CHAOS_LOG_THROTTLE_S", "60")
        r = FaultRegistry()
        r.arm("wal.append", Fault(kind="fail", nth=1, times=5))
        with caplog.at_level(logging.WARNING,
                             logger="greptimedb_tpu.fault"):
            for _ in range(5):
                with pytest.raises(Exception):
                    r.fire("wal.append")
        lines = [rec for rec in caplog.records
                 if "fault injected" in rec.getMessage()]
        assert len(lines) == 1, "throttle must collapse a fault storm"

    def test_no_trace_suffix_outside_a_span(self, caplog, monkeypatch):
        monkeypatch.setenv("GTPU_CHAOS_LOG_THROTTLE_S", "0")
        r = FaultRegistry()
        r.arm("wal.append", Fault(kind="fail", nth=1))
        with caplog.at_level(logging.WARNING,
                             logger="greptimedb_tpu.fault"):
            with pytest.raises(Exception):
                r.fire("wal.append")
        line = next(rec.getMessage() for rec in caplog.records
                    if "fault injected" in rec.getMessage())
        assert "trace_id=" not in line


# ---- live: the tier-1 explorer budget ---------------------------------------


class TestLiveExplorerBudget:
    def test_three_randomized_single_datanode_runs(self, tmp_path):
        """The always-on budget: 3 seeded random schedules + workloads
        against live single-datanode ProcessClusters, full oracle."""
        report = ex.explore(runs=3, seed=0, shrink=False,
                            num_datanodes=1, steps=24)
        bad = [r for r in report["runs"] if r["outcome"] != "pass"]
        assert not bad, f"explorer runs failed: {bad}"
        for r in report["runs"]:
            assert r["report"]["ops"] >= 24
            assert "wal_objects_checked" in r["report"]


@pytest.mark.slow
class TestDeepExplorerMatrix:
    def test_live_outcome_determinism(self):
        """Same seed, live clusters, twice: same schedule, same acked
        set, same outcome (the FoundationDB replay property)."""
        a = ex.explore(runs=2, seed=42, shrink=False,
                       num_datanodes=1, steps=24)
        b = ex.explore(runs=2, seed=42, shrink=False,
                       num_datanodes=1, steps=24)
        key = [(r["chaos_env"], r["outcome"], r["report"].get("acked"),
                r["report"].get("typed_failures")) for r in a["runs"]]
        assert key == [(r["chaos_env"], r["outcome"],
                        r["report"].get("acked"),
                        r["report"].get("typed_failures"))
                       for r in b["runs"]]

    def test_two_datanode_runs_with_kill_nemeses(self):
        """Multi-datanode matrix: kills + crash schedules + failover,
        12 seeded runs."""
        report = ex.explore(runs=12, seed=7, shrink=False,
                            num_datanodes=2, steps=26)
        bad = [r for r in report["runs"] if r["outcome"] != "pass"]
        assert not bad, f"explorer runs failed: {bad}"
        assert any(r["report"]["killed"] for r in report["runs"]), \
            "no run exercised a kill nemesis in 12 seeds"

    def test_replay_cli_reproduces_a_seed(self):
        import subprocess
        import sys

        out = subprocess.run(
            [sys.executable, "tools/chaos_explorer.py", "--replay",
             "--seed", "43"],
            capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "PASS" in out.stdout
