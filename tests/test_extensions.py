"""Pubsub, plugin system, and OTLP trace ingestion."""

import json
import struct

import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.meta.metasrv import HeartbeatRequest, Metasrv, RegionStat
from greptimedb_tpu.meta.pubsub import TOPIC_HEARTBEAT, SubscribeManager
from greptimedb_tpu.plugins import Plugins
from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    yield q
    engine.close()


class TestPubsub:
    def test_subscribe_publish_unsubscribe(self):
        mgr = SubscribeManager()
        got = []
        sid = mgr.subscribe("fe-1", [TOPIC_HEARTBEAT],
                            lambda t, m: got.append((t, m)))
        assert mgr.publish(TOPIC_HEARTBEAT, {"node": "dn-1"}) == 1
        assert got == [(TOPIC_HEARTBEAT, {"node": "dn-1"})]
        assert mgr.publish("other_topic", {}) == 0
        assert mgr.unsubscribe(sid)
        assert mgr.publish(TOPIC_HEARTBEAT, {}) == 0

    def test_unsubscribe_all_by_name(self):
        mgr = SubscribeManager()
        mgr.subscribe("fe-1", ["a"], lambda t, m: None)
        mgr.subscribe("fe-1", ["b"], lambda t, m: None)
        mgr.subscribe("fe-2", ["a"], lambda t, m: None)
        assert mgr.unsubscribe_all("fe-1") == 2
        assert len(mgr.subscribers_by_topic("a")) == 1

    def test_failing_subscriber_does_not_block_fanout(self):
        mgr = SubscribeManager()
        got = []
        mgr.subscribe("bad", ["t"], lambda t, m: 1 / 0)
        mgr.subscribe("good", ["t"], lambda t, m: got.append(m))
        assert mgr.publish("t", 42) == 1
        assert got == [42]

    def test_metasrv_publishes_heartbeats(self):
        m = Metasrv(MemoryKv())
        seen = []
        m.pubsub.subscribe("stats-cache", [TOPIC_HEARTBEAT],
                           lambda t, req: seen.append(req))
        m.handle_heartbeat(HeartbeatRequest(
            "dn-1", region_stats=[RegionStat(1, "t")], now_ms=0))
        assert len(seen) == 1
        assert seen[0].node_id == "dn-1"
        assert seen[0].region_stats[0].region_id == 1


class TestPlugins:
    def test_typed_container(self):
        class MyExt:
            pass

        p = Plugins()
        ext = MyExt()
        p.insert(ext)
        assert p.get(MyExt) is ext
        assert p.get(dict) is None

    def test_sql_interceptor_rewrites_and_vetoes(self, qe):
        seen = []

        def audit(sql, ctx):
            seen.append(sql)
            if "forbidden_table" in sql:
                raise PermissionError("vetoed by plugin")
            return sql.replace("__MAGIC__", "42")

        qe.plugins.register_sql_interceptor(audit)
        try:
            r = qe.execute_one("SELECT __MAGIC__ + 1")
            assert r.rows() == [[43]]
            assert seen
            with pytest.raises(PermissionError, match="vetoed"):
                qe.execute_one("SELECT * FROM forbidden_table")
        finally:
            qe.plugins._sql_interceptors.clear()

    def test_scalar_function_plugin(self, qe):
        qe.plugins.register_scalar_function(
            "double_it", lambda v: v * 2)
        try:
            qe.execute_one(
                "CREATE TABLE p (k STRING, v DOUBLE, ts TIMESTAMP TIME "
                "INDEX, PRIMARY KEY(k))")
            qe.execute_one("INSERT INTO p VALUES ('a', 3.5, 1000)")
            r = qe.execute_one("SELECT k, double_it(v) FROM p")
            assert r.rows() == [["a", 7.0]]
        finally:
            qe.plugins._scalar_functions.clear()

    def test_plugin_function_in_where_clause(self, qe):
        """A plugin scalar function inside WHERE routes the filter to
        host evaluation instead of failing on the device path."""
        qe.plugins.register_scalar_function("double_it", lambda v: v * 2)
        try:
            qe.execute_one(
                "CREATE TABLE pw (k STRING, v DOUBLE, ts TIMESTAMP TIME "
                "INDEX, PRIMARY KEY(k))")
            qe.execute_one(
                "INSERT INTO pw VALUES ('a', 1.0, 1000), ('b', 2.0, 2000)")
            r = qe.execute_one("SELECT k FROM pw WHERE double_it(v) > 3")
            assert r.rows() == [["b"]]
        finally:
            qe.plugins._scalar_functions.clear()

    def test_broken_env_plugin_raises_every_time(self, monkeypatch):
        import greptimedb_tpu.plugins as plug

        monkeypatch.setenv("GREPTIMEDB_TPU_PLUGINS", "no_such_plugin_mod")
        monkeypatch.setattr(plug, "_default", None)
        with pytest.raises(ModuleNotFoundError):
            plug.default_plugins()
        # not cached as a partial container: still raises
        with pytest.raises(ModuleNotFoundError):
            plug.default_plugins()

    def test_setup_module_loading(self, tmp_path, monkeypatch):
        mod = tmp_path / "my_plugin.py"
        mod.write_text(
            "def setup(plugins):\n"
            "    plugins.register_scalar_function('forty_two', "
            "lambda: 42)\n")
        monkeypatch.syspath_prepend(str(tmp_path))
        p = Plugins()
        p.setup_module("my_plugin")
        assert p.scalar_function("forty_two")() == 42


def _varint(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _field(tag, wt, payload):
    head = _varint((tag << 3) | wt)
    if wt == 2:
        return head + _varint(len(payload)) + payload
    if wt == 1:
        return head + payload
    return head + _varint(payload)


def _kv(key, val):
    any_value = _field(1, 2, val.encode())
    return _field(1, 2, key.encode()) + _field(2, 2, any_value)


def _make_span(trace_id, span_id, name, start_ns, end_ns, kind=2):
    body = _field(1, 2, trace_id)
    body += _field(2, 2, span_id)
    body += _field(5, 2, name.encode())
    body += _field(6, 0, kind)
    body += _field(7, 1, struct.pack("<Q", start_ns))
    body += _field(8, 1, struct.pack("<Q", end_ns))
    body += _field(9, 2, _kv("http.method", "GET"))
    status = _field(3, 0, 1)  # STATUS_CODE_OK
    body += _field(15, 2, status)
    return body


class TestStringFieldFilters:
    def test_string_field_where_with_ts_literal(self, qe):
        """Mixing a string-field predicate with a timestamp comparison:
        the host filter must still coerce the ts literal to the column
        unit (bind_host_expr)."""
        qe.execute_one(
            "CREATE TABLE notes (k STRING, note STRING, ts TIMESTAMP "
            "TIME INDEX, PRIMARY KEY(k))")
        qe.execute_one(
            "INSERT INTO notes VALUES ('a', 'keep', 1000), "
            "('b', 'drop', 2000), ('c', 'keep', 3000)")
        r = qe.execute_one(
            "SELECT k FROM notes WHERE note = 'keep' AND ts >= 2000 "
            "ORDER BY k")
        assert r.rows() == [["c"]]
        # LIKE over a string FIELD column (not a tag)
        r = qe.execute_one(
            "SELECT k FROM notes WHERE note LIKE 'ke%' ORDER BY k")
        assert r.rows() == [["a"], ["c"]]


class TestOtlpTraces:
    def test_traces_ingest_and_query(self, qe):
        from greptimedb_tpu.servers.otlp import handle_otlp_traces

        # ResourceSpans.resource -> Resource.attributes -> KeyValue
        resource = _field(1, 2, _field(1, 2, _kv("service.name", "checkout")))
        scope = _field(1, 2, _field(1, 2, b"my-lib") + _field(2, 2, b"1.0"))
        spans = b"".join([
            _field(2, 2, _make_span(b"\x01" * 16, b"\x0a" * 8, "GET /cart",
                                    1_000_000_000, 1_250_000_000)),
            _field(2, 2, _make_span(b"\x01" * 16, b"\x0b" * 8, "SELECT db",
                                    1_050_000_000, 1_100_000_000, kind=3)),
        ])
        scope_spans = _field(2, 2, scope + spans)
        body = _field(1, 2, resource + scope_spans)
        n = handle_otlp_traces(qe, body)
        assert n == 2
        r = qe.execute_one(
            "SELECT trace_id, span_name, span_kind, duration_nano "
            "FROM opentelemetry_traces ORDER BY span_name")
        rows = r.rows()
        assert rows[0][0] == "01" * 16
        assert rows[0][1] == "GET /cart"
        assert rows[0][2] == "SPAN_KIND_SERVER"
        assert rows[0][3] == pytest.approx(250_000_000.0)
        assert rows[1][2] == "SPAN_KIND_CLIENT"
        # resource attributes survive as JSON
        r = qe.execute_one(
            "SELECT resource_attributes FROM opentelemetry_traces LIMIT 1")
        attrs = json.loads(r.rows()[0][0])
        assert attrs["service.name"] == "checkout"
