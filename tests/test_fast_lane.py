"""Parse-free serving fast lane (concurrency/fast_lane.py, ISSUE 14):
the literal scanner, probe-verified binders, byte-for-byte parity with
the slow lane across HTTP/MySQL/Postgres, DDL-invalidation races, the
typed fallback matrix, the sharded hot counters, the lock-light
admission fast path, and the columnar INSERT seam."""

import json
import threading
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.concurrency import ConcurrencyConfig, ConcurrencyPlane
from greptimedb_tpu.concurrency import fast_lane as fl
from greptimedb_tpu.concurrency.admission import AdmissionController
from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine
from greptimedb_tpu.utils.metrics import FAST_LANE_EVENTS


def make_qe(tmp_path, plane=None, sub="a"):
    engine = RegionEngine(EngineConfig(
        data_dir=str(tmp_path / f"data_{sub}"), maintenance_workers=0))
    qe = QueryEngine(Catalog(MemoryKv()), engine, concurrency=plane)
    return engine, qe


def create_cpu(qe):
    qe.execute_one(
        "CREATE TABLE cpu (host STRING, v DOUBLE, ts TIMESTAMP(3) "
        "TIME INDEX, PRIMARY KEY(host))")


def ingest(qe, hosts=4, points=60):
    rows = []
    for h in range(hosts):
        for i in range(points):
            rows.append(f"('h{h}', {float((h + 1) * (i % 7))}, "
                        f"{i * 1000})")
    qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                   + ",".join(rows))


DASH = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, max(v), "
        "sum(v) FROM cpu WHERE host = '{host}' AND ts >= {lo} AND "
        "ts < {hi} GROUP BY minute")


def events():
    out: dict = {}
    for key, v in FAST_LANE_EVENTS._snapshot().items():
        e = dict(key)["event"]
        out[e] = out.get(e, 0) + v
    return out


# ---- scanner ----------------------------------------------------------------


class TestScanner:
    def test_rotating_literals_share_a_template(self):
        a, err = fl.scan("SELECT max(v) FROM cpu WHERE host = 'h1' "
                         "AND ts >= 1000 AND ts < 2000")
        b, err2 = fl.scan("SELECT max(v) FROM cpu WHERE host = 'h2' "
                          "AND ts >= 5000 AND ts < 9000")
        assert err is None and err2 is None
        assert a[0] == b[0]
        assert a[1] == ["h1", 1000, 2000]
        assert b[1] == ["h2", 5000, 9000]

    def test_value_types_match_the_parser(self):
        scanned, _ = fl.scan(
            "SELECT 1 WHERE a = 5 AND b = 5.5 AND c = 1e3 AND d = .5")
        assert scanned[1] == [1, 5, 5.5, 1000.0, 0.5]
        assert [type(v) for v in scanned[1]] \
            == [int, int, float, float, float]

    def test_identifier_digits_are_not_literals(self):
        scanned, _ = fl.scan("SELECT v2 FROM t1 WHERE host_1 = 3")
        assert scanned[1] == [3]

    def test_quoted_identifiers_stay_in_the_template(self):
        scanned, _ = fl.scan('SELECT "col2" FROM cpu WHERE "t5" = 7')
        assert scanned[1] == [7]
        assert '"col2"' in scanned[0] and '"t5"' in scanned[0]

    @pytest.mark.parametrize("sql,reason", [
        ("SELECT 1 -- trailing comment", "comment"),
        ("SELECT /* inline */ 1", "comment"),
        ("SELECT 'it''s' FROM cpu", "quoted_literal"),
        ("INSERT INTO cpu VALUES (1)", "non_select"),
        ("DROP TABLE cpu", "non_select"),
        ("SELECT 1; SELECT 2", "multi_statement"),
        ("SELECT '\x00'", "ambiguous"),
        ("SELECT " + "1," * 3000 + "2", "ambiguous"),
    ])
    def test_ambiguity_falls_back_typed(self, sql, reason):
        scanned, err = fl.scan(sql)
        assert scanned is None and err == reason

    def test_comment_marker_inside_string_is_fine(self):
        scanned, err = fl.scan("SELECT 1 WHERE a = '--not a comment'")
        assert err is None
        assert scanned[1] == [1, "--not a comment"]

    def test_trailing_semicolon_is_single_statement(self):
        scanned, err = fl.scan("SELECT max(v) FROM cpu ;")
        assert err is None


# ---- engine integration -----------------------------------------------------


class TestFastLaneServing:
    def test_hit_rebinds_and_matches_slow_lane(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe)
        sqls = [DASH.format(host=f"h{h}", lo=lo, hi=lo + 60_000)
                for h in range(3) for lo in (0, 10_000)]
        # first sighting marks the template, the second builds it
        for s in sqls:
            qe.execute_one(s)
        built = {s: qe.execute_one(s) for s in sqls}
        h0 = events().get("hit", 0)
        for s, want in built.items():
            got = qe.execute_one(s)
            slow = qe._execute_sql_slow(s, QueryContext())[-1]
            assert got.names == want.names == slow.names
            assert got.rows() == want.rows() == slow.rows()
        assert events().get("hit", 0) - h0 >= len(sqls)
        # distinct answers prove the rebind is real
        assert len({repr(r.rows()) for r in built.values()}) > 1
        engine.close()

    def test_negative_and_string_literals_bind(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                       "('a', -5.0, 1000), ('b', 3.0, 2000)")
        q = "SELECT host FROM cpu WHERE v > -6.0 AND ts >= 0 ORDER BY host"
        assert qe.execute_one(q).rows() == [["a"], ["b"]]
        q2 = "SELECT host FROM cpu WHERE v > -4.0 AND ts >= 0 ORDER BY host"
        assert qe.execute_one(q2).rows() == [["b"]]  # hit: -4 rebinds
        engine.close()

    def test_structural_values_pin_per_variant(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe)
        ql = "SELECT host, max(v) FROM cpu GROUP BY host ORDER BY host LIMIT {n}"
        assert len(qe.execute_one(ql.format(n=2)).rows()) == 2
        assert len(qe.execute_one(ql.format(n=2)).rows()) == 2
        # same template, new LIMIT: must NOT serve the LIMIT-2 plan
        assert len(qe.execute_one(ql.format(n=3)).rows()) == 3
        assert len(qe.execute_one(ql.format(n=3)).rows()) == 3
        qi = ("SELECT date_bin(INTERVAL '{iv}', ts) AS m, count(v) "
              "FROM cpu GROUP BY m ORDER BY m LIMIT 2")
        minute = qe.execute_one(qi.format(iv="1 minute"))
        qe.execute_one(qi.format(iv="1 minute"))
        second = qe.execute_one(qi.format(iv="30 seconds"))
        assert minute.rows() != second.rows()
        slow = qe._execute_sql_slow(qi.format(iv="30 seconds"),
                                    QueryContext())[-1]
        assert second.rows() == slow.rows()
        engine.close()

    def test_boolean_literals_are_constant_params(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        qe.execute_one("CREATE TABLE flags (host STRING, ok BOOLEAN, ts "
                       "TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host))")
        qe.execute_one("INSERT INTO flags (host, ok, ts) VALUES "
                       "('a', true, 1000), ('b', false, 2000)")
        q = "SELECT host FROM flags WHERE ok = true AND ts >= {lo}"
        assert qe.execute_one(q.format(lo=0)).rows() == [["a"]]
        assert qe.execute_one(q.format(lo=500)).rows() == [["a"]]
        assert qe.execute_one(q.format(lo=1500)).rows() == []
        engine.close()

    def test_ddl_invalidates_before_next_request(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe, hosts=2, points=5)
        sql = "SELECT * FROM cpu WHERE ts >= 0 AND ts < 10000"
        qe.execute_one(sql)
        qe.execute_one(sql)  # fast-lane hit
        qe.execute_one("ALTER TABLE cpu ADD COLUMN extra DOUBLE")
        after = qe.execute_one(sql)
        assert "extra" in after.names
        engine.close()

    def test_remote_style_ddl_caught_by_info_check(self, tmp_path):
        """DDL that bypasses this engine's hooks (another frontend's
        ALTER) is caught by the per-hit TableInfo snapshot check."""
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe, hosts=2, points=5)
        sql = "SELECT * FROM cpu WHERE ts >= 0 AND ts < 10000"
        qe.execute_one(sql)
        qe.execute_one(sql)
        # mutate the catalog behind the plane's back (no invalidation
        # hook fires): fast lane must notice via _info_matches
        from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
        from greptimedb_tpu.datatypes.types import DataType, SemanticType
        info = qe.catalog.table("public", "cpu")
        new_schema = Schema(list(info.schema.columns) + [
            ColumnSchema("extra", DataType.FLOAT64, SemanticType.FIELD,
                         True)])
        for rid in info.region_ids:
            qe.region_engine.alter_region_schema(rid, new_schema)
        info.schema = new_schema
        qe.catalog.update_table(info)
        inv0 = events().get("invalidate", 0)
        after = qe.execute_one(sql)
        assert "extra" in after.names
        assert events().get("invalidate", 0) > inv0
        engine.close()

    def test_alter_race_between_hit_and_execute(self, tmp_path):
        """An ALTER landing after the template hit but before execute:
        the request must not crash, and the NEXT request serves the new
        schema — identical to the slow lane's plan-cache race window."""
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe, hosts=2, points=5)
        sql = "SELECT host, v FROM cpu WHERE ts >= 0 AND ts < 10000"
        qe.execute_one(sql)
        qe.execute_one(sql)
        lane = qe.concurrency.fast_lane
        orig = lane._bind_execute
        fired = []

        def racing(qe_, entry, params):
            if not fired:
                fired.append(True)
                qe.execute_one("ALTER TABLE cpu ADD COLUMN extra DOUBLE")
            return orig(qe_, entry, params)

        lane._bind_execute = racing
        try:
            mid = qe.execute_one(sql)  # races the ALTER; must not crash
            assert mid.names == ["host", "v"]
        finally:
            lane._bind_execute = orig
        after = qe.execute_one("SELECT * FROM cpu WHERE ts >= 0 "
                               "AND ts < 10000")
        assert "extra" in after.names
        engine.close()

    def test_drop_and_recreate_serves_fresh(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe, hosts=2, points=5)
        sql = "SELECT count(v) FROM cpu WHERE ts >= 0"
        assert qe.execute_one(sql).rows() == [[10]]
        assert qe.execute_one(sql).rows() == [[10]]
        qe.execute_one("DROP TABLE cpu")
        create_cpu(qe)
        qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                       "('x', 1.0, 1000)")
        assert qe.execute_one(sql).rows() == [[1]]
        engine.close()

    def test_rollup_state_change_falls_back_until_reprobed(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe)
        sql = DASH.format(host="h0", lo=0, hi=60_000)
        qe.execute_one(sql)  # mark
        want = qe.execute_one(sql).rows()  # build
        h0 = events().get("hit", 0)
        assert qe.execute_one(sql).rows() == want  # hit
        assert events().get("hit", 0) == h0 + 1
        from greptimedb_tpu.maintenance import rollup

        rollup._bump_substitution_state()
        f0 = events().get("fallback", 0)
        assert qe.execute_one(sql).rows() == want  # slow lane re-probes
        assert events().get("fallback", 0) == f0 + 1
        # the re-probe re-stamped the shared plan-cache entry: hits resume
        assert qe.execute_one(sql).rows() == want
        assert events().get("hit", 0) == h0 + 2
        engine.close()

    def test_session_funcs_never_template(self, tmp_path):
        """database() depends on the session — the text cannot key the
        plan, so the template must go (and stay) uncacheable."""
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                       "('a', 1.0, 1000)")
        sql = "SELECT database() AS db, host FROM cpu WHERE ts >= 0"
        r1 = qe.execute_one(sql)
        r2 = qe.execute_one(sql)
        assert r1.rows() == r2.rows() == [["public", "a"]]
        assert len(qe.concurrency.fast_lane) == 0
        engine.close()

    def test_session_timezone_binds_per_request(self, tmp_path):
        """Naive string timestamp literals coerce in the SESSION
        timezone at bind time: the same text from differently zoned
        sessions must produce different (correct) answers, and the
        single-flight must not share across zones."""
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        # rows at epoch 0h and 2h (UTC)
        qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                       "('a', 1.0, 0), ('b', 2.0, 7200000)")
        sql = ("SELECT host FROM cpu WHERE ts >= '1970-01-01 01:00:00' "
               "ORDER BY host")
        for _ in range(2):  # second round: fast-lane hits
            assert qe.execute_sql(sql, QueryContext(
                timezone="UTC"))[-1].rows() == [["b"]]
            # 01:00 at +02:00 is 23:00Z the day before: both rows match
            assert qe.execute_sql(sql, QueryContext(
                timezone="+02:00"))[-1].rows() == [["a"], ["b"]]
        engine.close()

    def test_pinned_churn_marks_template_uncacheable(self, tmp_path):
        """A pinned slot rotating per request (ever-changing LIMIT)
        must not pay a probe rebuild forever — the churn guard marks
        the template uncacheable after the variant list saturates."""
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe)
        lane = qe.concurrency.fast_lane
        ql = "SELECT host, max(v) FROM cpu GROUP BY host ORDER BY host LIMIT {n}"
        for n in range(1, 50):
            r = qe.execute_one(ql.format(n=n))
            assert len(r.rows()) == min(n, 4)  # 4 hosts
        key = next(iter(lane._templates))
        assert lane._templates[key].uncacheable
        # still serves correctly through the slow lane
        assert len(qe.execute_one(ql.format(n=2)).rows()) == 2
        engine.close()

    def test_first_sighting_marks_second_builds(self, tmp_path):
        """A never-repeated ad-hoc statement must not pay the O(slots)
        probe build — entries appear on the SECOND sighting."""
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe, hosts=2, points=5)
        sql = "SELECT count(v) FROM cpu WHERE ts >= 0"
        qe.execute_one(sql)
        assert len(qe.concurrency.fast_lane) == 0  # marked, not built
        qe.execute_one(sql)
        assert len(qe.concurrency.fast_lane) == 1  # built
        engine.close()

    def test_interceptor_chain_runs_exactly_once(self, tmp_path):
        """Auditing interceptors count invocations: the fast lane must
        not double-run the chain on misses/fallbacks, and a rewriting
        interceptor routes to the slow lane (one run, rewritten text)."""
        from greptimedb_tpu.plugins import Plugins

        engine, qe = make_qe(tmp_path)
        # a PRIVATE container: default_plugins() is a process-wide
        # singleton, and a registered rewriter would poison every
        # later test in this interpreter
        qe.plugins = Plugins()
        create_cpu(qe)
        ingest(qe, hosts=2, points=5)
        calls = []

        def audit(sql, ctx):
            calls.append(sql)
            return sql

        qe.plugins.register_sql_interceptor(audit)
        sql = "SELECT count(v) FROM cpu WHERE ts >= 0"
        for expected in (1, 2, 3, 4):  # mark, build, hit, hit
            qe.execute_one(sql)
            assert len(calls) == expected
        # non-SELECT fallback: still exactly one run
        qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                       "('z', 1.0, 99000)")
        assert len(calls) == 5

        def rewrite(sql, ctx):
            calls.append(sql)
            return sql.replace("count(v)", "sum(v)")

        qe.plugins.register_sql_interceptor(rewrite)
        r = qe.execute_one(sql)
        # the rewritten text executed (sum, not count), chain ran once
        assert r.names == ["sum(v)"]
        assert calls[-2:] == [sql, sql]
        engine.close()

    def test_disabled_lane_is_inert(self, tmp_path):
        plane = ConcurrencyPlane(ConcurrencyConfig(fast_lane=False))
        engine, qe = make_qe(tmp_path, plane=plane)
        create_cpu(qe)
        ingest(qe, hosts=2, points=5)
        sql = "SELECT count(v) FROM cpu WHERE ts >= 0"
        qe.execute_one(sql)
        qe.execute_one(sql)
        assert len(qe.concurrency.fast_lane) == 0
        engine.close()


# ---- byte identity across protocols ----------------------------------------


class TestByteIdentity:
    def _twin_engines(self, tmp_path):
        """Two engines over identical data: one with the lane, one
        without — the oracle for byte-level response comparison."""
        fast = make_qe(tmp_path, plane=ConcurrencyPlane(
            ConcurrencyConfig()), sub="fast")
        slow = make_qe(tmp_path, plane=ConcurrencyPlane(
            ConcurrencyConfig(fast_lane=False)), sub="slow")
        for _, qe in (fast, slow):
            create_cpu(qe)
            ingest(qe)
        return fast, slow

    def test_http_payload_bytes_identical(self, tmp_path):
        from greptimedb_tpu.servers.encode import encode_sql_payload

        (ef, qf), (es, qs) = self._twin_engines(tmp_path)
        sqls = [DASH.format(host=f"h{h}", lo=lo, hi=lo + 60_000)
                for h in range(2) for lo in (0, 10_000)]
        for s in sqls * 3:  # round 1 marks, 2 builds, 3 hits
            bf = encode_sql_payload(qf.execute_sql(s, QueryContext()), 1.0)
            bs = encode_sql_payload(qs.execute_sql(s, QueryContext()), 1.0)
            assert bf == bs
        ef.close()
        es.close()

    def test_mysql_and_postgres_wire_parity(self, tmp_path):
        from greptimedb_tpu.servers.mysql import MysqlServer
        from greptimedb_tpu.servers.postgres import PostgresServer
        from tests.test_wire_protocols import MiniMysql, MiniPg

        (ef, qf), (es, qs) = self._twin_engines(tmp_path)
        servers, clients = [], []
        try:
            pairs = []
            for qe in (qf, qs):
                ms = MysqlServer(qe, port=0)
                ms.start()
                ps = PostgresServer(qe, port=0)
                ps.start()
                servers += [ms, ps]
                my = MiniMysql(ms.port)
                pg = MiniPg(ps.port)
                clients += [my, pg]
                pairs.append((my, pg))
            (my_f, pg_f), (my_s, pg_s) = pairs
            sqls = [DASH.format(host="h0", lo=0, hi=60_000),
                    "SELECT host, v FROM cpu WHERE ts >= 1000 AND "
                    "ts < 9000 ORDER BY host, ts"]
            for s in sqls * 2:
                assert my_f.query(s) == my_s.query(s)
                assert pg_f.query(s) == pg_s.query(s)
        finally:
            for c in clients:
                c.close()
            for srv in servers:
                srv.shutdown()
            ef.close()
            es.close()

    def test_threaded_50_client_parity(self, tmp_path):
        """50 concurrent HTTP clients on a fast-lane server: every
        response must equal the idle-server slow-lane response."""
        from greptimedb_tpu.servers.http import HttpServer

        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe)
        sqls = [DASH.format(host=f"h{h}", lo=lo, hi=lo + 60_000)
                for h in range(4) for lo in (0, 10_000, 20_000)]
        oracle = {}
        with qe.concurrency.suppress_batching():
            for s in sqls:
                r = qe._execute_sql_slow(s, QueryContext())[-1]
                oracle[s] = (list(r.names), r.rows())
        srv = HttpServer(qe, host="127.0.0.1", port=0)
        errors = []
        try:
            port = srv.start()
            url = f"http://127.0.0.1:{port}/v1/sql"

            def client(i):
                try:
                    for k in range(6):
                        s = sqls[(i + k) % len(sqls)]
                        body = urllib.parse.urlencode({"sql": s}).encode()
                        with urllib.request.urlopen(
                                urllib.request.Request(url, data=body),
                                timeout=120) as resp:
                            payload = json.loads(resp.read())
                        rec = payload["output"][0]["records"]
                        names = [c["name"]
                                 for c in rec["schema"]["column_schemas"]]
                        want_names, want_rows = oracle[s]
                        assert names == want_names
                        assert len(rec["rows"]) == len(want_rows)
                        for got, want in zip(rec["rows"], want_rows):
                            assert got == [
                                None if (isinstance(v, float) and v != v)
                                else v for v in want]
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(50)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(180)
        finally:
            srv.stop()
        assert not errors, errors[:3]
        hits = events().get("hit", 0)
        assert hits > 0
        engine.close()


# ---- sharded hot counters ---------------------------------------------------


class TestShardedCounters:
    def test_concurrent_incs_never_lose_counts(self):
        from greptimedb_tpu.utils.metrics import ShardedCounter

        c = ShardedCounter("greptimedb_tpu_test_shard_total", "test")
        n_threads, per = 16, 5000

        def work():
            for _ in range(per):
                c.inc(kind="a")
                c.inc(2.0, kind="b")

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.get(kind="a") == n_threads * per
        assert c.get(kind="b") == 2.0 * n_threads * per
        assert c.total() == 3.0 * n_threads * per

    def test_dead_thread_shard_folds_into_base(self):
        from greptimedb_tpu.utils.metrics import ShardedCounter

        c = ShardedCounter("greptimedb_tpu_test_fold_total", "test")
        t = threading.Thread(target=lambda: c.inc(5.0, kind="x"))
        t.start()
        t.join()
        del t
        import gc

        gc.collect()
        deadline = time.monotonic() + 5
        while c.shard_count() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert c.shard_count() == 0  # folded by the finalizer
        assert c.get(kind="x") == 5.0

    def test_render_merges_shards(self):
        from greptimedb_tpu.utils.metrics import ShardedCounter

        c = ShardedCounter("greptimedb_tpu_test_render_total", "test")
        c.inc(kind="a")
        lines = c.render()
        assert 'greptimedb_tpu_test_render_total{kind="a"} 1.0' in lines


# ---- admission fast path ----------------------------------------------------


class TestAdmissionFastPath:
    def test_uncontended_grab_and_release(self):
        ac = AdmissionController(4, queue_size=8)
        with ac.slot("t"):
            assert ac.active == 1
            with ac.slot("t"):  # re-entrant: same thread, same slot
                assert ac.active == 1
        assert ac.active == 0 and ac.queued == 0

    def test_contended_handoff_bounds_active(self):
        ac = AdmissionController(2, queue_size=64, queue_timeout_s=30)
        seen = []
        gate = threading.Semaphore(0)

        def work():
            with ac.slot("t"):
                seen.append(ac.active)
                time.sleep(0.005)
            gate.release()

        threads = [threading.Thread(target=work) for _ in range(12)]
        for t in threads:
            t.start()
        for _ in range(12):
            assert gate.acquire(timeout=30)
        for t in threads:
            t.join(10)
        assert max(seen) <= 2
        assert ac.active == 0 and ac.queued == 0

    def test_no_lost_wakeup_under_churn(self):
        """Hammer the enqueue/release race window: every waiter must be
        served long before the 5s timeout (a lost wakeup would eat the
        full timeout and fail the wall-clock bound)."""
        ac = AdmissionController(1, queue_size=256, queue_timeout_s=5.0)
        done = []

        def work():
            for _ in range(60):
                with ac.slot("t"):
                    pass
            done.append(1)

        threads = [threading.Thread(target=work) for _ in range(8)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(done) == 8
        assert time.monotonic() - t0 < 20
        assert ac.active == 0 and ac.queued == 0

    def test_queue_full_raises_typed_overloaded(self):
        from greptimedb_tpu.concurrency import Overloaded

        ac = AdmissionController(1, queue_size=0)
        hold = threading.Event()
        release = threading.Event()

        def holder():
            with ac.slot("t"):
                hold.set()
                release.wait(10)

        t = threading.Thread(target=holder)
        t.start()
        assert hold.wait(10)
        try:
            with pytest.raises(Overloaded):
                with ac.slot("other"):
                    pass
        finally:
            release.set()
            t.join(10)


# ---- encode header memos ----------------------------------------------------


class TestEncodeMemos:
    def test_sql_payload_matches_whole_document_dumps(self, tmp_path):
        from greptimedb_tpu.query.result import QueryResult
        from greptimedb_tpu.servers.encode import (
            encode_sql_payload,
            records_json,
        )
        from greptimedb_tpu.datatypes.types import DataType

        r = QueryResult(
            ["h", "v"], [DataType.STRING, DataType.FLOAT64],
            [np.asarray(["a", "b"], dtype=object),
             np.asarray([1.5, float("nan")])])
        aff = QueryResult.of_affected(3)
        got = encode_sql_payload([aff, r], 12.345)
        want = json.dumps({
            "code": 0,
            "output": [{"affectedrows": 3},
                       {"records": records_json(r)}],
            "execution_time_ms": 12.345}).encode()
        assert got == want
        # second call rides the memoized schema header — still identical
        assert encode_sql_payload([aff, r], 12.345) == want

    def test_mysql_header_packets_memoized_and_identical(self):
        from greptimedb_tpu.servers.encode import (
            _coldef,
            _eof,
            encode_mysql_rows,
            lenc_int,
            MYSQL_TYPE_VAR_STRING,
        )

        names = ["a", "b"]
        rows = [["x", 1], [None, 2.5]]
        got = encode_mysql_rows(names, rows)
        want = [lenc_int(2), _coldef("a", MYSQL_TYPE_VAR_STRING),
                _coldef("b", MYSQL_TYPE_VAR_STRING), _eof()]
        assert got[:4] == want
        assert got[4] == b"\x01x" + b"\x011"
        assert got[5] == b"\xfb" + b"\x032.5"
        assert encode_mysql_rows(names, rows) == got

    def test_postgres_row_description_memoized(self):
        from greptimedb_tpu.datatypes.types import DataType
        from greptimedb_tpu.servers.postgres import _row_description

        a = _row_description(["h", "v"], [DataType.STRING,
                                          DataType.FLOAT64])
        b = _row_description(["h", "v"], [DataType.STRING,
                                          DataType.FLOAT64])
        assert a is b  # memo, not a rebuild


# ---- columnar INSERT seam ---------------------------------------------------


class TestColumnarInsert:
    def test_parser_emits_columnar_values(self):
        from greptimedb_tpu.sql import parse_sql

        stmts = parse_sql("INSERT INTO cpu (host, v, ts) VALUES "
                          "('a', 1.5, 1000), ('b', NULL, 2000), "
                          "('c', true, 3000)" + " " * 40)
        assert len(stmts) == 1
        ins = stmts[0]
        assert ins.columnar_values == [
            ["a", "b", "c"], [1.5, None, True], [1000, 2000, 3000]]
        assert ins.rows == []

    def test_columnar_and_expression_inserts_agree(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        # literal fast path (columnar) — padded past the 64-char gate
        qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                       "('a', 1.5, 1000), ('b', 2.5, 2000)" + " " * 30)
        # expression path (full parser, per-cell evaluation)
        qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                       "('c', 1.0 + 0.5, 3000)")
        r = qe.execute_one("SELECT host, v FROM cpu WHERE ts >= 0 "
                           "ORDER BY host")
        assert r.rows() == [["a", 1.5], ["b", 2.5], ["c", 1.5]]
        engine.close()

    def test_arity_mismatch_still_typed_error(self, tmp_path):
        from greptimedb_tpu.query.expr import PlanError

        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        with pytest.raises(PlanError):
            qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                           "('a', 1.5)" + " " * 60)
        engine.close()

    def test_null_time_index_rejected(self, tmp_path):
        from greptimedb_tpu.query.expr import PlanError

        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        with pytest.raises(PlanError, match="time index"):
            qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES "
                           "('a', 1.5, NULL)" + " " * 50)
        engine.close()
