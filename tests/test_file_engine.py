"""File engine + datasource + COPY TO/FROM (reference src/file-engine,
common/datasource, operator/src/statement/copy_table_{to,from}.rs)."""

import gzip
import os

import numpy as np
import pyarrow as pa
import pytest

from greptimedb_tpu import datasource
from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.query.engine import QueryContext, QueryEngine
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "data")))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE cpu (host STRING, usage DOUBLE, ts TIMESTAMP(3) TIME INDEX, "
        "PRIMARY KEY(host))"
    )
    q.execute_one(
        "INSERT INTO cpu (host, usage, ts) VALUES "
        "('a', 1.0, 1000), ('a', 3.0, 61000), ('b', 10.0, 2000)"
    )
    yield q
    engine.close()


class TestDatasource:
    def test_format_inference(self):
        assert datasource.infer_format("/x/a.csv") == "csv"
        assert datasource.infer_format("/x/a.json.gz") == "json"
        assert datasource.infer_format("/x/a.ndjson") == "json"
        assert datasource.infer_format("/x/a.parquet") == "parquet"
        assert datasource.infer_format("/x/a.orc") == "orc"
        assert datasource.infer_format("/x/a.bin", "CSV") == "csv"
        with pytest.raises(datasource.DataSourceError):
            datasource.infer_format("/x/a.bin")
        with pytest.raises(datasource.DataSourceError):
            datasource.infer_format("/x/a.csv", "avro")

    @pytest.mark.parametrize("ext", ["csv", "json", "parquet", "orc"])
    def test_roundtrip(self, tmp_path, ext):
        t = pa.table({"host": ["a", "b"], "v": [1.5, 2.5], "ts": [100, 200]})
        path = str(tmp_path / f"t.{ext}")
        assert datasource.write_file(t, path) == 2
        back = datasource.read_file(path)
        assert back.num_rows == 2
        assert back.column("host").to_pylist() == ["a", "b"]
        assert back.column("v").to_pylist() == [1.5, 2.5]

    def test_gzip_csv(self, tmp_path):
        t = pa.table({"a": [1, 2, 3]})
        path = str(tmp_path / "t.csv.gz")
        datasource.write_file(t, path)
        with open(path, "rb") as f:
            assert f.read(2) == b"\x1f\x8b"  # really gzipped
        assert datasource.read_file(path).column("a").to_pylist() == [1, 2, 3]


class TestCopy:
    def test_copy_to_from_parquet(self, qe, tmp_path):
        path = str(tmp_path / "cpu.parquet")
        r = qe.execute_one(f"COPY cpu TO '{path}'")
        assert r.affected_rows == 3
        qe.execute_one("CREATE TABLE cpu2 (host STRING, usage DOUBLE, "
                       "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host))")
        r = qe.execute_one(f"COPY cpu2 FROM '{path}'")
        assert r.affected_rows == 3
        rows = qe.execute_one(
            "SELECT host, usage FROM cpu2 ORDER BY host, usage").rows()
        assert rows == [["a", 1.0], ["a", 3.0], ["b", 10.0]]

    def test_copy_csv_with_format(self, qe, tmp_path):
        path = str(tmp_path / "cpu.data")
        r = qe.execute_one(f"COPY TABLE cpu TO '{path}' WITH (format = 'csv')")
        assert r.affected_rows == 3
        qe.execute_one("DELETE FROM cpu WHERE host = 'b'")
        r = qe.execute_one(f"COPY cpu FROM '{path}' WITH (format = 'csv')")
        assert r.affected_rows == 3
        assert qe.execute_one(
            "SELECT count(*) FROM cpu WHERE host = 'b'").rows()[0][0] == 1

    def test_copy_to_from_orc(self, qe, tmp_path):
        """ORC parity with the reference's file_format.rs:57-61 set."""
        path = str(tmp_path / "cpu.orc")
        r = qe.execute_one(f"COPY cpu TO '{path}'")
        assert r.affected_rows == 3
        import pyarrow.orc as po
        assert po.read_table(path).num_rows == 3  # really ORC on disk
        qe.execute_one("CREATE TABLE cpu3 (host STRING, usage DOUBLE, "
                       "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host))")
        r = qe.execute_one(f"COPY cpu3 FROM '{path}' WITH (format = 'orc')")
        assert r.affected_rows == 3
        rows = qe.execute_one(
            "SELECT host, usage FROM cpu3 ORDER BY host, usage").rows()
        assert rows == [["a", 1.0], ["a", 3.0], ["b", 10.0]]

    def test_copy_database(self, qe, tmp_path):
        qe.execute_one("CREATE TABLE mem (host STRING, used DOUBLE, "
                       "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host))")
        qe.execute_one("INSERT INTO mem (host, used, ts) VALUES ('m', 7.0, 500)")
        outdir = str(tmp_path / "backup")
        r = qe.execute_one(f"COPY DATABASE public TO '{outdir}'")
        assert r.affected_rows == 4  # 3 cpu + 1 mem
        assert sorted(os.listdir(outdir)) == ["cpu.parquet", "mem.parquet"]
        # restore into a fresh database with same table defs
        qe.execute_one("TRUNCATE TABLE cpu")
        qe.execute_one("TRUNCATE TABLE mem")
        r = qe.execute_one(f"COPY DATABASE public FROM '{outdir}'")
        assert r.affected_rows == 4
        assert qe.execute_one("SELECT count(*) FROM cpu").rows()[0][0] == 3


class TestFileEngine:
    def test_external_table_explicit_schema(self, qe, tmp_path):
        t = pa.table({"city": ["sf", "nyc", "sf"],
                      "pop": [1.0, 2.0, 3.0],
                      "ts": [1000, 2000, 3000]})
        path = str(tmp_path / "city.parquet")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE city (city STRING, pop DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(city)) "
            f"WITH (location = '{path}', format = 'parquet')")
        rows = qe.execute_one(
            "SELECT city, pop FROM city ORDER BY ts").rows()
        assert rows == [["sf", 1.0], ["nyc", 2.0], ["sf", 3.0]]
        # aggregates run through the same device kernels
        agg = qe.execute_one(
            "SELECT city, sum(pop) FROM city GROUP BY city ORDER BY city").rows()
        assert agg == [["nyc", 2.0], ["sf", 4.0]]

    def test_external_table_orc(self, qe, tmp_path):
        t = pa.table({"city": ["sf", "nyc"], "pop": [1.0, 2.0],
                      "ts": [1000, 2000]})
        path = str(tmp_path / "city.orc")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE city_orc (city STRING, pop DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(city)) "
            f"WITH (location = '{path}', format = 'orc')")
        rows = qe.execute_one(
            "SELECT city, sum(pop) FROM city_orc GROUP BY city "
            "ORDER BY city").rows()
        assert rows == [["nyc", 2.0], ["sf", 1.0]]

    def test_external_table_inferred_schema(self, qe, tmp_path):
        t = pa.table({"host": ["x", "y"], "v": [1.5, 2.5],
                      "ts": pa.array([1000, 2000], type=pa.timestamp("ms"))})
        path = str(tmp_path / "infer.parquet")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE ext WITH (location = '{path}')")
        desc = qe.execute_one("DESCRIBE TABLE ext").rows()
        sem = {row[0]: row[5] for row in desc}
        assert sem["host"] == "TAG" and sem["v"] == "FIELD"
        assert sem["ts"] == "TIMESTAMP"
        assert qe.execute_one("SELECT count(*) FROM ext").rows()[0][0] == 2

    def test_external_table_readonly(self, qe, tmp_path):
        t = pa.table({"host": ["x"], "v": [1.0], "ts": [1000]})
        path = str(tmp_path / "ro.csv")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE ro (host STRING, v DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
            f"WITH (location = '{path}')")
        from greptimedb_tpu.storage.file_engine import FileEngineError
        with pytest.raises(FileEngineError):
            qe.execute_one("INSERT INTO ro (host, v, ts) VALUES ('y', 2, 2000)")

    def test_external_table_time_filter(self, qe, tmp_path):
        t = pa.table({"host": ["x", "x", "x"], "v": [1.0, 2.0, 3.0],
                      "ts": [1000, 2000, 3000]})
        path = str(tmp_path / "tf.parquet")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE tf (host STRING, v DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
            f"WITH (location = '{path}')")
        rows = qe.execute_one(
            "SELECT v FROM tf WHERE ts >= 2000 ORDER BY ts").rows()
        assert rows == [[2.0], [3.0]]

    def test_external_table_reopen(self, qe, tmp_path):
        """File region metadata survives in kv; a fresh engine reopens it."""
        t = pa.table({"host": ["x"], "v": [9.0], "ts": [1000]})
        path = str(tmp_path / "ro2.parquet")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE ro2 (host STRING, v DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
            f"WITH (location = '{path}')")
        rid = qe.catalog.table("public", "ro2").region_ids[0]
        # simulate restart: evict from the live engine, reopen via opener
        qe.region_engine.regions.pop(rid)
        qe._open_regions.discard(rid)
        assert qe.execute_one("SELECT v FROM ro2").rows() == [[9.0]]

    def test_truncate_external_rejected(self, qe, tmp_path):
        from greptimedb_tpu.query.expr import PlanError

        t = pa.table({"host": ["x"], "v": [1.0], "ts": [1000]})
        path = str(tmp_path / "tr.csv")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE tr (host STRING, v DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
            f"WITH (location = '{path}')")
        with pytest.raises(PlanError):
            qe.execute_one("TRUNCATE TABLE tr")
        assert qe.execute_one("SELECT count(*) FROM tr").rows()[0][0] == 1

    def test_copy_path_must_be_quoted(self, qe):
        from greptimedb_tpu.sql.parser import SqlError

        with pytest.raises(SqlError):
            qe.execute_one("COPY cpu TO WITH (format='csv')")

    def test_positional_insert_declared_order(self, qe):
        """Positional VALUES bind in CREATE TABLE order, not the
        canonical (tags, ts, fields) storage order."""
        qe.execute_one(
            "CREATE TABLE pos (host STRING, v DOUBLE, "
            "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host))")
        qe.execute_one("INSERT INTO pos VALUES ('a', 1.5, 1000)")
        assert qe.execute_one(
            "SELECT host, v, ts FROM pos").rows() == [["a", 1.5, 1000]]
        desc = qe.execute_one("DESCRIBE TABLE pos").rows()
        assert [row[0] for row in desc] == ["host", "v", "ts"]

    def test_external_reopen_fresh_engine(self, qe, tmp_path):
        """After a full restart (new RegionEngine + QueryEngine over the
        same kv), the file opener is registered eagerly and the external
        table still reads."""
        t = pa.table({"host": ["z"], "v": [5.0], "ts": [1000]})
        path = str(tmp_path / "fresh.parquet")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE fr (host STRING, v DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
            f"WITH (location = '{path}')")
        engine2 = RegionEngine(EngineConfig(data_dir=str(tmp_path / "d2")))
        qe2 = QueryEngine(qe.catalog, engine2)
        try:
            assert qe2.execute_one("SELECT v FROM fr").rows() == [[5.0]]
        finally:
            engine2.close()

    def test_null_tags_match_native_semantics(self, qe, tmp_path):
        t = pa.table({"host": ["x", None], "v": [1.0, 2.0],
                      "ts": [1000, 2000]})
        path = str(tmp_path / "nulls.parquet")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE nt (host STRING, v DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
            f"WITH (location = '{path}')")
        rows = qe.execute_one("SELECT host, v FROM nt ORDER BY ts").rows()
        assert rows == [["x", 1.0], [None, 2.0]]

    def test_copy_database_ndjson_roundtrip(self, qe, tmp_path):
        outdir = str(tmp_path / "njback")
        r = qe.execute_one(
            f"COPY DATABASE public TO '{outdir}' WITH (format = 'ndjson')")
        assert r.affected_rows == 3
        qe.execute_one("TRUNCATE TABLE cpu")
        r = qe.execute_one(
            f"COPY DATABASE public FROM '{outdir}' WITH (format = 'ndjson')")
        assert r.affected_rows == 3

    def test_alter_updates_column_order(self, qe):
        qe.execute_one("ALTER TABLE cpu ADD COLUMN extra DOUBLE")
        desc = [row[0] for row in qe.execute_one("DESCRIBE TABLE cpu").rows()]
        assert desc == ["host", "usage", "ts", "extra"]
        qe.execute_one("INSERT INTO cpu VALUES ('c', 5.0, 5000, 7.0)")
        assert qe.execute_one(
            "SELECT extra FROM cpu WHERE host = 'c'").rows() == [[7.0]]
        qe.execute_one("ALTER TABLE cpu DROP COLUMN extra")
        desc = [row[0] for row in qe.execute_one("DESCRIBE TABLE cpu").rows()]
        assert desc == ["host", "usage", "ts"]
        qe.execute_one("INSERT INTO cpu VALUES ('d', 6.0, 6000)")

    def test_drop_external_table(self, qe, tmp_path):
        t = pa.table({"host": ["x"], "v": [1.0], "ts": [1000]})
        path = str(tmp_path / "dr.csv")
        datasource.write_file(t, path)
        qe.execute_one(
            f"CREATE EXTERNAL TABLE dr (host STRING, v DOUBLE, "
            f"ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host)) "
            f"WITH (location = '{path}')")
        qe.execute_one("DROP TABLE dr")
        assert os.path.exists(path)  # dropping the table keeps the file
        assert "dr" not in [
            r[0] for r in qe.execute_one("SHOW TABLES").rows()]
