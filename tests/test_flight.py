"""Arrow Flight gRPC services: SQL query streaming, bulk Arrow ingest,
region-scan transport, handshake auth (reference servers::grpc,
src/servers/src/grpc/{flight.rs,region_server.rs})."""

import numpy as np
import pyarrow as pa
import pyarrow.flight as fl
import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.servers.flight import (
    FlightQueryClient,
    FlightServer,
    RegionFlightClient,
    scan_to_table,
    table_to_scan,
)
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE cpu (host STRING, usage DOUBLE, ts TIMESTAMP(3) TIME INDEX, "
        "PRIMARY KEY(host))"
    )
    q.execute_one(
        "INSERT INTO cpu (host, usage, ts) VALUES "
        "('a', 1.0, 1000), ('a', 3.0, 61000), ('b', 10.0, 2000)"
    )
    yield q
    engine.close()


@pytest.fixture
def server(qe):
    srv = FlightServer(qe, port=0)
    try:
        yield srv
    finally:
        srv.shutdown()


def _addr(srv):
    return f"127.0.0.1:{srv.port}"


class TestQueryService:
    def test_sql_roundtrip(self, server):
        client = FlightQueryClient(_addr(server))
        r = client.sql("SELECT host, usage, ts FROM cpu ORDER BY ts")
        assert r.names == ["host", "usage", "ts"]
        assert r.rows()[0] == ["a", 1.0, 1000]
        assert r.num_rows == 3
        client.close()

    def test_aggregate_over_flight(self, server):
        client = FlightQueryClient(_addr(server))
        r = client.sql("SELECT host, avg(usage) FROM cpu GROUP BY host "
                       "ORDER BY host")
        assert r.rows() == [["a", 2.0], ["b", 10.0]]
        client.close()

    def test_affected_rows_alias_not_misdetected(self, server):
        client = FlightQueryClient(_addr(server))
        r = client.sql("SELECT count(*) AS affected_rows FROM cpu")
        assert r.is_query
        assert r.rows() == [[3]]
        client.close()

    def test_ddl_dml_via_action(self, server):
        client = FlightQueryClient(_addr(server))
        r = client.sql("INSERT INTO cpu (host, usage, ts) VALUES ('c', 5, 5000)")
        assert r.affected_rows == 1
        assert client.health()
        client.close()

    def test_bulk_arrow_ingest(self, server):
        client = FlightQueryClient(_addr(server))
        data = pa.table({
            "host": ["d"] * 4,
            "usage": [1.0, 2.0, 3.0, 4.0],
            "ts": [100000, 200000, 300000, 400000],
        })
        n = client.insert("cpu", data)
        assert n == 4
        r = client.sql("SELECT count(*) FROM cpu WHERE host = 'd'")
        assert r.rows()[0][0] == 4
        client.close()

    def test_list_flights(self, server):
        client = fl.FlightClient(f"grpc://{_addr(server)}")
        flights = list(client.list_flights())
        paths = [tuple(p.decode() for p in f.descriptor.path)
                 for f in flights]
        assert ("public", "cpu") in paths
        client.close()


class TestRegionService:
    def test_region_scan_roundtrip(self, qe, server):
        info = qe.catalog.table("public", "cpu")
        rid = info.region_ids[0]
        client = RegionFlightClient(_addr(server))
        scan = client.scan(rid)
        assert scan is not None
        assert scan.num_rows == 3
        assert "host" in scan.tag_dicts
        # codes decode to the right hosts
        hosts = scan.tag_dicts["host"][scan.columns["host"]]
        assert sorted(hosts) == ["a", "a", "b"]
        assert scan.region_id == rid
        client.close()

    def test_region_scan_filters(self, qe, server):
        info = qe.catalog.table("public", "cpu")
        rid = info.region_ids[0]
        client = RegionFlightClient(_addr(server))
        scan = client.scan(rid, ts_range=(0, 10_000),
                           projection=["host", "usage", "ts"])
        assert scan is not None
        assert scan.num_rows <= 3
        client.close()

    def test_empty_region_scan(self, qe, server):
        qe.execute_one(
            "CREATE TABLE empty_t (host STRING, v DOUBLE, "
            "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")
        rid = qe.catalog.table("public", "empty_t").region_ids[0]
        client = RegionFlightClient(_addr(server))
        assert client.scan(rid) is None
        client.close()

    def test_scandata_serde(self, qe):
        info = qe.catalog.table("public", "cpu")
        scan = qe.region_engine.scan(info.region_ids[0])
        t = scan_to_table(scan)
        back = table_to_scan(t)
        assert back.num_rows == scan.num_rows
        np.testing.assert_array_equal(back.seq, scan.seq)
        np.testing.assert_array_equal(back.op_type, scan.op_type)
        for k in scan.columns:
            np.testing.assert_array_equal(back.columns[k], scan.columns[k])
        assert back.schema.names == scan.schema.names


class TestFlightAuth:
    def test_handshake(self, qe):
        from greptimedb_tpu.auth import StaticUserProvider

        srv = FlightServer(qe, port=0,
                           user_provider=StaticUserProvider({"u": "pw"}))
        try:
            ok = FlightQueryClient(f"127.0.0.1:{srv.port}", "u", "pw")
            assert ok.sql("SELECT count(*) FROM cpu").rows()[0][0] == 3
            ok.close()
            with pytest.raises(fl.FlightUnauthenticatedError):
                FlightQueryClient(f"127.0.0.1:{srv.port}", "u", "nope")
        finally:
            srv.shutdown()

    def test_identity_enforced_on_calls(self, qe):
        """Grants travel from the handshake into every handler's
        QueryContext (ADVICE r1 high: user=None skipped all checks)."""
        from greptimedb_tpu.auth import StaticUserProvider, UserInfo

        class ReadOnlyProvider(StaticUserProvider):
            def authenticate(self, username, password):
                info = super().authenticate(username, password)
                return UserInfo(info.username, grants=frozenset({"read"}))

        srv = FlightServer(qe, port=0,
                           user_provider=ReadOnlyProvider({"ro": "pw"}))
        try:
            c = FlightQueryClient(f"127.0.0.1:{srv.port}", "ro", "pw")
            # reads fine
            assert c.sql("SELECT count(*) FROM cpu").rows()[0][0] == 3
            # writes rejected via do_get(sql) path
            with pytest.raises(fl.FlightError):
                c.sql("INSERT INTO cpu (host, usage, ts) VALUES ('z',1,99)")
            # and via do_put bulk ingest
            t = pa.table({"host": ["z"], "usage": [1.0], "ts": [99]})
            with pytest.raises(fl.FlightError):
                c.insert("cpu", t)
            c.close()
        finally:
            srv.shutdown()

    def test_region_scan_requires_read(self, qe):
        """Raw region scans are reads: a write-only identity is rejected
        (code-review r2: the region_scan branch skipped identity)."""
        from greptimedb_tpu.auth import StaticUserProvider, UserInfo
        from greptimedb_tpu.servers.flight import RegionFlightClient

        class WriteOnlyProvider(StaticUserProvider):
            def authenticate(self, username, password):
                info = super().authenticate(username, password)
                return UserInfo(info.username, grants=frozenset({"write"}))

        srv = FlightServer(qe, port=0,
                           user_provider=WriteOnlyProvider({"wo": "pw"}))
        try:
            info = qe.catalog.table("public", "cpu")
            rc = RegionFlightClient(f"127.0.0.1:{srv.port}",
                                    user="wo", password="pw")
            with pytest.raises(fl.FlightError):
                rc.scan(info.region_ids[0])
            rc.close()
        finally:
            srv.shutdown()

    def test_do_put_protected_schema(self, qe):
        """Bulk ingest into greptime_private is rejected for non-admin
        users even with a write grant (code-review r2: do_put only
        checked the grant half)."""
        from greptimedb_tpu.auth import StaticUserProvider

        srv = FlightServer(qe, port=0,
                           user_provider=StaticUserProvider({"w": "pw"}))
        try:
            c = FlightQueryClient(f"127.0.0.1:{srv.port}", "w", "pw")
            t = pa.table({"host": ["z"], "usage": [1.0], "ts": [99]})
            with pytest.raises(fl.FlightError):
                c.insert("cpu", t, db="greptime_private")
            c.close()
        finally:
            srv.shutdown()
