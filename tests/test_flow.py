"""Flow engine tests: continuous aggregation into sink tables (reference
src/flow adapter tests analog)."""

import numpy as np
import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.flow import FlowEngine
from greptimedb_tpu.query.engine import QueryContext, QueryEngine
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE requests (host STRING, latency DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))"
    )
    yield q
    engine.close()


def seed(qe, offset=0, n=10):
    rows = []
    for h in ("a", "b"):
        for i in range(n):
            rows.append(f"('{h}', {i + offset}.0, {60_000 * i + offset + 1})")
    qe.execute_one("INSERT INTO requests (host, latency, ts) VALUES " + ",".join(rows))


class TestFlowDDL:
    def test_create_show_drop(self, qe):
        qe.execute_one(
            "CREATE FLOW f1 SINK TO req_summary AS "
            "SELECT host, avg(latency), date_bin(INTERVAL '5 minutes', ts) AS bucket "
            "FROM requests GROUP BY host, bucket"
        )
        res = qe.execute_one("SHOW FLOWS")
        assert res.rows()[0][0] == "f1"
        assert res.rows()[0][1] == "req_summary"
        assert "avg(latency)" in res.rows()[0][3]
        qe.execute_one("DROP FLOW f1")
        assert qe.execute_one("SHOW FLOWS").num_rows == 0

    def test_duplicate_create_raises(self, qe):
        sql = ("CREATE FLOW f1 SINK TO s AS SELECT host, count(*) "
               "FROM requests GROUP BY host")
        qe.execute_one(sql)
        with pytest.raises(ValueError, match="already exists"):
            qe.execute_one(sql)
        qe.execute_one(sql.replace("CREATE FLOW", "CREATE FLOW IF NOT EXISTS"))


class TestFlowTicking:
    def test_aggregate_materializes_into_sink(self, qe):
        seed(qe)
        qe.execute_one(
            "CREATE FLOW f SINK TO summary AS "
            "SELECT host, avg(latency) AS avg_lat, "
            "date_bin(INTERVAL '5 minutes', ts) AS bucket "
            "FROM requests GROUP BY host, bucket"
        )
        fe = qe.flow_engine
        ticked = fe.run_available()
        assert ticked.get("f", 0) > 0
        res = qe.execute_one(
            "SELECT host, avg_lat FROM summary ORDER BY host, bucket"
        )
        assert res.num_rows == 4  # 2 hosts x 2 buckets (10 min of minutely data)
        rows = res.rows()
        assert rows[0][0] == "a"
        assert rows[0][1] == pytest.approx(2.0)  # avg(0..4)

    def test_incremental_update_on_new_data(self, qe):
        seed(qe)
        qe.execute_one(
            "CREATE FLOW f SINK TO s2 AS "
            "SELECT host, count(*) AS n FROM requests GROUP BY host"
        )
        fe = qe.flow_engine
        fe.run_available()
        res = qe.execute_one("SELECT host, n FROM s2 ORDER BY host")
        assert [r[1] for r in res.rows()] == [10.0, 10.0]
        # no change -> no work
        assert fe.run_available() == {}
        # new rows -> sink catches up (upsert overwrites group rows)
        qe.execute_one("INSERT INTO requests (host, latency, ts) VALUES ('a', 9.0, 999)")
        out = fe.run_available()
        assert out.get("f", 0) > 0
        res = qe.execute_one("SELECT host, n FROM s2 ORDER BY host")
        assert [r[1] for r in res.rows()] == [11.0, 10.0]

    def test_incremental_state_merge_matches_oracle(self, tmp_path):
        """Append-mode source + decomposable aggregates take the
        incremental path: ticks fold ONLY new rows (seq-bounded scans)
        and merge per-group state planes persisted in the sink —
        results must match the direct SQL aggregate at every step."""
        engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        q = QueryEngine(Catalog(MemoryKv()), engine)
        q.execute_one(
            "CREATE TABLE req (host STRING, latency DOUBLE, "
            "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host)) "
            "WITH (append_mode = 'true')")
        q.execute_one(
            "CREATE FLOW f SINK TO inc_sink AS "
            "SELECT host, avg(latency) AS a, min(latency) AS lo, "
            "max(latency) AS hi, count(*) AS n, "
            "date_bin(INTERVAL '5 minutes', ts) AS bucket "
            "FROM req GROUP BY host, bucket")
        info = q.flow_engine.list_flows()[0]
        assert info.incremental is True

        def oracle():
            return q.execute_one(
                "SELECT host, avg(latency), min(latency), max(latency), "
                "count(*), date_bin(INTERVAL '5 minutes', ts) AS bucket "
                "FROM req GROUP BY host, bucket "
                "ORDER BY host, bucket").rows()

        def sink():
            return q.execute_one(
                "SELECT host, a, lo, hi, n, bucket FROM inc_sink "
                "ORDER BY host, bucket").rows()

        rows = [f"('h{i % 3}', {float(i)}, {i * 30_000 + 1})"
                for i in range(40)]
        q.execute_one("INSERT INTO req VALUES " + ", ".join(rows))
        q.flow_engine.run_available()
        assert sink() == oracle()
        assert FlowEngine.last_tick_stats["path"] == "incremental"
        engine.flush(q.catalog.table("public", "req").region_ids[0])

        # late + new data across existing and new buckets
        q.execute_one("INSERT INTO req VALUES ('h0', 100.0, 2), "
                      "('h1', -5.0, 1000000), ('h9', 7.0, 3000000)")
        out = q.flow_engine.run_available()
        assert out.get("f", 0) > 0
        # a tick scanned only the 3 new rows, not the 40 flushed ones
        assert FlowEngine.last_tick_stats["scanned_rows"] == 3
        assert sink() == oracle()
        engine.close()

    def test_incremental_tick_scans_only_new_rows_after_flush(self,
                                                              tmp_path):
        """O(new data): old SSTs are pruned whole by max_seq — the
        scan cost of a tick is the new rows, not the table (round-4
        verdict #8)."""
        engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        q = QueryEngine(Catalog(MemoryKv()), engine)
        q.execute_one(
            "CREATE TABLE big (host STRING, v DOUBLE, "
            "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host)) "
            "WITH (append_mode = 'true')")
        rid = q.catalog.table("public", "big").region_ids[0]
        rows = [f"('h{i % 5}', {float(i)}, {i * 1000 + 1})"
                for i in range(5000)]
        q.execute_one("INSERT INTO big VALUES " + ", ".join(rows))
        engine.flush(rid)
        q.execute_one(
            "CREATE FLOW fb SINK TO big_sink AS "
            "SELECT host, sum(v) AS s, count(*) AS n FROM big "
            "GROUP BY host")
        q.flow_engine.run_available()
        assert FlowEngine.last_tick_stats["scanned_rows"] == 5000
        engine.flush(rid)

        for round_i in range(3):
            q.execute_one(
                "INSERT INTO big VALUES "
                + ", ".join(f"('h{j}', 1.0, {10_000_000 + round_i * 10 + j})"
                            for j in range(5)))
            if round_i == 1:
                engine.flush(rid)  # new rows in their own SST still prune
            q.flow_engine.run_available()
            assert FlowEngine.last_tick_stats["scanned_rows"] == 5, \
                FlowEngine.last_tick_stats
        got = q.execute_one(
            "SELECT host, s, n FROM big_sink ORDER BY host").rows()
        want = q.execute_one(
            "SELECT host, sum(v), count(*) FROM big "
            "GROUP BY host ORDER BY host").rows()
        assert got == want
        engine.close()

    def test_incremental_survives_restart(self, tmp_path):
        """last_seqs persists: a fresh FlowEngine (and a restarted
        region engine) resumes folding from the stored boundary."""
        engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "d")))
        kv = MemoryKv()
        q = QueryEngine(Catalog(kv), engine)
        q.execute_one(
            "CREATE TABLE r2 (host STRING, v DOUBLE, "
            "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host)) "
            "WITH (append_mode = 'true')")
        q.execute_one(
            "CREATE FLOW fr SINK TO r2_sink AS "
            "SELECT host, sum(v) AS s FROM r2 GROUP BY host")
        q.execute_one("INSERT INTO r2 VALUES ('a', 1.0, 1000)")
        q.flow_engine.run_available()
        fe2 = FlowEngine(q)
        q.execute_one("INSERT INTO r2 VALUES ('a', 2.0, 2000)")
        assert fe2.run_available().get("fr", 0) > 0
        assert FlowEngine.last_tick_stats["scanned_rows"] == 1
        assert q.execute_one(
            "SELECT s FROM r2_sink WHERE host = 'a'").rows() == [[3.0]]
        engine.close()

    def test_non_decomposable_flow_falls_back(self, tmp_path):
        """median() has no mergeable state — the flow must stay on the
        dirty-span path and still produce correct results."""
        engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        q = QueryEngine(Catalog(MemoryKv()), engine)
        q.execute_one(
            "CREATE TABLE r3 (host STRING, v DOUBLE, "
            "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host)) "
            "WITH (append_mode = 'true')")
        q.execute_one(
            "CREATE FLOW fm SINK TO r3_sink AS "
            "SELECT host, median(v) AS m FROM r3 GROUP BY host")
        assert q.flow_engine.list_flows()[0].incremental is False
        q.execute_one("INSERT INTO r3 VALUES ('a', 1.0, 1000), "
                      "('a', 2.0, 2000), ('a', 9.0, 3000)")
        q.flow_engine.run_available()
        assert q.execute_one(
            "SELECT m FROM r3_sink WHERE host = 'a'").rows() == [[2.0]]
        engine.close()

    def test_flow_survives_engine_restart(self, qe):
        seed(qe)
        qe.execute_one(
            "CREATE FLOW f SINK TO s3 AS "
            "SELECT host, max(latency) AS m FROM requests GROUP BY host"
        )
        qe.flow_engine.run_available()
        # a fresh FlowEngine over the same kv picks the flow up
        fe2 = FlowEngine(qe)
        flows = fe2.list_flows()
        assert len(flows) == 1
        assert flows[0].sink_table == "s3"
        qe.execute_one("INSERT INTO requests (host, latency, ts) VALUES ('a', 99.0, 5)")
        assert fe2.run_available().get("f", 0) > 0
        res = qe.execute_one("SELECT m FROM s3 WHERE host = 'a'")
        assert res.rows() == [[99.0]]
