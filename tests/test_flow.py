"""Flow engine tests: continuous aggregation into sink tables (reference
src/flow adapter tests analog)."""

import numpy as np
import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.flow import FlowEngine
from greptimedb_tpu.query.engine import QueryContext, QueryEngine
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE requests (host STRING, latency DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))"
    )
    yield q
    engine.close()


def seed(qe, offset=0, n=10):
    rows = []
    for h in ("a", "b"):
        for i in range(n):
            rows.append(f"('{h}', {i + offset}.0, {60_000 * i + offset + 1})")
    qe.execute_one("INSERT INTO requests (host, latency, ts) VALUES " + ",".join(rows))


class TestFlowDDL:
    def test_create_show_drop(self, qe):
        qe.execute_one(
            "CREATE FLOW f1 SINK TO req_summary AS "
            "SELECT host, avg(latency), date_bin(INTERVAL '5 minutes', ts) AS bucket "
            "FROM requests GROUP BY host, bucket"
        )
        res = qe.execute_one("SHOW FLOWS")
        assert res.rows()[0][0] == "f1"
        assert res.rows()[0][1] == "req_summary"
        assert "avg(latency)" in res.rows()[0][3]
        qe.execute_one("DROP FLOW f1")
        assert qe.execute_one("SHOW FLOWS").num_rows == 0

    def test_duplicate_create_raises(self, qe):
        sql = ("CREATE FLOW f1 SINK TO s AS SELECT host, count(*) "
               "FROM requests GROUP BY host")
        qe.execute_one(sql)
        with pytest.raises(ValueError, match="already exists"):
            qe.execute_one(sql)
        qe.execute_one(sql.replace("CREATE FLOW", "CREATE FLOW IF NOT EXISTS"))


class TestFlowTicking:
    def test_aggregate_materializes_into_sink(self, qe):
        seed(qe)
        qe.execute_one(
            "CREATE FLOW f SINK TO summary AS "
            "SELECT host, avg(latency) AS avg_lat, "
            "date_bin(INTERVAL '5 minutes', ts) AS bucket "
            "FROM requests GROUP BY host, bucket"
        )
        fe = qe.flow_engine
        ticked = fe.run_available()
        assert ticked.get("f", 0) > 0
        res = qe.execute_one(
            "SELECT host, avg_lat FROM summary ORDER BY host, bucket"
        )
        assert res.num_rows == 4  # 2 hosts x 2 buckets (10 min of minutely data)
        rows = res.rows()
        assert rows[0][0] == "a"
        assert rows[0][1] == pytest.approx(2.0)  # avg(0..4)

    def test_incremental_update_on_new_data(self, qe):
        seed(qe)
        qe.execute_one(
            "CREATE FLOW f SINK TO s2 AS "
            "SELECT host, count(*) AS n FROM requests GROUP BY host"
        )
        fe = qe.flow_engine
        fe.run_available()
        res = qe.execute_one("SELECT host, n FROM s2 ORDER BY host")
        assert [r[1] for r in res.rows()] == [10.0, 10.0]
        # no change -> no work
        assert fe.run_available() == {}
        # new rows -> sink catches up (upsert overwrites group rows)
        qe.execute_one("INSERT INTO requests (host, latency, ts) VALUES ('a', 9.0, 999)")
        out = fe.run_available()
        assert out.get("f", 0) > 0
        res = qe.execute_one("SELECT host, n FROM s2 ORDER BY host")
        assert [r[1] for r in res.rows()] == [11.0, 10.0]

    def test_flow_survives_engine_restart(self, qe):
        seed(qe)
        qe.execute_one(
            "CREATE FLOW f SINK TO s3 AS "
            "SELECT host, max(latency) AS m FROM requests GROUP BY host"
        )
        qe.flow_engine.run_available()
        # a fresh FlowEngine over the same kv picks the flow up
        fe2 = FlowEngine(qe)
        flows = fe2.list_flows()
        assert len(flows) == 1
        assert flows[0].sink_table == "s3"
        qe.execute_one("INSERT INTO requests (host, latency, ts) VALUES ('a', 99.0, 5)")
        assert fe2.run_available().get("f", 0) > 0
        res = qe.execute_one("SELECT m FROM s3 WHERE host = 'a'")
        assert res.rows() == [[99.0]]
