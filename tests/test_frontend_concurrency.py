"""Frontend concurrency plane tests (concurrency/ package): the
shape-keyed parameterized plan cache and its invalidation under DDL and
rollup-state changes, bounded admission with per-tenant weighted fair
scheduling (a flooding tenant cannot starve a light one), typed
Overloaded rejection through the HTTP/MySQL error mapping, and the
cross-query batcher's bit-for-bit parity with serial execution — the
tier-1 concurrency smoke drives threaded clients through the full
frontend path (HTTP server -> admission -> plan cache -> batcher ->
device execution)."""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.concurrency import (
    ConcurrencyConfig,
    ConcurrencyPlane,
    Overloaded,
)
from greptimedb_tpu.concurrency.admission import (
    AdmissionController,
    parse_weights,
)
from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine
from greptimedb_tpu.utils.metrics import (
    ADMISSION_EVENTS,
    PLAN_CACHE_EVENTS,
    QUERY_BATCH_EVENTS,
)


def make_qe(tmp_path, plane=None, **engine_cfg):
    engine_cfg.setdefault("maintenance_workers", 0)
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "data"),
                                       **engine_cfg))
    qe = QueryEngine(Catalog(MemoryKv()), engine, concurrency=plane)
    return engine, qe


def create_cpu(qe):
    qe.execute_one(
        "CREATE TABLE cpu (host STRING, v DOUBLE, ts TIMESTAMP(3) "
        "TIME INDEX, PRIMARY KEY(host))")


def ingest(qe, hosts=4, points=120, step_ms=1000, t0=0):
    rows = []
    for h in range(hosts):
        for i in range(points):
            rows.append(f"('h{h}', {float((h + 1) * (i % 7))}, "
                        f"{t0 + i * step_ms})")
    qe.execute_one("INSERT INTO cpu (host, v, ts) VALUES " + ",".join(rows))


DASH_SQL = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, max(v), "
            "sum(v) FROM cpu WHERE host = '{host}' AND ts >= {lo} AND "
            "ts < {hi} GROUP BY minute")


def run_threads(fns, timeout=120):
    """Run fns concurrently; return per-fn results, raise on any error."""
    out = [None] * len(fns)
    errors = []
    barrier = threading.Barrier(len(fns))

    def wrap(i, fn):
        try:
            barrier.wait(timeout)
            out[i] = fn()
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i, fn))
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not errors, errors[:3]
    return out


# ---- plan cache ------------------------------------------------------------


class TestPlanCache:
    def test_shape_hit_rebinds_parameters(self, tmp_path):
        """2000 dashboard queries differing only in WHERE literals share
        ONE cache entry, and every rebind computes the RIGHT answer.
        (Fast lane off: the plan cache's own hit counter is asserted —
        with the lane on, repeats would be fast-lane hits instead.)"""
        engine, qe = make_qe(tmp_path, plane=ConcurrencyPlane(
            ConcurrencyConfig(fast_lane=False)))
        create_cpu(qe)
        ingest(qe)
        oracle = {}
        for host in ("h0", "h1", "h2"):
            for lo in (0, 60_000):
                sql = DASH_SQL.format(host=host, lo=lo, hi=lo + 60_000)
                oracle[sql] = qe.execute_one(sql).rows()
        assert len(qe.concurrency.plan_cache) == 1
        hits0 = PLAN_CACHE_EVENTS.get(event="hit")
        for sql, want in oracle.items():
            assert qe.execute_one(sql).rows() == want
        assert PLAN_CACHE_EVENTS.get(event="hit") - hits0 >= len(oracle)
        # distinct answers prove the rebind is real, not a stale replay
        assert len({repr(r) for r in oracle.values()}) > 1
        engine.close()

    def test_structural_values_are_distinct_shapes(self, tmp_path):
        """Literals OUTSIDE the WHERE clause (bucket width, LIMIT) change
        the plan structure — they must key separate entries."""
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe)
        a = ("SELECT date_bin(INTERVAL '1 minute', ts) AS b, max(v) "
             "FROM cpu WHERE ts >= 0 GROUP BY b")
        b = ("SELECT date_bin(INTERVAL '2 minutes', ts) AS b, max(v) "
             "FROM cpu WHERE ts >= 0 GROUP BY b")
        ra1, rb1 = qe.execute_one(a).rows(), qe.execute_one(b).rows()
        assert len(qe.concurrency.plan_cache) == 2
        assert qe.execute_one(a).rows() == ra1
        assert qe.execute_one(b).rows() == rb1
        assert ra1 != rb1
        engine.close()

    def test_capacity_eviction(self, tmp_path):
        plane = ConcurrencyPlane(ConcurrencyConfig(plan_cache_entries=2,
                                                   batching=False))
        engine, qe = make_qe(tmp_path, plane=plane)
        create_cpu(qe)
        ingest(qe, hosts=2, points=10)
        ev0 = PLAN_CACHE_EVENTS.get(event="evict")
        qe.execute_one("SELECT max(v) FROM cpu WHERE ts >= 0")
        qe.execute_one("SELECT min(v) FROM cpu WHERE ts >= 0")
        qe.execute_one("SELECT sum(v) FROM cpu WHERE ts >= 0")
        assert len(qe.concurrency.plan_cache) == 2
        assert PLAN_CACHE_EVENTS.get(event="evict") > ev0
        engine.close()

    @pytest.mark.parametrize("ddl", [
        "ALTER TABLE cpu ADD COLUMN extra DOUBLE",
        "TRUNCATE TABLE cpu",
        "DROP TABLE cpu",
    ])
    def test_ddl_invalidates_cached_shapes(self, tmp_path, ddl):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe, hosts=2, points=30)
        sql = DASH_SQL.format(host="h0", lo=0, hi=60_000)
        qe.execute_one(sql)
        qe.execute_one(sql)
        assert len(qe.concurrency.plan_cache) == 1
        inv0 = PLAN_CACHE_EVENTS.get(event="invalidate")
        qe.execute_one(ddl)
        assert len(qe.concurrency.plan_cache) == 0
        assert PLAN_CACHE_EVENTS.get(event="invalidate") > inv0
        engine.close()

    def test_alter_star_expansion_not_stale(self, tmp_path):
        """A cached `SELECT *` shape must not survive ALTER ADD COLUMN:
        the post-DDL query expands the NEW column set."""
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe, hosts=2, points=5)
        sql = "SELECT * FROM cpu WHERE ts >= 0 AND ts < 10000"
        before = qe.execute_one(sql)
        qe.execute_one(sql)
        qe.execute_one("ALTER TABLE cpu ADD COLUMN extra DOUBLE")
        after = qe.execute_one(sql)
        assert "extra" not in before.names
        assert "extra" in after.names
        engine.close()

    def test_truncate_then_drop_create_serve_fresh_plans(self, tmp_path):
        engine, qe = make_qe(tmp_path)
        create_cpu(qe)
        ingest(qe, hosts=2, points=30)
        sql = "SELECT count(*) FROM cpu WHERE ts >= 0"
        assert qe.execute_one(sql).rows() == [[60]]
        qe.execute_one("TRUNCATE TABLE cpu")
        assert qe.execute_one(sql).rows() == [[0]]
        qe.execute_one("DROP TABLE cpu")
        # same name, different schema: the old shape must not rebind
        qe.execute_one(
            "CREATE TABLE cpu (host STRING, v DOUBLE, w DOUBLE, "
            "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(host))")
        qe.execute_one(
            "INSERT INTO cpu (host, v, w, ts) VALUES ('h9', 1.0, 2.0, 5)")
        assert qe.execute_one(sql).rows() == [[1]]
        assert qe.execute_one(
            "SELECT w FROM cpu WHERE ts >= 0").rows() == [[2.0]]
        engine.close()

    def test_remote_ddl_caught_by_snapshot_comparison(self, tmp_path):
        """A DDL executed by ANOTHER engine over the same catalog (a
        peer frontend) never fires this engine's explicit invalidation —
        the per-hit TableInfo content check is the safety net."""
        engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "d"),
                                           maintenance_workers=0))
        catalog = Catalog(MemoryKv())
        qe1 = QueryEngine(catalog, engine)
        qe2 = QueryEngine(catalog, engine)
        create_cpu(qe1)
        ingest(qe1, hosts=2, points=5)
        sql = "SELECT * FROM cpu WHERE ts >= 0 AND ts < 10000"
        qe1.execute_one(sql)
        qe1.execute_one(sql)
        assert len(qe1.concurrency.plan_cache) == 1
        inv0 = PLAN_CACHE_EVENTS.get(event="invalidate")
        qe2.execute_one("ALTER TABLE cpu ADD COLUMN extra DOUBLE")
        after = qe1.execute_one(sql)  # qe1 never saw the ALTER
        assert "extra" in after.names
        assert PLAN_CACHE_EVENTS.get(event="invalidate") > inv0
        engine.close()

    def test_rollup_state_change_reprobes_substitution(self, tmp_path):
        """The cached entry memoizes 'substitution ineligible' — a
        finished roll must evict that memo, not keep serving raw scans
        for a now-substitutable shape."""
        engine, qe = make_qe(tmp_path, maintenance_workers=1,
                             rollup_rules=[{"resolution_ms": 60_000}])
        create_cpu(qe)
        ingest(qe, hosts=3, points=180)
        maint = qe.region_engine.maintenance
        for r in qe.execute_one("ADMIN flush_table('cpu')").rows():
            maint.wait(int(r[0]), timeout=30)
        sql = ("SELECT host, max(v), count(v) FROM cpu WHERE ts >= 0 AND "
               "ts < 120000 GROUP BY host ORDER BY host")
        # warm the shape BEFORE any rollup exists: memoizes skip-probe
        first = qe.execute_one(sql)
        qe.execute_one(sql)
        assert "+rollup" not in (qe.executor.last_path or "")
        jobs = [maint.wait(int(r[0]), timeout=30) for r in
                qe.execute_one("ADMIN rollup_table('cpu', '1m')").rows()]
        assert all(j.state == "done" for j in jobs), [j.error for j in jobs]
        got = qe.execute_one(sql)
        assert "+rollup" in (qe.executor.last_path or "")
        assert got.rows() == first.rows()
        engine.close()


# ---- admission control + fairness ------------------------------------------


class TestAdmission:
    def test_parse_weights(self):
        assert parse_weights("a=3, b=1,bad, c=x,=2") == {"a": 3, "b": 1}
        assert parse_weights("") == {}

    def test_queue_full_rejects_typed(self):
        ac = AdmissionController(1, queue_size=0)
        with ac.slot("t"):
            def blocked():
                # a second thread: the outer slot is thread-local
                def go():
                    with ac.slot("t"):
                        pass
                with pytest.raises(Overloaded):
                    go()
            run_threads([blocked])

    def test_queue_timeout_rejects_typed(self):
        ac = AdmissionController(1, queue_size=4, queue_timeout_s=0.05)
        release = threading.Event()

        def holder():
            with ac.slot("t"):
                release.wait(10)

        t = threading.Thread(target=holder)
        t.start()
        while ac.active == 0:
            time.sleep(0.001)
        rej0 = ADMISSION_EVENTS.get(event="reject_timeout", tenant="t")
        with pytest.raises(Overloaded):
            with ac.slot("t"):
                pass
        assert ADMISSION_EVENTS.get(event="reject_timeout", tenant="t") \
            > rej0
        release.set()
        t.join(10)

    def test_nested_statements_ride_the_outer_slot(self):
        ac = AdmissionController(1, queue_size=0)
        with ac.slot("t"):
            with ac.slot("t"):  # would deadlock if it re-acquired
                assert ac.depth() == 2
            assert ac.depth() == 1

    def test_slot_handoff_keeps_limit(self):
        ac = AdmissionController(2, queue_size=64)
        seen = []
        lock = threading.Lock()

        def worker():
            with ac.slot("t"):
                with lock:
                    seen.append(ac.active)
                time.sleep(0.002)

        run_threads([worker] * 16)
        assert max(seen) <= 2
        assert ac.active == 0 and ac.queued == 0

    def test_flooding_tenant_cannot_starve_light_tenant(self):
        """One slot, tenant `flood` parks a deep backlog, tenant `light`
        issues sequential queries: WRR must serve light after at most
        ~one turn, so light's p99 wait stays a small multiple of the
        work quantum while flood's backlog p99 is the whole drain."""
        ac = AdmissionController(1, queue_size=256, queue_timeout_s=60)
        quantum = 0.004
        flood_waits, light_waits = [], []
        lock = threading.Lock()

        def flood_one():
            t0 = time.perf_counter()
            with ac.slot("flood"):
                with lock:
                    flood_waits.append(time.perf_counter() - t0)
                time.sleep(quantum)

        def light_seq():
            # let the flood stack up first
            while ac.queued < 20:
                time.sleep(0.001)
            for _ in range(8):
                t0 = time.perf_counter()
                with ac.slot("light"):
                    light_waits.append(time.perf_counter() - t0)
                    time.sleep(quantum)

        run_threads([flood_one] * 40 + [light_seq])
        assert len(light_waits) == 8 and len(flood_waits) == 40
        p99_light = float(np.percentile(light_waits, 99))
        p99_flood = float(np.percentile(flood_waits, 99))
        # flood's tail waits the drain (~40 quanta); light never waits
        # more than a few quanta — assert a bounded ratio with slack
        assert p99_light < p99_flood / 3, (p99_light, p99_flood)

    def test_engine_overload_raises_typed(self, tmp_path):
        plane = ConcurrencyPlane(ConcurrencyConfig(
            max_concurrency=1, queue_size=0, batching=False))
        engine, qe = make_qe(tmp_path, plane=plane)
        create_cpu(qe)
        ingest(qe, hosts=2, points=10)
        release = threading.Event()
        entered = threading.Event()

        def holder():
            with qe.concurrency.admission.slot("big"):
                entered.set()
                release.wait(10)

        t = threading.Thread(target=holder)
        t.start()
        entered.wait(10)
        try:
            with pytest.raises(Overloaded):
                qe.execute_one("SELECT count(*) FROM cpu")
        finally:
            release.set()
            t.join(10)
        # slot free again: the statement goes through
        assert qe.execute_one("SELECT count(*) FROM cpu").rows() == [[20]]
        engine.close()

    def test_http_maps_overload_to_503(self, tmp_path):
        from greptimedb_tpu.servers.http import HttpServer

        plane = ConcurrencyPlane(ConcurrencyConfig(
            max_concurrency=1, queue_size=0, batching=False))
        engine, qe = make_qe(tmp_path, plane=plane)
        create_cpu(qe)
        ingest(qe, hosts=2, points=10)
        srv = HttpServer(qe, port=0)
        try:
            port = srv.start()
            release = threading.Event()
            entered = threading.Event()

            def holder():
                with qe.concurrency.admission.slot("big"):
                    entered.set()
                    release.wait(10)

            t = threading.Thread(target=holder)
            t.start()
            entered.wait(10)
            body = urllib.parse.urlencode(
                {"sql": "SELECT count(*) FROM cpu"}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/sql", data=body,
                headers={"X-Greptime-Tenant": "small"})
            try:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=30)
                assert ei.value.code == 503
            finally:
                release.set()
                t.join(10)
        finally:
            srv.stop()
        engine.close()


# ---- cross-query batching ---------------------------------------------------


class BatchPlane(ConcurrencyPlane):
    """A plane whose batcher treats every caller as busy and uses a wide
    window, so a threaded test reliably forms groups without depending
    on scheduler timing."""

    def __init__(self, window_ms=60.0, **kw):
        # the batcher is the layer under test: the parse-free fast lane
        # (which would otherwise serve these repeats before batching)
        # has its own suite in test_fast_lane.py
        kw.setdefault("fast_lane", False)
        super().__init__(ConcurrencyConfig(batch_window_ms=window_ms, **kw))

    def execute_select(self, qe, sel, info, ctx):
        if (not self.batcher.enabled or self.admission.depth() != 1
                or getattr(self._tls, "no_batch", False)):
            return qe._select_table(sel, info, ctx)
        return self.batcher.execute(qe, sel, info, ctx, busy=True)


class TestCrossQueryBatching:
    def _oracle(self, tmp_path, sqls, plane=None):
        """Serial ground truth + a batching engine over the same data."""
        engine, qe = make_qe(tmp_path, plane=plane or BatchPlane())
        create_cpu(qe)
        ingest(qe)
        serial = {}
        with qe.concurrency.suppress_batching():
            for sql in set(sqls):
                r = qe.execute_one(sql)
                serial[sql] = (r.names, r.rows())
        return engine, qe, serial

    def assert_parity(self, qe, sqls, serial, min_group=2):
        joined0 = QUERY_BATCH_EVENTS.get(event="join")
        got = run_threads(
            [lambda s=s: qe.execute_one(s) for s in sqls])
        for sql, res in zip(sqls, got):
            names, rows = serial[sql]
            assert res.names == names, sql
            assert res.rows() == rows, sql
        return QUERY_BATCH_EVENTS.get(event="join") - joined0

    def test_identical_statements_coalesce_bit_for_bit(self, tmp_path):
        sql = DASH_SQL.format(host="h1", lo=0, hi=120_000)
        sqls = [sql] * 12
        engine, qe, serial = self._oracle(tmp_path, sqls)
        co0 = QUERY_BATCH_EVENTS.get(event="coalesced")
        self.assert_parity(qe, sqls, serial)
        assert QUERY_BATCH_EVENTS.get(event="coalesced") > co0
        engine.close()

    def test_stacked_dispatch_bit_for_bit(self, tmp_path):
        """Members differing only in the selector tag value execute as
        ONE batched dispatch — the vmap'd stacked-parameter kernel, or
        the IN-list rewrite when it declines; each member's slice must
        equal its serial run exactly (values AND row order)."""
        sqls = [DASH_SQL.format(host=f"h{i % 4}", lo=0, hi=120_000)
                for i in range(16)]
        engine, qe, serial = self._oracle(tmp_path, sqls)
        st0 = (QUERY_BATCH_EVENTS.get(event="stacked")
               + QUERY_BATCH_EVENTS.get(event="vmapped"))
        self.assert_parity(qe, sqls, serial)
        assert (QUERY_BATCH_EVENTS.get(event="stacked")
                + QUERY_BATCH_EVENTS.get(event="vmapped")) > st0
        engine.close()

    def test_mixed_shapes_do_not_cross_batch(self, tmp_path):
        """Different shapes (different agg set / bucket / table-less)
        form separate groups — and every result is still exact."""
        sqls = ([DASH_SQL.format(host="h0", lo=0, hi=120_000)] * 3
                + [DASH_SQL.format(host="h2", lo=0, hi=120_000)] * 3
                + ["SELECT host, min(v) FROM cpu WHERE ts >= 0 AND "
                   "ts < 120000 GROUP BY host ORDER BY host"] * 3
                + ["SELECT count(*) FROM cpu WHERE ts >= 60000"] * 3)
        engine, qe, serial = self._oracle(tmp_path, sqls)
        self.assert_parity(qe, sqls, serial)
        engine.close()

    def test_leader_error_propagates_to_members(self, tmp_path):
        sql = "SELECT max(v) FROM cpu WHERE host = 'h0' GROUP BY host"
        engine, qe, _ = self._oracle(tmp_path, [sql])

        orig = qe._select_table
        calls = []

        def boom(sel, info, ctx):
            calls.append(1)
            raise RuntimeError("device fell over")

        qe._select_table = boom
        errors = []

        def one():
            try:
                qe.execute_one(sql)
            except RuntimeError as e:
                errors.append(e)

        ts = [threading.Thread(target=one) for _ in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        qe._select_table = orig
        assert len(errors) == 6
        # at least one member rode the leader's (failed) execution
        assert len(calls) < 6
        engine.close()

    def test_http_threaded_smoke_bit_for_bit(self, tmp_path):
        """The tier-1 concurrency smoke: threaded keep-alive HTTP
        clients through the FULL frontend path; every response's result
        payload must be bit-for-bit identical to the idle-server
        response for the same SQL (only the timing field may differ)."""
        import http.client

        from greptimedb_tpu.servers.http import HttpServer

        engine, qe = make_qe(tmp_path, plane=BatchPlane(window_ms=20.0))
        create_cpu(qe)
        ingest(qe)
        srv = HttpServer(qe, port=0)
        try:
            port = srv.start()

            def fetch(sql, tenant):
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                try:
                    body = urllib.parse.urlencode({"sql": sql}).encode()
                    conn.request(
                        "POST", "/v1/sql", body=body,
                        headers={"Content-Type":
                                 "application/x-www-form-urlencoded",
                                 "X-Greptime-Tenant": tenant})
                    resp = conn.getresponse()
                    data = resp.read()
                    assert resp.status == 200, data[:200]
                    payload = json.loads(data)
                    payload.pop("execution_time_ms", None)
                    return json.dumps(payload, sort_keys=True)
                finally:
                    conn.close()

            sqls = [DASH_SQL.format(host=f"h{i % 4}", lo=0, hi=120_000)
                    for i in range(8)]
            sqls += [sqls[0], sqls[1]] * 2  # identical duplicates too
            serial = {sql: fetch(sql, "warm") for sql in set(sqls)}
            for body in serial.values():
                assert json.loads(body)["output"]  # real rows came back
            got = run_threads(
                [lambda s=s, i=i: fetch(s, f"tenant{i % 3}")
                 for i, s in enumerate(sqls)])
            for sql, body in zip(sqls, got):
                assert body == serial[sql], sql
        finally:
            srv.stop()
        engine.close()

    def test_env_kill_switch_disables_batching(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv("GTPU_QUERY_BATCHING", "0")
        plane = ConcurrencyPlane()
        assert not plane.batcher.enabled
        monkeypatch.setenv("GTPU_CONCURRENCY", "0")
        plane = ConcurrencyPlane()
        assert not plane.admission.enabled
        assert not plane.plan_cache.enabled
