"""Function library parity (reference src/common/function): scalar math,
date functions, system functions, and order-statistic aggregates
(argmax/argmin/median/percentile/polyval)."""

import math

import numpy as np
import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.query.engine import QueryContext, QueryEngine
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE cpu (host STRING, usage DOUBLE, ts TIMESTAMP(3) TIME INDEX, "
        "PRIMARY KEY(host))"
    )
    q.execute_one(
        "INSERT INTO cpu (host, usage, ts) VALUES "
        "('a', 1.0, 1000), ('a', 3.0, 2000), ('a', 2.0, 3000), "
        "('b', 10.0, 1000), ('b', 30.0, 2000), ('b', 20.0, 3000)"
    )
    yield q
    engine.close()


def one(qe, sql):
    return qe.execute_one(sql).rows()[0][0]


class TestScalarFunctions:
    def test_math_literals(self, qe):
        assert one(qe, "SELECT abs(-3)") == 3
        assert one(qe, "SELECT mod(7, 3)") == pytest.approx(1.0)
        assert one(qe, "SELECT atan2(1, 1)") == pytest.approx(math.pi / 4)
        assert one(qe, "SELECT degrees(3.141592653589793)") == pytest.approx(180.0)
        assert one(qe, "SELECT radians(180)") == pytest.approx(math.pi)
        assert one(qe, "SELECT sinh(0)") == pytest.approx(0.0)
        assert one(qe, "SELECT greatest(1, 5, 3)") == 5
        assert one(qe, "SELECT least(4, 2, 9)") == 2

    def test_math_on_columns(self, qe):
        rows = qe.execute_one(
            "SELECT host, mod(usage, 3) AS m FROM cpu WHERE ts = 2000 "
            "ORDER BY host").rows()
        assert rows == [["a", 0.0], ["b", 0.0]]
        rows = qe.execute_one(
            "SELECT greatest(usage, 15.0) AS g FROM cpu WHERE host = 'b' "
            "ORDER BY ts").rows()
        assert [r[0] for r in rows] == [15.0, 30.0, 20.0]

    def test_coalesce_strings(self, qe):
        """coalesce over string/tag columns merges on `is None` instead of
        raising via float/NaN coercion (ADVICE r1)."""
        rows = qe.execute_one(
            "SELECT coalesce(host, 'missing') AS h FROM cpu "
            "WHERE ts = 1000 ORDER BY h").rows()
        assert [r[0] for r in rows] == ["a", "b"]
        assert one(qe, "SELECT coalesce(usage, 0.0) FROM cpu "
                       "WHERE host='a' AND ts=1000") == 1.0

    def test_date_format(self, qe):
        r = one(qe, "SELECT date_format(ts, '%Y-%m-%d %H:%M:%S') "
                    "FROM cpu WHERE host = 'a' AND ts = 1000")
        assert r == "1970-01-01 00:00:01"

    def test_system_functions(self, qe):
        assert "greptimedb-tpu" in one(qe, "SELECT version()")
        assert "jax" in one(qe, "SELECT build()")
        assert one(qe, "SELECT timezone()") == "UTC"
        assert one(qe, "SELECT database()") == "public"
        ctx = QueryContext(db="other")
        qe.execute_one("CREATE DATABASE other")
        assert qe.execute_one("SELECT database()", ctx).rows()[0][0] == "other"


class TestOrderStatAggs:
    def test_median(self, qe):
        rows = qe.execute_one(
            "SELECT host, median(usage) FROM cpu GROUP BY host "
            "ORDER BY host").rows()
        assert rows == [["a", 2.0], ["b", 20.0]]

    def test_percentile(self, qe):
        rows = qe.execute_one(
            "SELECT host, percentile(usage, 50) FROM cpu GROUP BY host "
            "ORDER BY host").rows()
        assert rows == [["a", 2.0], ["b", 20.0]]
        # p0 / p100 = min / max
        assert one(qe, "SELECT percentile(usage, 0) FROM cpu") == 1.0
        assert one(qe, "SELECT percentile(usage, 100) FROM cpu") == 30.0
        # interpolation between order statistics
        r = one(qe, "SELECT percentile(usage, 90) FROM cpu")
        assert r == pytest.approx(np.percentile(
            [1.0, 3.0, 2.0, 10.0, 30.0, 20.0], 90))

    def test_argmax_argmin(self, qe):
        # argmax/argmin return the row position of the extreme within the scan
        r = qe.execute_one(
            "SELECT host, argmax(usage) AS am FROM cpu GROUP BY host "
            "ORDER BY host")
        am = dict(r.rows())
        # verify the indices point at the right rows
        raw = qe.execute_one("SELECT host, usage FROM cpu").rows()
        assert raw[int(am["a"])] == ["a", 3.0]
        assert raw[int(am["b"])] == ["b", 30.0]
        r2 = qe.execute_one("SELECT argmin(usage) FROM cpu")
        assert raw[int(r2.rows()[0][0])] == ["a", 1.0]

    def test_polyval(self, qe):
        qe.execute_one("CREATE TABLE coef (k STRING, c DOUBLE, "
                       "ts TIMESTAMP(3) TIME INDEX, PRIMARY KEY(k))")
        # coefficients 2, 3, 5 (highest degree first): 2x^2 + 3x + 5 at x=2 = 19
        qe.execute_one("INSERT INTO coef (k, c, ts) VALUES "
                       "('p', 2, 1), ('p', 3, 2), ('p', 5, 3)")
        assert one(qe, "SELECT polyval(c, 2) FROM coef") == pytest.approx(19.0)

    def test_mixed_device_and_host_aggs(self, qe):
        rows = qe.execute_one(
            "SELECT host, avg(usage), median(usage), max(usage) FROM cpu "
            "GROUP BY host ORDER BY host").rows()
        assert rows == [["a", 2.0, 2.0, 3.0], ["b", 20.0, 20.0, 30.0]]

    def test_host_agg_with_where(self, qe):
        rows = qe.execute_one(
            "SELECT host, median(usage) FROM cpu WHERE usage > 1.5 "
            "GROUP BY host ORDER BY host").rows()
        assert rows == [["a", 2.5], ["b", 20.0]]

    def test_host_agg_time_bucket(self, qe):
        rows = qe.execute_one(
            "SELECT date_bin('1s', ts) AS b, median(usage) FROM cpu "
            "GROUP BY b ORDER BY b").rows()
        assert rows == [[1000, 5.5], [2000, 16.5], [3000, 11.0]]

    def test_host_agg_with_ts_string_predicate(self, qe):
        rows = qe.execute_one(
            "SELECT host, median(usage) FROM cpu "
            "WHERE ts >= '1970-01-01 00:00:02' GROUP BY host "
            "ORDER BY host").rows()
        assert rows == [["a", 2.5], ["b", 25.0]]

    def test_host_agg_with_tag_predicate(self, qe):
        rows = qe.execute_one(
            "SELECT median(usage) FROM cpu WHERE host = 'b'").rows()
        assert rows == [[20.0]]

    def test_approx_percentile_cont_fraction(self, qe):
        r = qe.execute_one(
            "SELECT approx_percentile_cont(usage, 0.5) FROM cpu "
            "WHERE host = 'a'").rows()
        assert r == [[2.0]]
        from greptimedb_tpu.query.expr import PlanError
        with pytest.raises(PlanError):
            qe.execute_one("SELECT approx_percentile_cont(usage, 95) FROM cpu")

    def test_database_in_table_query(self, qe):
        qe.execute_one("CREATE DATABASE otherdb")
        ctx = QueryContext(db="otherdb")
        qe.execute_one(
            "CREATE TABLE t (host STRING, v DOUBLE, ts TIMESTAMP(3) TIME INDEX, "
            "PRIMARY KEY(host))", ctx)
        qe.execute_one("INSERT INTO t (host, v, ts) VALUES ('x', 1, 1000)", ctx)
        rows = qe.execute_one(
            "SELECT database(), count(*) FROM t", ctx).rows()
        assert rows == [["otherdb", 1]]

    def test_percentile_non_numeric_param(self, qe):
        from greptimedb_tpu.query.expr import PlanError

        with pytest.raises(PlanError):
            qe.execute_one("SELECT percentile(usage, 'abc') FROM cpu")

    def test_percentile_validation(self, qe):
        from greptimedb_tpu.query.expr import PlanError

        with pytest.raises(PlanError):
            qe.execute_one("SELECT percentile(usage, 150) FROM cpu")
        with pytest.raises(PlanError):
            qe.execute_one("SELECT percentile(usage) FROM cpu")
