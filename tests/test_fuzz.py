"""Grammar-based fuzzing of DDL/DML/query paths against a shadow oracle
(reference tests-fuzz/: fuzz_create_table / fuzz_alter_table / fuzz_insert
targets + the crash-restart `unstable` target,
targets/unstable/fuzz_create_table_standalone.rs).

Every generated statement is schema-valid by construction, so any engine
error is a bug. SELECT results diff against an independently-maintained
row model (LWW dedup replicated in plain python). A subprocess target
os._exit()s mid-workload, then the data dir is reopened and must recover
to a queryable state with exactly the rows the WAL accepted."""

from __future__ import annotations

import math
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from fuzz_gen import Generator, TableModel

N_SEEDS = int(os.environ.get("FUZZ_SEEDS", "6"))
OPS_PER_SEED = int(os.environ.get("FUZZ_OPS", "40"))


def make_db(tmp_path, persistent_catalog=False):
    from greptimedb_tpu.catalog import Catalog, FileKv, MemoryKv
    from greptimedb_tpu.query import QueryEngine
    from greptimedb_tpu.storage import RegionEngine
    from greptimedb_tpu.storage.engine import EngineConfig

    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "d")))
    kv = FileKv(str(tmp_path / "catalog.json")) if persistent_catalog \
        else MemoryKv()
    return engine, QueryEngine(Catalog(kv), engine)


class Oracle:
    """Shadow row store with the engine's visible semantics: LWW dedup on
    (tags, ts) unless append_mode; NULL coercion per type (float->NaN,
    int->0, bool->False, tag/string->None)."""

    def __init__(self, model: TableModel):
        self.model = model
        self.rows: dict = {}  # key -> row dict (non-append)
        self.all_rows: list = []  # append mode

    def insert(self, rows: list[dict]):
        m = self.model
        for r in rows:
            coerced = dict(r)
            for c in m.cols:
                v = coerced[c.name]
                if v is None and c.semantic == "field":
                    if c.sql_type in ("DOUBLE", "FLOAT"):
                        coerced[c.name] = math.nan
                    elif c.sql_type == "BOOLEAN":
                        coerced[c.name] = False
                    else:
                        coerced[c.name] = 0
                elif c.sql_type == "FLOAT" and v is not None:
                    # the engine stores FLOAT as float32 — mirror the
                    # rounding or the oracle drifts past agg tolerance
                    coerced[c.name] = float(np.float32(v))
            if m.append_mode:
                self.all_rows.append(coerced)
            else:
                key = tuple(coerced[c.name] for c in m.tags) \
                    + (coerced[m.ts_col.name],)
                self.rows[key] = coerced
        # columns added by ALTER after earlier inserts: backfill with the
        # engine's NULL coercion
        names = {c.name for c in m.cols}
        for store in (self.rows.values(), self.all_rows):
            for row in store:
                for c in m.cols:
                    if c.name not in row:
                        row[c.name] = (math.nan
                                       if c.sql_type in ("DOUBLE", "FLOAT")
                                       else (False if c.sql_type == "BOOLEAN"
                                             else 0))
                for extra in set(row) - names:
                    del row[extra]

    def visible(self) -> list[dict]:
        return self.all_rows if self.model.append_mode \
            else list(self.rows.values())

    # -- expected answers ----------------------------------------------------

    def count(self) -> int:
        return len(self.visible())

    def agg(self, fname: str, tag, agg: str) -> dict:
        """{tag_value (or ()): expected} with SQL null semantics for
        float NaN (ignored by aggs; count skips them)."""
        groups: dict = {}
        if tag is None:
            # ungrouped aggregate: exactly one output row even over zero
            # input rows (count -> 0, others -> NULL)
            groups[()] = []
        for r in self.visible():
            k = r[tag.name] if tag is not None else ()
            groups.setdefault(k, []).append(r[fname])
        out = {}
        for k, vals in groups.items():
            clean = [v for v in vals
                     if not (isinstance(v, float) and math.isnan(v))]
            if agg == "count":
                out[k] = len(clean)
            elif not clean:
                out[k] = None
            elif agg == "sum":
                out[k] = float(sum(clean))
            elif agg == "min":
                out[k] = float(min(clean))
            elif agg == "max":
                out[k] = float(max(clean))
            else:
                out[k] = float(sum(clean)) / len(clean)
        return out

    def filter_count(self, tag, value) -> int:
        return sum(1 for r in self.visible() if r[tag.name] == value)


def check_agg(qe, oracle: Oracle, sql, fname, tag, agg):
    r = qe.execute_one(sql)
    expect = oracle.agg(fname.name, tag, agg)
    if tag is None:
        got = {(): r.rows()[0][0] if r.num_rows else None}
    else:
        got = {}
        for row in r.rows():
            got[row[0]] = row[1]
    assert set(got) == set(expect), \
        f"group keys differ for {sql}: {set(got) ^ set(expect)}"
    for k, ev in expect.items():
        gv = got[k]
        if ev is None:
            assert gv is None or (isinstance(gv, float) and math.isnan(gv)), \
                f"{sql} group {k}: expected NULL, got {gv}"
        else:
            assert gv is not None, f"{sql} group {k}: got NULL, want {ev}"
            np.testing.assert_allclose(float(gv), ev, rtol=1e-6, atol=1e-9,
                                       err_msg=f"{sql} group {k}")


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_fuzz_session(tmp_path, seed):
    """One randomized session: create tables, interleave inserts / alters
    / flush / queries, diff every query against the oracle."""
    engine, qe = make_db(tmp_path)
    g = Generator(seed)
    tables: list[tuple[TableModel, Oracle]] = []
    try:
        for _ in range(g.rng.randint(1, 3)):
            model, sql = g.gen_create_table()
            qe.execute_one(sql)
            tables.append((model, Oracle(model)))
        for _ in range(OPS_PER_SEED):
            model, oracle = g.rng.choice(tables)
            op = g.rng.random()
            if op < 0.45:
                sql, rows = g.gen_insert(model)
                qe.execute_one(sql)
                oracle.insert(rows)
            elif op < 0.55:
                qe.execute_one(f"ADMIN flush_table('{model.name}')")
            elif op < 0.62 and not model.append_mode:
                qe.execute_one(g.gen_add_column(model))
                oracle.insert([])  # trigger backfill of the new column
            elif op < 0.75:
                assert qe.execute_one(
                    g.gen_count_query(model)).rows()[0][0] == oracle.count()
            elif op < 0.9:
                q = g.gen_agg_query(model)
                if q is not None:
                    check_agg(qe, oracle, *q)
            else:
                q = g.gen_filter_query(model)
                if q is not None:
                    sql, tag, v = q
                    assert qe.execute_one(sql).rows()[0][0] == \
                        oracle.filter_count(tag, v), sql
        # final full sweep over every table
        for model, oracle in tables:
            assert qe.execute_one(
                g.gen_count_query(model)).rows()[0][0] == oracle.count()
            q = g.gen_agg_query(model)
            if q is not None:
                check_agg(qe, oracle, *q)
    finally:
        engine.close()


def test_all_null_tag_column(tmp_path):
    """Fuzz-found: a batch (and then an SST) whose tag dictionary is empty
    crashed dictionary remapping in memtable.write and _decode_sst."""
    engine, qe = make_db(tmp_path)
    try:
        qe.execute_one(
            "CREATE TABLE t (host STRING, ts TIMESTAMP(3) NOT NULL, "
            "v DOUBLE, TIME INDEX (ts), PRIMARY KEY (host))")
        qe.execute_one(
            "INSERT INTO t VALUES (NULL, 1000, 1.0), (NULL, 2000, 2.0)")
        assert qe.execute_one("SELECT count(*) FROM t").rows()[0][0] == 2
        qe.execute_one("ADMIN flush_table('t')")
        r = qe.execute_one("SELECT host, v FROM t ORDER BY ts")
        assert r.rows() == [[None, 1.0], [None, 2.0]]
        # LWW on the all-NULL key still applies after flush
        qe.execute_one("INSERT INTO t VALUES (NULL, 1000, 9.0)")
        r = qe.execute_one("SELECT v FROM t ORDER BY ts")
        assert r.rows() == [[9.0], [2.0]]
    finally:
        engine.close()


_CRASH_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, {testdir!r})
    import jax; jax.config.update("jax_platforms", "cpu")
    from test_fuzz import make_db
    from fuzz_gen import Generator
    from pathlib import Path

    g = Generator({seed})
    engine, qe = make_db(Path({home!r}), persistent_catalog=True)
    model, sql = g.gen_create_table()
    qe.execute_one(sql)
    with open({home!r} + "/model.txt", "w") as f:
        f.write(model.name)
    accepted = 0
    for i in range({n_batches}):
        ins, rows = g.gen_insert(model, max_rows=50)
        qe.execute_one(ins)
        accepted += len(rows)
        with open({home!r} + "/accepted.txt", "w") as f:
            f.write(str(accepted))
        if i == {flush_at}:
            qe.execute_one("ADMIN flush_table('" + model.name + "')")
    os._exit(9)  # crash: no close(), no flush, WAL tail possibly torn
""")


@pytest.mark.parametrize("seed", [101, 202])
def test_fuzz_crash_restart(tmp_path, seed):
    """Kill the process mid-workload; reopen the dir; every row the WAL
    accepted must be queryable (reference unstable fuzz target +
    region/opener.rs replay)."""
    testdir = os.path.dirname(os.path.abspath(__file__))
    child = _CRASH_CHILD.format(
        repo=os.path.dirname(testdir), testdir=testdir,
        seed=seed, home=str(tmp_path), n_batches=12, flush_at=5)
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode == 9, proc.stderr[-2000:]
    accepted = int((tmp_path / "accepted.txt").read_text())
    table = (tmp_path / "model.txt").read_text()
    assert accepted > 0

    # reopen in-process over the same dir: FileKv catalog + WAL + manifest
    # recovery (the standalone restart path)
    engine, qe = make_db(tmp_path, persistent_catalog=True)
    try:
        got = qe.execute_one(f"SELECT count(*) FROM {table}").rows()[0][0]
        # count can be < accepted only through LWW dedup of duplicate
        # (tags, ts) keys — ts strictly increases per generator, so keys
        # are unique and every accepted row must survive the crash
        assert got == accepted, f"recovered {got} of {accepted} rows"
    finally:
        engine.close()
