"""HTTP protocol tests over a real socket (mirrors the reference's
tests-integration protocol suites, SURVEY.md §4)."""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.catalog import Catalog, MemoryKv
from greptimedb_tpu.query import QueryEngine
from greptimedb_tpu.servers import HttpServer
from greptimedb_tpu.storage import RegionEngine
from greptimedb_tpu.storage.engine import EngineConfig


@pytest.fixture
def server(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path / "data")))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    srv = HttpServer(qe, port=0)  # ephemeral port
    port = srv.start()
    yield f"http://127.0.0.1:{port}"
    srv.stop()
    engine.close()


def get(url, **params):
    q = urllib.parse.urlencode(params)
    try:
        with urllib.request.urlopen(f"{url}?{q}") as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def post(url, body: bytes, content_type="application/octet-stream", **params):
    q = urllib.parse.urlencode(params)
    req = urllib.request.Request(f"{url}?{q}", data=body, method="POST",
                                 headers={"Content-Type": content_type})
    with urllib.request.urlopen(req) as resp:
        data = resp.read()
        return resp.status, json.loads(data) if data else {}


class TestSqlApi:
    def test_ddl_insert_query(self, server):
        status, out = get(f"{server}/v1/sql", sql=(
            "CREATE TABLE cpu (host STRING, ts TIMESTAMP(3) NOT NULL, "
            "val DOUBLE, TIME INDEX (ts), PRIMARY KEY (host))"
        ))
        assert status == 200 and out["code"] == 0
        status, out = get(f"{server}/v1/sql", sql=(
            "INSERT INTO cpu (host, ts, val) VALUES ('a', 1000, 1.5), ('b', 2000, 2.5)"
        ))
        assert out["output"][0]["affectedrows"] == 2
        status, out = get(f"{server}/v1/sql",
                          sql="SELECT host, val FROM cpu ORDER BY host")
        records = out["output"][0]["records"]
        assert [c["name"] for c in records["schema"]["column_schemas"]] == ["host", "val"]
        assert records["rows"] == [["a", 1.5], ["b", 2.5]]
        assert records["total_rows"] == 2

    def test_sql_error_shape(self, server):
        status, out = get(f"{server}/v1/sql", sql="SELECT FROM nope")
        assert status == 400
        assert "error" in out

    def test_multi_statement(self, server):
        status, out = get(f"{server}/v1/sql", sql=(
            "CREATE TABLE t (ts TIMESTAMP(3) NOT NULL, v DOUBLE, TIME INDEX (ts)); "
            "INSERT INTO t (ts, v) VALUES (1, 2.0); SELECT count(*) FROM t"
        ))
        assert out["code"] == 0
        assert len(out["output"]) == 3
        assert out["output"][2]["records"]["rows"] == [[1]]


class TestInfluxWrite:
    def test_write_and_query(self, server):
        lines = (b"weather,location=us-midwest temperature=82 1465839830100400200\n"
                 b"weather,location=us-east temperature=75,humidity=30i 1465839830100400200")
        status, _ = post(f"{server}/v1/influxdb/write", lines, "text/plain")
        assert status == 204
        _, out = get(f"{server}/v1/sql", sql=(
            "SELECT location, temperature, humidity FROM weather ORDER BY location"
        ))
        rows = out["output"][0]["records"]["rows"]
        assert rows == [["us-east", 75.0, 30.0], ["us-midwest", 82.0, None]]

    def test_auto_alter_new_field(self, server):
        post(f"{server}/v1/influxdb/write", b"m1,h=a f1=1.0 1000000000", "text/plain")
        post(f"{server}/v1/influxdb/write", b"m1,h=a f1=2.0,f2=9.0 2000000000", "text/plain")
        _, out = get(f"{server}/v1/sql", sql="SELECT f1, f2 FROM m1 ORDER BY ts")
        rows = out["output"][0]["records"]["rows"]
        assert rows == [[1.0, None], [2.0, 9.0]]

    def test_precision_param(self, server):
        post(f"{server}/v1/influxdb/write", b"m2 v=1.0 1465839830100", "text/plain",
             precision="ms")
        _, out = get(f"{server}/v1/sql", sql="SELECT ts FROM m2")
        assert out["output"][0]["records"]["rows"] == [[1465839830100]]


class TestOpentsdb:
    def test_put(self, server):
        body = json.dumps([
            {"metric": "sys.cpu", "timestamp": 1465839830, "value": 18.3,
             "tags": {"host": "web01"}},
            {"metric": "sys.cpu", "timestamp": 1465839890, "value": 18.9,
             "tags": {"host": "web01"}},
        ]).encode()
        status, out = post(f"{server}/v1/opentsdb/api/put", body, "application/json")
        assert status == 200 and out["success"] == 2
        _, out = get(f"{server}/v1/sql",
                     sql='SELECT greptime_value FROM "sys.cpu" ORDER BY ts')
        assert out["output"][0]["records"]["rows"] == [[18.3], [18.9]]


class TestPrometheusApi:
    @pytest.fixture
    def seeded(self, server):
        get(f"{server}/v1/sql", sql=(
            "CREATE TABLE http_requests (host STRING, ts TIMESTAMP(3) NOT NULL, "
            "val DOUBLE, TIME INDEX (ts), PRIMARY KEY (host)) "
            "WITH (append_mode = 'true')"
        ))
        rows = []
        for hi, h in enumerate(("a", "b")):
            for i in range(41):
                rows.append(f"('{h}', {(1000000 + i * 15) * 1000}, {(hi + 1) * 2.0 * i * 15})")
        get(f"{server}/v1/sql", sql=(
            "INSERT INTO http_requests (host, ts, val) VALUES " + ", ".join(rows)
        ))
        return server

    def test_query_range(self, seeded):
        status, out = get(f"{seeded}/v1/prometheus/api/v1/query_range",
                          query="rate(http_requests[2m])",
                          start=1000300, end=1000420, step=60)
        assert out["status"] == "success"
        data = out["data"]
        assert data["resultType"] == "matrix"
        by_host = {r["metric"]["host"]: r["values"] for r in data["result"]}
        assert len(by_host["a"]) == 3
        np.testing.assert_allclose(float(by_host["a"][0][1]), 2.0, rtol=1e-9)
        np.testing.assert_allclose(float(by_host["b"][0][1]), 4.0, rtol=1e-9)

    def test_instant_query(self, seeded):
        status, out = get(f"{seeded}/v1/prometheus/api/v1/query",
                          query="http_requests", time=1000300)
        data = out["data"]
        assert data["resultType"] == "vector"
        vals = {r["metric"]["host"]: float(r["value"][1]) for r in data["result"]}
        assert vals == {"a": 600.0, "b": 1200.0}
        assert data["result"][0]["metric"]["__name__"] == "http_requests"

    def test_labels_and_values(self, seeded):
        _, out = get(f"{seeded}/v1/prometheus/api/v1/labels")
        assert "host" in out["data"] and "__name__" in out["data"]
        _, out = get(f"{seeded}/v1/prometheus/api/v1/label/host/values")
        assert out["data"] == ["a", "b"]
        _, out = get(f"{seeded}/v1/prometheus/api/v1/label/__name__/values")
        assert "http_requests" in out["data"]

    def test_series(self, seeded):
        url = f"{seeded}/v1/prometheus/api/v1/series"
        q = urllib.parse.urlencode({"match[]": "http_requests", "start": 1000000,
                                    "end": 1001000})
        with urllib.request.urlopen(f"{url}?{q}") as resp:
            out = json.loads(resp.read())
        hosts = sorted(m["host"] for m in out["data"])
        assert hosts == ["a", "b"]

    def test_bad_query_is_400(self, seeded):
        status, out = get(f"{seeded}/v1/prometheus/api/v1/query_range",
                          query="rate(", start=0, end=10, step=1)
        assert status == 400


class TestOps:
    def test_health_and_metrics(self, server):
        status, _ = get(f"{server}/health")
        assert status == 200
        get(f"{server}/v1/sql", sql="SELECT 1")
        with urllib.request.urlopen(f"{server}/metrics") as resp:
            text = resp.read().decode()
        assert "greptimedb_tpu_http_requests_total" in text
        assert "greptimedb_tpu_query_duration_seconds" in text


class TestPromRemoteEndpoints:
    def test_remote_write_then_read(self, server):
        from tests.test_prom_store import (
            make_read_request,
            make_write_request,
            parse_read_response,
        )

        body = make_write_request([
            ({"__name__": "up", "job": "api"}, [(1.0, 1000), (0.0, 2000)]),
        ])
        status, _ = post(server + "/v1/prometheus/write", body)
        assert status == 204
        # query back over HTTP SQL
        status, out = get(server + "/v1/sql", sql="SELECT count(*) FROM up")
        assert status == 200
        # remote read
        req = make_read_request(0, 10_000, [(0, "__name__", "up")])
        import urllib.request as _ur

        r = _ur.Request(server + "/v1/prometheus/read", data=req, method="POST")
        with _ur.urlopen(r) as resp:
            assert resp.status == 200
            results = parse_read_response(resp.read())
        assert results[0][0][1] == [(1.0, 1000), (0.0, 2000)]

    def test_otlp_metrics_endpoint(self, server):
        from tests.test_prom_store import TestOtlp

        body = TestOtlp()._otlp_body()
        status, out = post(server + "/v1/otlp/v1/metrics", body,
                           content_type="application/x-protobuf")
        assert status == 200
        status, out = get(server + "/v1/sql", sql="SELECT host, greptime_value FROM my_gauge")
        assert status == 200
        rows = out["output"][0]["records"]["rows"]
        assert rows == [["h1", 42.0]]
