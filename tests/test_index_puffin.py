"""Puffin container + FST-analog inverted index (reference src/puffin +
src/index/src/inverted_index: format.rs:28, search/index_apply.rs:26-58)."""

import io

import numpy as np
import pytest

from greptimedb_tpu.objectstore import MemoryStore
from greptimedb_tpu.storage.index import (
    IndexApplier,
    InSet,
    InvertedIndexWriter,
    Range,
    Regex,
    deserialize_predicates,
    extract_tag_predicates,
    normalize_predicates,
    predicates_cache_key,
    serialize_predicates,
)
from greptimedb_tpu.storage.puffin import PuffinReader, PuffinWriter


# ---- container -------------------------------------------------------------


def test_puffin_roundtrip():
    w = PuffinWriter({"num_rows": 42})
    w.add_blob("type-a", b"hello", {"column": "host"})
    w.add_blob("type-b", b"\x00\x01\x02" * 100, {"column": "dc"})
    data = w.finish()

    r = PuffinReader(io.BytesIO(data))
    assert r.properties == {"num_rows": 42}
    assert [b.type for b in r.blobs] == ["type-a", "type-b"]
    assert r.read_blob(r.blobs[0]) == b"hello"
    assert r.read_blob(r.blobs[1]) == b"\x00\x01\x02" * 100
    assert r.blobs_of_type("type-b")[0].properties == {"column": "dc"}


def test_puffin_rejects_garbage():
    from greptimedb_tpu.storage.puffin import PuffinError

    with pytest.raises(PuffinError):
        PuffinReader(io.BytesIO(b"not a puffin file at all"))


# ---- index build + applier -------------------------------------------------


def make_index(store, codes, values, segment_rows=4, row_group_size=8,
               tag="host"):
    n = len(codes)
    w = InvertedIndexWriter("idx", store, segment_rows=segment_rows)
    w.write("f1", {tag: np.asarray(codes, dtype=np.int32)},
            {tag: np.asarray(values, dtype=object)}, row_group_size, n)
    return IndexApplier("idx", store)


def test_eq_pruning_segments_to_row_groups():
    store = MemoryStore()
    # 16 rows, segment_rows=4 -> 4 segments; row_group_size=8 -> 2 groups.
    # 'a' only in rows 0-3 (segment 0 -> group 0)
    codes = [0] * 4 + [1] * 12
    ap = make_index(store, codes, ["a", "b"])
    assert ap.apply("f1", {"host": {"a"}}) == [0]
    # 'b' misses segment 0 but both row groups still overlap a hit
    assert ap.apply("f1", {"host": {"b"}}) in (None, [0, 1])
    assert ap.apply("f1", {"host": {"zz"}}) == []
    # un-indexed tag: no pruning
    assert ap.apply("f1", {"other": {"x"}}) is None
    # file without an index: no pruning
    assert ap.apply("nope", {"host": {"a"}}) is None


def test_in_and_multi_tag_intersection():
    store = MemoryStore()
    n = 16
    host = np.asarray([0, 1, 2, 3] * 4, dtype=np.int32)  # every segment
    dc = np.asarray([0] * 8 + [1] * 8, dtype=np.int32)   # half each
    w = InvertedIndexWriter("idx", store, segment_rows=4)
    w.write("f1",
            {"host": host, "dc": dc},
            {"host": np.asarray(["h0", "h1", "h2", "h3"], dtype=object),
             "dc": np.asarray(["east", "west"], dtype=object)},
            8, n)
    ap = IndexApplier("idx", store)
    assert ap.apply("f1", {"dc": {"west"}}) == [1]
    assert ap.apply("f1", {"host": {"h1"}, "dc": {"east"}}) == [0]
    assert ap.apply("f1", {"host": {"h1", "h2"}, "dc": {"bogus"}}) == []


def test_range_predicate():
    store = MemoryStore()
    # terms sort as a < b < c < d; one value per segment
    ap = make_index(store, [0] * 4 + [1] * 4 + [2] * 4 + [3] * 4,
                    ["a", "b", "c", "d"], row_group_size=4)
    assert ap.apply("f1", {"host": (Range("b", "c"),)}) == [1, 2]
    assert ap.apply("f1", {"host": (Range("b", "c", lo_inc=False),)}) == [2]
    assert ap.apply("f1", {"host": (Range(None, "a"),)}) == [0]
    assert ap.apply("f1", {"host": (Range("e", None),)}) == []
    assert ap.apply("f1", {"host": (Range("a", "z"),)}) is None


def test_regex_predicate_and_null_semantics():
    store = MemoryStore()
    # code -1 = NULL rows in the last segment
    codes = [0] * 4 + [1] * 4 + [2] * 4 + [-1] * 4
    ap = make_index(store, codes, ["web-1", "web-2", "db-1"],
                    row_group_size=4)
    assert ap.apply("f1", {"host": (Regex("web-.*"),)}) == [0, 1]
    assert ap.apply("f1", {"host": (Regex("db-\\d"),)}) == [2]
    # a pattern matching the empty string must keep NULL segments
    # (PromQL: absent label == "")
    assert ap.apply("f1", {"host": (Regex("(web-1)?"),)}) == [0, 3]
    # eq "" keeps NULL segments too
    assert ap.apply("f1", {"host": {""}}) == [3]
    # invalid regex: never prune
    assert ap.apply("f1", {"host": (Regex("("),)}) is None


def test_pruning_never_drops_matching_rows_randomized():
    rng = np.random.default_rng(0)
    store = MemoryStore()
    values = np.asarray([f"v{i}" for i in range(17)], dtype=object)
    n = 1000
    codes = rng.integers(-1, 17, n).astype(np.int32)
    seg_rows, rg_rows = 32, 128
    w = InvertedIndexWriter("idx", store, segment_rows=seg_rows)
    w.write("f1", {"host": codes}, {"host": values}, rg_rows, n)
    ap = IndexApplier("idx", store)
    for pred, match in [
        ({"host": {"v3", "v11"}},
         lambda c: (c == 3) | (c == 11)),
        ({"host": (Range("v10", "v16"),)},  # string order: v10..v15,v16
         lambda c: np.isin(c, [i for i in range(17)
                               if "v10" <= f"v{i}" <= "v16"])),
        ({"host": (Regex("v1[0-3]"),)},
         lambda c: np.isin(c, [10, 11, 12, 13])),
    ]:
        groups = ap.apply("f1", pred)
        if groups is None:
            continue
        kept = np.zeros(n, dtype=bool)
        for g in groups:
            kept[g * rg_rows:(g + 1) * rg_rows] = True
        rows_matching = match(codes)
        assert not (rows_matching & ~kept).any(), pred


# ---- predicate plumbing ----------------------------------------------------


def test_serialize_roundtrip():
    preds = {
        "host": {"a", "b"},
        "dc": (Range("x", None, lo_inc=False), Regex("e.*")),
    }
    wire = serialize_predicates(preds)
    back = deserialize_predicates(wire)
    assert normalize_predicates(back) == normalize_predicates(preds)
    assert predicates_cache_key(back) == predicates_cache_key(preds)
    # legacy wire form (bare value lists)
    legacy = deserialize_predicates({"host": ["b", "a"]})
    assert normalize_predicates(legacy) == {"host": (InSet.of(["a", "b"]),)}


def test_extract_tag_predicates_rich():
    from greptimedb_tpu.datatypes import (
        ColumnSchema,
        DataType,
        Schema,
        SemanticType,
    )
    from greptimedb_tpu.sql import parse_sql

    schema = Schema([
        ColumnSchema("host", DataType.STRING, SemanticType.TAG),
        ColumnSchema("dc", DataType.STRING, SemanticType.TAG),
        ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND,
                     SemanticType.TIMESTAMP),
        ColumnSchema("v", DataType.FLOAT64),
    ])
    stmt = parse_sql(
        "SELECT v FROM t WHERE host = 'a' AND dc IN ('e','w') "
        "AND host >= 'a' AND host < 'm' AND dc LIKE 'e%' "
        "AND dc BETWEEN 'd' AND 'f' AND v > 3"
    )[0]
    preds = extract_tag_predicates(stmt.where, schema)
    assert InSet.of(["a"]) in preds["host"]
    assert Range("a", None, lo_inc=True) in preds["host"]
    assert Range(None, "m", hi_inc=False) in preds["host"]
    assert InSet.of(["e", "w"]) in preds["dc"]
    # LIKE lowers to a (?is) regex: the query-side filter is
    # case-insensitive, so pruning must be too
    assert Regex("(?is)e.*") in preds["dc"]
    assert Range("d", "f") in preds["dc"]
    assert "v" not in preds
    assert "ts" not in preds


def test_like_pruning_is_case_insensitive(tmp_path):
    """LIKE 'A%' must not prune files holding 'apple' — the query filter
    matches case-insensitively (code-review regression)."""
    from greptimedb_tpu.catalog import Catalog, MemoryKv
    from greptimedb_tpu.query import QueryEngine
    from greptimedb_tpu.storage import RegionEngine
    from greptimedb_tpu.storage.engine import EngineConfig

    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    qe.execute_one(
        "CREATE TABLE t (host STRING, ts TIMESTAMP(3) NOT NULL, v DOUBLE,"
        " TIME INDEX (ts), PRIMARY KEY (host))")
    qe.execute_one(
        "INSERT INTO t VALUES ('apple', 1, 1.0), ('banana', 2, 2.0)")
    engine.flush(qe.catalog.table("public", "t").region_ids[0])
    r = qe.execute_one("SELECT host FROM t WHERE host LIKE 'A%'")
    assert list(r.column("host")) == ["apple"]
    engine.close()


def test_scan_stream_close_releases_pins(tmp_path):
    """An abandoned (never-iterated) stream must not leak file pins
    (code-review regression)."""
    import numpy as np

    from greptimedb_tpu.datatypes import (
        ColumnSchema,
        DataType,
        DictVector,
        RecordBatch,
        Schema,
        SemanticType,
    )
    from greptimedb_tpu.storage import RegionEngine
    from greptimedb_tpu.storage.engine import EngineConfig

    schema = Schema([
        ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND,
                     SemanticType.TIMESTAMP),
        ColumnSchema("host", DataType.STRING, SemanticType.TAG),
        ColumnSchema("v", DataType.FLOAT64),
    ])
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    engine.create_region(1, schema)
    engine.put(1, RecordBatch(schema, {
        "ts": np.arange(100, dtype=np.int64),
        "host": DictVector.encode(["h"] * 100),
        "v": np.ones(100),
    }))
    engine.flush(1)
    region = engine.region(1)

    stream = region.scan_stream()
    assert any(region._file_refs.values()) if region._file_refs else False
    stream.close()
    assert not any(region._file_refs.values())
    stream.close()  # idempotent

    # fully-consumed streams unpin via the generator's finally
    stream = region.scan_stream()
    total = sum(n for _, n in stream.chunks())
    assert total == 100
    assert not any(region._file_refs.values())
    stream.close()
    engine.close()


def test_sql_e2e_pruning_correctness(tmp_path):
    """End-to-end: rich predicates through the SQL engine return exactly
    the same rows with and without the index present."""
    from greptimedb_tpu.catalog import Catalog, MemoryKv
    from greptimedb_tpu.query import QueryEngine
    from greptimedb_tpu.storage import RegionEngine
    from greptimedb_tpu.storage.engine import EngineConfig

    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    qe.execute_one(
        "CREATE TABLE t (host STRING, ts TIMESTAMP(3) NOT NULL, v DOUBLE,"
        " TIME INDEX (ts), PRIMARY KEY (host))")
    rows = []
    for i in range(400):
        rows.append(f"('h{i % 20}', {1000 + i}, {float(i)})")
    qe.execute_one(f"INSERT INTO t VALUES {', '.join(rows)}")
    rid = qe.catalog.table("public", "t").region_ids[0]
    engine.flush(rid)

    for where in [
        "host = 'h3'",
        "host IN ('h1', 'h19')",
        "host LIKE 'h1%'",
        "host BETWEEN 'h10' AND 'h19'",
        "host >= 'h5' AND host < 'h7'",
    ]:
        r = qe.execute_one(
            f"SELECT host, ts, v FROM t WHERE {where} ORDER BY host, ts")
        import re as _re

        vals = [f"h{i}" for i in range(20)]
        if "=" in where and "BETWEEN" not in where and ">=" not in where:
            pass
        # oracle in python over the same value set
        def match(h):
            if where.startswith("host = "):
                return h == "h3"
            if where.startswith("host IN"):
                return h in ("h1", "h19")
            if where.startswith("host LIKE"):
                return _re.fullmatch("h1.*", h) is not None
            if where.startswith("host BETWEEN"):
                return "h10" <= h <= "h19"
            return "h5" <= h < "h7"

        expect = sorted(
            [(f"h{i % 20}", 1000 + i, float(i)) for i in range(400)
             if match(f"h{i % 20}")],
            key=lambda r: (r[0], r[1]))
        got = list(zip(*(r.column(c) for c in ("host", "ts", "v"))))
        got = [(str(h), int(t), float(v)) for h, t, v in got]
        assert got == expect, where
    engine.close()
