"""information_schema virtual tables (reference
src/catalog/src/information_schema/*.rs)."""

import pytest

from greptimedb_tpu.catalog.catalog import Catalog
from greptimedb_tpu.catalog.kv import MemoryKv
from greptimedb_tpu.query.engine import QueryContext, QueryEngine
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine


@pytest.fixture
def qe(tmp_path):
    engine = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
    q = QueryEngine(Catalog(MemoryKv()), engine)
    q.execute_one(
        "CREATE TABLE cpu (host STRING, usage DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY(host))"
    )
    q.execute_one("CREATE DATABASE metrics")
    q.execute_one(
        "CREATE TABLE metrics.mem (host STRING, used DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))"
    )
    yield q
    engine.close()


def test_tables(qe):
    r = qe.execute_one(
        "SELECT table_schema, table_name, engine FROM information_schema.tables "
        "WHERE table_type = 'BASE TABLE' ORDER BY table_name")
    rows = r.rows()
    assert ["public", "cpu", "mito"] in rows
    assert ["metrics", "mem", "mito"] in rows


def test_tables_has_table_id(qe):
    r = qe.execute_one(
        "SELECT table_id FROM information_schema.tables "
        "WHERE table_name = 'cpu'")
    assert r.rows()[0][0] >= 1024


def test_columns(qe):
    r = qe.execute_one(
        "SELECT column_name, data_type, semantic_type "
        "FROM information_schema.columns WHERE table_name = 'cpu' "
        "ORDER BY column_name")
    rows = r.rows()
    assert ["host", "string", "TAG"] in rows
    assert ["usage", "float64", "FIELD"] in rows
    ts_rows = [row for row in rows if row[0] == "ts"]
    assert ts_rows and ts_rows[0][2] == "TIMESTAMP"


def test_schemata(qe):
    r = qe.execute_one("SELECT schema_name FROM information_schema.schemata")
    names = [row[0] for row in r.rows()]
    assert "public" in names and "metrics" in names
    assert "information_schema" in names


def test_partitions_and_region_peers(qe):
    r = qe.execute_one(
        "SELECT table_name, partition_name, greptime_partition_id "
        "FROM information_schema.partitions WHERE table_name = 'cpu'")
    assert len(r.rows()) == 1
    rid = r.rows()[0][2]
    r2 = qe.execute_one(
        f"SELECT region_id, is_leader, status FROM "
        f"information_schema.region_peers WHERE region_id = {rid}")
    assert r2.rows()[0][1:] == ["Yes", "ALIVE"]


def test_cluster_info(qe):
    r = qe.execute_one("SELECT peer_type, version FROM "
                       "information_schema.cluster_info")
    assert r.num_rows >= 1
    assert r.rows()[0][0] in ("STANDALONE", "DATANODE", "FRONTEND")


def test_runtime_metrics(qe):
    # generate at least one sample, then read it back through SQL
    qe.execute_one("SELECT count(*) FROM cpu")
    r = qe.execute_one(
        "SELECT metric_name, value FROM information_schema.runtime_metrics "
        "WHERE metric_name LIKE 'greptimedb_tpu%'")
    assert r.num_rows >= 1


def test_engines_and_flows(qe):
    r = qe.execute_one("SELECT engine FROM information_schema.engines")
    assert "mito" in [row[0] for row in r.rows()]
    r2 = qe.execute_one("SELECT count(*) FROM information_schema.flows")
    assert r2.rows()[0][0] == 0


def test_use_and_show(qe):
    ctx = QueryContext()
    qe.execute_one("USE information_schema", ctx)
    assert ctx.db == "information_schema"
    r = qe.execute_one("SHOW TABLES", ctx)
    names = [row[0] for row in r.rows()]
    assert "tables" in names and "columns" in names
    r2 = qe.execute_one("SELECT table_name FROM tables "
                        "WHERE table_schema = 'public'", ctx)
    assert ["cpu"] in r2.rows()
    r3 = qe.execute_one("SHOW DATABASES")
    assert ["information_schema"] in r3.rows()


def test_count_star(qe):
    r = qe.execute_one(
        "SELECT count(*) FROM information_schema.columns "
        "WHERE table_name = 'cpu'")
    assert r.rows()[0][0] == 3


def test_flows_listed(qe):
    qe.execute_one(
        "CREATE TABLE cpu_1m (host STRING, avg_usage DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")
    qe.execute_one(
        "CREATE FLOW f1 SINK TO cpu_1m AS SELECT host, avg(usage), "
        "date_bin(INTERVAL '1 minute', ts) FROM cpu GROUP BY host, 3")
    r = qe.execute_one(
        "SELECT flow_name, flow_schema, sink_table "
        "FROM information_schema.flows")
    assert ["f1", "public", "cpu_1m"] in r.rows()


def test_mixed_count_rejected(qe):
    from greptimedb_tpu.query.expr import PlanError

    with pytest.raises(PlanError):
        qe.execute_one("SELECT table_schema, count(*) "
                       "FROM information_schema.tables GROUP BY table_schema")
    with pytest.raises(PlanError):
        qe.execute_one("SELECT table_schema, count(*) "
                       "FROM information_schema.tables")


def test_desc_preserves_secondary_order(qe):
    qe.execute_one(
        "CREATE TABLE disk (host STRING, used DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY(host))")
    r = qe.execute_one(
        "SELECT table_schema, table_name FROM information_schema.tables "
        "WHERE table_type = 'BASE TABLE' "
        "ORDER BY table_schema DESC, table_name ASC")
    rows = r.rows()
    pub = [row[1] for row in rows if row[0] == "public"]
    assert pub == sorted(pub)


def test_reserved_database_name(qe):
    from greptimedb_tpu.catalog.catalog import CatalogError

    with pytest.raises(CatalogError):
        qe.execute_one("CREATE DATABASE information_schema")


def test_limit_and_like(qe):
    r = qe.execute_one(
        "SELECT table_name FROM information_schema.tables "
        "WHERE table_name LIKE 'c%' LIMIT 1")
    assert r.num_rows == 1


def test_offset_pagination(qe):
    all_rows = qe.execute_one(
        "SELECT table_name FROM information_schema.tables "
        "ORDER BY table_name").rows()
    page2 = qe.execute_one(
        "SELECT table_name FROM information_schema.tables "
        "ORDER BY table_name LIMIT 2 OFFSET 2").rows()
    assert page2 == all_rows[2:4]


def test_scalar_where(qe):
    n_all = qe.execute_one(
        "SELECT engine FROM information_schema.engines").num_rows
    n_true = qe.execute_one(
        "SELECT engine FROM information_schema.engines WHERE 1 = 1").num_rows
    assert n_true == n_all == 3


def test_in_between_predicates(qe):
    r = qe.execute_one(
        "SELECT table_name FROM information_schema.tables "
        "WHERE table_name IN ('cpu', 'mem')")
    names = [row[0] for row in r.rows()]
    assert "cpu" in names and "mem" in names


def test_order_by_numeric_and_nulls(qe):
    r = qe.execute_one(
        "SELECT table_name, table_id FROM information_schema.tables "
        "WHERE table_type = 'BASE TABLE' ORDER BY table_id")
    ids = [row[1] for row in r.rows()]
    assert ids == sorted(ids)
    # partition_expression is NULL for unpartitioned tables — must not crash
    qe.execute_one("SELECT * FROM information_schema.partitions "
                   "ORDER BY partition_expression")
