"""Ingest pipeline (ISSUE 9): per-region group-commit WAL, the columnar
protocol fast path, and the crash-mid-commit chaos scenario.

Three layers: a differential suite proving the group-commit path yields
bit-for-bit the region contents of the legacy serial path, concurrency
tests proving the fsync amortization and the typed-Overloaded
backpressure are real, and a 2-datanode ProcessCluster run SIGKILLing
the write owner mid-group-commit asserting no acknowledged write is
lost and the survivor replays a torn-free WAL.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from greptimedb_tpu.concurrency.admission import Overloaded
from greptimedb_tpu.datatypes import (
    ColumnSchema,
    DataType,
    DictVector,
    RecordBatch,
    Schema,
    SemanticType,
)
from greptimedb_tpu.fault import FAULTS, Fault, FaultError
from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine

RID = 77


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def make_schema() -> Schema:
    return Schema([
        ColumnSchema("host", DataType.STRING, SemanticType.TAG),
        ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND,
                     SemanticType.TIMESTAMP, nullable=False),
        ColumnSchema("v", DataType.FLOAT64, SemanticType.FIELD),
    ])


def make_engine(path, **cfg) -> RegionEngine:
    eng = RegionEngine(EngineConfig(data_dir=str(path), **cfg))
    eng.create_region(RID, make_schema())
    return eng


def make_batch(i: int, n: int = 50) -> RecordBatch:
    return RecordBatch(make_schema(), {
        "host": DictVector.encode([f"h{(i + j) % 7}" for j in range(n)]),
        "ts": np.arange(i * n, (i + 1) * n, dtype=np.int64),
        "v": np.arange(n, dtype=np.float64) + i,
    })


def scan_tuple(eng: RegionEngine, rid: int = RID):
    sd = eng.region(rid).scan()
    if sd is None:
        return None
    cols = {k: np.asarray(v) for k, v in sd.columns.items()}
    return cols, np.asarray(sd.seq), np.asarray(sd.op_type)


class TestGroupCommitDifferential:
    def test_serial_vs_group_bit_for_bit(self, tmp_path):
        """The acceptance differential: the same write sequence through
        the legacy serial path and the group-commit path must produce
        identical region contents — same columns, same seq order, same
        flush boundary, same replay."""
        legacy = make_engine(tmp_path / "legacy",
                             ingest_group_commit=False)
        group = make_engine(tmp_path / "group")
        assert legacy.region(RID).committer is None
        assert group.region(RID).committer is not None
        for eng in (legacy, group):
            for i in range(12):
                eng.put(RID, make_batch(i))
        a, b = scan_tuple(legacy), scan_tuple(group)
        for x, y in zip(a[0].values(), b[0].values()):
            np.testing.assert_array_equal(x, y)
        np.testing.assert_array_equal(a[1], b[1])  # seq ordering
        np.testing.assert_array_equal(a[2], b[2])
        # flush boundary: same rows land in the SST, same next_seq
        ml, mg = legacy.region(RID).flush(), group.region(RID).flush()
        assert ml.num_rows == mg.num_rows
        assert legacy.region(RID).next_seq == group.region(RID).next_seq
        # replay parity after reopen
        legacy.close()
        group.close()
        l2 = RegionEngine(EngineConfig(
            data_dir=str(tmp_path / "legacy"), ingest_group_commit=False))
        g2 = RegionEngine(EngineConfig(data_dir=str(tmp_path / "group")))
        l2.open_region(RID)
        g2.open_region(RID)
        a, b = scan_tuple(l2), scan_tuple(g2)
        np.testing.assert_array_equal(a[1], b[1])
        for x, y in zip(a[0].values(), b[0].values()):
            np.testing.assert_array_equal(x, y)
        l2.close()
        g2.close()

    def test_counts_and_zero_row_batches(self, tmp_path):
        eng = make_engine(tmp_path)
        empty = RecordBatch(make_schema(), {
            "host": DictVector.encode([]),
            "ts": np.asarray([], dtype=np.int64),
            "v": np.asarray([], dtype=np.float64)})
        counts = eng.region(RID).write_many(
            [(make_batch(0, 5), 0), (empty, 0), (make_batch(1, 3), 0)])
        assert counts == [5, 0, 3]
        eng.close()

    def test_delete_rides_the_pipeline(self, tmp_path):
        """DELETE is an op_type on the same write path — tombstones must
        flow through group commit like puts."""
        eng = make_engine(tmp_path)
        eng.put(RID, make_batch(0, 10))
        from greptimedb_tpu.storage.region import OP_DELETE

        eng.region(RID).write(make_batch(0, 10), OP_DELETE)
        sd = eng.region(RID).scan()
        assert (np.asarray(sd.op_type) == 1).sum() == 10
        eng.close()


class TestGroupCommitConcurrency:
    def test_concurrent_writers_amortize_fsyncs(self, tmp_path):
        eng = make_engine(tmp_path)
        errs: list = []

        def writer(k):
            try:
                for i in range(15):
                    eng.put(RID, make_batch(k * 15 + i))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        sd = eng.region(RID).scan()
        total = 8 * 15 * 50
        assert sd.num_rows == total
        # no seq gap, no duplicate: every row got exactly one sequence
        assert sorted(np.asarray(sd.seq).tolist()) == list(range(total))
        writes = 8 * 15
        assert eng.wal.sync_count < writes, (
            f"{eng.wal.sync_count} fsyncs for {writes} concurrent writes "
            "— group commit should coalesce")
        eng.close()

    def test_queue_overflow_is_typed_overloaded(self, tmp_path):
        eng = make_engine(tmp_path, ingest_queue_depth=2,
                          ingest_overlap=False)
        region = eng.region(RID)
        gate = threading.Event()
        entered = threading.Event()
        orig = region.group_commit

        def slow_commit(ticket, entries, blob=None):
            entered.set()
            gate.wait(10.0)
            return orig(ticket, entries, blob=blob)

        region.group_commit = slow_commit
        threads = []
        errs: list = []

        def write():
            try:
                eng.put(RID, make_batch(len(threads)))
            except Exception as e:  # noqa: BLE001 — collected
                errs.append(e)

        try:
            # leader enters the gated commit; two more fill the queue
            t0 = threading.Thread(target=write)
            t0.start()
            threads.append(t0)
            assert entered.wait(5.0)
            for _ in range(2):
                t = threading.Thread(target=write)
                t.start()
                threads.append(t)
            deadline = time.monotonic() + 5.0
            while region.committer._queue is not None \
                    and len(region.committer._queue) < 2:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            with pytest.raises(Overloaded):
                eng.put(RID, make_batch(99))
        finally:
            gate.set()
            for t in threads:
                t.join(10.0)
        assert not errs
        from greptimedb_tpu.utils.metrics import (
            INGEST_GROUP_COMMIT_EVENTS,
        )

        assert INGEST_GROUP_COMMIT_EVENTS.total(event="overflow") >= 1
        eng.close()

    def test_append_fault_fails_writers_without_ack(self, tmp_path):
        """A fault at the WAL append boundary must surface to the
        writers (unacknowledged), leave no rows behind, and leave the
        pipeline healthy for the next write."""
        eng = make_engine(tmp_path)
        FAULTS.arm("ingest.commit",
                   Fault(kind="fail", nth=1, match={"op": "append"}))
        with pytest.raises(FaultError):
            eng.put(RID, make_batch(0))
        assert eng.region(RID).scan() is None  # nothing applied
        # pipeline recovered: the next write commits normally
        assert eng.put(RID, make_batch(1)) == 50
        sd = eng.region(RID).scan()
        assert sd.num_rows == 50
        # the burned reservation left a seq gap; replay tolerates it
        eng.close()
        e2 = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        e2.open_region(RID)
        assert e2.region(RID).scan().num_rows == 50
        e2.close()

    def test_flush_during_inflight_commit_loses_nothing(self, tmp_path):
        """A flush racing the reserve→apply window must wait: flushing
        in between would record a flushed_seq past rows not yet in the
        memtable and skip their WAL entries on replay."""
        eng = make_engine(tmp_path)
        eng.put(RID, make_batch(0))
        # widen the dangerous window: the first commit sleeps between
        # the durable append and the memtable apply
        FAULTS.arm("ingest.commit",
                   Fault(kind="latency", arg=0.3, nth=1,
                         match={"op": "apply"}))
        t = threading.Thread(target=lambda: eng.put(RID, make_batch(1)))
        t.start()
        time.sleep(0.1)  # the writer is inside the latency window
        eng.region(RID).flush()
        t.join(10.0)
        assert eng.region(RID).scan().num_rows == 100
        eng.close()
        # crash-equivalent: reopen and replay — both batches survive
        e2 = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        e2.open_region(RID)
        assert e2.region(RID).scan().num_rows == 100
        e2.close()

    def test_drop_during_commit_refuses_ack(self, tmp_path):
        eng = make_engine(tmp_path)
        FAULTS.arm("ingest.commit",
                   Fault(kind="latency", arg=0.3, nth=1,
                         match={"op": "apply"}))
        from greptimedb_tpu.storage.region import RegionDroppedError

        errs: list = []

        def write():
            try:
                eng.put(RID, make_batch(0))
            except RegionDroppedError as e:
                errs.append(e)

        t = threading.Thread(target=write)
        t.start()
        time.sleep(0.1)
        from greptimedb_tpu.storage.engine import (
            RegionRequest,
            RequestType,
        )

        eng.handle_request(RegionRequest(RequestType.DROP, RID))
        t.join(10.0)
        assert errs, "a write racing DROP must not be acknowledged"
        eng.close()


# ---- line-protocol parse fuzz ----------------------------------------------


class TestLineProtocolFuzz:
    def _slab(self, text, **kw):
        from greptimedb_tpu.servers.influx import parse_lines_columnar

        return parse_lines_columnar(text, **kw)

    def test_escaped_commas_spaces_and_quotes(self):
        slabs = self._slab(
            'my\\ table,ta\\,g=va\\ lue,b=c\\=d '
            'f1=1.5,msg="say \\"hi\\", bye" 1000\n')
        slab = slabs["my table"]
        assert slab.tags["ta,g"] == ["va lue"]
        assert slab.tags["b"] == ["c=d"]
        assert slab.fields["msg"] == ['say "hi", bye']
        assert slab.fields["f1"] == [1.5]

    def test_nan_inf_rejected_with_line_numbers(self):
        from greptimedb_tpu.servers.influx import LineProtocolError

        body = ("cpu,h=a v=1.0 1000\n"
                "cpu,h=a v=NaN 2000\n"
                "cpu,h=a v=inf 3000\n"
                "cpu,h=a v=-Infinity 4000\n")
        with pytest.raises(LineProtocolError) as ei:
            self._slab(body)
        assert ei.value.lines == [2, 3, 4]
        assert "non-finite" in str(ei.value)

    def test_torn_partial_line_rejected_by_number(self):
        from greptimedb_tpu.servers.influx import LineProtocolError

        body = ("cpu,h=a v=1.0 1000\n"
                "cpu,h=b v=")  # torn mid-value (crashed client)
        with pytest.raises(LineProtocolError) as ei:
            self._slab(body)
        assert ei.value.lines == [2]
        assert "line 2" in str(ei.value)

    def test_out_of_order_tags_share_columns(self):
        slabs = self._slab("m,b=2,a=1 v=1.0 1000\n"
                           "m,a=3,b=4 v=2.0 2000\n")
        slab = slabs["m"]
        assert slab.tags["a"] == ["1", "3"]
        assert slab.tags["b"] == ["2", "4"]

    def test_sparse_fields_null_pad(self):
        slabs = self._slab("m f1=1.0 1000\n"
                           "m f2=2.0 2000\n")
        slab = slabs["m"]
        assert slab.fields["f1"] == [1.0, None]
        assert slab.fields["f2"] == [None, 2.0]

    def test_bad_timestamp_and_missing_fields(self):
        from greptimedb_tpu.servers.influx import LineProtocolError

        with pytest.raises(LineProtocolError) as ei:
            self._slab("m v=1.0 notatime\nm,h=a  \nok v=2.0 5\n")
        assert ei.value.lines == [1, 2]

    def test_integer_and_bool_suffixes(self):
        slabs = self._slab("m i=42i,u=7u,t=true,f=F,neg=-3i 1000\n")
        f = slabs["m"].fields
        assert f["i"] == [42] and f["u"] == [7] and f["neg"] == [-3]
        assert f["t"] == [True] and f["f"] == [False]

    def test_duplicate_key_last_wins(self):
        slabs = self._slab("m v=1.0,v=2.0 1000\n")
        assert slabs["m"].fields["v"] == [2.0]

    def test_trailing_junk_rejected_in_every_lane(self):
        from greptimedb_tpu.servers.influx import LineProtocolError

        # plain (fused lane) and escaped (char-walking lane) spellings
        # of the same junk-after-timestamp shape must BOTH reject —
        # lane parity
        for body in ("m v=1.0 123 456\n",
                     'm,t=a\\ b v=1.0 123 456\n'):
            with pytest.raises(LineProtocolError) as ei:
                self._slab(body)
            assert ei.value.lines == [1], body

    def test_precision_scaling_exact_at_ns(self):
        # ns-epoch values exceed 2^53 — integer math must stay exact
        ns = 1_465_839_830_100_400_200
        slabs = self._slab(f"m v=1.0 {ns}\n", precision="ns")
        assert slabs["m"].ts == [ns // 1_000_000]

    def test_http_400_names_bad_lines(self, tmp_path):
        import json
        import urllib.error
        import urllib.request

        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.servers import HttpServer

        eng = RegionEngine(EngineConfig(data_dir=str(tmp_path / "d")))
        qe = QueryEngine(Catalog(MemoryKv()), eng)
        srv = HttpServer(qe, port=0)
        port = srv.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/influxdb/write",
                data=b"cpu,h=a v=1.0 1000\ncpu,h=b v=oops 2000",
                method="POST",
                headers={"Content-Type": "text/plain"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 400
            body = json.loads(ei.value.read())
            assert body["lines"] == [2]
            assert "line 2" in body["error"]
        finally:
            srv.stop()
            eng.close()


class TestVectorParseLane:
    def test_parity_with_python_lane(self):
        """The Arrow-CSV vector lane and the Python fused lane must
        produce bit-identical batches for the uniform shape."""
        from greptimedb_tpu.servers.influx import (
            _PRECISION_TO_MS,
            _vector_parse,
            parse_lines_columnar,
        )

        rng = np.random.default_rng(3)
        fields = ["f0", "f1", "f2"]
        body = "\n".join(
            f"cpu,hostname=host_{int(h)},dc=dc{int(h) % 3} "
            + ",".join(f"{f}={v:.4f}" for f, v in zip(fields, row))
            + f" {1000 + j}"
            for j, (h, row) in enumerate(zip(
                rng.integers(0, 40, 500),
                rng.uniform(-50.0, 50.0, (500, 3)))))
        num, den = _PRECISION_TO_MS["ms"]
        vec = _vector_parse(body, num, den, now_ms=0)
        assert vec is not None and "cpu" in vec
        py = parse_lines_columnar(body, precision="ms", now_ms=0)
        schema = Schema([
            ColumnSchema("hostname", DataType.STRING, SemanticType.TAG),
            ColumnSchema("dc", DataType.STRING, SemanticType.TAG),
            ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND,
                         SemanticType.TIMESTAMP, nullable=False),
        ] + [ColumnSchema(f, DataType.FLOAT64, SemanticType.FIELD)
             for f in fields])
        bv, bp = vec["cpu"].to_batch(schema), py["cpu"].to_batch(schema)
        assert bv.num_rows == bp.num_rows == 500
        for name in schema.names:
            cv, cp = bv.columns[name], bp.columns[name]
            if hasattr(cv, "decode"):
                np.testing.assert_array_equal(cv.decode(), cp.decode())
            else:
                np.testing.assert_array_equal(np.asarray(cv),
                                              np.asarray(cp))

    def test_vector_lane_bails_to_python_diagnostics(self):
        """Every precondition miss must return None, never a wrong
        batch — and the Python lane then owns the line numbers."""
        from greptimedb_tpu.servers.influx import (
            _vector_parse,
            parse_lines_columnar,
        )

        cases = [
            "cpu,h=a v=1.0 1000\ncpu,h=b v=inf 2000",    # non-finite
            "cpu,h=a v=1.0 1000\nmem,h=b v=2.0 2000",    # mixed tables
            "cpu,h=a v=1.0 1000\ncpu,h=b v=2.0",         # mixed ts
            "cpu,h=a v=1.0 1000\ncpu,h=b v=",            # torn line
            'cpu,h=a msg="x" 1000',                      # string field
            "cpu,h=a v=2i 1000",                         # int suffix
            "cpu,h=a v=1.0 1000\ncpu,v=2.0,h=b x 1",     # ragged/odd
        ]
        for body in cases:
            assert _vector_parse(body, 1, 1, 0) is None, body
        # and the diagnostics lane still yields line numbers for the bad
        from greptimedb_tpu.servers.influx import LineProtocolError

        with pytest.raises(LineProtocolError) as ei:
            parse_lines_columnar(cases[0], precision="ms")
        assert ei.value.lines == [2]

    def test_write_lines_roundtrip_through_vector_lane(self, tmp_path):
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.servers.influx import write_lines

        eng = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        qe = QueryEngine(Catalog(MemoryKv()), eng)
        body = ("vm,host=a cpu=1.5,mem=10.0 1000\n"
                "vm,host=b cpu=2.5,mem=20.0 2000\n"
                "vm,host=a cpu=3.5,mem=30.0 3000")
        assert write_lines(qe, "public", body, precision="ms") == 3
        res = qe.execute_one("SELECT host, cpu, mem FROM vm ORDER BY ts")
        assert res.rows() == [["a", 1.5, 10.0], ["b", 2.5, 20.0],
                              ["a", 3.5, 30.0]]
        eng.close()


# ---- columnar front doors land on the bulk path -----------------------------


class TestColumnarFrontDoors:
    def test_batched_auto_alter_one_schema_swap(self, tmp_path):
        """A request introducing several new fields must alter the
        schema ONCE (one region flush), not once per column."""
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.servers.influx import write_lines

        eng = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        qe = QueryEngine(Catalog(MemoryKv()), eng)
        write_lines(qe, "public", "m,h=a f1=1.0 1000\n", precision="ms")
        from greptimedb_tpu.query.engine import QueryContext

        info = qe._table("m", QueryContext(db="public"))
        rid = info.region_ids[0]
        region = eng.region(rid)
        flushes_before = len(region.files)
        write_lines(qe, "public",
                    "m,h=a f1=1.0,f2=2.0,f3=3.0,f4=4.0 2000\n",
                    precision="ms")
        info = qe._table("m", QueryContext(db="public"))
        for fn in ("f2", "f3", "f4"):
            assert fn in info.schema
        # one ALTER = one flush of the old memtable, not three
        assert len(region.files) - flushes_before <= 1
        res = qe.execute_one("SELECT f1, f2, f3, f4 FROM m WHERE ts = 2000")
        assert res.rows()[0] == [1.0, 2.0, 3.0, 4.0]
        eng.close()

    def test_remote_write_series_bulk_extend(self, tmp_path):
        """Prometheus remote-write lands columnar: one RecordBatch per
        metric, tag columns extended per series, NULLs for labels a
        series does not carry."""
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.servers.prom_store import (
            handle_remote_write,
        )
        from greptimedb_tpu.utils import protowire as pw
        from greptimedb_tpu.utils import snappy

        def label(n, v):
            return pw.field_bytes(1, pw.field_str(1, n)
                                  + pw.field_str(2, v))

        def sample(val, ts):
            return pw.field_bytes(2, pw.field_double(1, val)
                                  + pw.field_varint(2, ts))

        ts1 = pw.field_bytes(1, label("__name__", "up")
                             + label("job", "api")
                             + sample(1.0, 1000) + sample(0.0, 2000))
        ts2 = pw.field_bytes(1, label("__name__", "up")
                             + label("job", "db")
                             + label("zone", "z1")
                             + sample(1.0, 1500))
        body = snappy.compress(ts1 + ts2)
        eng = RegionEngine(EngineConfig(data_dir=str(tmp_path)))
        qe = QueryEngine(Catalog(MemoryKv()), eng)
        n = handle_remote_write(qe, body)
        assert n == 3
        res = qe.execute_one(
            "SELECT job, zone, greptime_value FROM up "
            "ORDER BY greptime_timestamp")
        assert res.rows() == [["api", None, 1.0], ["db", "z1", 1.0],
                              ["api", None, 0.0]]
        eng.close()


# ---- the acceptance chaos scenario ------------------------------------------


@pytest.mark.chaos
class TestCrashMidGroupCommit:
    def test_2dn_owner_killed_mid_commit_no_acked_loss(
            self, tmp_path, monkeypatch):
        """SIGKILL the write owner while group commits are in flight on
        a 2-datanode ProcessCluster: every INSERT acknowledged to the
        client must survive failover (the survivor replays the shared
        remote WAL), and the replay must not trip on a torn frame."""
        import os

        from greptimedb_tpu.cluster.process_cluster import ProcessCluster
        from greptimedb_tpu.meta.metasrv import MetasrvOptions

        seed = int(os.environ.get("GTPU_CHAOS_SEED", "0")) or 909
        monkeypatch.setenv("GTPU_CHAOS_SEED", str(seed))
        # children widen the append→apply window so the SIGKILL lands
        # mid-group-commit with high probability
        monkeypatch.setenv(
            "GTPU_CHAOS",
            f"ingest.commit=latency,arg:0.05,prob:0.5,@op:apply,seed:{seed}")
        c = ProcessCluster(str(tmp_path), num_datanodes=2,
                           opts=MetasrvOptions())
        try:
            t = 0.0
            for _ in range(5):
                c.beat_all(t)
                t += 3000.0
            c.sql("CREATE TABLE m (host STRING, v DOUBLE, "
                  "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")
            rid = c.catalog.table("public", "m").region_ids[0]
            owner = c.metasrv.routes.get(
                str(rid >> 32)).regions[0].leader_node
            for _ in range(3):
                c.beat_all(t)
                t += 3000.0
            acked: list = []
            lock = threading.Lock()

            def writer(w):
                for i in range(25):
                    key = f"h{w}_{i:02d}"
                    try:
                        c.sql(f"INSERT INTO m VALUES ('{key}', "
                              f"{float(w * 100 + i)}, {1000 * (i + 1)})")
                        with lock:
                            acked.append((key, float(w * 100 + i)))
                    except Exception:  # noqa: BLE001 — unacked may fail
                        pass

            threads = [threading.Thread(target=writer, args=(w,))
                       for w in range(3)]
            for th in threads:
                th.start()
            # kill only once the stream is demonstrably mid-flight:
            # some writes acked, more still coming
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                with lock:
                    if len(acked) >= 5:
                        break
                time.sleep(0.01)
            c.kill_datanode(owner)
            for th in threads:
                th.join(30.0)
            assert acked, "no write was acknowledged before the kill"
            for _ in range(30):
                c.beat_all(t)
                t += 3000.0
            assert c.tick(t), "failover should start"
            c.beat_all(t)  # deliver OPEN_REGION to the survivor
            rows = c.sql("SELECT host, v FROM m ORDER BY host").rows()
            got = {r[0]: r[1] for r in rows}
            for key, v in acked:
                assert got.get(key) == v, \
                    f"acknowledged write {key} lost after failover"
            survivor = c.metasrv.routes.get(
                str(rid >> 32)).regions[0].leader_node
            assert survivor != owner
        finally:
            c.close()
